"""Discovery-chain compiler: config entries → a routing graph.

Mirrors the reference's discovery chain (reference
agent/consul/discoverychain/compile.go + structs/discovery_chain.go):
the L7 config entries for one service — ``service-router``,
``service-splitter``, ``service-resolver`` — compile into a walkable
graph of router → splitter → resolver nodes ending in concrete
targets (service, subset, datacenter), with redirects followed,
failover recorded per resolver, and reference-style defaults (a
service with no entries compiles to a single default resolver).

Entry shapes (the subset of each kind this compiler evaluates,
snake_case like the rest of the config-entry surface):

  service-router:   {"routes": [{"match": {"http": {"path_prefix"|
                     "path_exact"|"header": [{"name","exact"}]}},
                     "destination": {"service", "service_subset"}}]}
  service-splitter: {"splits": [{"weight", "service",
                     "service_subset"}]}
  service-resolver: {"default_subset", "subsets": {name: {"filter"}},
                     "redirect": {"service","service_subset",
                     "datacenter"}, "failover": {subset|"*":
                     {"service", "datacenters": [...]}},
                     "connect_timeout"}

Circular redirects and router/splitter references are a compile error
(compile.go's circular-reference detection via its string stack).
"""

from __future__ import annotations

from typing import Any, Optional

ROUTER = "router"
SPLITTER = "splitter"
RESOLVER = "resolver"

DEFAULT_CONNECT_TIMEOUT = "5s"


class ChainCompileError(ValueError):
    pass


class _Compiler:
    def __init__(self, get_entry, service: str, datacenter: str):
        self.get_entry = get_entry
        self.service = service
        self.datacenter = datacenter
        self.nodes: dict[str, dict] = {}
        self.targets: dict[str, dict] = {}
        self._stack: list[str] = []  # circular-reference guard

    # -- helpers -------------------------------------------------------
    def _entry(self, kind: str, name: str) -> Optional[dict]:
        return self.get_entry(kind, name)

    def _target_id(self, service: str, subset: str, dc: str) -> str:
        # The reference's target naming: <subset>.<service>.<ns>.<dc>;
        # single-namespace here.
        return f"{subset or 'default'}.{service}.{dc}"

    def _ensure_target(self, service: str, subset: str, dc: str,
                       subset_def: Optional[dict]) -> str:
        tid = self._target_id(service, subset, dc)
        if tid not in self.targets:
            self.targets[tid] = {
                "id": tid, "service": service,
                "service_subset": subset or "",
                "datacenter": dc,
                "subset": dict(subset_def or {}),
            }
        return tid

    def _guard(self, node_name: str):
        if node_name in self._stack:
            cycle = " -> ".join([*self._stack, node_name])
            raise ChainCompileError(
                f"circular reference in discovery chain: {cycle}")
        self._stack.append(node_name)

    def _unguard(self):
        self._stack.pop()

    # -- node builders (compile.go assembleChain) ----------------------
    def entry_node(self, service: str) -> str:
        """The first node for ``service``: router, else splitter, else
        resolver (assembleChain's ordering)."""
        if self._entry("service-router", service) is not None:
            return self.router_node(service)
        if self._entry("service-splitter", service) is not None:
            return self.splitter_node(service)
        return self.resolver_node(service, "")

    def router_node(self, service: str) -> str:
        name = f"{ROUTER}:{service}"
        if name in self.nodes:
            return name
        self._guard(name)
        try:
            entry = self._entry("service-router", service) or {}
            self.nodes[name] = node = {"type": ROUTER, "name": service,
                                       "routes": []}
            for route in entry.get("routes", []):
                dest = route.get("destination") or {}
                svc = dest.get("service") or service
                subset = dest.get("service_subset", "")
                nxt = (self.resolver_node(svc, subset) if subset
                       else self.next_after_router(svc))
                node["routes"].append({
                    "match": route.get("match") or {},
                    "next_node": nxt,
                })
            # The implicit catch-all default route to the service
            # itself (compile.go appends a default route).
            node["routes"].append({
                "match": None,
                "next_node": self.next_after_router(service),
            })
        finally:
            self._unguard()
        return name

    def next_after_router(self, service: str) -> str:
        if self._entry("service-splitter", service) is not None:
            return self.splitter_node(service)
        return self.resolver_node(service, "")

    def splitter_node(self, service: str) -> str:
        name = f"{SPLITTER}:{service}"
        if name in self.nodes:
            return name
        self._guard(name)
        try:
            entry = self._entry("service-splitter", service) or {}
            splits_in = entry.get("splits", [])
            total = sum(float(s.get("weight", 0)) for s in splits_in)
            if splits_in and abs(total - 100.0) > 0.01:
                raise ChainCompileError(
                    f"service-splitter {service!r} weights sum to "
                    f"{total}, must be 100")
            self.nodes[name] = node = {"type": SPLITTER, "name": service,
                                       "splits": []}
            for s in splits_in or [{"weight": 100}]:
                svc = s.get("service") or service
                node["splits"].append({
                    "weight": float(s.get("weight", 0)),
                    "next_node": self.resolver_node(
                        svc, s.get("service_subset", "")),
                })
        finally:
            self._unguard()
        return name

    def resolver_node(self, service: str, subset: str,
                      dc_override: str = "") -> str:
        entry = self._entry("service-resolver", service) or {}
        redirect = entry.get("redirect") or {}
        r_svc = redirect.get("service", "")
        if redirect and r_svc and r_svc != service:
            # A redirect to a DIFFERENT service re-enters the chain at
            # the destination's resolver (compile.go), carrying subset
            # and datacenter overrides along; cycle-guarded.
            self._guard(f"redirect:{service}")
            try:
                return self.resolver_node(
                    r_svc,
                    redirect.get("service_subset", subset),
                    dc_override=redirect.get("datacenter", dc_override))
            finally:
                self._unguard()
        if redirect:
            # Same-service redirect (subset-only and/or dc-only — both
            # valid reference shapes): adopt the overrides WITHOUT
            # recursion, so the cycle guard can never trip on them.
            subset = redirect.get("service_subset", subset)
            dc_override = redirect.get("datacenter", dc_override)
        subset = subset or entry.get("default_subset", "")
        dc = dc_override or self.datacenter
        name = f"{RESOLVER}:{subset or 'default'}.{service}" + (
            f".{dc}" if dc_override else "")
        if name in self.nodes:
            return name
        subsets = entry.get("subsets") or {}
        if subset and subset not in subsets:
            raise ChainCompileError(
                f"service-resolver {service!r} has no subset {subset!r}")
        tid = self._ensure_target(service, subset, dc,
                                  subsets.get(subset))
        failover = None
        fo_map = entry.get("failover") or {}
        fo = fo_map.get(subset or "*") or fo_map.get("*")
        if fo:
            fo_svc = fo.get("service") or service
            fo_targets = [
                self._ensure_target(fo_svc, fo.get("service_subset", ""),
                                    fdc, None)
                for fdc in (fo.get("datacenters") or [self.datacenter])
            ]
            failover = {"targets": fo_targets}
        self.nodes[name] = {
            "type": RESOLVER, "name": f"{subset or 'default'}.{service}",
            "resolver": {
                "target": tid,
                "connect_timeout": entry.get(
                    "connect_timeout", DEFAULT_CONNECT_TIMEOUT),
                "default": not entry,
                "failover": failover,
            },
        }
        return name


def compile_chain(get_entry, service: str,
                  datacenter: str = "dc1") -> dict:
    """``get_entry(kind, name) -> entry|None`` over the config-entry
    store; returns the reference's CompiledDiscoveryChain shape."""
    c = _Compiler(get_entry, service, datacenter)
    start = c.entry_node(service)
    return {
        "service_name": service,
        "datacenter": datacenter,
        "start_node": start,
        "nodes": c.nodes,
        "targets": c.targets,
    }
