"""Prepared queries: stored service lookups with templates + failover.

Mirrors the reference's prepared-query subsystem (reference
agent/consul/prepared_query_endpoint.go, agent/structs/prepared_query.go,
agent/consul/prepared_query/template.go): a raft-replicated definition
of a health-filtered service lookup — tag/metadata filters, RTT ``near``
sorting, cross-DC failover — resolvable by id or by name, with
``name_prefix_match`` templates rendered against the looked-up name.

This module is the pure logic (normalization, template rendering,
result filtering); the raft/RPC plumbing lives in
``server/endpoints.py`` and storage in ``server/state_store.py``.
"""

from __future__ import annotations

import re
from typing import Any, Optional

from consul_tpu.utils import health

TEMPLATE_NAME_PREFIX_MATCH = "name_prefix_match"

_DEFAULTS: dict[str, Any] = {
    "id": "", "name": "", "session": "", "token": "",
    "template": {"type": "", "regexp": "", "remove_empty_tags": False},
    "service": {
        "service": "",
        "failover": {"nearest_n": 0, "datacenters": []},
        "only_passing": False,
        "ignore_check_ids": [],
        "near": "",
        "tags": [],
        "node_meta": {},
        "service_meta": {},
    },
    "dns": {"ttl": ""},
}


def _merge_defaults(defaults: dict, given: dict) -> dict:
    out = {}
    for k, d in defaults.items():
        v = given.get(k, d)
        if isinstance(d, dict) and isinstance(v, dict) and d:
            # Fixed-schema subdict: recurse so missing knobs default.
            out[k] = _merge_defaults(d, v)
        else:
            # Scalar, list, or an OPEN map (empty-dict default like
            # node_meta/service_meta): the given value rides verbatim.
            out[k] = v if v is not None else d
    return out


def normalize(q: dict) -> dict:
    """Fill defaults and validate (reference parseQuery + parseService,
    prepared_query_endpoint.go:120-214). Raises ValueError on a bad
    definition."""
    unknown = sorted(set(q) - set(_DEFAULTS))
    if unknown:
        raise ValueError(f"unknown prepared query fields: {unknown}")
    out = _merge_defaults(_DEFAULTS, q)
    if not out["service"]["service"]:
        raise ValueError("prepared query must specify a Service to query")
    t = out["template"]["type"]
    if t and t != TEMPLATE_NAME_PREFIX_MATCH:
        raise ValueError(f"bad template type {t!r} "
                         f"(only {TEMPLATE_NAME_PREFIX_MATCH!r})")
    if out["template"]["regexp"]:
        try:
            re.compile(out["template"]["regexp"])
        except re.error as e:
            raise ValueError(f"bad template regexp: {e}") from e
    nn = out["service"]["failover"]["nearest_n"]
    if not isinstance(nn, int) or nn < 0:
        raise ValueError(f"bad NearestN {nn!r}")
    return out


def is_template(q: dict) -> bool:
    return bool(q.get("template", {}).get("type"))


_INTERP = re.compile(r"\$\{\s*([a-z.]+(?:\(\d+\))?)\s*\}")


def render(q: dict, name: str) -> dict:
    """Render a template query against the looked-up ``name``
    (reference prepared_query/template.go Render: the go-hcl
    interpolation over every string field, with ``name.full``/
    ``name.prefix``/``name.suffix`` and ``match(N)`` regexp captures).

    The interpolation here covers the fields a service query reads —
    service name, tags, node/service metadata values — which is where
    the reference's walk visits strings that matter."""
    prefix = q.get("name", "")
    variables = {
        "name.full": name,
        "name.prefix": prefix,
        "name.suffix": name[len(prefix):] if name.startswith(prefix) else "",
    }
    rx = q.get("template", {}).get("regexp", "")
    if rx:
        m = re.match(rx, name)
        if m:
            for i, g in enumerate(m.groups(), start=1):
                variables[f"match({i})"] = g or ""

    def interp(s: str) -> str:
        return _INTERP.sub(lambda mo: variables.get(mo.group(1), ""), s)

    out = {k: (dict(v) if isinstance(v, dict) else v) for k, v in q.items()}
    svc = dict(out["service"])
    svc["service"] = interp(svc["service"])
    tags = [interp(t) for t in svc.get("tags", [])]
    if out.get("template", {}).get("remove_empty_tags"):
        tags = [t for t in tags if t]
    svc["tags"] = tags
    svc["node_meta"] = {k: interp(v)
                        for k, v in (svc.get("node_meta") or {}).items()}
    svc["service_meta"] = {k: interp(v)
                           for k, v in (svc.get("service_meta") or {}).items()}
    out["service"] = svc
    return out


def filter_nodes(q: dict, rows: list[dict]) -> list[dict]:
    """Apply the query's health + tag + metadata filters to health
    rows ({node, service, checks, ...}) — reference
    CheckServiceNodes.FilterIgnore + tagFilter + nodeMetaFilter +
    serviceMetaFilter (prepared_query_endpoint.go:560-640)."""
    svc = q["service"]
    ignore = set(svc.get("ignore_check_ids") or [])
    required = [t.lower() for t in svc.get("tags", [])
                if t and not t.startswith("!")]
    forbidden = [t[1:].lower() for t in svc.get("tags", [])
                 if t.startswith("!")]
    node_meta = svc.get("node_meta") or {}
    service_meta = svc.get("service_meta") or {}
    out = []
    for row in rows:
        worst = 0
        for c in row.get("checks", []):
            if c.get("check_id") in ignore:
                continue
            worst = max(worst, health.severity(c.get("status")))
        # only_passing drops warnings too; default drops critical only
        # (reference FilterIgnore).
        if worst >= (1 if svc.get("only_passing") else 2):
            continue
        tags = {t.lower() for t in (row["service"].get("tags") or [])}
        if any(t not in tags for t in required):
            continue
        if any(t in tags for t in forbidden):
            continue
        smeta = row["service"].get("meta") or {}
        if any(smeta.get(k) != v for k, v in service_meta.items()):
            continue
        nmeta = row.get("node_meta") or {}
        if node_meta and any(nmeta.get(k) != v
                             for k, v in node_meta.items()):
            continue
        out.append(row)
    return out


def resolve(queries: list[dict], id_or_name: str) -> Optional[dict]:
    """Resolve by exact id, exact name, then longest matching
    ``name_prefix_match`` template — rendered (reference
    state/prepared_query.go PreparedQueryResolve)."""
    if not id_or_name:
        raise ValueError("missing query id or name")
    by_id = next((q for q in queries if q["id"] == id_or_name), None)
    if by_id is not None:
        if is_template(by_id):
            raise ValueError(
                "prepared query templates can only be resolved by name, "
                "not by id")
        return by_id
    exact = next((q for q in queries
                  if q["name"] == id_or_name and not is_template(q)), None)
    if exact is not None:
        return exact
    best = None
    for q in queries:
        if not is_template(q):
            continue
        if id_or_name.startswith(q["name"]):
            if best is None or len(q["name"]) > len(best["name"]):
                best = q
    if best is not None:
        return render(best, id_or_name)
    return None


def nearest_sorted(nodes: list[dict], near_node: str, sort_fn) -> list[dict]:
    """RTT-order an executed query's nodes from ``near_node``, then
    float the queried-from node itself to position 0 when it lands near
    the front (reference Execute:430-441, depth-capped at 10 — a node
    asking for its own service should be offered itself first).

    ``sort_fn(near, rows)`` is the nearness sorter — host
    ``rtt.sort_nodes_by_distance`` over store coordinate sets or the
    device serving plane's batched path; this helper stays pure either
    way.
    """
    nodes = list(sort_fn(near_node, nodes))
    for i, row in enumerate(nodes[:10]):
        if row["node"] == near_node:
            nodes[0], nodes[i] = nodes[i], nodes[0]
            break
    return nodes
