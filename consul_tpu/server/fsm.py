"""The replicated state machine: raft log entries -> state store writes.

Mirrors the reference FSM (reference agent/consul/fsm/fsm.go:107-152):
entries are typed commands dispatched to a handler per message type,
applied with the raft log index so every replica lands on identical
modify indexes; snapshot/restore round-trips every table including
coordinates (reference fsm/snapshot*.go, commands_oss.go:218-230
``applyCoordinateBatchUpdate``).
"""

from __future__ import annotations

from typing import Any

from consul_tpu.server.state_store import StateStore

# Message types (reference agent/structs/structs.go MessageType values).
REGISTER = "register"
DEREGISTER = "deregister"
KV = "kv"
SESSION = "session"
COORDINATE_BATCH_UPDATE = "coordinate-batch-update"
CONFIG_ENTRY = "config-entry"
AUTOPILOT = "autopilot"
PREPARED_QUERY = "prepared-query"
ACL = "acl"
INTENTION = "intention"
CONNECT_CA = "connect-ca"
TXN = "txn"

# Tables each op type can write (for scoped TXN undo logs). KV ops can
# cascade into sessions? No — but session destroys cascade into kv, and
# node deletes cascade widely; keep cascading types conservative.
_TXN_TABLES: dict[str, set] = {
    KV: {"kv"},
    SESSION: {"sessions", "kv", "prepared_queries"},
    COORDINATE_BATCH_UPDATE: {"coordinates"},
    CONFIG_ENTRY: {"config_entries"},
    PREPARED_QUERY: {"prepared_queries"},
    ACL: {"acl_tokens", "acl_policies", "acl_meta"},
    INTENTION: {"intentions"},
    CONNECT_CA: {"connect_ca"},
    REGISTER: {"nodes", "services", "checks"},
    DEREGISTER: {"nodes", "services", "checks", "coordinates",
                 "sessions", "kv", "prepared_queries"},
}


class FSM:
    def __init__(self, store: StateStore | None = None):
        self.store = store if store is not None else StateStore()

    def apply(self, index: int, command: dict) -> Any:
        """Apply one committed log entry at raft ``index``. Must be
        deterministic: replicas apply the same sequence and converge."""
        mtype = command["type"]
        if mtype == REGISTER:
            # One registration can carry node + service + check, like
            # structs.RegisterRequest (fsm.go applyRegister). A
            # service/check-only registration (no "address" — the txn
            # Service/Check verbs) must not touch the node row: the
            # reference's TxnServiceOp requires the node to exist and
            # leaves it alone; a missing node aborts the (txn) apply.
            r = command
            if "address" in r:
                self.store.ensure_node(r["node"], r["address"],
                                       r.get("node_meta"), index=index)
            elif self.store.get_node(r["node"]) is None:
                raise KeyError(f"node {r['node']!r} not registered")
            if "service" in r:
                s = r["service"]
                self.store.ensure_service(
                    r["node"], s.get("id", s["service"]), s["service"],
                    s.get("port", 0), s.get("tags"), s.get("meta"), index=index,
                )
            if "check" in r:
                c = r["check"]
                self.store.ensure_check(
                    r["node"], c["check_id"], c.get("status", "critical"),
                    c.get("service_id", ""), c.get("output", ""), index=index,
                )
            return index
        if mtype == DEREGISTER:
            r = command
            if "service_id" in r:
                return self.store.delete_service(r["node"], r["service_id"],
                                                 index=index)
            if "check_id" in r:
                return self.store.delete_check(r["node"], r["check_id"],
                                               index=index)
            return self.store.delete_node(r["node"], index=index)
        if mtype == KV:
            op = command["op"]
            if op == "get":
                # Read-inside-txn (reference txn KVGet): the row rides
                # the results list; a missing key fails the batch
                # ("key does not exist", agent/consul/txn_endpoint.go).
                e = self.store.kv_get(command["key"])
                if e is None:
                    raise KeyError(
                        f"key {command['key']!r} does not exist")
                return e
            if op == "unlock":
                _, ok = self.store.kv_unlock(command["key"],
                                             command.get("session"),
                                             index=index)
                return ok
            if op in ("set", "cas", "lock"):
                _, ok = self.store.kv_set(
                    command["key"], command.get("value", b""),
                    command.get("flags", 0),
                    command.get("cas_index") if op == "cas" else None,
                    command.get("session"), index=index,
                )
                return ok
            if op in ("delete", "delete-tree", "delete-cas"):
                _, ok = self.store.kv_delete(
                    command["key"], op == "delete-tree",
                    command.get("cas_index") if op == "delete-cas" else None,
                    index=index,
                )
                return ok
            raise ValueError(f"unknown KV op {op!r}")
        if mtype == SESSION:
            if command["op"] == "create":
                self.store.session_create(
                    command["id"], command["node"], command.get("ttl_s", 0.0),
                    command.get("behavior", "release"), command.get("checks"),
                    lock_delay_s=command.get("lock_delay_s", 15.0),
                    index=index,
                )
                return command["id"]
            self.store.session_destroy(command["id"], index=index)
            return True
        if mtype == COORDINATE_BATCH_UPDATE:
            return self.store.coordinate_batch_update(command["updates"],
                                                      index=index)
        if mtype == CONFIG_ENTRY:
            # Ops mirror reference ConfigEntryRequest (structs/config_
            # entry.go: Upsert/UpsertCAS/Delete[CAS]); CAS evaluates
            # deterministically at apply time and returns the verdict.
            cas = command.get("cas_index")
            if command.get("op") in ("delete", "delete-cas"):
                _, ok = self.store.config_delete(
                    command["kind"], command["name"],
                    cas_index=cas, index=index)
                return ok
            _, ok = self.store.config_set(
                command["kind"], command["name"], command["entry"],
                cas_index=cas, index=index)
            return ok
        if mtype == PREPARED_QUERY:
            # Reference fsm applyPreparedQueryOperation (fsm/commands_
            # oss.go): create/update upsert by id, delete removes.
            # Name-collision on a replicated create is an apply-time
            # verdict (False), like CAS — never a replica divergence.
            if command["op"] == "delete":
                self.store.pq_delete(command["id"], index=index)
                return True
            try:
                self.store.pq_set(command["query"], index=index)
            except ValueError:
                return False
            return command["query"]["id"]
        if mtype == ACL:
            # Reference fsm applyACL* (fsm/commands_oss.go): token and
            # policy upserts/deletes plus the one-shot bootstrap
            # marker. Bootstrap races resolve deterministically at
            # apply time: the second committed bootstrap is a False
            # verdict (acl_endpoint.go Bootstrap "already bootstrapped").
            op = command["op"]
            if op == "token-set":
                self.store.acl_token_set(command["token"], index=index)
                return command["token"]["accessor_id"]
            if op == "token-delete":
                self.store.acl_token_delete(command["accessor_id"],
                                            index=index)
                return True
            if op == "policy-set":
                self.store.acl_policy_set(command["policy"], index=index)
                return command["policy"]["name"]
            if op == "policy-delete":
                self.store.acl_policy_delete(command["name"], index=index)
                return True
            if op == "bootstrap":
                if self.store.acl_bootstrapped():
                    return False
                self.store.acl_mark_bootstrapped(index=index)
                self.store.acl_token_set(command["token"], index=index)
                return True
            raise ValueError(f"unknown ACL op {op!r}")
        if mtype == CONNECT_CA:
            # Reference fsm applyConnectCAOperation: the PEM material
            # is generated ONCE at the endpoint and carried in the log
            # (an FSM must never generate randomness); init is
            # idempotent — a racing second init is a False verdict.
            op = command["op"]
            if op == "set-root":
                if command.get("only_if_uninitialized") and \
                        self.store.ca_active_root() is not None:
                    return False
                self.store.ca_set_root(command["root"],
                                       activate=True, index=index)
                return command["root"]["id"]
            if op == "set-config":
                self.store.ca_config_set(command["config"], index=index)
                return True
            raise ValueError(f"unknown connect-ca op {op!r}")
        if mtype == INTENTION:
            # Reference fsm applyIntentionOperation: upsert/delete by
            # id; a duplicate (source, destination) pair on a
            # replicated create is an apply-time False verdict.
            if command["op"] == "delete":
                self.store.intention_delete(command["id"], index=index)
                return True
            try:
                self.store.intention_set(command["intention"], index=index)
            except ValueError:
                return False
            return command["intention"]["id"]
        if mtype == AUTOPILOT:
            # Operator autopilot configuration (reference
            # fsm applyAutopilotUpdate, operator_autopilot_endpoint.go):
            # CAS evaluated deterministically at apply time.
            _, ok = self.store.autopilot_set(
                command["config"], cas_index=command.get("cas_index"),
                index=index)
            return ok
        if mtype == TXN:
            # All-or-nothing batch (reference agent/consul/txn_endpoint.go)
            # applied inside one store transaction: the store lock is
            # held across verify + apply + (possible) rollback, so a
            # concurrent reader can never observe a partial or
            # later-rolled-back batch — the reference's single-commit
            # memdb Txn visibility.
            with self.store.transaction():
                for op in command["ops"]:
                    if op["type"] == KV and op["op"] in ("cas", "delete-cas"):
                        e = self.store.kv_get(op["key"])
                        cur = e["modify_index"] if e else 0
                        if cur != op.get("cas_index", 0):
                            return {"ok": False, "failed": op["key"]}
                # Undo log covers only the tables this batch can touch —
                # O(touched tables), not O(store) (the reference's memdb
                # txn abort is similarly scoped to written radix nodes).
                touched: set = set()
                for op in command["ops"]:
                    touched |= _TXN_TABLES.get(op["type"], set(StateStore.TABLES))
                undo = self.store.snapshot(tables=touched)
                results = []
                try:
                    for op in command["ops"]:
                        result = self.apply(index, op)
                        # Ops that *return* failure (lock/unlock/CAS
                        # inside the batch) abort the TXN just like ops
                        # that raise.
                        if result is False:
                            self.store.restore(undo)
                            return {"ok": False,
                                    "failed": op.get("key", op["type"])}
                        results.append(result)
                except Exception as e:  # noqa: BLE001
                    self.store.restore(undo)
                    return {"ok": False, "error": repr(e)}
                return {"ok": True, "results": results}
        raise ValueError(f"unknown message type {mtype!r}")

    # Snapshot/restore delegate to the store (fsm.go:134,152).
    def snapshot(self) -> dict:
        return self.store.snapshot()

    def restore(self, snap: dict) -> None:
        self.store.restore(snap)
