"""Inter-process RPC wire: msgpack-RPC over TCP with first-byte demux.

The reference's agent↔server and server↔server RPC rides a
yamux-multiplexed TCP pool speaking msgpack-RPC, selected by a
first-byte protocol marker on each fresh connection (reference
agent/pool/pool.go:122-533, agent/pool/conn.go:3-30, dispatch at
agent/consul/rpc.go:81-133). This module is that tier for the
framework — the piece that makes a *separate-process* client agent
real rather than an in-process import:

  - A server process runs one listener. The first byte of every
    connection picks the protocol; RPC_CONSUL is implemented here
    (the gossip bytes ride the PacketBridge seam, not this port).
  - Requests are length-prefixed msgpack envelopes
    ``{"seq", "method", "args"}`` answered by ``{"seq", "ok"}`` or a
    typed error — each request is served on its own thread, so
    pipelined blocking queries on one connection proceed concurrently,
    the role yamux streams play in the reference.
  - The client keeps one connection, pipelines by seq, reconnects on
    failure, and surfaces typed errors (NotLeader, NoPathToDatacenter)
    as the same exceptions the in-process path raises — so
    agent/pool.py's ServerPool routing policy works unchanged over
    real sockets.

bytes round-trip natively (use_bin_type msgpack), so KV values and
payloads cross the wire intact.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Any, Callable, Optional

import msgpack

from consul_tpu.server.endpoints import NoPathToDatacenter
from consul_tpu.server.raft import NotLeader

RPC_CONSUL = 0x00   # conn.go RPCConsul role: the msgpack-RPC stream
_MAX_FRAME = 64 << 20


class RpcWireError(ConnectionError):
    pass


def _send_frame(sock: socket.socket, obj: dict, lock: threading.Lock):
    raw = msgpack.packb(obj, use_bin_type=True, default=_default)
    with lock:
        sock.sendall(struct.pack(">I", len(raw)) + raw)


def _default(o):
    if isinstance(o, (set, frozenset, tuple)):
        return list(o)
    raise TypeError(f"unserializable RPC value: {type(o)!r}")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise RpcWireError("connection closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> dict:
    (length,) = struct.unpack(">I", _recv_exact(sock, 4))
    if length > _MAX_FRAME:
        raise RpcWireError(f"oversized RPC frame ({length} bytes)")
    return msgpack.unpackb(_recv_exact(sock, length), raw=False)


# ----------------------------------------------------------------------
# Server side
# ----------------------------------------------------------------------

class RpcListener:
    """One TCP listener serving RPC_CONSUL connections against
    ``rpc_fn(method, **args)`` (a Server.rpc or a leader-routing
    closure). Unknown first bytes are dropped, like the reference's
    demux rejecting unregistered protocol versions."""

    def __init__(self, rpc_fn: Callable[..., Any],
                 host: str = "127.0.0.1", port: int = 0):
        self.rpc_fn = rpc_fn
        self._sock = socket.create_server((host, port))
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        wlock = threading.Lock()
        try:
            proto = _recv_exact(conn, 1)[0]
            if proto != RPC_CONSUL:
                return  # unknown protocol byte: hang up
            while not self._stop.is_set():
                req = _recv_frame(conn)
                threading.Thread(
                    target=self._serve_one, args=(conn, wlock, req),
                    daemon=True,
                ).start()
        except (RpcWireError, OSError):
            pass
        finally:
            conn.close()

    def _serve_one(self, conn, wlock, req):
        seq = req.get("seq", 0)
        try:
            out = self.rpc_fn(req["method"], **req.get("args", {}))
            resp = {"seq": seq, "ok": out}
        except NotLeader as e:
            resp = {"seq": seq, "err_type": "not_leader",
                    "leader": e.leader_hint}
        except NoPathToDatacenter as e:
            resp = {"seq": seq, "err_type": "no_path", "dc": e.dc,
                    "err": str(e)}
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            # Application-level errors stay typed across the wire so a
            # client agent's HTTP tier maps them to 400s exactly like
            # server mode (and the pool does NOT rotate on them).
            resp = {"seq": seq, "err_type": "app",
                    "app_class": type(e).__name__, "err": str(e)[:500]}
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            resp = {"seq": seq, "err": repr(e)[:500]}
        try:
            _send_frame(conn, resp, wlock)
        except (OSError, RpcWireError):
            pass  # client went away mid-call

    def close(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


# ----------------------------------------------------------------------
# Client side
# ----------------------------------------------------------------------

class RpcClient:
    """One pooled connection to a server's RPC port: pipelined seq-
    matched calls, lazy connect, reconnect-on-failure. The per-server
    callable shape (``call(method, **args)``) matches what
    agent/pool.ServerPool expects, so the reference's routing policy
    (shuffle, rotate-past-failure, rebalance) composes directly."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        self.addr = (host, int(port))
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending: dict[int, dict] = {}
        self._seq = 0

    def _connect(self):
        with self._state_lock:
            if self._sock is not None:
                return
            sock = socket.create_connection(self.addr, timeout=10.0)
            sock.settimeout(None)
            sock.sendall(bytes([RPC_CONSUL]))
            self._sock = sock
            threading.Thread(target=self._read_loop, args=(sock,),
                             daemon=True).start()

    def _read_loop(self, sock):
        try:
            while True:
                resp = _recv_frame(sock)
                with self._state_lock:
                    slot = self._pending.get(resp.get("seq"))
                if slot is not None:
                    slot["resp"] = resp
                    slot["done"].set()
        except (RpcWireError, OSError):
            with self._state_lock:
                if self._sock is sock:
                    self._sock = None
                pending, self._pending = self._pending, {}
            for slot in pending.values():
                slot["resp"] = None  # connection died under the call
                slot["done"].set()

    def call(self, method: str, **args) -> Any:
        self._connect()
        with self._state_lock:
            self._seq += 1
            seq = self._seq
            slot = {"done": threading.Event(), "resp": None}
            self._pending[seq] = slot
            sock = self._sock
        try:
            _send_frame(sock, {"seq": seq, "method": method, "args": args},
                        self._wlock)
        except (OSError, AttributeError) as e:
            with self._state_lock:
                self._pending.pop(seq, None)
                self._sock = None
            raise RpcWireError(f"send failed: {e}") from e
        # Blocking queries legitimately park server-side for their
        # requested wait; the wire timeout must outlast it or a long
        # ?wait= long-poll would read as a dead server.
        timeout = max(self.timeout_s, float(args.get("wait_s", 0)) + 15.0)
        if not slot["done"].wait(timeout):
            with self._state_lock:
                self._pending.pop(seq, None)
            raise RpcWireError(f"RPC {method} timed out")
        resp = slot["resp"]
        if resp is None:
            raise RpcWireError("connection lost mid-call")
        if "ok" in resp:
            return resp["ok"]
        if resp.get("err_type") == "not_leader":
            raise NotLeader(resp.get("leader"))
        if resp.get("err_type") == "no_path":
            raise NoPathToDatacenter(resp.get("dc", "?"))
        if resp.get("err_type") == "app":
            cls = {"ValueError": ValueError, "KeyError": KeyError,
                   "TypeError": TypeError,
                   "AttributeError": AttributeError}.get(
                resp.get("app_class", ""), ValueError)
            raise cls(resp.get("err", "remote application error"))
        raise RpcWireError(resp.get("err", "unknown RPC error"))

    def close(self):
        with self._state_lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
