"""Inter-process RPC wire: msgpack-RPC over TCP with first-byte demux.

The reference's agent↔server and server↔server RPC rides a
yamux-multiplexed TCP pool speaking msgpack-RPC, selected by a
first-byte protocol marker on each fresh connection (reference
agent/pool/pool.go:122-533, agent/pool/conn.go:3-30, dispatch at
agent/consul/rpc.go:81-133). This module is that tier for the
framework — the piece that makes a *separate-process* client agent
real rather than an in-process import:

  - A server process runs one listener. The first byte of every
    connection picks the protocol. Three roles are implemented with
    the reference's byte values (conn.go:3-30):
      RPC_CONSUL  (0x00) — the msgpack-RPC request stream;
      RPC_TLS     (0x03) — TLS upgrade: handshake, then read the
                  *inner* first byte and dispatch again (the
                  reference wraps the conn and re-reads the role,
                  pool.go:307-315);
      RPC_SNAPSHOT(0x05) — one-shot state snapshot save/restore
                  (reference snapshot/snapshot.go:29,145 streamed
                  over rpc.go:196's RPCSnapshot byte), so a client
                  agent on the wire tier can save/restore without an
                  HTTP listener.
    The gossip bytes ride the PacketBridge seam, not this port.
  - Requests are length-prefixed msgpack envelopes
    ``{"seq", "method", "args"}`` answered by ``{"seq", "ok"}`` or a
    typed error — each request is served on its own thread, so
    pipelined blocking queries on one connection proceed concurrently,
    the role yamux streams play in the reference. In-flight requests
    per connection are CAPPED (yamux's stream window): beyond
    ``max_inflight`` the server answers a typed ``busy`` error
    immediately instead of spawning a thread, so a runaway or
    malicious client cannot exhaust server threads.
  - The client keeps one connection, pipelines by seq, reconnects on
    failure, and surfaces typed errors (NotLeader, NoPathToDatacenter)
    as the same exceptions the in-process path raises — so
    agent/pool.py's ServerPool routing policy works unchanged over
    real sockets. Unclassified *remote* errors raise
    :class:`RpcRemoteError` (NOT a ConnectionError), so an
    application bug on a healthy server does not make the pool rotate
    it out as failed; ``busy`` raises :class:`RpcBusyError` (a
    ConnectionError) because routing to a less-loaded server is the
    right response to saturation.

bytes round-trip natively (use_bin_type msgpack), so KV values and
payloads cross the wire intact.
"""

from __future__ import annotations

import socket
import ssl
import struct
import threading
import time
from typing import Any, Callable, Optional

import msgpack

from consul_tpu.server.endpoints import NoPathToDatacenter
from consul_tpu.server.raft import NotLeader
from consul_tpu.utils.telemetry import Sink

# First-byte connection roles, byte values per reference
# agent/pool/conn.go:3-30.
RPC_CONSUL = 0x00
RPC_TLS = 0x03
RPC_SNAPSHOT = 0x05
_MAX_FRAME = 64 << 20
DEFAULT_MAX_INFLIGHT = 64  # yamux default stream window role


class RpcWireError(ConnectionError):
    pass


class RpcBusyError(ConnectionError):
    """Server refused the request: per-connection in-flight cap hit.
    A ConnectionError on purpose — the pool should rotate to a
    less-loaded server, the same way yamux backpressure pushes load
    elsewhere."""


class RpcRemoteError(RuntimeError):
    """The server hit an unclassified error serving the request. NOT a
    ConnectionError: the server is healthy and reachable, so the pool
    must not rotate it out as failed over an application bug."""


def _send_frame(sock: socket.socket, obj: dict, lock: threading.Lock):
    raw = msgpack.packb(obj, use_bin_type=True, default=_default)
    with lock:
        sock.sendall(struct.pack(">I", len(raw)) + raw)


def _default(o):
    if isinstance(o, (set, frozenset, tuple)):
        return list(o)
    raise TypeError(f"unserializable RPC value: {type(o)!r}")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise RpcWireError("connection closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> dict:
    (length,) = struct.unpack(">I", _recv_exact(sock, 4))
    if length > _MAX_FRAME:
        raise RpcWireError(f"oversized RPC frame ({length} bytes)")
    return msgpack.unpackb(_recv_exact(sock, length), raw=False)


# ----------------------------------------------------------------------
# Server side
# ----------------------------------------------------------------------

class _SinkMetricsView:
    """Read-through view of the listener's wire counters living in the
    shared telemetry :class:`Sink` (the listener's old ad-hoc dict,
    preserved as an interface: ``listener.metrics["busy_rejections"]``
    still works, but the numbers now come from — and are visible in —
    the sink under the ``sim.rpc.*`` names)."""

    _KEYS = ("busy_rejections", "peak_inflight", "tls_conns",
             "plain_conns")

    def __init__(self, sink: Sink):
        self._sink = sink

    def __getitem__(self, key: str) -> int:
        if key not in self._KEYS:
            raise KeyError(key)
        if key == "peak_inflight":
            return int(self._sink.gauge_value("sim.rpc.peak_inflight"))
        return int(self._sink.counter_sum(f"sim.rpc.{key}"))

    def __iter__(self):
        return iter(self._KEYS)

    def __len__(self):
        return len(self._KEYS)

    def keys(self):
        return self._KEYS

    def items(self):
        return [(k, self[k]) for k in self._KEYS]

    def __contains__(self, key):
        return key in self._KEYS

    def get(self, key, default=None):
        return self[key] if key in self._KEYS else default

    def __repr__(self):
        return repr(dict(self.items()))

    def __eq__(self, other):
        return dict(self.items()) == other


class RpcListener:
    """One TCP listener demuxing connections by first byte against
    ``rpc_fn(method, **args)`` (a Server.rpc or a leader-routing
    closure). Unknown first bytes are dropped, like the reference's
    demux rejecting unregistered protocol versions.

    ``tls`` (a utils/tls.Configurator) enables the RPC_TLS upgrade
    path; ``require_tls`` additionally refuses plaintext RPC_CONSUL
    (during migration a server accepts both, conn.go RPCTLS +
    pool.go:307-315). Client-certificate verification is the
    Configurator's ``verify_incoming`` knob — require_tls alone
    encrypts but does not authenticate peers; the reference's
    VerifyIncoming is both together (tlsutil/config.go).
    ``snapshot_fn``/``restore_fn`` serve the RPC_SNAPSHOT role.
    ``sink`` is the shared telemetry sink; wire counters
    (``sim.rpc.*``) and per-request timing (``consul.rpc.request`` /
    ``consul.rpc.query`` MeasureSince, reference agent/consul/
    rpc.go:190,220) land there, with :attr:`metrics` kept as a
    read-through view.
    """

    def __init__(self, rpc_fn: Callable[..., Any],
                 host: str = "127.0.0.1", port: int = 0,
                 tls=None, require_tls: bool = False,
                 max_inflight: int = DEFAULT_MAX_INFLIGHT,
                 snapshot_fn: Optional[Callable[[], Any]] = None,
                 restore_fn: Optional[Callable[[Any], Any]] = None,
                 sink: Optional[Sink] = None):
        if require_tls and tls is None:
            raise ValueError("require_tls needs a TLS configurator")
        self.rpc_fn = rpc_fn
        self.tls = tls
        self.require_tls = require_tls
        self.max_inflight = int(max_inflight)
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.sink = sink if sink is not None else Sink()
        self.metrics = _SinkMetricsView(self.sink)
        self._mlock = threading.Lock()
        self._sock = socket.create_server((host, port))
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket, *, inside_tls=False):
        try:
            proto = _recv_exact(conn, 1)[0]
            if proto == RPC_TLS and self.tls is not None and not inside_tls:
                # TLS upgrade: handshake, then the client writes the
                # real role byte inside the channel (pool.go:307-315).
                conn.settimeout(10.0)
                tconn = self.tls.incoming_ctx().wrap_socket(
                    conn, server_side=True)
                tconn.settimeout(None)
                self.sink.incr_counter("sim.rpc.tls_conns")
                self.sink.incr_counter("consul.rpc.accept_conn")
                self._serve_conn(tconn, inside_tls=True)
                return
            if proto == RPC_SNAPSHOT:
                if not inside_tls and self.require_tls:
                    return
                self._serve_snapshot(conn)
                return
            if proto != RPC_CONSUL:
                return  # unknown protocol byte: hang up
            if not inside_tls:
                if self.require_tls:
                    return  # plaintext refused (VerifyIncoming)
                self.sink.incr_counter("sim.rpc.plain_conns")
                self.sink.incr_counter("consul.rpc.accept_conn")
            self._serve_rpc_stream(conn)
        except (RpcWireError, OSError, ssl.SSLError):
            pass
        finally:
            conn.close()

    def _serve_rpc_stream(self, conn: socket.socket):
        wlock = threading.Lock()
        inflight = [0]
        ilock = threading.Lock()
        while not self._stop.is_set():
            req = _recv_frame(conn)
            with ilock:
                admitted = inflight[0] < self.max_inflight
                if admitted:
                    inflight[0] += 1
                    # Read-modify-write max under _mlock: concurrent
                    # connections race on the shared peak gauge.
                    with self._mlock:
                        self.sink.set_gauge("sim.rpc.peak_inflight", max(
                            self.sink.gauge_value("sim.rpc.peak_inflight"),
                            inflight[0]))
            if not admitted:
                # Cap hit: answer busy INLINE, no thread spawned — the
                # yamux stream-window refusal. The send happens OUTSIDE
                # ilock: a client that stops draining its socket blocks
                # this sendall, and workers finishing their requests
                # must still be able to decrement the in-flight count.
                self.sink.incr_counter("sim.rpc.busy_rejections")
                busy = {"seq": req.get("seq", 0), "err_type": "busy",
                        "err": f"server busy: >{self.max_inflight} "
                               "in-flight requests on connection"}
                try:
                    _send_frame(conn, busy, wlock)
                except (OSError, RpcWireError):
                    return
                continue
            threading.Thread(
                target=self._serve_one,
                args=(conn, wlock, req, inflight, ilock),
                daemon=True,
            ).start()

    def _serve_one(self, conn, wlock, req, inflight, ilock):
        seq = req.get("seq", 0)
        t0 = time.perf_counter()
        args = req.get("args", {})
        try:
            out = self.rpc_fn(req["method"], **args)
            resp = {"seq": seq, "ok": out}
        except NotLeader as e:
            resp = {"seq": seq, "err_type": "not_leader",
                    "leader": e.leader_hint}
        except NoPathToDatacenter as e:
            resp = {"seq": seq, "err_type": "no_path", "dc": e.dc,
                    "err": str(e)}
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            # Application-level errors stay typed across the wire so a
            # client agent's HTTP tier maps them to 400s exactly like
            # server mode (and the pool does NOT rotate on them).
            resp = {"seq": seq, "err_type": "app",
                    "app_class": type(e).__name__, "err": str(e)[:500]}
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            resp = {"seq": seq, "err": repr(e)[:500]}
        finally:
            with ilock:
                inflight[0] -= 1
            # Per-request service time under the reference's names
            # (rpc.go MeasureSince): every request samples
            # consul.rpc.request; blocking queries (a min_index arg —
            # agent/structs QueryOptions.MinQueryIndex) additionally
            # sample consul.rpc.query.
            self.sink.measure_since("consul.rpc.request", t0)
            if "min_index" in args:
                self.sink.measure_since("consul.rpc.query", t0)
        try:
            _send_frame(conn, resp, wlock)
        except (OSError, RpcWireError):
            pass  # client went away mid-call

    def _serve_snapshot(self, conn: socket.socket):
        """One-shot snapshot exchange (rpc.go:196 RPCSnapshot): a
        single ``{"op": "save"}`` or ``{"op": "restore", "data": snap}``
        frame, one reply, hang up."""
        wlock = threading.Lock()
        req = _recv_frame(conn)
        op = req.get("op")
        try:
            if op == "save":
                if self.snapshot_fn is None:
                    raise ValueError("snapshot not served on this listener")
                resp = {"ok": self.snapshot_fn()}
            elif op == "restore":
                if self.restore_fn is None:
                    raise ValueError("restore not served on this listener")
                self.restore_fn(req.get("data"))
                resp = {"ok": True}
            else:
                raise ValueError(f"unknown snapshot op {op!r}")
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            resp = {"err": repr(e)[:500]}
        try:
            _send_frame(conn, resp, wlock)
        except (OSError, RpcWireError):
            pass

    def close(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


# ----------------------------------------------------------------------
# Client side
# ----------------------------------------------------------------------

def _dial(addr, tls, role: int) -> socket.socket:
    """Dial the RPC port in the given role, upgrading to TLS first
    when a configurator is supplied (write RPC_TLS plaintext →
    handshake → write the real role inside, pool.go:307-315)."""
    sock = socket.create_connection(addr, timeout=10.0)
    try:
        if tls is not None:
            sock.sendall(bytes([RPC_TLS]))
            ctx = tls.outgoing_ctx() if hasattr(tls, "outgoing_ctx") else tls
            sock = ctx.wrap_socket(sock, server_hostname=addr[0])
        sock.sendall(bytes([role]))
        sock.settimeout(None)
        return sock
    except (OSError, ssl.SSLError):
        sock.close()
        raise


class RpcClient:
    """One pooled connection to a server's RPC port: pipelined seq-
    matched calls, lazy connect, reconnect-on-failure. The per-server
    callable shape (``call(method, **args)``) matches what
    agent/pool.ServerPool expects, so the reference's routing policy
    (shuffle, rotate-past-failure, rebalance) composes directly.
    ``tls`` (utils/tls.Configurator or SSLContext) turns on the
    RPC_TLS upgrade for every connection."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0,
                 tls=None):
        self.addr = (host, int(port))
        self.timeout_s = timeout_s
        self.tls = tls
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending: dict[int, dict] = {}
        self._seq = 0

    def _connect(self):
        with self._state_lock:
            if self._sock is not None:
                return
            try:
                sock = _dial(self.addr, self.tls, RPC_CONSUL)
            except ssl.SSLError as e:
                raise RpcWireError(f"TLS handshake failed: {e}") from e
            except OSError as e:
                # TimeoutError and friends are OSError but NOT
                # ConnectionError — normalize so the pool's
                # rotate-past-failure policy sees a blackholed server
                # the same as a refused one.
                raise RpcWireError(f"dial failed: {e}") from e
            self._sock = sock
            threading.Thread(target=self._read_loop, args=(sock,),
                             daemon=True).start()

    def _read_loop(self, sock):
        try:
            while True:
                resp = _recv_frame(sock)
                with self._state_lock:
                    slot = self._pending.get(resp.get("seq"))
                if slot is not None:
                    slot["resp"] = resp
                    slot["done"].set()
        except (RpcWireError, OSError, ssl.SSLError):
            with self._state_lock:
                if self._sock is sock:
                    self._sock = None
                pending, self._pending = self._pending, {}
            for slot in pending.values():
                slot["resp"] = None  # connection died under the call
                slot["done"].set()

    def call(self, method: str, **args) -> Any:
        self._connect()
        with self._state_lock:
            self._seq += 1
            seq = self._seq
            slot = {"done": threading.Event(), "resp": None}
            self._pending[seq] = slot
            sock = self._sock
        try:
            _send_frame(sock, {"seq": seq, "method": method, "args": args},
                        self._wlock)
        except (OSError, AttributeError) as e:
            with self._state_lock:
                self._pending.pop(seq, None)
                self._sock = None
            raise RpcWireError(f"send failed: {e}") from e
        # Blocking queries legitimately park server-side for their
        # requested wait; the wire timeout must outlast it or a long
        # ?wait= long-poll would read as a dead server.
        timeout = max(self.timeout_s, float(args.get("wait_s", 0)) + 15.0)
        if not slot["done"].wait(timeout):
            with self._state_lock:
                self._pending.pop(seq, None)
            raise RpcWireError(f"RPC {method} timed out")
        resp = slot["resp"]
        if resp is None:
            raise RpcWireError("connection lost mid-call")
        if "ok" in resp:
            return resp["ok"]
        if resp.get("err_type") == "not_leader":
            raise NotLeader(resp.get("leader"))
        if resp.get("err_type") == "no_path":
            raise NoPathToDatacenter(resp.get("dc", "?"))
        if resp.get("err_type") == "busy":
            raise RpcBusyError(resp.get("err", "server busy"))
        if resp.get("err_type") == "app":
            cls = {"ValueError": ValueError, "KeyError": KeyError,
                   "TypeError": TypeError,
                   "AttributeError": AttributeError}.get(
                resp.get("app_class", ""), ValueError)
            raise cls(resp.get("err", "remote application error"))
        raise RpcRemoteError(resp.get("err", "unknown RPC error"))

    def close(self):
        with self._state_lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


# ----------------------------------------------------------------------
# Snapshot role client (one-shot per connection, snapshot/snapshot.go)
# ----------------------------------------------------------------------

def snapshot_save(host: str, port: int, tls=None) -> Any:
    """Pull the server's state snapshot over the RPC port."""
    return _snapshot_exchange((host, int(port)), tls, {"op": "save"})


def snapshot_restore(host: str, port: int, snap: Any, tls=None) -> bool:
    """Push a snapshot to the server over the RPC port."""
    return _snapshot_exchange((host, int(port)), tls,
                              {"op": "restore", "data": snap})


def _snapshot_exchange(addr, tls, req: dict) -> Any:
    try:
        sock = _dial(addr, tls, RPC_SNAPSHOT)
    except ssl.SSLError as e:
        raise RpcWireError(f"TLS handshake failed: {e}") from e
    except OSError as e:
        raise RpcWireError(f"dial failed: {e}") from e
    try:
        _send_frame(sock, req, threading.Lock())
        resp = _recv_frame(sock)
    finally:
        sock.close()
    if "ok" in resp:
        return resp["ok"]
    raise RpcRemoteError(resp.get("err", "snapshot RPC failed"))
