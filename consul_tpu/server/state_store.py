"""Indexed state store with watch-based change notification.

The reference's state store is go-memdb — immutable radix trees with
per-table modify indexes and watch channels that fire on commit,
feeding the blocking-query engine (reference agent/consul/state/,
``blockingQuery`` agent/consul/rpc.go:457-539). The Python equivalent
keeps the same *contract* — every entry carries ``(create_index,
modify_index)``, every read returns the table's max index, and blocked
readers wake exactly when a write commits to a table they watched —
implemented with one lock + per-table ``threading.Condition``.

Tables (reference agent/consul/state/catalog.go, kvs.go, session.go,
coordinate.go:13-48, config_entry.go): nodes, services, checks, kv,
sessions, coordinates, config_entries.

Writes normally arrive through the FSM (raft-applied, see fsm.py);
direct calls are for single-server/dev mode, mirroring how dev agents
run an in-memory raft (reference agent/consul/server.go:177).
"""

from __future__ import annotations

import contextlib
import dataclasses
import fnmatch
import threading
import time
from typing import Any, Callable, Iterable, Optional


@dataclasses.dataclass
class Entry:
    value: Any
    create_index: int
    modify_index: int


class Table:
    """One indexed table: key -> Entry + the table's max modify index."""

    def __init__(self, name: str):
        self.name = name
        self.rows: dict[str, Entry] = {}
        self.max_index = 0

    # All mutation goes through the store (which holds the lock,
    # assigns the global index, and notifies the store-wide condition —
    # coarser than memdb's per-radix-node watch channels but the same
    # contract: a watcher re-checks its tables' indexes on wake).


class StateStore:
    """All replicated tables behind one global modify index.

    The reference uses a single raft index across all tables; reads
    return it so ``?index=`` blocking works uniformly
    (reference agent/consul/state/state_store.go).
    """

    TABLES = (
        "nodes",          # node name -> {id, address, meta, ...}
        "services",       # node/service_id -> {service, port, tags, meta}
        "checks",         # node/check_id -> {status, output, service_id}
        "kv",             # key -> {value, flags, session}
        "sessions",       # session id -> {node, ttl, behavior, checks}
        "coordinates",    # node[:segment] -> coordinate dict
        "config_entries",  # kind/name -> entry
        "autopilot",      # "config" -> operator autopilot configuration
        "prepared_queries",  # query id -> definition
        "acl_tokens",     # accessor id -> token (carries secret id)
        "acl_policies",   # policy name -> {id, rules, description}
        "acl_meta",       # "bootstrap" -> one-shot marker
        "intentions",     # intention id -> {source, destination, action}
        "connect_ca",     # "config" + "root:<id>" -> CA material
    )

    def __init__(self):
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self.index = 0
        self.tables = {name: Table(name) for name in self.TABLES}
        # Lock-delay windows (reference state.lockDelay): key -> wall
        # expiry. Server-local soft state, consulted leader-side at
        # acquire time; never replicated or snapshotted.
        self._lock_delays: dict[str, float] = {}

    @contextlib.contextmanager
    def transaction(self):
        """Hold the store lock across a multi-op batch.

        Every read path acquires the same lock, so a concurrent reader
        (including a blocking query re-run) can never observe a
        half-applied — possibly later rolled-back — batch, and never a
        non-monotonic index: the visibility contract of the reference's
        single-commit memdb transaction (reference
        agent/consul/state/state_store.go Txn.Commit; blocked readers in
        ``blocking_query`` sit in ``Condition.wait``, which releases the
        underlying lock, so holding it here cannot deadlock them).
        """
        with self._lock:
            yield

    # ------------------------------------------------------------------
    # Core commit path
    # ------------------------------------------------------------------
    def _commit(self, table: str, key: str, value: Any, *, delete: bool = False,
                index: Optional[int] = None) -> int:
        """Apply one write under the lock; bump indexes; wake watchers.

        ``index`` lets the FSM impose the raft log index so replicas
        converge on identical indexes (reference fsm.go applies with the
        raft index; state.go maxIndex bookkeeping).
        """
        with self._lock:
            if index is None:
                self.index += 1
                index = self.index
            else:
                self.index = max(self.index, index)
            t = self.tables[table]
            if delete:
                if key in t.rows:
                    del t.rows[key]
                    t.max_index = index
                    self._cond.notify_all()
            else:
                old = t.rows.get(key)
                create = old.create_index if old else index
                t.rows[key] = Entry(value, create, index)
                t.max_index = index
                self._cond.notify_all()
            return index

    def _bump(self, table: str, index: Optional[int] = None) -> int:
        """Record a table-level change with no row mutation (e.g. a
        batch already applied row-by-row under one raft index)."""
        with self._lock:
            if index is None:
                self.index += 1
                index = self.index
            else:
                self.index = max(self.index, index)
            t = self.tables[table]
            t.max_index = max(t.max_index, index)
            self._cond.notify_all()
            return index

    # ------------------------------------------------------------------
    # Blocking reads (the blockingQuery engine, rpc.go:457-539)
    # ------------------------------------------------------------------
    def blocking_query(
        self,
        tables: Iterable[str],
        min_index: int,
        fn: Callable[[], Any],
        timeout_s: float = 10.0,
    ) -> tuple[int, Any]:
        """Run ``fn`` under the lock; if the watched tables' max index is
        still <= min_index, block until a commit touches one of them (or
        the timeout elapses), then re-run — the long-poll contract of
        ``?index=&wait=`` (reference agent/consul/rpc.go:457-539).
        """
        deadline = time.monotonic() + timeout_s
        names = list(tables)
        with self._lock:
            while True:
                idx = max(self.tables[nm].max_index for nm in names)
                if min_index <= 0 or idx > min_index:
                    return max(idx, 1), fn()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return max(idx, 1), fn()
                self._cond.wait(remaining)

    # ------------------------------------------------------------------
    # Catalog (reference agent/consul/state/catalog.go)
    # ------------------------------------------------------------------
    def ensure_node(self, node: str, address: str, meta: Optional[dict] = None,
                    index: Optional[int] = None) -> int:
        return self._commit(
            "nodes", node, {"node": node, "address": address, "meta": meta or {}},
            index=index,
        )

    def delete_node(self, node: str, index: Optional[int] = None) -> int:
        # Cascading deletes mirror state/catalog.go deleteNodeTxn:
        # services, checks, coordinates, and session invalidation.
        with self._lock:
            idx = self._commit("nodes", node, None, delete=True, index=index)
            for svc_key in [k for k in self.tables["services"].rows
                            if k.split("/", 1)[0] == node]:
                self._commit("services", svc_key, None, delete=True, index=idx)
            for chk_key in [k for k in self.tables["checks"].rows
                            if k.split("/", 1)[0] == node]:
                self._commit("checks", chk_key, None, delete=True, index=idx)
            for coord_key in [k for k in self.tables["coordinates"].rows
                              if k.split(":", 1)[0] == node]:
                self._commit("coordinates", coord_key, None, delete=True, index=idx)
            self._invalidate_sessions_for_node(node, idx)
            return idx

    def nodes(self) -> list[dict]:
        with self._lock:
            return [e.value | {"modify_index": e.modify_index}
                    for e in self.tables["nodes"].rows.values()]

    def get_node(self, node: str) -> Optional[dict]:
        with self._lock:
            e = self.tables["nodes"].rows.get(node)
            return None if e is None else e.value | {"modify_index": e.modify_index}

    def ensure_service(self, node: str, service_id: str, service: str,
                       port: int = 0, tags: Optional[list] = None,
                       meta: Optional[dict] = None,
                       index: Optional[int] = None) -> int:
        if self.get_node(node) is None:
            raise KeyError(f"node {node!r} not registered")
        return self._commit(
            "services", f"{node}/{service_id}",
            {"node": node, "id": service_id, "service": service, "port": port,
             "tags": tags or [], "meta": meta or {}},
            index=index,
        )

    def delete_service(self, node: str, service_id: str,
                       index: Optional[int] = None) -> int:
        with self._lock:
            idx = self._commit("services", f"{node}/{service_id}", None,
                               delete=True, index=index)
            for chk_key, e in list(self.tables["checks"].rows.items()):
                if e.value.get("service_id") == service_id and \
                        chk_key.split("/", 1)[0] == node:
                    self._commit("checks", chk_key, None, delete=True, index=idx)
            return idx

    def services(self) -> dict[str, list[str]]:
        """service name -> union of tags (reference catalog /v1/catalog/services)."""
        with self._lock:
            out: dict[str, set] = {}
            for e in self.tables["services"].rows.values():
                out.setdefault(e.value["service"], set()).update(e.value["tags"])
            return {k: sorted(v) for k, v in out.items()}

    def service_nodes(self, service: str, tag: Optional[str] = None) -> list[dict]:
        with self._lock:
            rows = []
            for e in self.tables["services"].rows.values():
                if e.value["service"] != service:
                    continue
                if tag is not None and tag not in e.value["tags"]:
                    continue
                node = self.get_node(e.value["node"]) or {}
                rows.append(e.value | {"address": node.get("address"),
                                       "modify_index": e.modify_index})
            return rows

    def node_services(self, node: str) -> list[dict]:
        with self._lock:
            return [e.value for e in self.tables["services"].rows.values()
                    if e.value["node"] == node]

    # ------------------------------------------------------------------
    # Health checks (reference agent/consul/state/catalog.go checks)
    # ------------------------------------------------------------------
    def ensure_check(self, node: str, check_id: str, status: str,
                     service_id: str = "", output: str = "",
                     index: Optional[int] = None) -> int:
        if status not in ("passing", "warning", "critical"):
            raise ValueError(f"bad check status {status!r}")
        # Resolve the service NAME too: /v1/health/checks/:service
        # filters by name (reference health_endpoint.go ServiceChecks),
        # while registrations carry only the id.
        with self._lock:
            svc = self.tables["services"].rows.get(f"{node}/{service_id}")
            service_name = svc.value["service"] if svc else ""
            return self._commit(
                "checks", f"{node}/{check_id}",
                {"node": node, "check_id": check_id, "status": status,
                 "service_id": service_id, "service_name": service_name,
                 "output": output},
                index=index,
            )

    def delete_check(self, node: str, check_id: str,
                     index: Optional[int] = None) -> int:
        with self._lock:
            idx = self._commit("checks", f"{node}/{check_id}", None,
                               delete=True, index=index)
            self._invalidate_sessions_on_check(node, check_id, idx)
            return idx

    def checks(self, node: Optional[str] = None, service: Optional[str] = None,
               state: Optional[str] = None) -> list[dict]:
        with self._lock:
            out = []
            for e in self.tables["checks"].rows.values():
                v = e.value
                if node is not None and v["node"] != node:
                    continue
                if service is not None and v["service_id"] != service \
                        and v.get("service_name") != service:
                    continue
                if state is not None and state != "any" and v["status"] != state:
                    continue
                out.append(v | {"modify_index": e.modify_index})
            return out

    def node_health(self, node: str) -> str:
        """Worst check status for the node ('passing' if no checks)."""
        order = {"passing": 0, "warning": 1, "critical": 2}
        worst = "passing"
        for c in self.checks(node=node):
            if order[c["status"]] > order[worst]:
                worst = c["status"]
        return worst

    # ------------------------------------------------------------------
    # KV (reference agent/consul/state/kvs.go)
    # ------------------------------------------------------------------
    def kv_set(self, key: str, value: bytes, flags: int = 0,
               cas_index: Optional[int] = None,
               session: Optional[str] = None,
               index: Optional[int] = None) -> tuple[int, bool]:
        """Set (optionally check-and-set / lock-acquire). Returns
        (index, success) — CAS failure does not bump the index, like the
        reference's SetCAS (state/kvs.go)."""
        with self._lock:
            e = self.tables["kv"].rows.get(key)
            if cas_index is not None:
                cur = e.modify_index if e else 0
                if cur != cas_index:
                    return self.index, False
            if session is not None:
                if session not in self.tables["sessions"].rows:
                    return self.index, False
                if e and e.value.get("session") not in (None, session):
                    return self.index, False  # lock held by someone else
            val = {"value": value, "flags": flags,
                   "session": session if session else
                   (e.value.get("session") if e else None)}
            return self._commit("kv", key, val, index=index), True

    def kv_unlock(self, key: str, session: str,
                  index: Optional[int] = None) -> tuple[int, bool]:
        """Release a lock held by ``session`` (reference KVUnlock verb,
        state/kvs.go kvsUnlockTxn: fails unless that session holds it)."""
        with self._lock:
            e = self.tables["kv"].rows.get(key)
            if session is None or e is None or \
                    e.value.get("session") != session:
                return self.index, False
            return (
                self._commit("kv", key, e.value | {"session": None},
                             index=index),
                True,
            )

    def kv_lock_delayed(self, key: str) -> bool:
        """Is ``key`` inside a post-invalidation lock-delay window?
        (reference state/kvs.go KVSLockDelay). Expired windows are
        dropped on the way through."""
        with self._lock:
            exp = self._lock_delays.get(key)
            if exp is None:
                return False
            if time.time() >= exp:
                del self._lock_delays[key]
                return False
            return True

    def kv_get(self, key: str) -> Optional[dict]:
        with self._lock:
            e = self.tables["kv"].rows.get(key)
            if e is None:
                return None
            return e.value | {"key": key, "create_index": e.create_index,
                              "modify_index": e.modify_index}

    def kv_list(self, prefix: str = "") -> list[dict]:
        with self._lock:
            return [e.value | {"key": k, "modify_index": e.modify_index}
                    for k, e in sorted(self.tables["kv"].rows.items())
                    if k.startswith(prefix)]

    def kv_delete(self, key: str, recurse: bool = False,
                  cas_index: Optional[int] = None,
                  index: Optional[int] = None) -> tuple[int, bool]:
        with self._lock:
            if cas_index is not None:
                e = self.tables["kv"].rows.get(key)
                cur = e.modify_index if e else 0
                if cur != cas_index:
                    return self.index, False
            keys = ([k for k in self.tables["kv"].rows if k.startswith(key)]
                    if recurse else [key])
            idx = self.index
            for k in keys:
                idx = self._commit("kv", k, None, delete=True, index=index)
            return idx, True

    # ------------------------------------------------------------------
    # Sessions (reference agent/consul/state/session.go)
    # ------------------------------------------------------------------
    def session_create(self, session_id: str, node: str, ttl_s: float = 0.0,
                       behavior: str = "release",
                       checks: Optional[list[str]] = None,
                       lock_delay_s: float = 15.0,
                       index: Optional[int] = None) -> int:
        if self.get_node(node) is None:
            raise KeyError(f"node {node!r} not registered")
        return self._commit(
            "sessions", session_id,
            {"id": session_id, "node": node, "ttl_s": ttl_s,
             "behavior": behavior, "checks": checks or [],
             "lock_delay_s": lock_delay_s},
            index=index,
        )

    def session_get(self, session_id: str) -> Optional[dict]:
        with self._lock:
            e = self.tables["sessions"].rows.get(session_id)
            return None if e is None else e.value

    def session_list(self) -> list[dict]:
        with self._lock:
            return [e.value for e in self.tables["sessions"].rows.values()]

    def session_destroy(self, session_id: str,
                        index: Optional[int] = None) -> int:
        """Destroy a session, applying its behavior to held locks
        (release or delete, reference state/session.go invalidation).

        Each released key enters a LOCK-DELAY window (session.go:322-370
        + kvs_endpoint.go:73-78): re-acquisition is refused until
        ``lock_delay_s`` after the invalidation — the reference's
        split-brain guard, so a deposed holder that still thinks it owns
        the lock has time to notice before a new holder acts. Like the
        reference's ``lockDelay`` map this is SERVER-LOCAL soft state
        (wall clock, not raft-replicated, not snapshotted): only the
        leader consults it, at acquire time."""
        with self._lock:
            e = self.tables["sessions"].rows.get(session_id)
            behavior = e.value.get("behavior", "release") if e else "release"
            delay = min(float((e.value.get("lock_delay_s", 15.0)
                               if e else 0.0) or 0.0), 60.0)
            idx = self._commit("sessions", session_id, None, delete=True,
                               index=index)
            now = time.time()
            for k, kv in list(self.tables["kv"].rows.items()):
                if kv.value.get("session") == session_id:
                    if delay > 0:
                        self._lock_delays[k] = now + delay
                    if behavior == "delete":
                        self._commit("kv", k, None, delete=True, index=idx)
                    else:
                        self._commit("kv", k, kv.value | {"session": None},
                                     index=idx)
            self._invalidate_queries_for_session(session_id, idx)
            return idx

    # ------------------------------------------------------------------
    # Prepared queries (reference state/prepared_query.go)
    # ------------------------------------------------------------------
    def pq_set(self, query: dict, index: Optional[int] = None) -> int:
        """Upsert one prepared query by id. Name uniqueness is enforced
        here (reference state/prepared_query.go PreparedQuerySet: the
        wrapped name index) so a replicated create can never land two
        queries on one name."""
        with self._lock:
            name = query.get("name", "")
            if name:
                for qid, e in self.tables["prepared_queries"].rows.items():
                    if qid != query["id"] and e.value.get("name") == name:
                        raise ValueError(
                            f"prepared query name {name!r} already in use")
            return self._commit("prepared_queries", query["id"], query,
                                index=index)

    def pq_delete(self, query_id: str, index: Optional[int] = None) -> int:
        return self._commit("prepared_queries", query_id, None, delete=True,
                            index=index)

    def pq_get(self, query_id: str) -> Optional[dict]:
        with self._lock:
            e = self.tables["prepared_queries"].rows.get(query_id)
            return None if e is None else e.value

    def pq_list(self) -> list[dict]:
        with self._lock:
            return [e.value for _, e in
                    sorted(self.tables["prepared_queries"].rows.items())]

    # ------------------------------------------------------------------
    # ACL tokens + policies (reference state/acl.go)
    # ------------------------------------------------------------------
    def acl_token_set(self, token: dict, index: Optional[int] = None) -> int:
        return self._commit("acl_tokens", token["accessor_id"], token,
                            index=index)

    def acl_token_delete(self, accessor_id: str,
                         index: Optional[int] = None) -> int:
        return self._commit("acl_tokens", accessor_id, None, delete=True,
                            index=index)

    def acl_token_get(self, accessor_id: str) -> Optional[dict]:
        with self._lock:
            e = self.tables["acl_tokens"].rows.get(accessor_id)
            return None if e is None else e.value

    def acl_token_by_secret(self, secret_id: str) -> Optional[dict]:
        """Resolve a presented secret (reference state/acl.go
        ACLTokenGetBySecret — an indexed lookup there; a scan here,
        fine at control-plane token counts)."""
        with self._lock:
            for e in self.tables["acl_tokens"].rows.values():
                if e.value.get("secret_id") == secret_id:
                    return e.value
            return None

    def acl_token_list(self) -> list[dict]:
        with self._lock:
            return [e.value for _, e in
                    sorted(self.tables["acl_tokens"].rows.items())]

    def acl_policy_set(self, policy: dict,
                       index: Optional[int] = None) -> int:
        return self._commit("acl_policies", policy["name"], policy,
                            index=index)

    def acl_policy_delete(self, name: str,
                          index: Optional[int] = None) -> int:
        return self._commit("acl_policies", name, None, delete=True,
                            index=index)

    def acl_policy_get(self, name: str) -> Optional[dict]:
        with self._lock:
            e = self.tables["acl_policies"].rows.get(name)
            return None if e is None else e.value

    def acl_policy_list(self) -> list[dict]:
        with self._lock:
            return [e.value for _, e in
                    sorted(self.tables["acl_policies"].rows.items())]

    def acl_bootstrapped(self) -> bool:
        with self._lock:
            return "bootstrap" in self.tables["acl_meta"].rows

    def acl_mark_bootstrapped(self, index: Optional[int] = None) -> int:
        return self._commit("acl_meta", "bootstrap", {"done": True},
                            index=index)

    # ------------------------------------------------------------------
    # Connect CA (reference state/connect_ca.go)
    # ------------------------------------------------------------------
    def ca_set_root(self, root: dict, activate: bool = True,
                    index: Optional[int] = None) -> int:
        """Store a root; activating it deactivates every other root
        (reference CARootSetCAS keeps old roots inactive for trust-
        bundle continuity)."""
        with self._lock:
            idx = index
            if activate:
                for k, e in list(self.tables["connect_ca"].rows.items()):
                    if k.startswith("root:") and e.value.get("active"):
                        idx = self._commit(
                            "connect_ca", k,
                            e.value | {"active": False}, index=idx)
            return self._commit("connect_ca", f"root:{root['id']}",
                                dict(root, active=activate), index=idx)

    def ca_roots(self) -> list[dict]:
        with self._lock:
            return [e.value for k, e in
                    sorted(self.tables["connect_ca"].rows.items())
                    if k.startswith("root:")]

    def ca_active_root(self) -> Optional[dict]:
        with self._lock:
            for k, e in self.tables["connect_ca"].rows.items():
                if k.startswith("root:") and e.value.get("active"):
                    return e.value
            return None

    def ca_config_set(self, config: dict,
                      index: Optional[int] = None) -> int:
        return self._commit("connect_ca", "config", config, index=index)

    def ca_config_get(self) -> Optional[dict]:
        with self._lock:
            e = self.tables["connect_ca"].rows.get("config")
            return None if e is None else e.value

    # ------------------------------------------------------------------
    # Intentions (reference state/intention.go)
    # ------------------------------------------------------------------
    def intention_set(self, ixn: dict, index: Optional[int] = None) -> int:
        """Upsert by id; the (source, destination) pair is unique
        (reference state/intention.go IntentionSet: the source/
        destination index) — enforced here so replicated creates
        cannot double up."""
        with self._lock:
            for iid, e in self.tables["intentions"].rows.items():
                if iid != ixn["id"] and \
                        e.value["source"] == ixn["source"] and \
                        e.value["destination"] == ixn["destination"]:
                    raise ValueError(
                        f"duplicate intention "
                        f"{ixn['source']!r} -> {ixn['destination']!r}")
            return self._commit("intentions", ixn["id"], ixn, index=index)

    def intention_delete(self, intention_id: str,
                         index: Optional[int] = None) -> int:
        return self._commit("intentions", intention_id, None, delete=True,
                            index=index)

    def intention_get(self, intention_id: str) -> Optional[dict]:
        with self._lock:
            e = self.tables["intentions"].rows.get(intention_id)
            return None if e is None else e.value

    def intention_list(self) -> list[dict]:
        """All intentions, highest precedence first (reference
        structs.Intentions sort order)."""
        with self._lock:
            rows = [e.value for e in self.tables["intentions"].rows.values()]
            return sorted(rows, key=lambda x: (-x["precedence"],
                                               x["destination"], x["source"]))

    def _invalidate_queries_for_session(self, session_id: str, index: int):
        """A query tied to a session dies with it (reference
        state/prepared_query.go: the session invalidation path deletes
        bound queries, mirroring KV lock release)."""
        for qid, e in list(self.tables["prepared_queries"].rows.items()):
            if e.value.get("session") == session_id:
                self._commit("prepared_queries", qid, None, delete=True,
                             index=index)

    def _invalidate_sessions_for_node(self, node: str, index: int):
        for sid, e in list(self.tables["sessions"].rows.items()):
            if e.value["node"] == node:
                self.session_destroy(sid, index=index)

    def _invalidate_sessions_on_check(self, node: str, check_id: str, index: int):
        for sid, e in list(self.tables["sessions"].rows.items()):
            if e.value["node"] == node and check_id in e.value.get("checks", []):
                self.session_destroy(sid, index=index)

    # ------------------------------------------------------------------
    # Coordinates (reference agent/consul/state/coordinate.go:13-172)
    # ------------------------------------------------------------------
    def coordinate_batch_update(self, updates: list[dict],
                                index: Optional[int] = None) -> int:
        """Apply a batch of coordinate updates in one index. Unknown
        nodes are silently skipped, exactly like the reference
        (state/coordinate.go:152-158 — inconsistency with the catalog is
        expected during anti-entropy convergence)."""
        with self._lock:
            applied = False
            idx = index
            for u in updates:
                if u["node"] not in self.tables["nodes"].rows:
                    continue
                key = u["node"] + (":" + u["segment"] if u.get("segment") else "")
                idx = self._commit("coordinates", key,
                                   {"node": u["node"],
                                    "segment": u.get("segment", ""),
                                    "coord": u["coord"]},
                                   index=idx if index is not None else None)
                applied = True
            if not applied:
                # Still consume/record the raft index.
                idx = self._bump("coordinates", index)
            return idx if idx is not None else self.index

    def coordinates(self) -> list[dict]:
        with self._lock:
            return [e.value for _, e in
                    sorted(self.tables["coordinates"].rows.items())]

    def coordinate_for(self, node: str, segment: str = "") -> Optional[dict]:
        with self._lock:
            key = node + (":" + segment if segment else "")
            e = self.tables["coordinates"].rows.get(key)
            return None if e is None else e.value

    # ------------------------------------------------------------------
    # Config entries (reference state/config_entry.go)
    # ------------------------------------------------------------------
    def config_set(self, kind: str, name: str, entry: dict,
                   cas_index: Optional[int] = None,
                   index: Optional[int] = None) -> tuple[int, bool]:
        """Upsert, optionally check-and-set on the entry's modify index
        (reference EnsureConfigEntryCAS, state/config_entry.go; 0 =
        only-if-absent). CAS failure does not bump the index."""
        with self._lock:
            if cas_index is not None:
                e = self.tables["config_entries"].rows.get(f"{kind}/{name}")
                if (e.modify_index if e else 0) != cas_index:
                    return self.index, False
            return self._commit("config_entries", f"{kind}/{name}", entry,
                                index=index), True

    def config_delete(self, kind: str, name: str,
                      cas_index: Optional[int] = None,
                      index: Optional[int] = None) -> tuple[int, bool]:
        with self._lock:
            if cas_index is not None:
                e = self.tables["config_entries"].rows.get(f"{kind}/{name}")
                if (e.modify_index if e else 0) != cas_index:
                    return self.index, False
            return self._commit("config_entries", f"{kind}/{name}", None,
                                delete=True, index=index), True

    def autopilot_set(self, config: dict, cas_index: Optional[int] = None,
                      index: Optional[int] = None) -> tuple[int, bool]:
        """Operator autopilot configuration (reference
        state/autopilot.go AutopilotCASConfig: CAS on the modify
        index, 0 = only-if-absent)."""
        with self._lock:
            if cas_index is not None:
                e = self.tables["autopilot"].rows.get("config")
                if (e.modify_index if e else 0) != cas_index:
                    return self.index, False
            return self._commit("autopilot", "config", config,
                                index=index), True

    def autopilot_get(self) -> Optional[dict]:
        with self._lock:
            e = self.tables["autopilot"].rows.get("config")
            if e is None:
                return None
            return dict(e.value, modify_index=e.modify_index)

    def config_get(self, kind: str, name: str) -> Optional[dict]:
        with self._lock:
            e = self.tables["config_entries"].rows.get(f"{kind}/{name}")
            return None if e is None else e.value

    def config_get_meta(self, kind: str, name: str) -> Optional[dict]:
        """Entry plus its raft indexes — what the ConfigEntry endpoints
        return so clients can CAS (reference structs RaftIndex)."""
        with self._lock:
            e = self.tables["config_entries"].rows.get(f"{kind}/{name}")
            if e is None:
                return None
            return {"kind": kind, "name": name, "entry": e.value,
                    "create_index": e.create_index,
                    "modify_index": e.modify_index}

    def config_list(self, kind: str = "*") -> list[tuple[str, dict]]:
        with self._lock:
            return [(k, e.value) for k, e in
                    sorted(self.tables["config_entries"].rows.items())
                    if fnmatch.fnmatch(k.split("/", 1)[0], kind)]

    def config_list_meta(self, kind: str = "*") -> list[dict]:
        with self._lock:
            return [
                {"kind": k.split("/", 1)[0], "name": k.split("/", 1)[1],
                 "entry": e.value, "create_index": e.create_index,
                 "modify_index": e.modify_index}
                for k, e in
                sorted(self.tables["config_entries"].rows.items())
                if fnmatch.fnmatch(k.split("/", 1)[0], kind)
            ]

    # ------------------------------------------------------------------
    # Snapshot / restore (reference fsm/snapshot*.go persists every
    # table including coordinates)
    # ------------------------------------------------------------------
    def snapshot(self, tables: Optional[Iterable[str]] = None) -> dict:
        """Deep-copy the named tables (all by default). A subset makes a
        cheap undo log for transactions that touch few tables.

        ``table_indexes`` records each table's max_index at snapshot
        time: a deletion leaves no surviving row carrying the index, so
        recomputing from rows on restore would regress the visibility
        index (long-pollers would see X-Consul-Index go backwards)."""
        names = list(tables) if tables is not None else list(self.TABLES)
        with self._lock:
            snap = {
                "index": self.index,
                "table_indexes": {
                    name: self.tables[name].max_index for name in names
                },
                "tables": {
                    name: {k: dataclasses.asdict(e)
                           for k, e in self.tables[name].rows.items()}
                    for name in names
                },
            }
            if "sessions" in names:
                # Session mutations write lock-delay soft state; a TXN
                # undo snapshot must roll those side effects back too
                # (an aborted batch must not leave phantom windows).
                snap["lock_delays"] = dict(self._lock_delays)
            return snap

    def restore(self, snap: dict) -> None:
        """Restore the tables present in the snapshot (others are left
        untouched, supporting partial undo)."""
        with self._lock:
            self.index = snap["index"]
            if "lock_delays" in snap:
                self._lock_delays = dict(snap["lock_delays"])
            recorded = snap.get("table_indexes", {})
            for name, rows in snap["tables"].items():
                t = self.tables[name]
                t.rows = {k: Entry(**e) for k, e in rows.items()}
                t.max_index = recorded.get(name) if name in recorded else max(
                    [e.modify_index for e in t.rows.values()], default=0
                )
            self._cond.notify_all()
