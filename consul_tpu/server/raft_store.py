"""Durable raft storage: term/vote, log, and compaction snapshot on disk.

The reference wires a BoltDB-backed LogStore/StableStore into raft
(reference vendor/github.com/hashicorp/raft-boltdb/bolt_store.go:1-305,
mounted at agent/consul/server.go:558-600) so consensus state survives
``kill -9``: on restart a server rejoins the cluster with its term,
vote, snapshot, and log intact. This module is that role for raft-lite
(server/raft.py) — the FSM *content* rides the compaction snapshot,
exactly as the reference splits raft-boltdb (log/stable) from the FSM
snapshot store.

Layout under one directory per node::

    stable.json     {"term": T, "voted_for": ...}      atomic rewrite
    snapshot.json   {"base_index", "base_term", "snapshot", "sha256"}
    log.jsonl       one {"term","index","command"} per line, append-only
                    between truncations/compactions (those rewrite)

Write ordering follows raft's durability rules: the vote/term hit disk
before the reply that promises them leaves the node, and appended
entries hit disk before the follower acks them — both guaranteed here
because persistence happens synchronously inside the handler while the
in-memory transport defers delivery to the next pump.

``fsync=False`` by default: the tests model crash-stop of the process
(state survives in the OS page cache), not power loss. Flip it on for
real deployments where the host itself may die.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
from typing import Any, Optional


def _to_jsonable(x: Any) -> Any:
    """Commands and FSM snapshots carry ``bytes`` (KV values, serialized
    payloads — the reference's msgpack log encodes them natively,
    rpc.go:377-447); JSON needs a tagged escape. Round-trips exactly
    through :func:`_from_jsonable`."""
    if isinstance(x, bytes):
        return {"__b64__": base64.b64encode(x).decode()}
    if isinstance(x, dict):
        return {k: _to_jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_to_jsonable(v) for v in x]
    return x


def _from_jsonable(x: Any) -> Any:
    if isinstance(x, dict):
        if set(x) == {"__b64__"}:
            return base64.b64decode(x["__b64__"])
        return {k: _from_jsonable(v) for k, v in x.items()}
    if isinstance(x, list):
        return [_from_jsonable(v) for v in x]
    return x


def _atomic_write(path: str, data: str, fsync: bool) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)


class DurableRaftStore:
    """One node's persistent raft state. All mutators keep the on-disk
    files consistent with the in-memory arguments at return time."""

    def __init__(self, directory: str, fsync: bool = False):
        self.dir = directory
        self.fsync = fsync
        os.makedirs(directory, exist_ok=True)
        self._stable_path = os.path.join(directory, "stable.json")
        self._snap_path = os.path.join(directory, "snapshot.json")
        self._log_path = os.path.join(directory, "log.jsonl")
        self._log_f = None

    # -- recovery ------------------------------------------------------
    def load(self) -> Optional[dict]:
        """Everything persisted, or None for a fresh directory. A torn
        final log line (crash mid-append) is dropped; a digest mismatch
        on the snapshot raises — a corrupt snapshot must not silently
        become an empty FSM (reference bolt_store would likewise fail
        hard on a corrupt db)."""
        if not os.path.exists(self._stable_path):
            return None
        with open(self._stable_path) as f:
            stable = json.load(f)
        suffrage = stable.get("suffrage")  # absent in pre-suffrage files
        base_index, base_term, snapshot = 0, 0, None
        if os.path.exists(self._snap_path):
            with open(self._snap_path) as f:
                snap = json.load(f)
            payload = json.dumps(snap["snapshot"], sort_keys=True)
            digest = hashlib.sha256(payload.encode()).hexdigest()
            if digest != snap["sha256"]:
                raise ValueError(
                    f"raft snapshot digest mismatch in {self._snap_path}"
                )
            base_index = snap["base_index"]
            base_term = snap["base_term"]
            snapshot = _from_jsonable(snap["snapshot"])
        entries = []
        if os.path.exists(self._log_path):
            with open(self._log_path) as f:
                for ln in f:
                    try:
                        entries.append(_from_jsonable(json.loads(ln)))
                    except ValueError:
                        break  # torn tail from a crash mid-append
        # Entries at or below the snapshot horizon are already compacted.
        entries = [e for e in entries if e["index"] > base_index]
        return {
            "term": stable["term"],
            "voted_for": stable.get("voted_for"),
            "suffrage": suffrage,
            "base_index": base_index,
            "base_term": base_term,
            "snapshot": snapshot,
            "entries": entries,
        }

    # -- stable store (term / vote / suffrage) -------------------------
    def set_stable(self, term: int, voted_for: Optional[str],
                   suffrage: Optional[dict] = None) -> None:
        """Suffrage = {"voter": bool, "voters": [...]} — the voter
        configuration must survive a crash (the reference persists it
        as log configuration entries) or a restarted non-voter would
        resurrect with full suffrage, bypassing autopilot's
        stabilization gate."""
        doc = {"term": term, "voted_for": voted_for}
        if suffrage is not None:
            doc["suffrage"] = suffrage
        _atomic_write(self._stable_path, json.dumps(doc), self.fsync)

    # -- log store -----------------------------------------------------
    def _log_handle(self):
        if self._log_f is None or self._log_f.closed:
            self._log_f = open(self._log_path, "a")
        return self._log_f

    def append(self, entries: list[dict]) -> None:
        f = self._log_handle()
        for e in entries:
            f.write(json.dumps(_to_jsonable(e)) + "\n")
        f.flush()
        if self.fsync:
            os.fsync(f.fileno())

    def rewrite_log(self, entries: list[dict]) -> None:
        """Truncation (conflict suffix delete) or compaction rewrite."""
        if self._log_f is not None and not self._log_f.closed:
            self._log_f.close()
        _atomic_write(
            self._log_path,
            "".join(json.dumps(_to_jsonable(e)) + "\n" for e in entries),
            self.fsync,
        )

    # -- snapshot store ------------------------------------------------
    def save_snapshot(self, snapshot: Any, base_index: int,
                      base_term: int) -> None:
        snap_j = _to_jsonable(snapshot)
        payload = json.dumps(snap_j, sort_keys=True)
        _atomic_write(
            self._snap_path,
            json.dumps({
                "base_index": base_index,
                "base_term": base_term,
                "snapshot": snap_j,
                "sha256": hashlib.sha256(payload.encode()).hexdigest(),
            }),
            self.fsync,
        )

    def close(self) -> None:
        if self._log_f is not None and not self._log_f.closed:
            self._log_f.close()
