"""RPC endpoint layer: the msgpack-RPC surface of the reference server.

One ``Server`` owns a raft node + FSM + store and exposes the endpoint
objects the reference registers (reference agent/consul/server_oss.go:
4-18, *_endpoint.go): Catalog, Health, KVS, Session, Coordinate, Status,
Txn. Calls go through :meth:`Server.rpc`, which forwards writes to the
leader exactly like the reference's ``forward`` retry loop (reference
agent/consul/rpc.go:231-292) — here an in-process hop through the
server registry (the moral equivalent of the yamux conn pool).

Reads support the blocking contract (``index``/``wait``) via the state
store's watch machinery and the ``near=`` RTT sort (reference
agent/consul/rpc.go:457-539, rtt.go:187-221).

Coordinate.Update follows the reference's write-batching design
(reference agent/consul/coordinate_endpoint.go:42-153): updates stage
in a map keyed node:segment, validated and ACL-free here; a periodic
flush applies at most ``update_max_batches × update_batch_size`` staged
entries per period through raft, discarding the excess with a counter —
the natural TPU shape is the same batch (SURVEY.md §2.5).
"""

from __future__ import annotations

import math
import random
import uuid
from time import monotonic as _monotonic, sleep as _sleep
from typing import Any, Optional

from consul_tpu.server import acl as acl_mod
from consul_tpu.server import fsm as fsm_mod
from consul_tpu.server import rtt
from consul_tpu.server.fsm import FSM
from consul_tpu.server.raft import NotLeader, RaftCluster, RaftNode
from consul_tpu.server.state_store import StateStore
from consul_tpu.utils.telemetry import Sink

# Reference defaults (agent/consul/config.go:519-521).
COORDINATE_UPDATE_PERIOD_S = 5.0
COORDINATE_UPDATE_BATCH_SIZE = 128
COORDINATE_UPDATE_MAX_BATCHES = 5


class NoPathToDatacenter(Exception):
    """No live route to the target DC (reference rpc.go:315
    'No path to datacenter')."""

    def __init__(self, dc: str, why: str = "no live server"):
        super().__init__(f"no path to datacenter {dc!r}: {why}")
        self.dc = dc


class Server:
    """One server: raft participant + FSM + endpoint dispatch."""

    def __init__(self, node_id: str, raft_node: RaftNode, fsm: FSM,
                 registry: dict[str, "Server"],
                 vivaldi_dimensionality: int = 8, dc: str = "dc1",
                 sink: Optional[Sink] = None):
        self.id = node_id
        self.raft = raft_node
        # Telemetry sink shared with the raft node (reference
        # lib/telemetry.go: one go-metrics sink per process); the raft
        # timers (consul.raft.*) and the leader loop's reconcile timer
        # (consul.leader.reconcile) land here.
        self.sink = sink if sink is not None else Sink()
        if getattr(raft_node, "sink", None) is None:
            raft_node.sink = self.sink
        self.fsm = fsm
        self.registry = registry
        self.vivaldi_dimensionality = vivaldi_dimensionality
        self.dc = dc
        # Cross-DC plumbing, populated by federate()/join_wan (reference
        # WAN serf membership feeding agent/router/serf_adapter.go).
        self.router = None                  # server/router.Router
        self.wan_registry = None            # "<id>.<dc>" -> Server
        registry[node_id] = self
        # Coordinate staging (coordinate_endpoint.go:42-53).
        self._coord_updates: dict[str, dict] = {}
        # Optional device serving plane (consul_tpu/serving): when
        # attached, ?near= sorting and prepared-query NearestN go
        # through one batched device kernel instead of per-row host
        # math. rtt.py stays the reference path (and the fallback).
        self.serving = None
        # Leader-side session TTL timers (leader.SessionTimers),
        # attached by the runtime pump while this server leads.
        self.session_timers = None
        self.metrics = {"coordinate_updates_discarded": 0,
                        "rpc_forwarded": 0, "rpc_cross_dc": 0}

    @property
    def wan_id(self) -> str:
        """WAN member name, ``<node>.<dc>`` (reference serf WAN naming,
        agent/consul/server_serf.go:33-113)."""
        return f"{self.id}.{self.dc}"

    def join_wan(self, router, wan_registry: dict[str, "Server"]) -> None:
        self.router = router
        self.wan_registry = wan_registry

    @property
    def store(self) -> StateStore:
        return self.fsm.store

    def attach_serving(self, plane) -> None:
        """Route this server's nearness sorting through a device
        serving plane (consul_tpu/serving.ServingPlane)."""
        self.serving = plane

    def _near_sorted(self, near: str, rows: list,
                     node_key: str = "node") -> list:
        """``?near=`` nearness sort: batched device kernel when a
        serving plane is attached (which itself falls back on shapes it
        can't represent), host ``rtt.py`` — the documented reference
        implementation — otherwise. Same contract either way: stable
        order, unknown coordinates last, rows unchanged for an unknown
        source."""
        sets = rtt.coord_sets_from_store(self.store.coordinates())
        if self.serving is not None:
            return self.serving.sort_rows(sets, near, rows,
                                          node_key=node_key)
        return rtt.sort_nodes_by_distance(sets, near, rows,
                                          node_key=node_key)

    def is_leader(self) -> bool:
        return self.raft.state == "leader" and not self.raft.stopped

    # ------------------------------------------------------------------
    # Dispatch + forwarding
    # ------------------------------------------------------------------
    def rpc(self, method: str, dc: Optional[str] = None, **args) -> Any:
        """Invoke ``Endpoint.Method`` (e.g. ``"Catalog.Register"``),
        forwarding writes to the leader when needed. A non-local ``dc``
        routes the call to that datacenter through the WAN router
        (reference rpc.go:315-337 forwardDC) — the reference's everyday
        ``?dc=`` path."""
        if dc and dc != self.dc:
            return self._forward_dc(method, dc, args)
        endpoint, name = method.split(".", 1)
        handler = getattr(self, f"_{endpoint.lower()}_{_snake(name)}", None)
        if handler is None:
            raise AttributeError(f"unknown RPC {method}")
        return handler(**args)

    def _forward_dc(self, method: str, dc: str, args: dict) -> Any:
        """Route to a server of ``dc`` via Router.find_route, rotating
        past down servers (reference rpc.go:315-337: FindRoute +
        NotifyFailedServer on connect failure, retrying the next
        server in the manager's rotation)."""
        if self.router is None or self.wan_registry is None:
            raise NoPathToDatacenter(dc, "not WAN-joined")
        managers = self.router.get_datacenter_maps()
        for _ in range(max(1, len(managers.get(dc, ())))):
            sid = self.router.find_route(dc)
            if sid is None:
                break
            target = self.wan_registry.get(sid)
            if target is None or target.raft.stopped:
                # Connection failure: rotate this server to the end and
                # try the next one (manager.go NotifyFailedServer).
                self.router.fail_server(sid)
                continue
            self.metrics["rpc_cross_dc"] += 1
            return target.rpc(method, **args)
        raise NoPathToDatacenter(dc)

    def global_rpc(self, method: str, **args) -> dict[str, Any]:
        """Fan the call out to every known datacenter, local included
        (reference rpc.go:340-365 globalRPC). Returns dc -> result;
        a DC with no live route reports its error string."""
        out = {self.dc: self.rpc(method, **args)}
        if self.router is None:
            return out
        for dc in self.router.datacenters():
            if dc == self.dc:
                continue
            try:
                out[dc] = self.rpc(method, dc=dc, **args)
            except NoPathToDatacenter as e:
                out[dc] = {"error": str(e)}
        return out

    def _raft_apply(self, command: dict) -> Any:
        """Propose through the leader (forwarding like rpc.go:231-292);
        the caller is responsible for stepping the cluster to commit —
        RaftCluster.propose_and_commit does both in drivers/tests."""
        if self.is_leader():
            return self.raft.propose(command)
        leader = self.raft.leader_id
        # leader == self.id can only mean stale knowledge (we are not
        # the leader per the check above) — never self-forward.
        if leader is None or leader == self.id or leader not in self.registry:
            raise NotLeader(None)
        self.metrics["rpc_forwarded"] += 1
        return self.registry[leader]._raft_apply(command)

    def _blocking(self, tables, min_index, wait_s, fn):
        if min_index:
            idx, val = self.store.blocking_query(
                tables, min_index, fn, timeout_s=wait_s
            )
        else:
            idx, val = max(
                self.store.tables[t].max_index for t in tables
            ) or 1, fn()
        return {"index": idx, "value": val}

    # ------------------------------------------------------------------
    # Status endpoint (reference agent/consul/status_endpoint.go)
    # ------------------------------------------------------------------
    def _serving_apply_index(self) -> int:
        """``Serving.ApplyIndex``: the attached device plane's monotone
        raft-style apply index — the ``X-Consul-Index`` a write-attached
        plane serves blocking queries against (consul_tpu/serving/
        watch.py). 0 when no plane (or no write path) is attached."""
        srv = self.serving
        if srv is None or not getattr(srv, "has_writes",
                                      lambda: False)():
            return 0
        return int(srv.apply_index)

    def _serving_stats(self) -> Optional[dict]:
        """``Serving.Stats``: the attached plane's flat stats dict
        (query/write batch counters, watch fan-out tallies) — None when
        no plane is attached."""
        return None if self.serving is None else self.serving.stats()

    def _status_leader(self) -> Optional[str]:
        return self.raft.leader_id

    def _status_apply_result(self, index: int) -> dict:
        """FSM response for a committed log index (the resolved value of
        the reference's raftApply future, rpc.go:377-447). Returns
        ``{"found": bool, "result": ...}`` — found distinguishes a
        genuine FSM verdict (which may itself be falsy, e.g. a lost
        CAS) from an unavailable one. Checked locally first; a miss
        (e.g. this replica caught up via InstallSnapshot, or the ring
        evicted it) falls through to the leader, which applied the
        entry from its own log."""
        if index in self.raft.apply_results:
            return {"found": True, "result": self.raft.apply_results[index]}
        leader = self.raft.leader_id
        if leader is not None and leader != self.id and leader in self.registry:
            lr = self.registry[leader].raft.apply_results
            if index in lr:
                return {"found": True, "result": lr[index]}
        return {"found": False, "result": None}

    def _status_peers(self) -> list[str]:
        return sorted([self.raft.id, *self.raft.peers])

    def _status_datacenter(self) -> str:
        """This server's datacenter — the WAN-join handshake reads it
        over the wire to learn which DC an address belongs to (the
        reference learns it from serf WAN member tags)."""
        return self.dc

    # ------------------------------------------------------------------
    # Catalog endpoint (reference agent/consul/catalog_endpoint.go)
    # ------------------------------------------------------------------
    def _catalog_register(self, node: str, address: str = "",
                          service: Optional[dict] = None,
                          check: Optional[dict] = None,
                          node_meta: Optional[dict] = None) -> int:
        # Validate before proposing (the reference validates in the
        # endpoint, catalog_endpoint.go Register) — a committed entry
        # that fails to apply would diverge-or-skip on every replica.
        if not node:
            raise ValueError("node name required")
        if check and check.get("status", "critical") not in (
            "passing", "warning", "critical"
        ):
            raise ValueError(f"bad check status {check.get('status')!r}")
        cmd = {"type": fsm_mod.REGISTER, "node": node, "address": address}
        if service:
            cmd["service"] = service
        if check:
            cmd["check"] = check
        if node_meta:
            cmd["node_meta"] = node_meta
        return self._raft_apply(cmd)

    def _catalog_deregister(self, node: str, service_id: Optional[str] = None,
                            check_id: Optional[str] = None) -> int:
        cmd = {"type": fsm_mod.DEREGISTER, "node": node}
        if service_id:
            cmd["service_id"] = service_id
        if check_id:
            cmd["check_id"] = check_id
        return self._raft_apply(cmd)

    def _catalog_list_nodes(self, min_index: int = 0, wait_s: float = 10.0,
                            near: str = "") -> dict:
        out = self._blocking(["nodes"], min_index, wait_s, self.store.nodes)
        if near:
            out["value"] = self._near_sorted(near, out["value"])
        return out

    def _catalog_list_services(self, min_index: int = 0,
                               wait_s: float = 10.0) -> dict:
        return self._blocking(["services"], min_index, wait_s,
                              self.store.services)

    def _catalog_service_nodes(self, service: str, tag: Optional[str] = None,
                               min_index: int = 0, wait_s: float = 10.0,
                               near: str = "") -> dict:
        out = self._blocking(
            ["services", "nodes"], min_index, wait_s,
            lambda: self.store.service_nodes(service, tag),
        )
        if near:
            out["value"] = self._near_sorted(near, out["value"])
        return out

    def _catalog_node_services(self, node: str) -> dict:
        return {"index": self.store.index,
                "value": self.store.node_services(node)}

    def _catalog_list_datacenters(self) -> list[str]:
        """Known datacenters sorted by WAN coordinate distance from
        this one (reference catalog_endpoint.go ListDatacenters via
        router.GetDatacentersByDistance, router.go:395). A non-
        federated server knows only itself."""
        if self.router is None:
            return [self.dc]
        return self.router.get_datacenters_by_distance()

    # ------------------------------------------------------------------
    # Health endpoint (reference agent/consul/health_endpoint.go)
    # ------------------------------------------------------------------
    def _health_service_nodes(self, service: str, passing_only: bool = False,
                              min_index: int = 0, wait_s: float = 10.0,
                              near: str = "") -> dict:
        def fn():
            rows = []
            for svc in self.store.service_nodes(service):
                checks = self.store.checks(node=svc["node"])
                health = self.store.node_health(svc["node"])
                # ?passing drops anything not fully passing, warnings
                # included (reference health_endpoint.go filterNonPassing:
                # check.Status != api.HealthPassing).
                if passing_only and health != "passing":
                    continue
                rows.append({"node": svc["node"], "service": svc,
                             "checks": checks, "aggregate_status": health})
            return rows

        out = self._blocking(["services", "checks", "nodes"],
                             min_index, wait_s, fn)
        if near:
            out["value"] = self._near_sorted(near, out["value"])
        return out

    def _health_node_checks(self, node: str, min_index: int = 0,
                            wait_s: float = 10.0) -> dict:
        return self._blocking(["checks"], min_index, wait_s,
                              lambda: self.store.checks(node=node))

    def _health_checks_in_state(self, state: str, min_index: int = 0,
                                wait_s: float = 10.0) -> dict:
        return self._blocking(["checks"], min_index, wait_s,
                              lambda: self.store.checks(state=state))

    def _health_service_checks(self, service: str, min_index: int = 0,
                               wait_s: float = 10.0) -> dict:
        """Checks for one service (reference /v1/health/checks/:service,
        health_endpoint.go ServiceChecks)."""
        return self._blocking(["checks"], min_index, wait_s,
                              lambda: self.store.checks(service=service))

    # ------------------------------------------------------------------
    # KVS endpoint (reference agent/consul/kvs_endpoint.go)
    # ------------------------------------------------------------------
    def _kvs_apply(self, op: str, key: str, value: bytes = b"",
                   flags: int = 0, cas_index: Optional[int] = None,
                   session: Optional[str] = None) -> Any:
        if op == "lock":
            # Lock-delay rejection (reference kvs_endpoint.go:73-78
            # preApply): an acquire inside the post-invalidation window
            # fails WITHOUT a raft entry — same false verdict a lost
            # lock race produces. The check must run on the LEADER —
            # the delay map is soft state recorded where the destroy
            # applied first; an arbitrary RPC-receiving follower may
            # lag it (the reference also pre-applies after forward()).
            if not self.is_leader():
                leader = self.raft.leader_id
                if leader and leader != self.id and \
                        leader in self.registry:
                    self.metrics["rpc_forwarded"] += 1
                    return self.registry[leader]._kvs_apply(
                        op, key, value, flags, cas_index, session)
            if self.store.kv_lock_delayed(key):
                return False
        return self._raft_apply({
            "type": fsm_mod.KV, "op": op, "key": key, "value": value,
            "flags": flags, "cas_index": cas_index, "session": session,
        })

    def _kvs_get(self, key: str, min_index: int = 0,
                 wait_s: float = 10.0) -> dict:
        return self._blocking(["kv"], min_index, wait_s,
                              lambda: self.store.kv_get(key))

    def _kvs_list(self, prefix: str = "", min_index: int = 0,
                  wait_s: float = 10.0) -> dict:
        return self._blocking(["kv"], min_index, wait_s,
                              lambda: self.store.kv_list(prefix))

    # ------------------------------------------------------------------
    # Session endpoint (reference agent/consul/session_endpoint.go)
    # ------------------------------------------------------------------
    def _session_apply(self, op: str, node: str = "", session_id: str = "",
                       ttl_s: float = 0.0, behavior: str = "release",
                       checks: Optional[list] = None,
                       lock_delay_s: float = 15.0) -> Any:
        if op == "create":
            # Validate before proposing (like the catalog endpoint): a
            # committed entry must not fail on apply. The local store
            # may be marginally stale on a follower; the FSM/raft
            # apply-error backstop covers that residual race.
            if self.store.get_node(node) is None:
                raise KeyError(f"node {node!r} not registered")
            session_id = session_id or str(uuid.uuid4())
            idx = self._raft_apply({
                "type": fsm_mod.SESSION, "op": "create", "id": session_id,
                "node": node, "ttl_s": ttl_s, "behavior": behavior,
                "checks": checks,
                # Reference structs.Session.LockDelay (default 15s,
                # capped at MaxLockDelay=60s at invalidation time).
                "lock_delay_s": float(lock_delay_s),
            })
            # Both the pre-assigned id AND the raft index: callers that
            # answer synchronously (the HTTP tier) must wait for the
            # apply, or an immediate follow-up (renew, acquire) races
            # the commit and reads no-such-session.
            return {"id": session_id, "index": idx}
        return self._raft_apply({"type": fsm_mod.SESSION, "op": "destroy",
                                 "id": session_id})

    def _session_list(self) -> dict:
        return {"index": self.store.index, "value": self.store.session_list()}

    def _session_get(self, session_id: str, min_index: int = 0,
                     wait_s: float = 10.0) -> dict:
        """Blocking read of one session (reference session_endpoint.go
        Get → /v1/session/info/:id). value is a LIST — empty for an
        unknown id, like the reference's Sessions slice."""
        def fn():
            s = self.store.session_get(session_id)
            return [] if s is None else [s]
        return self._blocking(("sessions",), min_index, wait_s, fn)

    def _session_node_sessions(self, node: str, min_index: int = 0,
                               wait_s: float = 10.0) -> dict:
        """Sessions held by one node (reference session_endpoint.go
        NodeSessions → /v1/session/node/:node)."""
        def fn():
            return [s for s in self.store.session_list()
                    if s.get("node") == node]
        return self._blocking(("sessions",), min_index, wait_s, fn)

    def _session_renew(self, session_id: str) -> dict:
        """Reset a TTL session's destroy deadline and return the
        session (reference session_endpoint.go Renew →
        resetSessionTimer). The timer itself is leader-side state
        (leader.SessionTimers, attached by the runtime's pump); a
        renew of an unknown session is an error like the reference."""
        s = self.store.session_get(session_id)
        if s is None:
            raise KeyError(f"unknown session {session_id!r}")
        if self.session_timers is not None:
            self.session_timers.renew(session_id)
        elif not self.is_leader():
            # Timers live with the leader; forward so the renew lands
            # where the deadline is tracked (rpc.go:231 forward).
            leader = self.raft.leader_id
            if leader and leader != self.id and leader in self.registry:
                self.metrics["rpc_forwarded"] += 1
                return self.registry[leader]._session_renew(session_id)
        return s

    # ------------------------------------------------------------------
    # Txn endpoint (reference agent/consul/txn_endpoint.go)
    # ------------------------------------------------------------------
    def _txn_apply(self, ops: list[dict]) -> int:
        return self._raft_apply({"type": fsm_mod.TXN, "ops": ops})

    # ------------------------------------------------------------------
    # ConfigEntry endpoint (reference agent/consul/config_endpoint.go:
    # Apply w/ optional CAS, Get, List, Delete — blocking reads over the
    # config_entries table)
    # ------------------------------------------------------------------
    def _configentry_apply(self, kind: str, name: str, entry: dict,
                           cas_index: Optional[int] = None) -> int:
        cmd = {"type": fsm_mod.CONFIG_ENTRY, "kind": kind, "name": name,
               "entry": entry,
               "op": "set" if cas_index is None else "cas"}
        if cas_index is not None:
            cmd["cas_index"] = cas_index
        return self._raft_apply(cmd)

    def _configentry_delete(self, kind: str, name: str,
                            cas_index: Optional[int] = None) -> int:
        cmd = {"type": fsm_mod.CONFIG_ENTRY, "kind": kind, "name": name,
               "op": "delete" if cas_index is None else "delete-cas"}
        if cas_index is not None:
            cmd["cas_index"] = cas_index
        return self._raft_apply(cmd)

    def _configentry_get(self, kind: str, name: str, min_index: int = 0,
                         wait_s: float = 10.0) -> dict:
        return self._blocking(
            ["config_entries"], min_index, wait_s,
            lambda: self.store.config_get_meta(kind, name),
        )

    def _configentry_list(self, kind: str = "*", min_index: int = 0,
                          wait_s: float = 10.0) -> dict:
        return self._blocking(
            ["config_entries"], min_index, wait_s,
            lambda: self.store.config_list_meta(kind),
        )

    # ------------------------------------------------------------------
    # Operator endpoint (reference agent/consul/operator_raft_endpoint.go
    # :1-89 RaftGetConfiguration/RaftRemovePeerByAddress,
    # operator_autopilot_endpoint.go:1-76 get/set autopilot config)
    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    # ACL endpoint (reference agent/consul/acl_endpoint.go:
    # Bootstrap + Token/Policy CRUD; resolution for enforcement)
    # ------------------------------------------------------------------
    def _acl_bootstrap(self) -> dict:
        """Mint the initial management token exactly once
        (acl_endpoint.go Bootstrap). The one-shot guard is an
        apply-time verdict so a bootstrap race across servers resolves
        to a single winner."""
        if self.store.acl_bootstrapped():
            raise ValueError("ACL system already bootstrapped")
        token = {
            "accessor_id": str(uuid.uuid4()),
            "secret_id": str(uuid.uuid4()),
            "description": "Bootstrap Token (Global Management)",
            "policies": [acl_mod.MANAGEMENT_POLICY],
        }
        idx = self._raft_apply({"type": fsm_mod.ACL, "op": "bootstrap",
                                "token": token})
        return {"token": token, "index": idx}

    def _acl_token_set(self, token: dict) -> dict:
        t = dict(token)
        t.setdefault("accessor_id", str(uuid.uuid4()))
        existing = self.store.acl_token_get(t["accessor_id"])
        if existing is not None:
            # SecretID is immutable on update (reference acl_endpoint.go
            # TokenSet: "cannot change the secret") — a rewrite could
            # collide with another token's secret and make resolution
            # ambiguous.
            t["secret_id"] = existing["secret_id"]
        else:
            t.setdefault("secret_id", str(uuid.uuid4()))
            clash = self.store.acl_token_by_secret(t["secret_id"])
            if clash is not None and \
                    clash["accessor_id"] != t["accessor_id"]:
                raise ValueError("secret id already in use")
        t.setdefault("description", "")
        t.setdefault("policies", [])
        for p in t["policies"]:
            if p != acl_mod.MANAGEMENT_POLICY and \
                    self.store.acl_policy_get(p) is None:
                raise KeyError(f"unknown ACL policy {p!r}")
        idx = self._raft_apply({"type": fsm_mod.ACL, "op": "token-set",
                                "token": t})
        return {"token": t, "index": idx}

    def _acl_token_delete(self, accessor_id: str) -> int:
        if self.store.acl_token_get(accessor_id) is None:
            raise KeyError(f"unknown ACL token {accessor_id!r}")
        return self._raft_apply({"type": fsm_mod.ACL, "op": "token-delete",
                                 "accessor_id": accessor_id})

    def _acl_token_get(self, accessor_id: str, min_index: int = 0,
                       wait_s: float = 10.0) -> dict:
        def fn():
            t = self.store.acl_token_get(accessor_id)
            return [] if t is None else [t]
        return self._blocking(("acl_tokens",), min_index, wait_s, fn)

    def _acl_token_list(self, min_index: int = 0,
                        wait_s: float = 10.0) -> dict:
        # Listings never expose secrets (acl_endpoint.go TokenList
        # redacts unless the caller proves management; the HTTP tier
        # has already gated this on acl:read).
        def fn():
            return [{k: v for k, v in t.items() if k != "secret_id"}
                    for t in self.store.acl_token_list()]
        return self._blocking(("acl_tokens",), min_index, wait_s, fn)

    def _acl_policy_set(self, policy: dict) -> dict:
        p = dict(policy)
        if not p.get("name"):
            raise ValueError("ACL policy needs a name")
        if p["name"] == acl_mod.MANAGEMENT_POLICY:
            raise ValueError(f"{acl_mod.MANAGEMENT_POLICY!r} is builtin")
        acl_mod.parse_rules(p.get("rules"))  # validate before commit
        p.setdefault("id", str(uuid.uuid4()))
        p.setdefault("description", "")
        idx = self._raft_apply({"type": fsm_mod.ACL, "op": "policy-set",
                                "policy": p})
        return {"policy": p, "index": idx}

    def _acl_policy_delete(self, name: str) -> int:
        if self.store.acl_policy_get(name) is None:
            raise KeyError(f"unknown ACL policy {name!r}")
        return self._raft_apply({"type": fsm_mod.ACL, "op": "policy-delete",
                                 "name": name})

    def _acl_policy_get(self, name: str, min_index: int = 0,
                        wait_s: float = 10.0) -> dict:
        def fn():
            p = self.store.acl_policy_get(name)
            return [] if p is None else [p]
        return self._blocking(("acl_policies",), min_index, wait_s, fn)

    def _acl_policy_list(self, min_index: int = 0,
                         wait_s: float = 10.0) -> dict:
        return self._blocking(("acl_policies",), min_index, wait_s,
                              self.store.acl_policy_list)

    def _acl_resolve(self, secret_id: str,
                     default_allow: bool = True) -> dict:
        """Secret → the token's compiled rule inputs (reference
        acl.go ResolveToken): the HTTP tier builds the Authorizer.
        Unknown secrets are anonymous, NOT an error (the reference
        treats them as anonymous when down-policy permits; a hard
        error would turn every stale token into an outage)."""
        t = self.store.acl_token_by_secret(secret_id) if secret_id else None
        if t is None:
            return {"known": False, "management": False, "rules": []}
        management = acl_mod.MANAGEMENT_POLICY in t.get("policies", [])
        docs = []
        for name in t.get("policies", []):
            p = self.store.acl_policy_get(name)
            if p is not None:
                docs.append(acl_mod.parse_rules(p.get("rules")))
        return {"known": True, "management": management, "rules": docs,
                "accessor_id": t["accessor_id"]}

    # ------------------------------------------------------------------
    # ConnectCA endpoint (reference agent/consul/connect_ca_endpoint.go:
    # Roots / ConfigurationGet / ConfigurationSet / Sign)
    # ------------------------------------------------------------------
    def _ca_ensure_initialized(self) -> dict:
        """Active root, lazily minted on first use (the reference
        initializes the CA when a leader establishes; lazy-on-demand
        gives the same replicated outcome). Generation happens HERE,
        the log carries the PEMs; a racing double-init resolves to one
        winner via the FSM's only_if_uninitialized verdict."""
        from consul_tpu.server import connect_ca as ca_mod
        root = self.store.ca_active_root()
        if root is not None:
            return root
        cfg = self.store.ca_config_get() or {}
        cluster_id = cfg.get("cluster_id") or ca_mod.new_cluster_id()
        new_root = ca_mod.generate_root(cluster_id)
        init_idx = self._raft_apply(
            {"type": fsm_mod.CONNECT_CA, "op": "set-root",
             "root": new_root, "only_if_uninitialized": True})
        if self.store.ca_config_get() is None:
            self._raft_apply({"type": fsm_mod.CONNECT_CA,
                              "op": "set-config",
                              "config": {"provider": "consul",
                                         "cluster_id": cluster_id}})
        # The proposal applies when the raft pump commits it; poll for
        # the replicated copy (a racing init may have won with a
        # DIFFERENT root — the store is the truth, and a leaf minted
        # under a losing root would verify against nothing in the
        # bundle). Only when the entry provably never even resolved —
        # no pump stepped at all, i.e. the step-driven test harness,
        # where no concurrent proposer can exist either — fall back to
        # the material we just proposed.
        deadline = _monotonic() + 2.0
        while _monotonic() < deadline:
            root = self.store.ca_active_root()
            if root is not None:
                return root
            res = self._status_apply_result(init_idx)
            if res["found"] and res["result"] is False:
                # Our init lost the race; the winner's root is about
                # to land in the store — keep polling for it.
                pass
            _sleep(0.005)
        res = self._status_apply_result(init_idx)
        if res["found"] and res["result"] is False:
            raise RuntimeError(
                "connect CA init lost a race and the winning root "
                "never became visible")
        return new_root

    @staticmethod
    def _ca_public_root(root: dict) -> dict:
        """A root WITHOUT its private key — what Roots() serves
        (connect_ca_endpoint.go redacts signing material)."""
        return {k: v for k, v in root.items() if k != "private_key"}

    def _connectca_roots(self, min_index: int = 0,
                         wait_s: float = 10.0) -> dict:
        self._ca_ensure_initialized()

        def fn():
            roots = [self._ca_public_root(r)
                     for r in self.store.ca_roots()]
            active = next((r for r in roots if r.get("active")), None)
            return {
                "active_root_id": active["id"] if active else None,
                "trust_domain": active.get("trust_domain")
                if active else None,
                "roots": roots,
            }
        return self._blocking(("connect_ca",), min_index, wait_s, fn)

    def _connectca_configuration_get(self) -> dict:
        self._ca_ensure_initialized()
        return dict(self.store.ca_config_get() or {})

    def _connectca_configuration_set(self, config: dict) -> dict:
        """Apply CA config; supplying root material (or requesting
        rotation) mints/installs a new ACTIVE root, keeping old roots
        in the trust bundle (the reference's rotation, minus the
        cross-signing intermediate window — documented)."""
        from consul_tpu.server import connect_ca as ca_mod
        cfg = dict(config)
        rotate = bool(cfg.pop("rotate", False))
        provided = cfg.pop("root_cert", None)
        provided_key = cfg.pop("private_key", None)
        cfg.setdefault("provider", "consul")
        old = self.store.ca_config_get() or {}
        cfg.setdefault("cluster_id",
                       old.get("cluster_id") or ca_mod.new_cluster_id())
        idx = self._raft_apply({"type": fsm_mod.CONNECT_CA,
                                "op": "set-config", "config": cfg})
        if provided and provided_key:
            td = ca_mod.trust_domain(cfg["cluster_id"])
            root = {"id": ca_mod.root_id(provided),
                    "name": "Provided CA Root Cert",
                    "root_cert": provided, "private_key": provided_key,
                    "trust_domain": td}
            idx = self._raft_apply({"type": fsm_mod.CONNECT_CA,
                                    "op": "set-root", "root": root})
        elif rotate:
            idx = self._raft_apply({"type": fsm_mod.CONNECT_CA,
                                    "op": "set-root",
                                    "root": ca_mod.generate_root(
                                        cfg["cluster_id"])})
        # A bare int return rides _rpc_write's synchronous-raftApply
        # contract: the HTTP 200 waits for the LAST applied entry
        # (the rotation, when one happened), so a rotate-then-read
        # sequence observes the new bundle.
        return idx

    def _connectca_sign(self, service: str,
                        ttl_s: Optional[float] = None) -> dict:
        """Mint a leaf for ``service`` under the active root
        (connect_ca_endpoint.go Sign; the /v1/agent/connect/ca/leaf
        read rides this)."""
        from consul_tpu.server import connect_ca as ca_mod
        root = self._ca_ensure_initialized()
        return ca_mod.sign_leaf(
            root, service, self.dc,
            ttl_s=ttl_s or ca_mod.DEFAULT_LEAF_TTL_S)

    # ------------------------------------------------------------------
    # DiscoveryChain endpoint (reference agent/consul/
    # discoverychain_endpoint.go Get + discoverychain/compile.go)
    # ------------------------------------------------------------------
    def _discoverychain_get(self, service: str, min_index: int = 0,
                            wait_s: float = 10.0) -> dict:
        """Compile the service's router/splitter/resolver config
        entries into the walkable chain — a blocking read over the
        config_entries table, so watchers recompile on entry changes."""
        from consul_tpu.server import discovery_chain as dch

        def fn():
            return dch.compile_chain(self.store.config_get, service,
                                     datacenter=self.dc)
        return self._blocking(("config_entries",), min_index, wait_s, fn)

    # ------------------------------------------------------------------
    # Intention endpoint (reference agent/consul/intention_endpoint.go:
    # Apply/Get/List/Match/Check; structs/intention.go precedence)
    # ------------------------------------------------------------------
    @staticmethod
    def _intention_precedence(source: str, destination: str) -> int:
        """More-specific-first ordering (structs/intention.go
        UpdatePrecedence, names-only form): exact destination beats
        wildcard, then exact source beats wildcard."""
        base = 9 if destination != "*" else 3
        return base if source != "*" else base - 1

    def _intention_apply(self, op: str, intention: Optional[dict] = None,
                         intention_id: Optional[str] = None) -> Any:
        if op == "delete":
            if self.store.intention_get(intention_id) is None:
                raise KeyError(f"unknown intention {intention_id!r}")
            return self._raft_apply({"type": fsm_mod.INTENTION,
                                     "op": "delete", "id": intention_id})
        x = dict(intention or {})
        for field in ("source", "destination"):
            v = x.get(field, "")
            if not v:
                raise ValueError(f"intention {field} must be set")
            if "*" in v and v != "*":
                # Partial wildcards are invalid (Validate:177-196).
                raise ValueError(
                    f"intention {field}: '*' cannot be used with "
                    "partial values")
        if x.get("action") not in ("allow", "deny"):
            raise ValueError("intention action must be allow or deny")
        if op == "create":
            x["id"] = str(uuid.uuid4())
        elif not x.get("id") or self.store.intention_get(x["id"]) is None:
            raise KeyError(f"unknown intention {x.get('id')!r}")
        x.setdefault("description", "")
        x.setdefault("meta", {})
        # Precedence is read-only, recomputed on every write
        # (UpdatePrecedence runs on Apply).
        x["precedence"] = self._intention_precedence(
            x["source"], x["destination"])
        idx = self._raft_apply({"type": fsm_mod.INTENTION, "op": op,
                                "intention": x})
        return {"id": x["id"], "index": idx}

    def _intention_get(self, intention_id: str, min_index: int = 0,
                       wait_s: float = 10.0) -> dict:
        def fn():
            x = self.store.intention_get(intention_id)
            return [] if x is None else [x]
        return self._blocking(("intentions",), min_index, wait_s, fn)

    def _intention_list(self, min_index: int = 0,
                        wait_s: float = 10.0) -> dict:
        return self._blocking(("intentions",), min_index, wait_s,
                              self.store.intention_list)

    def _intention_match(self, by: str, name: str, min_index: int = 0,
                         wait_s: float = 10.0) -> dict:
        """Intentions whose ``by`` side (source|destination) covers
        ``name`` — exact or wildcard — highest precedence first
        (intention_endpoint.go Match / state IntentionMatch)."""
        if by not in ("source", "destination"):
            raise ValueError(f"match by must be source|destination, "
                             f"got {by!r}")

        def fn():
            return [x for x in self.store.intention_list()
                    if x[by] in (name, "*")]
        return self._blocking(("intentions",), min_index, wait_s, fn)

    def _intention_check(self, source: str, destination: str,
                         default_allow: bool = True) -> dict:
        """Would a connection source → destination be authorized?
        (intention_endpoint.go Check): the highest-precedence
        destination match whose source also covers the caller decides;
        no match falls through to ``default_allow`` (the reference
        derives it from the ACL default policy — the HTTP tier passes
        its configured default in)."""
        matches = [x for x in self.store.intention_list()
                   if x["destination"] in (destination, "*")]
        for x in matches:  # already precedence-sorted
            if x["source"] in (source, "*"):
                return {"allowed": x["action"] == "allow",
                        "matched": x["id"]}
        return {"allowed": bool(default_allow), "matched": None}

    # ------------------------------------------------------------------
    # PreparedQuery endpoint (reference agent/consul/
    # prepared_query_endpoint.go: Apply/Get/List/Explain/Execute/
    # ExecuteRemote over the raft-replicated prepared_queries table)
    # ------------------------------------------------------------------
    def _preparedquery_apply(self, op: str, query: Optional[dict] = None,
                             query_id: Optional[str] = None) -> Any:
        from consul_tpu.server import prepared_query as pq_mod

        if op == "delete":
            if self.store.pq_get(query_id) is None:
                raise KeyError(f"unknown prepared query {query_id!r}")
            return self._raft_apply({"type": fsm_mod.PREPARED_QUERY,
                                     "op": "delete", "id": query_id})
        q = pq_mod.normalize(query or {})
        if op == "create":
            q["id"] = str(uuid.uuid4())
        else:
            if not q["id"] or self.store.pq_get(q["id"]) is None:
                raise KeyError(f"unknown prepared query {q['id']!r}")
        if q["session"] and self.store.session_get(q["session"]) is None:
            # Validated before proposing, like the reference endpoint
            # (prepared_query_endpoint.go:67-75 session verification);
            # the query dies with the session afterwards.
            raise KeyError(f"unknown session {q['session']!r}")
        idx = self._raft_apply({"type": fsm_mod.PREPARED_QUERY,
                                "op": op, "query": q})
        return {"id": q["id"], "index": idx}

    def _preparedquery_get(self, query_id: str, min_index: int = 0,
                           wait_s: float = 10.0) -> dict:
        def fn():
            q = self.store.pq_get(query_id)
            return [] if q is None else [q]
        return self._blocking(("prepared_queries",), min_index, wait_s, fn)

    def _preparedquery_list(self, min_index: int = 0,
                            wait_s: float = 10.0) -> dict:
        return self._blocking(("prepared_queries",), min_index, wait_s,
                              self.store.pq_list)

    def _preparedquery_explain(self, query_id_or_name: str) -> dict:
        """The fully-rendered query an execute would run (reference
        Explain — the template-debugging verb)."""
        from consul_tpu.server import prepared_query as pq_mod
        q = pq_mod.resolve(self.store.pq_list(), query_id_or_name)
        if q is None:
            raise KeyError(f"prepared query {query_id_or_name!r} not found")
        return {"query": q, "index": self.store.index}

    def _pq_run_local(self, q: dict) -> dict:
        """Local-DC execution without sort/failover (reference
        prepared_query_endpoint.go:511-558 execute): health rows for
        the service, then the query's health/tag/meta filters."""
        from consul_tpu.server import prepared_query as pq_mod
        svc = q["service"]["service"]
        rows = []
        for s in self.store.service_nodes(svc):
            nd = self.store.get_node(s["node"]) or {}
            rows.append({"node": s["node"], "service": s,
                         "checks": self.store.checks(node=s["node"]),
                         "node_meta": nd.get("meta", {})})
        return {"service": svc, "nodes": pq_mod.filter_nodes(q, rows),
                "datacenter": self.dc, "failovers": 0,
                "dns": q.get("dns", {}), "index": self.store.index}

    def _preparedquery_execute(self, query_id_or_name: str, limit: int = 0,
                               near: str = "") -> dict:
        """Resolve → run → shuffle → RTT sort → limit → DC failover
        (reference Execute, prepared_query_endpoint.go:331-458).
        ``near`` here is already a node name — the ``_agent`` magic
        value is the HTTP tier's to resolve, since only it knows the
        requesting agent."""
        from consul_tpu.server import prepared_query as pq_mod
        q = pq_mod.resolve(self.store.pq_list(), query_id_or_name)
        if q is None:
            raise KeyError(f"prepared query {query_id_or_name!r} not found")
        reply = self._pq_run_local(q)
        nodes = reply["nodes"]
        # Shuffle for load spread (Execute's Nodes.Shuffle) —
        # deterministically seeded so replicas and tests agree.
        random.Random(f"{q['id']}|{self.store.index}").shuffle(nodes)
        near_node = near or q["service"].get("near", "")
        if near_node:
            nodes = pq_mod.nearest_sorted(nodes, near_node,
                                          self._near_sorted)
        if limit and len(nodes) > limit:
            nodes = nodes[:limit]
        reply["nodes"] = nodes
        if not nodes:
            self._pq_failover(q, limit, reply)
        return reply

    def _preparedquery_execute_remote(self, query: dict,
                                      limit: int = 0) -> dict:
        """Run an already-resolved query shipped from another DC
        (reference ExecuteRemote:466-509 — the full definition rides
        the request since this DC's store doesn't hold it; no onward
        failover, fan-out stays one level)."""
        reply = self._pq_run_local(query)
        random.Random(
            f"{query.get('id', '')}|{self.store.index}"
        ).shuffle(reply["nodes"])
        if limit and len(reply["nodes"]) > limit:
            reply["nodes"] = reply["nodes"][:limit]
        return reply

    def _pq_failover(self, q: dict, limit: int, reply: dict) -> None:
        """Try other DCs when the local result is empty (reference
        queryFailover:677-770): the nearest N by WAN RTT, then any
        explicitly listed DCs we know about, in order, stopping at the
        first DC that answers with nodes."""
        fo = q["service"]["failover"]
        nearest_n = fo.get("nearest_n", 0)
        explicit = fo.get("datacenters", [])
        if nearest_n <= 0 and not explicit:
            return
        known = [d for d in self._catalog_list_datacenters()
                 if d != self.dc]
        dcs = list(known[:nearest_n])
        for d in explicit:
            # Unknown DCs are skipped, not errors (queryFailover:713).
            if d in known and d not in dcs:
                dcs.append(d)
        failovers = 0
        for dc in dcs:
            failovers += 1
            try:
                remote = self._forward_dc(
                    "PreparedQuery.ExecuteRemote", dc,
                    {"query": q, "limit": limit})
            except Exception:  # noqa: BLE001 — dead DC: try the next
                continue
            if remote["nodes"]:
                reply["nodes"] = remote["nodes"]
                reply["datacenter"] = remote["datacenter"]
                break
        reply["failovers"] = failovers

    def _operator_raft_get_configuration(self) -> dict:
        """The raft membership as this server's raft layer sees it:
        id/address/leader/voter per server (reference
        operator_raft_endpoint.go:1-50 resolving serf members against
        raft.GetConfiguration)."""
        n = self.raft
        servers = []
        for sid in sorted({n.id, *n.peers}):
            servers.append({
                "id": sid, "node": sid, "address": sid,
                "leader": sid == n.leader_id,
                "voter": sid in n.voters,
            })
        return {"index": n.commit_index, "servers": servers}

    def _operator_raft_remove_peer(self, id: str) -> int:
        """Kick a peer out of the raft configuration (reference
        operator_raft_endpoint.go:52-89 RaftRemovePeerByAddress — the
        stuck-server escape hatch). Rides the replicated configuration
        entry; quorum-guarded like autopilot's cleanup."""
        from consul_tpu.server.autopilot import can_remove_servers
        from consul_tpu.server.raft import RAFT_CONFIG

        n = self.raft
        if id not in {n.id, *n.peers}:
            raise ValueError(f"id {id!r} is not a raft peer")
        if id in n.voters and not can_remove_servers(len(n.voters), 1):
            raise ValueError(
                f"removing {id!r} would leave fewer than a quorum of "
                f"the {len(n.voters)}-voter configuration")
        return self._raft_apply({"type": RAFT_CONFIG, "op": "remove",
                                 "id": id})

    def _operator_autopilot_get_configuration(self) -> dict:
        from consul_tpu.server.autopilot import DEFAULT_AUTOPILOT_CONFIG
        stored = self.store.autopilot_get()
        return dict(DEFAULT_AUTOPILOT_CONFIG, **(stored or {}))

    def _operator_autopilot_set_configuration(
            self, config: dict, cas_index: Optional[int] = None) -> int:
        from consul_tpu.server.autopilot import DEFAULT_AUTOPILOT_CONFIG
        # modify_index is part of what GET returns (the struct's raft
        # index, like the reference Config.ModifyIndex) — accept the
        # round-trip, it is not a settable field.
        config = {k: v for k, v in config.items() if k != "modify_index"}
        unknown = sorted(set(config) - set(DEFAULT_AUTOPILOT_CONFIG))
        if unknown:
            raise ValueError(f"unknown autopilot config keys: {unknown}")
        cmd = {"type": fsm_mod.AUTOPILOT,
               "config": dict(DEFAULT_AUTOPILOT_CONFIG, **config)}
        if cas_index is not None:
            cmd["cas_index"] = cas_index
        return self._raft_apply(cmd)

    def _operator_server_health(self) -> dict:
        """Autopilot's per-server health verdicts plus the cluster
        rollup (reference operator_autopilot_endpoint.go:56-76
        ServerHealth → OperatorHealthReply: healthy/voter/leader per
        server, FailureTolerance = healthy voters beyond quorum).
        Scored from the same stats the autopilot loop fetches
        (autopilot.server_health), taken over this server's raft view
        of the configuration."""
        from consul_tpu.server import autopilot as ap

        leader_id = self.raft.leader_id
        ids = sorted({self.raft.id, *self.raft.peers})
        if leader_id is None or leader_id not in self.registry:
            # No scorable leader (mid-transition, or leader_id points
            # at a peer gone from the registry after remove-peer).
            # This endpoint is the diagnostic an operator reaches for
            # EXACTLY then — report every server unscored rather than
            # erroring the whole request (the reference still answers
            # with per-server rows from its last stats).
            return {
                "healthy": False, "failure_tolerance": 0,
                "servers": [{
                    "id": sid, "name": sid, "healthy": False,
                    "voter": sid in self.raft.voters, "leader": False,
                    "last_contact_ticks": None, "trailing_logs": 0,
                    "reason": "no leader to score health from",
                } for sid in ids],
            }
        leader = self.registry[leader_id].raft
        stats: dict[str, Optional[dict]] = {}
        for sid in ids:
            srv = self.registry.get(sid)
            n = srv.raft if srv is not None else None
            if n is None or n.stopped:
                stats[sid] = None
            else:
                stats[sid] = {
                    "last_index": n.last_log_index(), "term": n.term,
                    "contact_age": n.ticks - n.last_contact_tick,
                    "voter": n.voter, "is_leader": n.state == "leader",
                }
        servers = []
        for sid in ids:
            srv = self.registry.get(sid)
            if srv is None:
                h = ap.ServerHealth(sid, False, False, None, 0,
                                    "unknown server")
            else:
                # stats is pre-fetched, so server_health never touches
                # its cluster argument (the StatsFetcher contract).
                h = ap.server_health(None, srv.raft, leader, stats)
            servers.append({
                "id": h.id, "name": h.id, "healthy": h.healthy,
                "voter": h.voter, "leader": h.id == leader_id,
                "last_contact_ticks": h.last_contact_ticks,
                "trailing_logs": h.trailing_logs, "reason": h.reason,
            })
        n_voters = len(self.raft.voters)
        healthy_voters = sum(1 for s in servers
                             if s["healthy"] and s["voter"])
        quorum = n_voters // 2 + 1
        return {
            "healthy": all(s["healthy"] for s in servers),
            "failure_tolerance": max(0, healthy_voters - quorum),
            "servers": servers,
        }

    # ------------------------------------------------------------------
    # Internal endpoint (reference agent/consul/internal_endpoint.go:
    # 1-100 NodeInfo/NodeDump — the combined node+services+checks view
    # the UI and `consul debug` read)
    # ------------------------------------------------------------------
    def _node_dump_row(self, nd: dict) -> dict:
        name = nd["node"]
        return {"node": name, "address": nd.get("address", ""),
                "meta": nd.get("meta", {}),
                "services": self.store.node_services(name),
                "checks": self.store.checks(node=name)}

    def _internal_node_info(self, node: str, min_index: int = 0,
                            wait_s: float = 10.0) -> dict:
        def fn():
            nd = self.store.get_node(node)
            return [] if nd is None else [self._node_dump_row(nd)]
        return self._blocking(["nodes", "services", "checks"],
                              min_index, wait_s, fn)

    def _internal_node_dump(self, min_index: int = 0,
                            wait_s: float = 10.0) -> dict:
        return self._blocking(
            ["nodes", "services", "checks"], min_index, wait_s,
            lambda: [self._node_dump_row(nd) for nd in
                     sorted(self.store.nodes(), key=lambda d: d["node"])])

    # ------------------------------------------------------------------
    # Coordinate endpoint (reference agent/consul/coordinate_endpoint.go)
    # ------------------------------------------------------------------
    def _coordinate_update(self, node: str, coord: dict,
                           segment: str = "") -> None:
        """Stage one update; validation mirrors coordinate_endpoint.go:
        122-146 (dimensionality + finite components)."""
        vec = coord.get("vec", [])
        if len(vec) != self.vivaldi_dimensionality:
            raise ValueError(
                f"coordinate dimensionality {len(vec)} != "
                f"{self.vivaldi_dimensionality}"
            )
        comps = [*vec, coord.get("error", 0.0), coord.get("height", 0.0),
                 coord.get("adjustment", 0.0)]
        if not all(math.isfinite(c) for c in comps):
            raise ValueError("coordinate has non-finite components")
        if not self.is_leader():
            leader = self.raft.leader_id
            if leader and leader != self.id and leader in self.registry:
                self.metrics["rpc_forwarded"] += 1
                return self.registry[leader]._coordinate_update(
                    node, coord, segment
                )
            raise NotLeader(None)
        key = f"{node}:{segment}"
        if key not in self._coord_updates and len(self._coord_updates) >= \
                COORDINATE_UPDATE_BATCH_SIZE * COORDINATE_UPDATE_MAX_BATCHES:
            # Rate limit: discard, like coordinate_endpoint.go:66-71.
            self.metrics["coordinate_updates_discarded"] += 1
            return None
        self._coord_updates[key] = {"node": node, "segment": segment,
                                    "coord": coord}
        return None

    def flush_coordinates(self) -> list[int]:
        """Apply staged updates in raft batches of ``update_batch_size``
        (the 5s background batchUpdate, coordinate_endpoint.go:42-111).
        Called by the driver on its update period."""
        if not self._coord_updates:
            return []
        staged = list(self._coord_updates.values())
        self._coord_updates.clear()
        indexes = []
        for i in range(0, len(staged), COORDINATE_UPDATE_BATCH_SIZE):
            batch = staged[i:i + COORDINATE_UPDATE_BATCH_SIZE]
            indexes.append(self._raft_apply({
                "type": fsm_mod.COORDINATE_BATCH_UPDATE, "updates": batch,
            }))
        return indexes

    def _coordinate_list_nodes(self, min_index: int = 0,
                               wait_s: float = 10.0) -> dict:
        return self._blocking(["coordinates"], min_index, wait_s,
                              self.store.coordinates)

    def _coordinate_node(self, node: str, min_index: int = 0,
                         wait_s: float = 10.0) -> dict:
        def fn():
            return [c for c in self.store.coordinates() if c["node"] == node]
        return self._blocking(["coordinates"], min_index, wait_s, fn)

    def _coordinate_list_datacenters(self) -> list[dict]:
        """WAN coordinates of every datacenter's servers (reference
        coordinate_endpoint.go:159-176 ListDatacenters reading the
        router's area maps). Like Catalog.ListDatacenters, a
        non-federated server still reports its own DC (the WAN serf
        always contains self)."""
        if self.router is None:
            return [{"datacenter": self.dc, "area_id": "wan",
                     "coordinates": []}]
        out = []
        for dc, sids in sorted(self.router.get_datacenter_maps().items()):
            coords = [{"node": sid, "coord": self.router.coords[sid]}
                      for sid in sids if sid in self.router.coords]
            out.append({"datacenter": dc,
                        "area_id": type(self.router).LOCAL_AREA,
                        "coordinates": coords})
        return out


def _snake(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


class ServerCluster:
    """In-process multi-server harness: n Servers over one deterministic
    raft transport (the reference's in-process cluster test idiom,
    agent/consul/helper_test.go + TestAgent)."""

    def __init__(self, n: int = 3, seed: int = 0,
                 snapshot_threshold: int = 4096,
                 vivaldi_dimensionality: int = 8,
                 bootstrap_expect: int = 0,
                 data_dir: str = "", dc: str = "dc1"):
        self.registry: dict[str, Server] = {}
        fsms: dict[str, FSM] = {}

        def apply_factory(node_id):
            fsms[node_id] = FSM(StateStore())
            return fsms[node_id].apply

        # data_dir makes consensus state durable (reference -data-dir,
        # raft-boltdb at server.go:558): each node persists under
        # <data_dir>/raft/<node_id> and a process restart with the same
        # dir resumes term/vote/log/snapshot from disk.
        store_factory = None
        if data_dir:
            import os

            from consul_tpu.server.raft_store import DurableRaftStore
            store_factory = lambda nid: DurableRaftStore(  # noqa: E731
                os.path.join(data_dir, "raft", nid))

        # One shared sink for the whole in-process cluster, so a test
        # or bench can read consul.raft.* / consul.leader.* timers from
        # a single place regardless of which node leads.
        self.sink = Sink()
        self.raft = RaftCluster(
            n, apply_factory, seed=seed,
            snapshot_threshold=snapshot_threshold,
            snapshot_factory=lambda nid: fsms[nid].snapshot,
            restore_factory=lambda nid: fsms[nid].restore,
            store_factory=store_factory,
            sink=self.sink,
        )
        self.dc = dc
        self.servers = [
            Server(nid, self.raft.nodes[nid], fsms[nid], self.registry,
                   vivaldi_dimensionality, dc=dc, sink=self.sink)
            for nid in sorted(self.raft.nodes)
        ]
        # bootstrap-expect (reference server_serf.go:236 maybeBootstrap):
        # with a non-zero expectation, raft stays dormant — no elections,
        # no log — until maybe_bootstrap() has seen that many server
        # members (via serf tags) all agreeing on the expectation.
        self.bootstrap_expect = bootstrap_expect
        self.bootstrapped = bootstrap_expect == 0
        if not self.bootstrapped:
            for node in self.raft.nodes.values():
                node.stopped = True

    def maybe_bootstrap(self, members: list[dict]) -> bool:
        """Feed serf member observations (dicts with ``name`` and a
        ``tags`` map: role/expect, reference server_serf.go:33-113).
        Bootstraps raft once ``bootstrap_expect`` servers are known and
        every one of them advertises the same expectation
        (server_serf.go:236-330 maybeBootstrap; mismatched expect values
        log and wait, they never bootstrap a wrong-size quorum)."""
        if self.bootstrapped:
            return True
        servers = [m for m in members
                   if m.get("tags", {}).get("role") == "consul"]
        expects = set()
        for m in servers:
            try:
                expects.add(int(m["tags"].get("expect", 0)))
            except (TypeError, ValueError):
                # Malformed gossip tag: skip the member, never crash
                # the serf-event loop (maybeBootstrap logs-and-skips).
                return False
        if len(servers) < self.bootstrap_expect:
            return False
        if expects != {self.bootstrap_expect}:
            return False  # conflicting -bootstrap-expect: refuse
        for node in self.raft.nodes.values():
            node.stopped = False
        self.bootstrapped = True
        return True

    def step(self, rounds: int = 1):
        self.raft.step(rounds)

    def wait_converged(self, max_rounds: int = 400) -> Server:
        """Step until every running node agrees on the same leader (the
        testrpc.WaitForLeader idiom, reference testrpc/wait.go:14-38)."""
        return self.registry[self.raft.wait_converged(max_rounds).id]

    def leader_server(self) -> Server:
        return self.wait_converged()

    def any_follower(self) -> Server:
        led = self.wait_converged()
        return next(s for s in self.servers if s.id != led.id)

    def write(self, server: Server, method: str, **args) -> Any:
        """Issue a write RPC and step raft until it commits AND every
        running replica has applied it (the synchronous raftApply
        contract of rpc.go:377, plus full replication so follower
        reads — which are stale-by-design, like the reference's
        default consistency mode — observe the write in tests)."""
        out = server.rpc(method, **args)
        if isinstance(out, int):
            for _ in range(300):
                self.step()
                if all(n.last_applied >= out
                       for n in self.raft.nodes.values() if not n.stopped):
                    return out
            raise TimeoutError(f"index {out} not fully applied")
        self.step(5)
        return out


def federate(*clusters: "ServerCluster", seed: int = 0):
    """Wire single-DC ServerClusters into one WAN: every server gets a
    Router seeded with every cluster's server list and a shared
    ``wan_id -> Server`` registry — the in-process equivalent of WAN
    serf membership + flood join feeding each server's router
    (reference agent/consul/flood.go:27-66, agent/router/serf_adapter.go;
    the registry plays the yamux connection pool's role).

    Returns the shared WAN registry."""
    from consul_tpu.server.router import Router, flood_join

    dcs = [c.dc for c in clusters]
    if len(set(dcs)) != len(dcs):
        raise ValueError(f"duplicate datacenter names: {dcs}")
    wan_registry: dict[str, Server] = {
        s.wan_id: s for c in clusters for s in c.servers
    }
    for c in clusters:
        for s in c.servers:
            router = Router(local_dc=c.dc, seed=seed)
            for other in clusters:
                flood_join(router, other.dc,
                           [o.wan_id for o in other.servers])
            s.join_wan(router, wan_registry)
    return wan_registry
