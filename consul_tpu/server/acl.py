"""ACL system: tokens, policies, and the authorizer.

Mirrors the reference ACL core (reference acl/policy.go rule model,
acl/acl.go enforcement semantics, agent/consul/acl_endpoint.go CRUD):
policies are rule documents over resource families — ``key``/
``key_prefix``, ``node``/``node_prefix``, ``service``/
``service_prefix``, ``session``/``session_prefix``, ``event``/
``event_prefix``, ``query``/``query_prefix``, ``agent``/
``agent_prefix``, plus the scalar ``operator``, ``keyring`` and
``acl`` switches — each granting ``read``/``write``/``deny``.
Rules may be written as the reference's HCL DSL (``key_prefix "foo/"
{ policy = "write" }``, parsed by utils/hcl) or as the equivalent
JSON object.

Enforcement semantics (acl/acl.go): an exact rule for the name wins;
otherwise the LONGEST matching prefix rule; otherwise the default
policy. When several policies on one token speak to the same rule,
``deny`` takes precedence over ``write`` over ``read``
(acl/policy_merger.go).

Tokens pair a public accessor id with a secret id and carry a policy
list; the builtin ``global-management`` policy grants everything
(agent/structs/acl.go ACLPolicyGlobalManagement), and the bootstrap
endpoint mints the first management token exactly once
(acl_endpoint.go Bootstrap / the reset-index escape hatch is out of
scope).
"""

from __future__ import annotations

from typing import Any, Optional, Union

RESOURCES = ("key", "node", "service", "session", "event", "query",
             "agent")
SCALARS = ("operator", "keyring", "acl")
ACCESS = ("deny", "read", "write")

MANAGEMENT_POLICY = "global-management"

# deny beats write beats read when policies collide on one rule
# (acl/policy_merger.go).
_PRECEDENCE = {"deny": 2, "write": 1, "read": 0}


def parse_rules(rules: Union[str, dict, None]) -> dict:
    """Rules document → validated {family: {name: access}} form.
    Accepts the HCL DSL or the equivalent dict; raises ValueError on
    unknown families/accesses (acl/policy.go parse validation)."""
    if rules is None or rules == "":
        return {}
    if isinstance(rules, str):
        from consul_tpu.utils import hcl
        doc = hcl.parse(rules)
    else:
        doc = rules
    out: dict = {}
    for fam, body in doc.items():
        base = fam[:-7] if fam.endswith("_prefix") else fam
        if fam in SCALARS:
            if body not in ACCESS:
                raise ValueError(f"bad {fam} policy {body!r}")
            out[fam] = body
            continue
        if base not in RESOURCES:
            raise ValueError(f"unknown ACL resource {fam!r}")
        if not isinstance(body, dict):
            raise ValueError(f"{fam} rules must be a block, got {body!r}")
        slot = out.setdefault(fam, {})
        for name, spec in body.items():
            pol = spec.get("policy") if isinstance(spec, dict) else spec
            if pol not in ACCESS:
                raise ValueError(f"bad policy {pol!r} for {fam} {name!r}")
            slot[name] = pol
    return out


class Authorizer:
    """Compiled rule set for one token (the merged view over its
    policies). ``allowed(resource, name, "read"|"write")``."""

    def __init__(self, policy_docs: list[dict],
                 default_allow: bool = True,
                 management: bool = False):
        self.default_allow = default_allow
        self.management = management
        self.exact: dict[str, dict[str, str]] = {r: {} for r in RESOURCES}
        self.prefix: dict[str, dict[str, str]] = {r: {} for r in RESOURCES}
        self.scalar: dict[str, str] = {}
        for doc in policy_docs:
            for fam, body in doc.items():
                if fam in SCALARS:
                    self._put(self.scalar, fam, body)
                    continue
                is_prefix = fam.endswith("_prefix")
                base = fam[:-7] if is_prefix else fam
                tgt = self.prefix[base] if is_prefix else self.exact[base]
                for name, pol in body.items():
                    self._put(tgt, name, pol)

    @staticmethod
    def _put(d: dict, k: str, pol: str):
        cur = d.get(k)
        if cur is None or _PRECEDENCE[pol] > _PRECEDENCE[cur]:
            d[k] = pol

    def _grants(self, access: Optional[str], want: str) -> Optional[bool]:
        if access is None:
            return None
        if access == "deny":
            return False
        return access == "write" or want == "read"

    def allowed_prefix(self, resource: str, prefix: str,
                       want: str = "read") -> bool:
        """Authorize an operation covering the WHOLE subtree under
        ``prefix`` (recursive KV reads/deletes, key listings) —
        reference acl.go KeyWritePrefix: the deepest prefix rule
        covering the subtree must grant it, and no rule *within* the
        subtree may refuse it. An exact-key grant never extends to
        the subtree."""
        if self.management:
            return True
        if resource in SCALARS:
            return self.allowed(resource, "", want)
        best = None
        for p in self.prefix[resource]:
            if prefix.startswith(p):
                if best is None or len(p) > len(best):
                    best = p
        base = (self._grants(self.prefix[resource][best], want)
                if best is not None else self.default_allow)
        if not base:
            return False
        for rules in (self.exact[resource], self.prefix[resource]):
            for name, pol in rules.items():
                if name.startswith(prefix) and \
                        not self._grants(pol, want):
                    return False
        return True

    def allowed(self, resource: str, name: str, want: str = "read") -> bool:
        if self.management:
            return True
        if resource in SCALARS:
            got = self._grants(self.scalar.get(resource), want)
            return self.default_allow if got is None else got
        got = self._grants(self.exact[resource].get(name), want)
        if got is not None:
            return got
        best = None
        for p in self.prefix[resource]:
            if name.startswith(p):
                if best is None or len(p) > len(best):
                    best = p
        if best is not None:
            return bool(self._grants(self.prefix[resource][best], want))
        return self.default_allow


def management_authorizer() -> Authorizer:
    return Authorizer([], default_allow=True, management=True)


def anonymous_authorizer(default_allow: bool) -> Authorizer:
    return Authorizer([], default_allow=default_allow)
