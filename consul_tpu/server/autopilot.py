"""Autopilot: server health scoring and dead-server cleanup.

Mirrors the reference autopilot subsystem (reference
agent/consul/autopilot/autopilot.go, structs.go): each server gets a
health verdict from its raft progress (leader contact recency, log
lag, term agreement); unhealthy *failed* servers are removed from the
raft configuration automatically, but only when removal cannot break
quorum (``canRemoveServers`` autopilot.go) — the guard that makes the
cleanup safe.

Membership change here is the simplified single-op reconfiguration of
raft-lite: the cluster driver removes the peer from every node's peer
list and the transport (the reference pipes this through raft
RemoveServer; the safety rule is the same).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from consul_tpu.server.raft import RaftCluster, RaftNode

# Reference defaults (agent/consul/config.go AutopilotConfig /
# autopilot/structs.go): contact threshold 200ms, max trailing logs 250,
# server stabilization time 10s before a non-voter earns suffrage.
LAST_CONTACT_THRESHOLD_TICKS = 10
MAX_TRAILING_LOGS = 250
SERVER_STABILIZATION_TICKS = 30

# The operator-settable subset (reference autopilot/structs.go Config;
# operator_autopilot_endpoint.go get/set). Stored raft-replicated in
# the state store's autopilot table; the Autopilot loop re-reads it
# each pass when wired with config_fn.
DEFAULT_AUTOPILOT_CONFIG = {
    "cleanup_dead_servers": True,
    "last_contact_threshold_ticks": LAST_CONTACT_THRESHOLD_TICKS,
    "max_trailing_logs": MAX_TRAILING_LOGS,
    "server_stabilization_ticks": SERVER_STABILIZATION_TICKS,
}


def fetch_stats(cluster: RaftCluster) -> dict[str, Optional[dict]]:
    """StatsFetcher (reference agent/consul/stats_fetcher.go:1-90): poll
    every server's raft stats ahead of the health evaluation. A stopped
    server doesn't answer (None) — the reference's fetch timeout."""
    out: dict[str, Optional[dict]] = {}
    for nid, node in cluster.nodes.items():
        if node.stopped:
            out[nid] = None
        else:
            out[nid] = {
                "last_index": node.last_log_index(),
                "term": node.term,
                "contact_age": node.ticks - node.last_contact_tick,
                "voter": node.voter,
                "is_leader": node.state == "leader",
            }
    return out


@dataclasses.dataclass
class ServerHealth:
    id: str
    healthy: bool
    voter: bool
    last_contact_ticks: Optional[int]
    trailing_logs: int
    reason: str = ""


def server_health(cluster: RaftCluster, node: RaftNode,
                  leader: RaftNode,
                  stats: Optional[dict] = None,
                  max_trailing: int = MAX_TRAILING_LOGS,
                  contact_threshold: int = LAST_CONTACT_THRESHOLD_TICKS,
                  ) -> ServerHealth:
    """Health verdict for one server from the leader's vantage point,
    scored from *fetched stats* (reference autopilot.go
    updateServerHealth consuming the StatsFetcher's ServerStats:
    last-index lag, term agreement, last leader contact). The
    thresholds are the operator-settable autopilot knobs."""
    st = (stats or fetch_stats(cluster)).get(node.id)
    if st is None:
        return ServerHealth(node.id, False, node.voter, None, 0,
                            "not responding")
    if node.id == leader.id:
        return ServerHealth(node.id, True, True, 0, 0)
    trailing = leader.last_log_index() - st["last_index"]
    if st["term"] != leader.term:
        return ServerHealth(node.id, False, node.voter, None, trailing,
                            f"term {st['term']} != leader term {leader.term}")
    if trailing > max_trailing:
        return ServerHealth(node.id, False, node.voter, None, trailing,
                            f"trailing {trailing} logs")
    if st["contact_age"] > contact_threshold:
        return ServerHealth(node.id, False, node.voter,
                            st["contact_age"], trailing,
                            f"no leader contact for {st['contact_age']} ticks")
    return ServerHealth(node.id, True, node.voter,
                        st["contact_age"], trailing)


def cluster_health(cluster: RaftCluster) -> list[ServerHealth]:
    leader = cluster.leader()
    if leader is None:
        return []
    stats = fetch_stats(cluster)
    return [server_health(cluster, n, leader, stats)
            for n in cluster.nodes.values()]


def can_remove_servers(n_peers: int, n_remove: int) -> bool:
    """Quorum-preservation guard (reference autopilot.go
    canRemoveServers): removal is allowed only while the remaining
    voters still form a majority of the *original* configuration."""
    remaining = n_peers - n_remove
    return remaining >= (n_peers // 2) + 1


def remove_server(cluster: RaftCluster, server_id: str) -> None:
    """Apply the membership change as a replicated configuration entry
    (reference raft RemoveServer appends a LogConfiguration entry):
    every member — including one crashed mid-change, which recovers
    the entry from its persisted log — drops the server from its peer
    list and voter set at append time. The transport-level cleanup
    (queues, node object) stays a cluster-harness concern."""
    from consul_tpu.server.raft import RAFT_CONFIG

    if server_id not in cluster.nodes:
        return
    led = cluster.wait_leader()
    led.propose({"type": RAFT_CONFIG, "op": "remove", "id": server_id})
    for _ in range(400):
        live = [n for n in cluster.nodes.values()
                if not n.stopped and n.id != server_id]
        if all(server_id not in n.voters and server_id not in n.peers
               for n in live):
            break
        cluster.step()
    node = cluster.nodes.pop(server_id, None)
    if node is not None:
        node.stop()
    cluster.transport.nodes.pop(server_id, None)
    cluster.transport.queues.pop(server_id, None)


def clean_dead_servers(cluster: RaftCluster, healths=None) -> list[str]:
    """Remove failed servers, quorum permitting (reference
    autopilot.go pruneDeadServers). Returns removed ids. Pass
    ``healths`` to reuse an evaluation already done this tick."""
    if healths is None:
        healths = cluster_health(cluster)
    elif cluster.leader() is None:
        return []
    dead = [h.id for h in healths
            if not h.healthy and h.reason == "not responding"]
    if not dead or not can_remove_servers(len(cluster.nodes), len(dead)):
        return []
    for sid in dead:
        remove_server(cluster, sid)
    return dead


class Autopilot:
    """The periodic autopilot loop with state: dead-server cleanup plus
    **non-voter promotion after a stabilization window** (reference
    agent/consul/autopilot/autopilot.go:256-320 promoteStableServers:
    a non-voter must be continuously healthy for ServerStabilizationTime
    before it earns suffrage; any unhealthy observation resets its
    clock)."""

    def __init__(self, cluster: RaftCluster,
                 stabilization_ticks: int = SERVER_STABILIZATION_TICKS,
                 cleanup_dead_servers: bool = True,
                 config_fn=None):
        self.cluster = cluster
        self.stabilization_ticks = stabilization_ticks
        self.cleanup_dead_servers = cleanup_dead_servers
        # Live operator configuration (reference autopilot reads the
        # raft-replicated config each pass): a callable returning the
        # current config dict, e.g. a Server's
        # Operator.AutopilotGetConfiguration.
        self.config_fn = config_fn
        self.max_trailing_logs = MAX_TRAILING_LOGS
        self.last_contact_threshold_ticks = LAST_CONTACT_THRESHOLD_TICKS
        self._ticks = 0
        self._healthy_since: dict[str, int] = {}
        self.promoted: list[str] = []
        self.removed: list[str] = []

    def run(self) -> None:
        """One autopilot pass (the leader's periodic serverHealthLoop,
        reference autopilot.go:73-120). Call at the cluster-step cadence."""
        self._ticks += 1
        if self.config_fn is not None:
            cfg = self.config_fn()
            self.stabilization_ticks = int(
                cfg.get("server_stabilization_ticks",
                        self.stabilization_ticks))
            self.cleanup_dead_servers = bool(
                cfg.get("cleanup_dead_servers", self.cleanup_dead_servers))
            self.max_trailing_logs = int(
                cfg.get("max_trailing_logs", self.max_trailing_logs))
            self.last_contact_threshold_ticks = int(
                cfg.get("last_contact_threshold_ticks",
                        self.last_contact_threshold_ticks))
        leader = self.cluster.leader()
        if leader is None:
            return
        stats = fetch_stats(self.cluster)
        healths = {
            h.id: h for h in (
                server_health(
                    self.cluster, n, leader, stats,
                    max_trailing=self.max_trailing_logs,
                    contact_threshold=self.last_contact_threshold_ticks)
                for n in self.cluster.nodes.values()
            )
        }
        # Stabilization bookkeeping for non-voters.
        for nid, h in healths.items():
            if h.voter:
                self._healthy_since.pop(nid, None)
                continue
            if not h.healthy:
                self._healthy_since.pop(nid, None)  # clock resets
                continue
            self._healthy_since.setdefault(nid, self._ticks)
        # Promote every non-voter that has been stable long enough.
        for nid, since in list(self._healthy_since.items()):
            if self._ticks - since >= self.stabilization_ticks:
                self.cluster.promote(nid)
                self.promoted.append(nid)
                del self._healthy_since[nid]
        if self.cleanup_dead_servers:
            self.removed.extend(
                clean_dead_servers(self.cluster, list(healths.values()))
            )
