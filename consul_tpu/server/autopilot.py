"""Autopilot: server health scoring and dead-server cleanup.

Mirrors the reference autopilot subsystem (reference
agent/consul/autopilot/autopilot.go, structs.go): each server gets a
health verdict from its raft progress (leader contact recency, log
lag, term agreement); unhealthy *failed* servers are removed from the
raft configuration automatically, but only when removal cannot break
quorum (``canRemoveServers`` autopilot.go) — the guard that makes the
cleanup safe.

Membership change here is the simplified single-op reconfiguration of
raft-lite: the cluster driver removes the peer from every node's peer
list and the transport (the reference pipes this through raft
RemoveServer; the safety rule is the same).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from consul_tpu.server.raft import RaftCluster, RaftNode

# Reference defaults (agent/consul/config.go AutopilotConfig /
# autopilot/structs.go): contact threshold 200ms, max trailing logs 250.
LAST_CONTACT_THRESHOLD_TICKS = 10
MAX_TRAILING_LOGS = 250


@dataclasses.dataclass
class ServerHealth:
    id: str
    healthy: bool
    voter: bool
    last_contact_ticks: Optional[int]
    trailing_logs: int
    reason: str = ""


def server_health(cluster: RaftCluster, node: RaftNode,
                  leader: RaftNode) -> ServerHealth:
    """Health verdict for one server from the leader's vantage point
    (reference autopilot.go updateServerHealth / queryServerHealth)."""
    if node.stopped:
        return ServerHealth(node.id, False, True, None, 0, "not responding")
    if node.id == leader.id:
        return ServerHealth(node.id, True, True, 0, 0)
    match = leader.match_index.get(node.id, 0)
    trailing = leader.last_log_index() - match
    if node.term != leader.term:
        return ServerHealth(node.id, False, True, None, trailing,
                            f"term {node.term} != leader term {leader.term}")
    if trailing > MAX_TRAILING_LOGS:
        return ServerHealth(node.id, False, True, None, trailing,
                            f"trailing {trailing} logs")
    return ServerHealth(node.id, True, True, 0, trailing)


def cluster_health(cluster: RaftCluster) -> list[ServerHealth]:
    leader = cluster.leader()
    if leader is None:
        return []
    return [server_health(cluster, n, leader)
            for n in cluster.nodes.values()]


def can_remove_servers(n_peers: int, n_remove: int) -> bool:
    """Quorum-preservation guard (reference autopilot.go
    canRemoveServers): removal is allowed only while the remaining
    voters still form a majority of the *original* configuration."""
    remaining = n_peers - n_remove
    return remaining >= (n_peers // 2) + 1


def remove_server(cluster: RaftCluster, server_id: str) -> None:
    """Apply the membership change: drop the server from every peer
    list and the transport (raft-lite's out-of-band reconfiguration)."""
    for node in cluster.nodes.values():
        if server_id in node.peers:
            node.peers.remove(server_id)
        node.next_index.pop(server_id, None)
        node.match_index.pop(server_id, None)
    node = cluster.nodes.pop(server_id, None)
    if node is not None:
        node.stop()
    cluster.transport.nodes.pop(server_id, None)
    cluster.transport.queues.pop(server_id, None)


def clean_dead_servers(cluster: RaftCluster) -> list[str]:
    """Remove failed servers, quorum permitting (reference
    autopilot.go pruneDeadServers). Returns removed ids."""
    leader = cluster.leader()
    if leader is None:
        return []
    dead = [h.id for h in cluster_health(cluster)
            if not h.healthy and h.reason == "not responding"]
    if not dead or not can_remove_servers(len(cluster.nodes), len(dead)):
        return []
    for sid in dead:
        remove_server(cluster, sid)
    return dead
