"""Host-side coordinate math for RTT estimation and nearness sorting.

The serializable coordinate format (``{"vec": [...], "error": e,
"height": h, "adjustment": a}``) matches the reference's
``coordinate.Coordinate`` struct (reference serf/coordinate/
coordinate.go:14-37); distances follow ``Coordinate.DistanceTo`` +
``lib.ComputeDistance`` (reference coordinate.go:121-132, lib/rtt.go:
13-19): Euclidean + both heights, plus both adjustments when the
adjusted value stays positive, infinity for nil/mismatched coordinates.

This is the read-side math behind ``consul rtt`` and catalog ``?near=``
sorting (reference command/rtt/rtt.go, agent/consul/rtt.go:21-221).

This module is the documented REFERENCE IMPLEMENTATION for the device
serving plane (``consul_tpu/serving`` + ``ops/serving.py``): the
batched NearestN/distance kernel must agree with ``compute_distance``
and ``sort_nodes_by_distance`` bit-for-bit in ordering — including the
+inf unknown-coordinate rule and the adjustment clamp — and the
golden-parity suite in tests/test_serving.py pins that agreement. Keep
behavior changes here mirrored in the kernel (and vice versa).
"""

from __future__ import annotations

import math
from typing import Optional


def compute_distance(a: Optional[dict], b: Optional[dict]) -> float:
    """Estimated RTT in seconds; +inf when either side is unknown
    (reference lib/rtt.go:13-19)."""
    if a is None or b is None:
        return math.inf
    va, vb = a["vec"], b["vec"]
    if len(va) != len(vb):
        return math.inf
    dist = math.sqrt(sum((x - y) ** 2 for x, y in zip(va, vb)))
    dist += a.get("height", 0.0) + b.get("height", 0.0)
    adjusted = dist + a.get("adjustment", 0.0) + b.get("adjustment", 0.0)
    return adjusted if adjusted > 0.0 else dist


def intersect(set_a: dict[str, dict], set_b: dict[str, dict]) -> tuple:
    """Pick comparable coordinates from two per-segment coordinate sets
    (reference lib/rtt.go:31-52 CoordinateSet.Intersect): use the
    default segment unless both sides share a named segment."""
    segment = ""
    if len(set_a) == 1 and "" not in set_a:
        segment = next(iter(set_a))
    if len(set_b) == 1 and "" not in set_b:
        segment = next(iter(set_b))
    return set_a.get(segment), set_b.get(segment)


def sort_nodes_by_distance(coord_sets: dict[str, dict[str, dict]],
                           source: str, rows: list[dict],
                           node_key: str = "node") -> list[dict]:
    """Stable-sort catalog/health rows by estimated RTT from ``source``
    (reference agent/consul/rtt.go:187-221 sortNodesByDistanceFrom).
    Unknown coordinates sort last (infinite distance)."""
    src_set = coord_sets.get(source)
    if not src_set:
        return rows

    def dist(row):
        other = coord_sets.get(row[node_key])
        if not other:
            return math.inf
        a, b = intersect(src_set, other)
        return compute_distance(a, b)

    return sorted(rows, key=dist)


def coord_sets_from_store(coords: list[dict]) -> dict[str, dict[str, dict]]:
    """Group store coordinate rows into per-node segment sets."""
    out: dict[str, dict[str, dict]] = {}
    for row in coords:
        out.setdefault(row["node"], {})[row.get("segment", "")] = row["coord"]
    return out
