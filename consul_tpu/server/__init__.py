"""Server tier: consensus, replicated state, and RPC endpoints.

The host-side control plane around the TPU data plane — the equivalent
of the reference's ``agent/consul`` server core (SURVEY.md §2.2). The
gossip/coordinate hot loops run as tensor programs (consul_tpu.models);
this package holds the parts the reference keeps transactional and
strongly consistent: the raft log, the FSM, the indexed state store
with watch-based blocking queries, and the RPC endpoint objects.
"""
