"""Serf→server plumbing: tags, member translation, the LAN event loop.

The reference wires gossip into the control plane in three pieces this
module reproduces (reference agent/consul/server_serf.go):

  - ``setupSerf`` stamps every server's serf member with tags — role,
    dc, id, port, expect, protocol versions (:33-113) — which is how
    servers find each other inside a mixed client/server member list
    (:func:`build_tags` / :func:`parse_tags`, the metadata.IsConsulServer
    contract);
  - ``lanEventHandler`` (:131) consumes serf member events and funnels
    them to ``maybeBootstrap`` (:236, bootstrap-expect) and — via
    ``reconcileCh`` — the leader's serf↔catalog reconciliation
    (:class:`LanEventHandler`);
  - the member list itself; here it comes from the *simulated* gossip
    plane: :func:`members_from_sim` reads one observer seat's view row
    (one batched device→host fetch) and translates each subject into
    the reconcile shape with serf's reap semantics applied — the bridge
    from the eventually-consistent data plane into the raft-backed
    catalog, closing the loop the reference closes through
    serf.Members().
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

from consul_tpu.config import SimConfig, to_ticks
from consul_tpu.models import coalesce
from consul_tpu.ops import merge
from consul_tpu.server.leader import reconcile

VSN_TAGS = {"vsn": "2", "vsn_min": "1", "vsn_max": "3",
            "raft_vsn": "3", "wan_join_port": "8302"}


def build_tags(node_id: str, dc: str = "dc1", server: bool = True,
               expect: int = 0, port: int = 8300,
               segment: str = "") -> dict[str, str]:
    """The setupSerf tag map (server_serf.go:33-113)."""
    tags = {"id": node_id, "dc": dc, "segment": segment, **VSN_TAGS}
    if server:
        tags["role"] = "consul"
        tags["port"] = str(port)
        if expect:
            tags["expect"] = str(expect)
    else:
        tags["role"] = "node"
    return tags


def parse_tags(member: dict) -> Optional[dict]:
    """metadata.IsConsulServer: a member's tags parsed into server
    attributes, or None for non-server members (clients)."""
    tags = member.get("tags", {})
    if tags.get("role") != "consul":
        return None
    try:
        return {
            "id": tags.get("id", member.get("name", "")),
            "dc": tags.get("dc", ""),
            "port": int(tags.get("port", 8300)),
            "expect": int(tags.get("expect", 0)),
        }
    except (TypeError, ValueError):
        return None  # malformed gossip tags never crash the handler


def members_from_sim(cfg: SimConfig, topo, serf_state, observer: int,
                     name_fn=None) -> list[dict]:
    """Translate one observer seat's membership view into reconcile's
    member-dict shape, with reap semantics (serf.go:1544-1568): dead
    past reconnect-timeout and left past tombstone-timeout report as
    "reap" so the catalog sweep deregisters them."""
    name_fn = name_fn or (lambda i: f"sim-{i}")
    s = serf_state
    g = cfg.gossip
    row = np.asarray(s.swim.view_key[observer])
    down = np.asarray(s.down_since[observer])
    t = int(s.swim.t)
    off = np.asarray(topo.off)
    reconnect = to_ticks(cfg.serf.reconnect_timeout_ms, g.tick_ms)
    tombstone = to_ticks(cfg.serf.tombstone_timeout_ms, g.tick_ms)
    n = cfg.n
    # The local node is always in its own member list (serf.Members()
    # includes self) — without it the reconcile reap sweep would
    # deregister the live observer.
    out = [{"name": name_fn(observer), "address": name_fn(observer),
            "status": "alive"}]
    for c in range(row.shape[0]):
        key = int(row[c])
        st = merge.key_status_int(key)
        if key == merge.UNKNOWN:
            continue  # never-heard subjects are not members yet
        down_ticks = (t - int(down[c])) if down[c] >= 0 else 0
        if st == merge.ALIVE or st == merge.SUSPECT:
            status = "alive"   # suspicion is not yet failure (leader
            #                    reconcile acts on serf's final states)
        elif st == merge.DEAD:
            status = "reap" if down_ticks > reconnect else "failed"
        else:  # LEFT
            status = "reap" if down_ticks > tombstone else "left"
        subject = (observer + int(off[c])) % n
        out.append({"name": name_fn(subject),
                    "address": name_fn(subject), "status": status})
    return out


def sync_coordinates(sim, server, seats: Iterable[int],
                     name_fn=None, flush_every: int = 512) -> int:
    """The agent coordinate loop for simulated seats (reference
    agent/agent.go:1891-1940 sendCoordinate -> Coordinate.Update):
    read the named seats' Vivaldi rows in one fused device->host fetch
    and stage them on the server's coordinate endpoint — the
    write-batching design of coordinate_endpoint.go:42-153 maps
    directly onto the tensor batch (SURVEY §2.5). Large seat sets are
    flushed every ``flush_every`` updates so the endpoint's rate
    limiter (batch_size x max_batches pending) never silently discards
    any; the returned count is therefore exactly what landed. A final
    ``server.flush_coordinates()`` commits the tail."""
    import jax

    name_fn = name_fn or (lambda i: f"sim-{i}")
    seats = list(seats)
    if not seats:
        return 0
    viv = sim.swim_state.viv
    idx = np.asarray(seats, dtype=np.int64)
    vecs, heights, errors, adjs = jax.device_get(
        (viv.vec[idx], viv.height[idx], viv.error[idx],
         viv.adjustment[idx]))
    staged = 0
    for i, seat in enumerate(seats):
        server.rpc(
            "Coordinate.Update", node=name_fn(seat),
            coord={"vec": [float(x) for x in vecs[i]],
                   "height": float(heights[i]),
                   "error": float(errors[i]),
                   "adjustment": float(adjs[i])},
        )
        staged += 1
        if staged % flush_every == 0:
            server.flush_coordinates()
    return staged


class LanEventHandler:
    """lanEventHandler (server_serf.go:131): consume member events,
    maintain the member map, feed bootstrap-expect and the leader's
    reconcile. Accepts the coalescer's Event stream, so bursts arrive
    already collapsed (serf wires the coalescer in front of the
    handler)."""

    def __init__(self, server, cluster=None):
        self.server = server
        self.cluster = cluster   # ServerCluster for maybe_bootstrap
        self.members: dict[str, dict] = {}

    def handle_events(self, events: Iterable[coalesce.Event]) -> list[int]:
        """Apply a batch of member events; returns reconcile indexes."""
        for e in events:
            if e.type == coalesce.MEMBER_JOIN:
                m = self.members.setdefault(
                    e.name, {"name": e.name, "tags": {}})
                m["status"] = "alive"
                if isinstance(e.payload, dict):
                    m["tags"] = e.payload
            elif e.type == coalesce.MEMBER_FAILED:
                self.members.setdefault(e.name, {"name": e.name})[
                    "status"] = "failed"
            elif e.type == coalesce.MEMBER_LEAVE:
                self.members.setdefault(e.name, {"name": e.name})[
                    "status"] = "left"
            elif e.type == coalesce.MEMBER_REAP:
                self.members.pop(e.name, None)
        member_list = list(self.members.values())
        if self.cluster is not None and not self.cluster.bootstrapped:
            self.cluster.maybe_bootstrap(member_list)
        if self.server.is_leader():
            return reconcile(self.server, [
                {"name": m["name"],
                 # Never clobber a known catalog address with "": the
                 # alive path re-registers when addresses differ.
                 "address": m.get("address")
                 or (self.server.store.get_node(m["name"]) or {}).get(
                     "address", ""),
                 "status": m.get("status", "alive")}
                for m in member_list
            ])
        return []
