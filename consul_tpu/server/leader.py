"""Leader duties: serf↔catalog reconciliation and session TTL expiry.

The reference leader runs a loop (reference agent/consul/leader.go:49,
:143) that, among ACL/CA duties out of scope here, keeps the raft-backed
catalog consistent with gossip-observed membership
(``reconcileMember`` leader.go:1065-1093) and expires session TTLs.

In the TPU framework the "serf members" come from the simulation's
membership views (consul_tpu.models.serf member state), so reconcile is
the bridge from the data plane's eventually-consistent world into the
strongly-consistent catalog — the same boundary the reference draws.
"""

from __future__ import annotations

import threading
import time
from typing import Iterable, Optional

from consul_tpu.server.endpoints import Server

SERF_HEALTH = "serfHealth"  # reference structs.SerfCheckID


def reconcile_member(server: Server, name: str, address: str, status: str):
    """Reconcile one member observation into the catalog (reference
    leader.go reconcileMember: handleAliveMember / handleFailedMember /
    handleLeftMember-handleReapMember).

    status: "alive" | "failed" | "left" | "reap"
    Returns the raft index of the write, or None when already in sync.
    """
    node = server.store.get_node(name)
    checks = {c["check_id"]: c for c in server.store.checks(node=name)}
    serf_check = checks.get(SERF_HEALTH)

    if status == "alive":
        if node is not None and serf_check is not None and \
                serf_check["status"] == "passing" and \
                node["address"] == address:
            return None
        return server.rpc(
            "Catalog.Register", node=name, address=address,
            check={"check_id": SERF_HEALTH, "status": "passing",
                   "output": "Agent alive and reachable"},
        )
    if status == "failed":
        if node is None:
            return None
        if serf_check is not None and serf_check["status"] == "critical":
            return None
        return server.rpc(
            "Catalog.Register", node=name, address=address or node["address"],
            check={"check_id": SERF_HEALTH, "status": "critical",
                   "output": "Agent not live or unreachable"},
        )
    if status in ("left", "reap"):
        if node is None:
            return None
        return server.rpc("Catalog.Deregister", node=name)
    raise ValueError(f"unknown member status {status!r}")


def reconcile(server: Server, members: Iterable[dict]) -> list[int]:
    """Reconcile a full member list; returns raft indexes of the writes
    issued (the lanEventHandler → reconcileCh → reconcile path,
    reference agent/consul/server_serf.go:131, leader.go:918-…)."""
    if not server.is_leader():
        return []
    t0 = time.perf_counter()
    indexes = []
    seen = set()
    for m in members:
        seen.add(m["name"])
        idx = reconcile_member(server, m["name"], m.get("address", ""),
                               m["status"])
        if idx is not None:
            indexes.append(idx)
    # Catalog nodes that serf has reaped entirely (absent from the
    # member list) but that still linger in the catalog: deregister.
    # Identified by their serfHealth check, so externally-registered
    # nodes (no agent, no serf check) are never touched (reference
    # reconcileReaped leader.go:992-1060).
    for check in server.store.checks():
        if check["check_id"] != SERF_HEALTH:
            continue
        if check["node"] in seen:
            continue
        idx = reconcile_member(server, check["node"], "", "reap")
        if idx is not None:
            indexes.append(idx)
    sink = getattr(server, "sink", None)
    if sink is not None:
        # Reference metrics.MeasureSince([]string{"leader", "reconcile"},
        # ...) around the member sweep (leader.go:918).
        sink.measure_since("consul.leader.reconcile", t0)
    return indexes


class SessionTimers:
    """Leader-side session TTL tracking (reference leader.go
    initializeSessionTimers / resetSessionTimer): sessions with a TTL
    are destroyed ``2 * ttl`` after their last renew (the reference's
    lenient multiplier)."""

    TTL_MULTIPLIER = 2.0  # reference session_ttl.go

    def __init__(self, server: Server, now: Optional[float] = None):
        self.server = server
        self.deadlines: dict[str, float] = {}
        # Renews arrive on HTTP handler threads while the agent pump
        # runs tick() — the deadline map is shared mutable state.
        self._lock = threading.Lock()
        now = time.monotonic() if now is None else now
        for s in server.store.session_list():
            if s.get("ttl_s", 0) > 0:
                self.deadlines[s["id"]] = now + s["ttl_s"] * self.TTL_MULTIPLIER

    def renew(self, session_id: str, now: Optional[float] = None):
        s = self.server.store.session_get(session_id)
        if s is None or s.get("ttl_s", 0) <= 0:
            return
        now = time.monotonic() if now is None else now
        with self._lock:
            self.deadlines[session_id] = now + s["ttl_s"] * self.TTL_MULTIPLIER

    def expire(self, now: Optional[float] = None) -> list[str]:
        """Destroy sessions past their deadline; returns their ids."""
        now = time.monotonic() if now is None else now
        expired = []
        with self._lock:
            for sid in [s for s, dl in self.deadlines.items() if dl <= now]:
                # Re-check under the lock: a renew that raced in since
                # the scan keeps the session (its client got a 200).
                if self.deadlines.get(sid, now + 1) <= now:
                    del self.deadlines[sid]
                    expired.append(sid)
        for sid in expired:
            if self.server.store.session_get(sid) is not None:
                self.server.rpc("Session.Apply", op="destroy", session_id=sid)
        return expired

    def tick(self, now: Optional[float] = None) -> list[str]:
        """One leader-loop pass: start tracking TTL sessions created
        since the last pass (the reference arms a timer at session
        apply, session_ttl.go resetSessionTimer — here a scan picks
        them up), then expire. Returns expired ids."""
        now = time.monotonic() if now is None else now
        sessions = self.server.store.session_list()
        live = {s["id"] for s in sessions}
        with self._lock:
            for s in sessions:
                if s.get("ttl_s", 0) > 0 and s["id"] not in self.deadlines:
                    self.deadlines[s["id"]] = \
                        now + s["ttl_s"] * self.TTL_MULTIPLIER
            # Deadlines for sessions destroyed through other paths
            # (explicit destroy, node dereg cascade) retire silently.
            for sid in [x for x in self.deadlines if x not in live]:
                del self.deadlines[sid]
        return self.expire(now)
