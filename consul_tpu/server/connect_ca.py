"""Connect CA: certificate authority for service identities.

Mirrors the reference's built-in Consul CA provider (reference
agent/consul/connect_ca_endpoint.go + agent/connect/ca/
provider_consul.go + agent/connect/spiffe.go): an EC P-256 root
certificate per cluster with a SPIFFE trust-domain URI SAN, leaf
certificates for services carrying ``spiffe://<trust-domain>/ns/
default/dc/<dc>/svc/<service>`` identities, and root rotation through
the CA configuration endpoint.

Crypto is real (the ``cryptography`` package): generated certs verify
with any X.509 stack. Division of labor mirrors the raft rules the
reference follows — key/cert GENERATION happens at the endpoint
(once, like pre-assigned session ids: an FSM must never generate
randomness), and the raft log carries the finished PEM material so
every replica stores identical roots.

Simplifications vs the reference, documented: rotation activates the
new root immediately without the cross-signing intermediate window,
and leaf private keys are generated server-side (the reference's
agent generates a CSR locally; the wire trust boundary is the same
HTTPS hop either way here).
"""

from __future__ import annotations

import datetime
import hashlib
import uuid
from typing import Optional

# Optional dependency: server/endpoints.py imports this module lazily,
# and a crypto-less environment must still collect/serve everything
# except the Connect CA itself (HAVE_CRYPTOGRAPHY gates; every cert
# operation below raises RuntimeError when it is missing).
try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID
    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover — crypto-less environment
    HAVE_CRYPTOGRAPHY = False
    x509 = hashes = serialization = ec = NameOID = None

DEFAULT_ROOT_TTL_S = 10 * 365 * 24 * 3600.0   # reference: 10 years
DEFAULT_LEAF_TTL_S = 72 * 3600.0              # reference: 72h


def _require_crypto():
    if not HAVE_CRYPTOGRAPHY:
        raise RuntimeError(
            "the Connect CA requires the 'cryptography' package")


def trust_domain(cluster_id: str) -> str:
    return f"{cluster_id}.consul"


def _key_pem(key) -> str:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    ).decode()


def _cert_pem(cert) -> str:
    return cert.public_bytes(serialization.Encoding.PEM).decode()


def generate_root(cluster_id: str,
                  ttl_s: float = DEFAULT_ROOT_TTL_S) -> dict:
    """A self-signed EC P-256 root with the SPIFFE trust-domain URI
    SAN (provider_consul.go GenerateRoot)."""
    _require_crypto()
    td = trust_domain(cluster_id)
    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    name = x509.Name([x509.NameAttribute(
        NameOID.COMMON_NAME, f"Consul CA {td}")])
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(seconds=ttl_s))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .add_extension(x509.KeyUsage(
            digital_signature=True, key_cert_sign=True, crl_sign=True,
            content_commitment=False, key_encipherment=False,
            data_encipherment=False, key_agreement=False,
            encipher_only=False, decipher_only=False), critical=True)
        .add_extension(x509.SubjectAlternativeName(
            [x509.UniformResourceIdentifier(f"spiffe://{td}")]),
            critical=False)
        .sign(key, hashes.SHA256())
    )
    cert_pem = _cert_pem(cert)
    return {
        "id": root_id(cert_pem),
        "name": f"Consul CA Root Cert",
        "root_cert": cert_pem,
        "private_key": _key_pem(key),
        "trust_domain": td,
        "serial_number": cert.serial_number,
        "not_after": cert.not_valid_after_utc.isoformat(),
    }


def root_id(cert_pem: str) -> str:
    """Stable root identifier (the reference hashes the cert)."""
    return hashlib.sha256(cert_pem.encode()).hexdigest()[:32]


def spiffe_id(td: str, dc: str, service: str) -> str:
    return f"spiffe://{td}/ns/default/dc/{dc}/svc/{service}"


def sign_leaf(root: dict, service: str, dc: str,
              ttl_s: float = DEFAULT_LEAF_TTL_S) -> dict:
    """Mint a leaf for ``service`` signed by ``root`` (the Sign RPC +
    the agent leaf endpoint, connect_ca_endpoint.go Sign)."""
    _require_crypto()
    ca_key = serialization.load_pem_private_key(
        root["private_key"].encode(), password=None)
    ca_cert = x509.load_pem_x509_certificate(root["root_cert"].encode())
    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    uri = spiffe_id(root["trust_domain"], dc, service)
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([x509.NameAttribute(
            NameOID.COMMON_NAME, service)]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(seconds=ttl_s))
        .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                       critical=True)
        .add_extension(x509.KeyUsage(
            digital_signature=True, key_encipherment=True,
            key_cert_sign=False, crl_sign=False,
            content_commitment=False, data_encipherment=False,
            key_agreement=False, encipher_only=False,
            decipher_only=False), critical=True)
        .add_extension(x509.ExtendedKeyUsage(
            [x509.ExtendedKeyUsageOID.CLIENT_AUTH,
             x509.ExtendedKeyUsageOID.SERVER_AUTH]), critical=False)
        .add_extension(x509.SubjectAlternativeName(
            [x509.UniformResourceIdentifier(uri)]), critical=False)
        .sign(ca_key, hashes.SHA256())
    )
    return {
        "serial_number": format(cert.serial_number, "x"),
        "cert_pem": _cert_pem(cert),
        "private_key_pem": _key_pem(key),
        "service": service,
        "spiffe_id": uri,
        "valid_after": cert.not_valid_before_utc.isoformat(),
        "valid_before": cert.not_valid_after_utc.isoformat(),
        "root_id": root["id"],
    }


def verify_leaf(leaf_cert_pem: str, root_cert_pem: str) -> bool:
    """Does the leaf chain to the root? (test/diagnostic helper)."""
    _require_crypto()
    leaf = x509.load_pem_x509_certificate(leaf_cert_pem.encode())
    root = x509.load_pem_x509_certificate(root_cert_pem.encode())
    try:
        leaf.verify_directly_issued_by(root)
        return True
    except Exception:  # noqa: BLE001 — any failure = not verified
        return False


def new_cluster_id() -> str:
    return str(uuid.uuid4())
