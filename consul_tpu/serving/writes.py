"""WriteBatcher: coalesce concurrent catalog/KV/session writes into
fixed-shape device batches with admission control.

The write-side sibling of :class:`~consul_tpu.serving.batcher.
QueryBatcher` — same power-bucketed park-and-pump shape (no background
thread: ``submit()`` parks up to ``max_wait_s`` and whoever expires
first pumps EVERY pending write as one batch), but the batch is a
:class:`~consul_tpu.ops.deltas.WriteBatch` applied to the plane's
device-resident :class:`~consul_tpu.ops.deltas.WriteState` through the
jitted leader-apply kernel. Applied writes become visible to readers
ONLY at the next snapshot flip (``ServingPlane.publish``): the batcher
advances the *pending* write state, the flip captures it, and the
response's ``index`` tells the caller which ``X-Consul-Index`` its
effect is consistent as of.

Admission control (the ISSUE's backpressure contract): the pending
queue is bounded at ``max_pending``. Policy ``reject`` refuses the NEW
submit with :class:`ServingOverloadError`; policy ``shed_oldest``
completes the OLDEST parked waiter with a ``shed`` result and admits
the new one. Both paths count — ``sim.serving.{writes,write_batches,
rejected,shed}`` — so saturation is visible, never silent.

String KV keys live on the host in :class:`KeyTable` (stable key ->
slot allocation, bounded by the write state's slot axis); the device
KV models one i32 payload word per slot (documented narrowing,
``ops/deltas.py``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import NamedTuple, Optional, Sequence

import numpy as np

from consul_tpu.analysis import ledger
from consul_tpu.obs import trace as obs_trace
from consul_tpu.ops import deltas
from consul_tpu.serving.batcher import (ServingClosedError,
                                        ServingOverloadError)


class WriteResult(NamedTuple):
    """One write's outcome. ``index`` is the device apply index
    assigned to the op (for ``applied`` results, the snapshot index
    the write becomes visible at); ``status`` is ``applied`` /
    ``rejected`` (invalid op, e.g. out-of-range target) / ``shed``
    (dropped by admission control before reaching the device)."""

    applied: bool
    index: int
    status: str


class KeyTable:
    """Stable host-side string-key -> device-slot allocation. Slots are
    never recycled (a deleted key keeps its slot so a later re-put
    diffs as the same watch target); allocation past ``slots`` returns
    -1 and the batcher surfaces it as overload."""

    def __init__(self, slots: int):
        self.slots = int(slots)
        self._by_key: dict[str, int] = {}
        self._by_slot: list[str] = []
        self._lock = ledger.make_lock("KeyTable._lock")

    def slot_for(self, key: str, create: bool = False) -> int:
        with self._lock:
            i = self._by_key.get(key, -1)
            if i < 0 and create and len(self._by_slot) < self.slots:
                i = len(self._by_slot)
                self._by_key[key] = i
                self._by_slot.append(key)
            return i

    def key_of(self, slot: int) -> Optional[str]:
        if 0 <= slot < len(self._by_slot):
            return self._by_slot[slot]
        return None

    def __len__(self) -> int:
        return len(self._by_slot)


class _WriteWaiter:
    __slots__ = ("op", "target", "arg", "done", "result", "error")

    def __init__(self, op: int, target: int, arg: int):
        self.op = op
        self.target = target
        self.arg = arg
        self.done = threading.Event()
        self.result: Optional[WriteResult] = None
        self.error: Optional[Exception] = None


class WriteBatcher:
    """Packs (op, target, arg) writes into padded bucketed batches and
    applies each as one ``deltas.apply_writes`` launch against
    ``plane.write_state``."""

    def __init__(self, plane, buckets: Sequence[int] = (1, 8, 64),
                 max_wait_s: float = 0.002, max_pending: int = 1024,
                 policy: str = "reject"):
        if not buckets:
            raise ValueError("need at least one batch bucket")
        if policy not in ("reject", "shed_oldest"):
            raise ValueError(f"unknown admission policy {policy!r}")
        self.plane = plane
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.max_batch = self.buckets[-1]
        self.max_wait_s = float(max_wait_s)
        self.max_pending = int(max_pending)
        self.policy = policy
        self._lock = ledger.make_lock("WriteBatcher._lock")
        self._pending: list[_WriteWaiter] = []
        self._closed = False
        # Plain-int counters mirror the sink emissions (stats() without
        # a sink attached, the QueryBatcher discipline).
        self.writes = 0
        self.write_batches = 0
        self.rejected = 0
        self.shed = 0
        self.padded_slots = 0
        self.latencies_s: deque[float] = deque(maxlen=4096)

    # ------------------------------------------------------------------
    # Synchronous batched path
    # ------------------------------------------------------------------
    def execute(self, ops: Sequence[tuple[int, int, int]]
                ) -> list[WriteResult]:
        """Apply a caller-assembled batch of (op, target, arg);
        oversize inputs chunk at the largest bucket. One kernel launch
        + one device_get per chunk."""
        out: list[WriteResult] = []
        for i in range(0, len(ops), self.max_batch):
            out.extend(self._run_batch(ops[i:i + self.max_batch]))
        return out

    def _bucket(self, b: int) -> int:
        for cap in self.buckets:
            if cap >= b:
                return cap
        return self.max_batch

    def _run_batch(self, ops: Sequence[tuple[int, int, int]]
                   ) -> list[WriteResult]:
        # With the raft tier armed (models/raft.py), the batch becomes
        # a proposal instead of an immediate apply: the gate stages it
        # on a raft group, returns provisional ``proposed`` results,
        # and the commit pump calls back into ``_apply_batch`` ONLY
        # once a quorum holds the entries — so the apply index
        # (X-Consul-Index) moves strictly at quorum commit and an
        # acknowledged index survives leader loss by construction.
        gate = getattr(self.plane, "raft_gate", None)
        if gate is not None:
            return gate.stage(self, ops)
        return self._apply_batch(ops)

    def _apply_batch(self, ops: Sequence[tuple[int, int, int]]
                     ) -> list[WriteResult]:
        import jax

        t0 = time.perf_counter()
        b = len(ops)
        bucket = self._bucket(b)
        op = np.full(bucket, deltas.OP_NOOP, dtype=np.int32)
        tgt = np.zeros(bucket, dtype=np.int32)
        arg = np.full(bucket, -1, dtype=np.int32)
        for j, (o, t, a) in enumerate(ops):
            op[j] = o
            tgt[j] = t
            arg[j] = a
        do, dt, da = jax.device_put((op, tgt, arg))
        batch = deltas.WriteBatch(op=do, target=dt, arg=da)
        # The plane serializes batches against flips: apply_writes
        # consumes the CURRENT pending state and installs its
        # successor atomically under the plane's write lock.
        with self.plane.write_lock:
            ws = self.plane.write_state
            new_ws, applied, idx = deltas.apply_writes(ws, batch)
            self.plane.write_state = new_ws
        h_applied, h_idx = jax.device_get((applied, idx))

        n_applied = int(h_applied[:b].sum())
        pad = bucket - b
        # _apply_batch runs from caller threads AND the raft commit
        # pump; the counters share self._lock with submit()'s admission
        # bookkeeping (TH114). The device_get above stays outside it.
        with self._lock:
            self.latencies_s.append(time.perf_counter() - t0)
            self.writes += n_applied
            self.rejected += b - n_applied
            self.write_batches += 1
            self.padded_slots += pad
        sink = getattr(self.plane, "sink", None)
        if sink is not None:
            sink.incr_counter("sim.serving.write_batches", 1)
            if n_applied:
                sink.incr_counter("sim.serving.writes", n_applied)
            if b - n_applied:
                sink.incr_counter("sim.serving.rejected", b - n_applied)
        # Thread the apply index through the sim's GossipCounters fold:
        # cumulative counters["writes_applied"] IS the device apply
        # index, so counter snapshots and bench artifacts carry it.
        self.plane.fold_write_counters(n_applied)

        return [WriteResult(applied=bool(h_applied[j]),
                            index=int(h_idx[j]),
                            status="applied" if h_applied[j]
                            else "rejected")
                for j in range(b)]

    def count_rejected(self, n: int = 1) -> None:
        """Record ``n`` rejections decided outside the batcher (e.g.
        the plane's CAS admission check) under the counter lock."""
        with self._lock:
            self.rejected += n

    # ------------------------------------------------------------------
    # Concurrent submit/fan-out path with admission control
    # ------------------------------------------------------------------
    def submit(self, op: int, target: int, arg: int = -1,
               timeout_s: float = 10.0) -> WriteResult:
        """Enqueue one write and block for its outcome. Concurrent
        submitters coalesce exactly like QueryBatcher.submit; a full
        pending queue triggers the admission policy instead of
        unbounded growth."""
        w = _WriteWaiter(int(op), int(target), int(arg))
        to_shed: Optional[_WriteWaiter] = None
        with self._lock:
            if self._closed:
                raise ServingClosedError("serving write plane is closed")
            if len(self._pending) >= self.max_pending:
                if self.policy == "reject":
                    self.rejected += 1
                    sink = getattr(self.plane, "sink", None)
                    if sink is not None:
                        sink.incr_counter("sim.serving.rejected", 1)
                    raise ServingOverloadError(
                        f"write queue full ({self.max_pending} pending, "
                        "policy=reject)")
                to_shed = self._pending.pop(0)
                self.shed += 1
            self._pending.append(w)
            full = len(self._pending) >= self.max_batch
        if to_shed is not None:
            sink = getattr(self.plane, "sink", None)
            if sink is not None:
                sink.incr_counter("sim.serving.shed", 1)
            to_shed.result = WriteResult(applied=False, index=0,
                                         status="shed")
            to_shed.done.set()
        if full:
            self.pump()
        deadline = time.monotonic() + timeout_s
        while not w.done.wait(self.max_wait_s):
            if time.monotonic() >= deadline:
                raise TimeoutError("serving write timed out")
            self.pump()
        if w.error is not None:
            raise w.error
        assert w.result is not None
        return w.result

    def pump(self) -> int:
        """Drain pending waiters (up to one max bucket) into one
        apply; returns how many were served."""
        with self._lock:
            batch = self._pending[:self.max_batch]
            del self._pending[:len(batch)]
        if not batch:
            return 0
        with obs_trace.span("serving.write_pump", cat="serving",
                            args={"n": len(batch)}):
            results = self._run_batch(
                [(w.op, w.target, w.arg) for w in batch])
        for w, r in zip(batch, results):
            w.result = r
            w.done.set()
        return len(batch)

    # ------------------------------------------------------------------
    # Shutdown (shared discipline with QueryBatcher.close)
    # ------------------------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        with self._lock:
            self._closed = True
            pending, self._pending = self._pending, []
        for w in pending:
            w.error = ServingClosedError("serving plane closed while "
                                         "write was pending")
            w.done.set()

    # ------------------------------------------------------------------
    # Stats
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        lats = sorted(self.latencies_s)
        if lats:
            p50 = lats[len(lats) // 2]
            p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))]
        else:
            p50 = p99 = 0.0
        return {
            "writes": self.writes,
            "write_batches": self.write_batches,
            "rejected": self.rejected,
            "shed": self.shed,
            "padded_slots": self.padded_slots,
            "p50_batch_ms": round(p50 * 1e3, 3),
            "p99_batch_ms": round(p99 * 1e3, 3),
        }
