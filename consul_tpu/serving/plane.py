"""ServingPlane: double-buffered device snapshots + high-level reads.

The plane owns two snapshot slots and an index to the current one;
``publish`` projects live state into the idle slot and then atomically
swaps the index. Readers that grabbed the previous snapshot keep using
it — JAX arrays are immutable, so a reader's view stays coherent as of
its snapshot's tick while the simulation (and future publishes) race
ahead. Readers never block the scan loop and never observe a torn
state.

Two sources can feed a plane (one per instance, never both):

* **sim** — attached to a ``models/cluster.py`` Simulation; the scan
  loop republishes at every chunk boundary (``publish_serving``).
  Queries address nodes by simulation index.
* **host** — built from server-store coordinate rows
  (``publish_coords``); this is what backs catalog/health ``?near=``
  sorting and prepared-query NearestN. Queries address nodes by name.
  Coordinate sets using named segments fall back to the host
  ``server/rtt.py`` reference path (documented narrowing: the device
  snapshot models one default-segment coordinate per node).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Sequence

import numpy as np

from consul_tpu.analysis import ledger
from consul_tpu.ops import serving as kernels
from consul_tpu.serving.batcher import QueryBatcher, QueryResult


class NearestResult(NamedTuple):
    """A NearestN answer with ids resolved to the plane's addressing
    (simulation indices or node names)."""

    nodes: list          # [(node, rtt_s)] ascending RTT, len == count
    count: int
    tick: int


class ServingPlane:
    def __init__(self, k: int = 16,
                 buckets: Sequence[int] = (1, 8, 64, 512),
                 max_wait_s: float = 0.002, sink=None,
                 num_services: int = 0):
        self.k = int(k)
        self.sink = sink
        # Synthetic service labels for sim mode: node i -> service
        # i mod num_services (0/1 = single unlabeled service). Enough
        # to exercise health-filtered service lookups at scale; the
        # host mode carries real store rows instead.
        self.num_services = int(num_services)
        self.batcher = QueryBatcher(self, k=k, buckets=buckets,
                                    max_wait_s=max_wait_s)
        # Double buffer: write the idle slot, then swap the index.
        self._slots: list = [None, None]
        self._cur = -1
        self._source: Optional[str] = None  # "sim" | "host"
        self._service_labels = None  # cached per-n device labels (sim)
        self._labels_key = None      # (n, mesh fingerprint) of the cache
        # Device mesh of the attached simulation (None = single-device
        # or host mode). Refreshed on every publish so an elastic
        # reshard retargets the two-stage kernel automatically.
        self._mesh = None
        self.cache_hits = 0
        self._sim = None
        self._closed = False
        # Write path (attach_writes): the PENDING device WriteState the
        # WriteBatcher advances between flips, the (snapshot,
        # write-state) pair captured AT the current flip (what readers
        # and the watch diff see), and the host-side key table.
        self.write_state = None
        self.write_lock = ledger.make_lock("ServingPlane.write_lock")
        self.writes = None   # WriteBatcher
        self.watch = None    # WatchPlane
        self.keys = None     # KeyTable
        self._flip_pair = None  # (Snapshot, WriteState) as of last flip
        # Host-mode name table (publish_coords).
        self._names: tuple[str, ...] = ()
        self._name_idx: dict[str, int] = {}
        self._host_fp = None
        self._host_d = 0
        self._host_version = 0
        self._host_usable: dict[str, bool] = {}

    # ------------------------------------------------------------------
    # Snapshot publication
    # ------------------------------------------------------------------
    def snapshot(self) -> kernels.Snapshot:
        if self._cur < 0:
            raise RuntimeError("serving plane has no published snapshot")
        return self._slots[self._cur]

    @property
    def tick(self) -> int:
        import jax

        return int(jax.device_get(self.snapshot().tick))

    def _flip(self, snap: kernels.Snapshot) -> None:
        idle = 1 - self._cur if self._cur >= 0 else 0
        self._slots[idle] = snap
        self._cur = idle

    def attach(self, sim) -> None:
        """Bind to a Simulation: adopt its sink, register on the sim so
        the scan loop republishes each chunk, and publish now."""
        if self._source == "host":
            raise RuntimeError("plane already serves host coordinates")
        self._source = "sim"
        self._sim = sim
        if self.sink is None:
            self.sink = getattr(sim, "sink", None)
        sim.serving = self
        self.publish(sim)

    def publish(self, sim) -> None:
        """Project the sim's current state into the idle buffer and
        swap. Called by the scan loop at chunk boundaries; one jitted
        projection, no host round-trip."""
        self._mesh = getattr(sim, "mesh", None)
        self.publish_state(sim.swim_state)

    def publish_state(self, state) -> None:
        from consul_tpu.parallel.mesh import mesh_key

        n = state.alive_truth.shape[0]
        if self.write_state is not None:
            # Write plane attached: snapshot labels come from the
            # device write state (the merge point — a write becomes
            # visible to readers exactly here, at the flip). Capture
            # the pending state atomically against concurrent batches.
            from consul_tpu.ops import deltas as deltas_mod

            with self.write_lock:
                ws = self.write_state
            snap = kernels.project(state, deltas_mod.labels_of(ws))
            self._flip(snap)
            prev = self._flip_pair
            self._flip_pair = (snap, ws)
            if self.watch is not None:
                self.watch.on_flip(prev, self._flip_pair)
            return
        labels = self._synthetic_labels(n, mesh_key(self._mesh))
        self._flip(kernels.project(state, labels))

    def _synthetic_labels(self, n: int, mkey):
        """Cached sim-mode service labels (node i -> i mod
        num_services), node-axis placed under a mesh."""
        import jax.numpy as jnp

        labels = self._service_labels
        lk = (n, mkey)
        if labels is None or self._labels_key != lk:
            if self.num_services > 1:
                labels = (jnp.arange(n, dtype=jnp.int32)
                          % jnp.int32(self.num_services))
            else:
                labels = jnp.zeros(n, dtype=jnp.int32)
            if self._mesh is not None:
                # Explicit node-axis placement: an unsharded [N] label
                # array next to a sharded state would replicate on
                # every chip (the TH110 hazard).
                from consul_tpu.parallel import shard_step

                labels = shard_step.place(self._mesh, labels, n)
            self._service_labels = labels
            self._labels_key = lk
        return labels

    def kernel(self):
        """The batch executor the QueryBatcher runs: the two-stage
        shard_map top-k (ops/serving.sharded_kernel_for) when the
        attached simulation is mesh-sharded and the node axis divides
        the shards, else the single-device kernel. Same signature and
        result contract either way."""
        mesh = self._mesh
        if mesh is not None and self._cur >= 0:
            from consul_tpu.parallel.mesh import node_axes

            n = int(self.snapshot().height.shape[0])
            _, shards = node_axes(mesh)
            if n % shards == 0 and shards > 1:
                return kernels.sharded_kernel_for(self.k, mesh)
        return kernels.kernel_for(self.k)

    # ------------------------------------------------------------------
    # Write path + watch plane (consul_tpu/serving/writes.py, watch.py)
    # ------------------------------------------------------------------
    def attach_writes(self, kv_slots: int = 256,
                      buckets: Sequence[int] = (1, 8, 64),
                      max_wait_s: float = 0.002, max_pending: int = 1024,
                      policy: str = "reject", watch_k: int = 64,
                      watch_queue: int = 256) -> None:
        """Attach the device write path + watch plane to a sim-backed
        plane: build the initial WriteState (every sim seat registered
        with its synthetic label, so no read changes until the first
        write), place its [N] leaves through the sim's node funnel
        (``cluster._place_node`` — sharded under a mesh, never
        replicated), and republish so the first flip carries it."""
        import jax

        from consul_tpu.ops import deltas as deltas_mod
        from consul_tpu.serving.watch import WatchPlane
        from consul_tpu.serving.writes import KeyTable, WriteBatcher

        if self._source != "sim" or self._sim is None:
            raise RuntimeError(
                "write plane needs a sim-attached serving plane "
                "(host-coordinate planes serve reads only)")
        if self.write_state is not None:
            raise RuntimeError("write plane already attached")
        sim = self._sim
        n = sim.cfg.n
        labels = np.arange(n, dtype=np.int32) % max(self.num_services, 1)
        host_ws = deltas_mod.init_state(n, kv_slots, service=labels)
        place = getattr(sim, "_place_node", None)
        if place is not None:
            kv_used, kv_val, kv_ver, aidx = jax.device_put(
                (host_ws.kv_used, host_ws.kv_val, host_ws.kv_ver,
                 host_ws.apply_index))
            ws = deltas_mod.WriteState(
                service=place(host_ws.service),
                registered=place(host_ws.registered),
                session=place(host_ws.session),
                kv_used=kv_used, kv_val=kv_val, kv_ver=kv_ver,
                apply_index=aidx)
        else:
            ws = jax.device_put(host_ws)
        self.write_state = ws
        self.keys = KeyTable(kv_slots)
        self.writes = WriteBatcher(self, buckets=buckets,
                                   max_wait_s=max_wait_s,
                                   max_pending=max_pending, policy=policy)
        self.watch = WatchPlane(self, k=watch_k, max_queue=watch_queue)
        self.publish(sim)

    def has_writes(self) -> bool:
        return self.write_state is not None

    @property
    def raft_gate(self):
        """The attached sim's RaftPlane when its raft tier is armed
        (``Simulation.set_raft``) and the write path is up — the
        WriteBatcher then stages batches as raft proposals and the
        commit pump applies them at quorum commit (serving/writes.py
        ``_run_batch``). None routes writes straight to the apply
        kernel, the pre-raft behavior byte for byte."""
        if self._sim is None or self.write_state is None:
            return None
        return getattr(self._sim, "raft", None)

    @property
    def apply_index(self) -> int:
        """The device apply index the CURRENT flip is consistent as of
        (0 before the first write-attached flip) — what the HTTP tier
        serves as ``X-Consul-Index``."""
        return self.watch.apply_index if self.watch is not None else 0

    def fold_write_counters(self, n_applied: int) -> None:
        """Thread applied-write tallies into the attached sim's
        GossipCounters fold: cumulative ``counters['writes_applied']``
        equals the device apply index (and flows to the telemetry sink
        under the METRIC_NAMES mapping like every device counter)."""
        if n_applied and self._sim is not None:
            fold = getattr(self._sim, "_fold_counter_deltas", None)
            if fold is not None:
                fold({"writes_applied": int(n_applied)})

    # -- host-friendly write/read verbs (sim addressing) ----------------
    def register(self, node: int, service: int, **kw):
        """Catalog register: label ``node`` with ``service``. Visible
        to reads at the next flip; the result carries the apply index
        that flip will be consistent as of."""
        from consul_tpu.ops import deltas as deltas_mod

        return self.writes.submit(deltas_mod.OP_REGISTER, node, service,
                                  **kw)

    def deregister(self, node: int, **kw):
        from consul_tpu.ops import deltas as deltas_mod

        return self.writes.submit(deltas_mod.OP_DEREGISTER, node, **kw)

    def kv_put(self, key: str, value: int, **kw):
        """Device KV put: one i32 payload word per string key (the
        documented ops/deltas.py narrowing). A full slot table is an
        admission failure, not silence."""
        from consul_tpu.ops import deltas as deltas_mod
        from consul_tpu.serving.batcher import ServingOverloadError

        slot = self.keys.slot_for(key, create=True)
        if slot < 0:
            self.writes.count_rejected()
            if self.sink is not None:
                self.sink.incr_counter("sim.serving.rejected", 1)
            raise ServingOverloadError(
                f"kv slot table full ({self.keys.slots} slots)")
        return self.writes.submit(deltas_mod.OP_KV_PUT, slot, int(value),
                                  **kw)

    def kv_delete(self, key: str, **kw):
        from consul_tpu.ops import deltas as deltas_mod

        slot = self.keys.slot_for(key)
        if slot < 0:
            from consul_tpu.serving.writes import WriteResult

            return WriteResult(applied=False, index=0, status="rejected")
        return self.writes.submit(deltas_mod.OP_KV_DELETE, slot, **kw)

    def session_create(self, node: int, session_id: int, **kw):
        from consul_tpu.ops import deltas as deltas_mod

        return self.writes.submit(deltas_mod.OP_SESSION_CREATE, node,
                                  int(session_id), **kw)

    def session_destroy(self, node: int, **kw):
        from consul_tpu.ops import deltas as deltas_mod

        return self.writes.submit(deltas_mod.OP_SESSION_DESTROY, node,
                                  **kw)

    def kv_get(self, key: str):
        """Read one KV slot AS OF THE CURRENT FLIP (snapshot
        semantics: a write between flips is not visible yet). Returns
        ``{"Key", "Value", "ModifyIndex"}`` or None."""
        import jax

        slot = self.keys.slot_for(key) if self.keys is not None else -1
        if slot < 0 or self._flip_pair is None:
            return None
        _, ws = self._flip_pair
        used, val, ver = jax.device_get(
            (ws.kv_used[slot], ws.kv_val[slot], ws.kv_ver[slot]))
        if not bool(used):
            return None
        return {"Key": key, "Value": int(val), "ModifyIndex": int(ver)}

    def node_entry(self, node: int):
        """One node's catalog row as of the current flip:
        ``{"Node", "Service", "Registered", "Session", "Live"}``."""
        import jax

        if self._flip_pair is None:
            return None
        snap, ws = self._flip_pair
        n = ws.service.shape[0]
        if not 0 <= int(node) < n:
            return None
        svc, reg, ses, live = jax.device_get(
            (ws.service[node], ws.registered[node], ws.session[node],
             snap.live[node]))
        return {"Node": int(node), "Service": int(svc),
                "Registered": bool(reg), "Session": int(ses),
                "Live": bool(live)}

    # ------------------------------------------------------------------
    # Shutdown (satellite: the agent/cache.py close discipline, plumbed
    # through Agent.close)
    # ------------------------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        """Idempotent: close the query batcher, the write batcher, and
        the watch plane — wake every parked waiter, reject every new
        submit with ServingClosedError."""
        self._closed = True
        self.batcher.close()
        if self.writes is not None:
            self.writes.close()
        if self.watch is not None:
            self.watch.close()

    # ------------------------------------------------------------------
    # Host-coordinate publication (server store rows)
    # ------------------------------------------------------------------
    @staticmethod
    def _flatten(cset: dict) -> Optional[dict]:
        # Device snapshots model one default-segment coordinate per
        # node; anything else falls back to rtt.py's pairwise
        # intersect() semantics on the host.
        if set(cset) == {""}:
            return cset[""]
        return None

    def publish_coords(self, coord_sets: dict) -> bool:
        """Build/refresh a device snapshot from per-node coordinate
        sets (``rtt.coord_sets_from_store`` shape). Returns False —
        leaving any prior snapshot untouched — when the sets use
        segment shapes the device path doesn't model."""
        import jax

        if self._source == "sim":
            raise RuntimeError("plane already serves a simulation")
        flat: dict[str, Optional[dict]] = {}
        fp = []
        for name in sorted(coord_sets):
            c = self._flatten(coord_sets[name])
            if c is None:
                return False
            flat[name] = c
            fp.append((name, tuple(c.get("vec", ())),
                       float(c.get("height", 0.0)),
                       float(c.get("adjustment", 0.0))))
        fp = tuple(fp)
        if fp == self._host_fp:
            return True

        names = tuple(flat)
        dims = [len(c.get("vec", ())) for c in flat.values()]
        # Modal dimensionality hosts the snapshot; off-dimension nodes
        # are "unknown" (sort_rows falls back when the SOURCE itself is
        # off-dimension, where host math would still be finite).
        d = max(set(dims), key=dims.count) if dims else 1
        d = max(d, 1)
        # Pad the node axis to a power of two so snapshot shapes (and
        # the executables compiled against them) stay stable as
        # membership grows.
        n_pad = max(4, 1 << (max(len(names), 1) - 1).bit_length())
        vec = np.zeros((n_pad, d), dtype=np.float32)
        height = np.zeros(n_pad, dtype=np.float32)
        adj = np.zeros(n_pad, dtype=np.float32)
        known = np.zeros(n_pad, dtype=bool)
        live = np.zeros(n_pad, dtype=bool)
        usable: dict[str, bool] = {}
        for i, (name, c) in enumerate(flat.items()):
            v = c.get("vec", ())
            ok = (len(v) == d and all(math.isfinite(x) for x in v)
                  and math.isfinite(c.get("height", 0.0))
                  and math.isfinite(c.get("adjustment", 0.0)))
            usable[name] = ok
            live[i] = True
            if ok:
                vec[i] = np.asarray(v, dtype=np.float32)
                height[i] = c.get("height", 0.0)
                adj[i] = c.get("adjustment", 0.0)
                known[i] = True
        # concurrent publishers bump the version under write_lock; the
        # device_put below uses the captured value outside it (TH117)
        with self.write_lock:
            self._names = names
            self._name_idx = {name: i for i, name in enumerate(names)}
            self._host_fp = fp
            self._host_d = d
            self._host_usable = usable
            self._host_version += 1
            version = self._host_version
        dv, dh, da_, dk, dl, ds, dt = jax.device_put(
            (vec, height, adj, known, live,
             np.zeros(n_pad, dtype=np.int32),
             np.int32(version)))
        self._source = "host"
        self._flip(kernels.Snapshot(vec=dv, height=dh, adjustment=da_,
                                    known=dk, live=dl, service=ds,
                                    tick=dt))
        return True

    # ------------------------------------------------------------------
    # High-level reads
    # ------------------------------------------------------------------
    def _to_idx(self, node) -> int:
        if isinstance(node, str):
            return self._name_idx.get(node, -1)
        return int(node)

    def _from_idx(self, i: int):
        if self._source == "host" and 0 <= i < len(self._names):
            return self._names[i]
        return i

    def _resolve(self, res: QueryResult) -> NearestResult:
        nodes = [(self._from_idx(int(res.ids[j])), float(res.rtts[j]))
                 for j in range(min(res.count, len(res.ids)))
                 if int(res.ids[j]) >= 0]
        return NearestResult(nodes=nodes, count=res.count, tick=res.tick)

    def nearest(self, src, service: int = -1,
                timeout_s: float = 10.0) -> NearestResult:
        """Top-k live nodes by estimated RTT from ``src`` (batched with
        concurrent callers via the QueryBatcher)."""
        res = self.batcher.submit(kernels.MODE_NEAREST, self._to_idx(src),
                                  service, timeout_s=timeout_s)
        return self._resolve(res)

    def nearest_many(self, sources: Sequence,
                     service: int = -1) -> list[NearestResult]:
        """One caller, many sources: a single pre-assembled batch."""
        qs = [(kernels.MODE_NEAREST, self._to_idx(s), service)
              for s in sources]
        return [self._resolve(r) for r in self.batcher.execute(qs)]

    def node_distance(self, a, b, timeout_s: float = 10.0) -> float:
        """Estimated RTT seconds between two nodes; +inf when either
        side is unknown (the rtt.compute_distance rule)."""
        bi = self._to_idx(b)
        res = self.batcher.submit(kernels.MODE_DIST, self._to_idx(a), bi,
                                  timeout_s=timeout_s)
        if res.count < 1:
            return math.inf
        return float(res.rtts[0])

    def catalog_nodes(self, service: int = -1,
                      timeout_s: float = 10.0) -> NearestResult:
        """Registered nodes (id order, optionally one service label)."""
        res = self.batcher.submit(kernels.MODE_CATALOG, 0, service,
                                  timeout_s=timeout_s)
        return self._resolve(res)

    def health_nodes(self, service: int = -1,
                     timeout_s: float = 10.0) -> NearestResult:
        """Live (health-passing) nodes, id order."""
        res = self.batcher.submit(kernels.MODE_HEALTH, 0, service,
                                  timeout_s=timeout_s)
        return self._resolve(res)

    # ------------------------------------------------------------------
    # Host row sorting (?near= and prepared-query NearestN)
    # ------------------------------------------------------------------
    def sort_rows(self, coord_sets: dict, source: str, rows: list,
                  node_key: str = "node") -> list:
        """Drop-in for ``rtt.sort_nodes_by_distance``: same contract
        (stable sort, unknown coordinates last, rows unchanged for an
        unknown source) but the distances come from one batched device
        kernel — one MODE_DIST slot per row. Falls back to the host
        reference path whenever the device snapshot can't represent
        the inputs exactly."""
        from consul_tpu.server import rtt

        if not coord_sets.get(source):
            return list(rows)
        if len(rows) <= 1:
            return list(rows)
        if not self.publish_coords(coord_sets):
            return rtt.sort_nodes_by_distance(coord_sets, source, rows,
                                              node_key=node_key)
        si = self._name_idx.get(source, -1)
        if si < 0 or not self._host_usable.get(source, False):
            # Off-dimension / non-finite source: host math can still
            # yield finite same-dimension distances — defer to it.
            return rtt.sort_nodes_by_distance(coord_sets, source, rows,
                                              node_key=node_key)
        qs = [(kernels.MODE_DIST, si,
               self._name_idx.get(row.get(node_key), -1)) for row in rows]
        results = self.batcher.execute(qs)
        keys = [float(r.rtts[0]) if r.count >= 1 else math.inf
                for r in results]
        order = sorted(range(len(rows)), key=keys.__getitem__)
        return [rows[i] for i in order]

    # ------------------------------------------------------------------
    # Cache front (agent/cache.py)
    # ------------------------------------------------------------------
    def register_cache_type(self, cache, name: str = "serving-nearest",
                            ttl_s: float = 0.5) -> None:
        """Register the batched device path as a CacheType: the fetcher
        IS a serving query, so repeated NearestN reads within the TTL
        cost zero device round-trips."""

        def factory(src=0, service=-1):
            def fetch(min_index: int, wait_s: float) -> dict:
                res = self.nearest(src, service=service)
                return {"index": res.tick,
                        "value": {"nodes": res.nodes, "count": res.count,
                                  "tick": res.tick}}

            return fetch

        cache.register_type(name, factory, ttl_s=ttl_s, refresh=False)
        self._cache_type = name

    def cached_nearest(self, cache, src, service: int = -1,
                       name: str = "serving-nearest") -> dict:
        """NearestN through the agent cache, counting hits into
        ``sim.serving.cache_hits``."""
        before = cache.metrics["hits"]
        val = cache.get_typed(name, src=self._to_idx(src), service=service)
        if cache.metrics["hits"] > before:
            self.note_cache_hit()
        return val

    def note_cache_hit(self) -> None:
        with self.write_lock:
            self.cache_hits += 1
        if self.sink is not None:
            self.sink.incr_counter("sim.serving.cache_hits", 1)

    # ------------------------------------------------------------------
    # Stats
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        out = self.batcher.stats()
        out["cache_hits"] = self.cache_hits
        # Flat keys: stats() feeds consul.serving.* gauges one scalar
        # per key (agent/http.py metrics loop).
        if self.writes is not None:
            for k, v in self.writes.stats().items():
                out[k if k.startswith("write") else f"write_{k}"] = v
        if self.watch is not None:
            out.update(self.watch.stats())
        return out
