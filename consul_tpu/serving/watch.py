"""Watch/streaming plane: blocking queries and watches served as
device-computed deltas between consecutive snapshot flips.

Every flip of a write-attached :class:`ServingPlane` runs ONE
fixed-shape diff kernel (``ops/deltas.diff_snapshots``) over the
(snapshot, write-state) pair either side of the flip — changed service
membership, health transitions, KV slot changes — and one device_get
brings the frame to the host. Fan-out then walks a two-level
reduction-tree dispatch (the Tascade-style aggregate-before-fanout
shape, arxiv 2311.15810): changes aggregate into (kind, key) groups
first — one event per group per flip, however many rows contributed —
and only branches with registered watchers are visited, so dispatch
cost is O(groups + matches), never O(changes x watchers).

The frame's ``apply_index`` is the raft-style device apply index the
new snapshot is consistent as of; :meth:`WatchPlane.wait_index` is the
blocking-query primitive the HTTP tier's ``?index=`` sites park on
(return immediately when the index has advanced past the caller's,
wait for a flip otherwise, never return a smaller index than called
with — the reference blockingQuery contract).

Backpressure: each watcher's queue is bounded; a full queue drops the
OLDEST event (watch semantics are level-ish — the newest delta
matters most) and counts it into ``sim.serving.shed``. Registered
watchers and delivered deltas count into ``sim.serving.watchers`` /
``sim.serving.deltas``.
"""

from __future__ import annotations

from collections import deque
from typing import NamedTuple, Optional

from consul_tpu.analysis import ledger
from consul_tpu.obs import trace as obs_trace
from consul_tpu.ops import deltas
from consul_tpu.serving.batcher import ServingClosedError

# Watch kinds. "service"/"node"/"kv" take a key (service label, node
# id, exact key string); "kv_prefix" takes a string prefix; "any"
# receives every group's event.
KINDS = ("service", "node", "kv", "kv_prefix", "any")


class WatchEvent(NamedTuple):
    """One aggregated delivery: everything that changed for this
    watcher's (kind, key) branch in one flip. ``index`` is the device
    apply index the delta is consistent as of; ``truncated`` marks a
    frame whose change count exceeded the kernel's fixed width K (the
    watcher should re-read instead of trusting the id list to be
    complete — no silent caps)."""

    kind: str
    key: object
    index: int
    tick: int
    changes: tuple        # node rows: (id, kindmask); kv rows: (key, ver)
    truncated: bool


class Watcher:
    """One registered watch: a bounded queue of :class:`WatchEvent`
    plus a condition to park on. ``poll`` returns the next event (None
    on timeout or plane close)."""

    def __init__(self, kind: str, key, max_queue: int):
        self.kind = kind
        self.key = key
        self.queue: deque[WatchEvent] = deque(maxlen=max_queue)
        self.dropped = 0
        self.index = 0          # last delivered apply index
        self.cond = ledger.make_condition("Watcher.cond")
        self.closed = False

    def _offer(self, ev: WatchEvent) -> bool:
        """Append under the watcher's lock; returns False when the
        bounded queue evicted its oldest entry (shed)."""
        with self.cond:
            shed = len(self.queue) == self.queue.maxlen
            if shed:
                self.dropped += 1
            self.queue.append(ev)
            self.index = ev.index
            self.cond.notify_all()
        return not shed

    def poll(self, timeout_s: float = 5.0) -> Optional[WatchEvent]:
        import time

        deadline = time.monotonic() + timeout_s
        with self.cond:
            while not self.queue and not self.closed:
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self.cond.wait(left)
            return self.queue.popleft() if self.queue else None


class WatchPlane:
    def __init__(self, plane, k: int = 64, max_queue: int = 256):
        self.plane = plane
        self.k = int(k)
        self.max_queue = int(max_queue)
        self._lock = ledger.make_lock("WatchPlane._lock")
        # Two-level reduction tree: kind -> key -> watcher group. The
        # per-branch counts let dispatch skip whole kinds with zero
        # registrations without touching their keys.
        self._tree: dict[str, dict] = {kind: {} for kind in KINDS}
        self._kind_counts: dict[str, int] = {kind: 0 for kind in KINDS}
        self._closed = False
        # Blocking-query index plumbing: the apply index of the CURRENT
        # flip, advanced by on_flip under _index_cond.
        self.apply_index = 0
        self._index_cond = ledger.make_condition("WatchPlane._index_cond")
        # Index listeners (the async frontend's wake seam): called with
        # the new apply index AFTER the condition broadcast, outside
        # every plane lock, so a listener may re-enter the plane.
        self._index_listeners: list = []
        # Plain-int counters mirroring the sink emissions.
        self.watchers = 0
        self.deltas = 0
        self.shed = 0
        self.flips = 0
        self.truncated_frames = 0

    # ------------------------------------------------------------------
    # Registration
    # ------------------------------------------------------------------
    def register(self, kind: str, key=None) -> Watcher:
        if kind not in KINDS:
            raise ValueError(f"unknown watch kind {kind!r} "
                             f"(want one of {KINDS})")
        with self._lock:
            if self._closed:
                raise ServingClosedError("watch plane is closed")
            w = Watcher(kind, key, self.max_queue)
            self._tree[kind].setdefault(key, []).append(w)
            self._kind_counts[kind] += 1
            self.watchers += 1
        sink = getattr(self.plane, "sink", None)
        if sink is not None:
            sink.incr_counter("sim.serving.watchers", 1)
        return w

    def unregister(self, w: Watcher) -> None:
        with self._lock:
            group = self._tree.get(w.kind, {}).get(w.key)
            if group and w in group:
                group.remove(w)
                self._kind_counts[w.kind] -= 1
                if not group:
                    del self._tree[w.kind][w.key]

    # ------------------------------------------------------------------
    # Flip fan-out
    # ------------------------------------------------------------------
    def on_flip(self, prev_pair, cur_pair) -> None:
        """Called by the plane after every snapshot flip with the
        (snapshot, write-state) pairs either side. Runs the diff
        kernel, fetches the frame in one device_get, advances the
        blocking index, and dispatches through the reduction tree."""
        import jax

        if prev_pair is None:
            # First flip: nothing to diff — just learn the index.
            _, ws = cur_pair
            self._advance(int(jax.device_get(ws.apply_index)))
            return
        tr = obs_trace.get_tracer()
        t0_us = tr.now_us()
        prev_snap, prev_ws = prev_pair
        cur_snap, cur_ws = cur_pair
        frame = deltas.diff_kernel_for(self.k)(
            prev_snap, prev_ws, cur_snap, cur_ws)
        h = jax.device_get(frame)
        index = int(h.apply_index)
        tick = int(h.tick)
        n_nodes = int(h.n_node_changes)
        n_kv = int(h.n_kv_changes)
        truncated = n_nodes > self.k or n_kv > self.k
        # on_flip runs on whichever thread triggered the publish, and
        # register/stats read the counters from others — share _lock
        # (TH114); the device_get above stays outside it
        with self._lock:
            self.flips += 1
            if truncated:
                self.truncated_frames += 1

        # Level 1 of the tree: aggregate changed rows into (kind, key)
        # branches — one event per branch regardless of row count.
        groups: dict[tuple, list] = {}
        for j in range(min(n_nodes, self.k)):
            nid = int(h.node_ids[j])
            if nid < 0:
                continue
            row = (nid, int(h.node_kinds[j]))
            groups.setdefault(("node", nid), []).append(row)
            sp, sc = int(h.svc_prev[j]), int(h.svc_cur[j])
            if sp >= 0:
                groups.setdefault(("service", sp), []).append(row)
            if sc >= 0 and sc != sp:
                groups.setdefault(("service", sc), []).append(row)
            groups.setdefault(("any", None), []).append(row)
        keys = getattr(self.plane, "keys", None)
        for j in range(min(n_kv, self.k)):
            slot = int(h.kv_slots[j])
            if slot < 0:
                continue
            key = keys.key_of(slot) if keys is not None else None
            key = key if key is not None else f"slot:{slot}"
            row = (key, int(h.kv_vers[j]))
            groups.setdefault(("kv", key), []).append(row)
            groups.setdefault(("any", None), []).append(row)
            with self._lock:
                prefixes = list(self._tree["kv_prefix"]
                                ) if self._kind_counts["kv_prefix"] else []
            for pfx in prefixes:
                if key.startswith(pfx):
                    groups.setdefault(("kv_prefix", pfx), []).append(row)

        # Level 2: deliver each branch's one event to its watchers.
        delivered = 0
        shed = 0
        for (kind, key), rows in groups.items():
            with self._lock:
                if not self._kind_counts[kind]:
                    continue
                group = list(self._tree[kind].get(key, ()))
            if not group:
                continue
            ev = WatchEvent(kind=kind, key=key, index=index, tick=tick,
                            changes=tuple(rows), truncated=truncated)
            for w in group:
                if w._offer(ev):
                    delivered += 1
                else:
                    delivered += 1
                    shed += 1
        with self._lock:
            self.deltas += delivered
            self.shed += shed
        sink = getattr(self.plane, "sink", None)
        if sink is not None:
            if delivered:
                sink.incr_counter("sim.serving.deltas", delivered)
            if shed:
                sink.incr_counter("sim.serving.shed", shed)
        self._advance(index)
        # Span recorded with explicit timing so the fan-out stats ride
        # along as args (they only exist once delivery finished).
        tr.complete("watch.on_flip", t0_us, tr.now_us() - t0_us,
                    cat="serving",
                    args={"delivered": delivered, "shed": shed})

    def _advance(self, index: int) -> None:
        with self._index_cond:
            if index > self.apply_index:
                self.apply_index = index
            self._index_cond.notify_all()
            listeners = list(self._index_listeners)
        for fn in listeners:
            fn(self.apply_index)

    def add_index_listener(self, fn) -> None:
        """Register ``fn(apply_index)`` to fire after every flip's
        index advance (threaded waiters keep using :meth:`wait_index`;
        the async frontend parks futures here instead of threads)."""
        with self._index_cond:
            self._index_listeners.append(fn)

    def remove_index_listener(self, fn) -> None:
        with self._index_cond:
            if fn in self._index_listeners:
                self._index_listeners.remove(fn)

    # ------------------------------------------------------------------
    # Blocking-query primitive (the ?index= contract)
    # ------------------------------------------------------------------
    def wait_index(self, min_index: int = 0, wait_s: float = 10.0) -> int:
        """Park until the device apply index exceeds ``min_index`` (or
        the wait expires). Returns immediately when it already has.
        Never returns a smaller index than it was called with, and
        never less than 1 (the reference blockingQuery floor)."""
        import time

        deadline = time.monotonic() + max(0.0, wait_s)
        with self._index_cond:
            while (self.apply_index <= min_index and not self._closed):
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._index_cond.wait(left)
            return max(self.apply_index, min_index, 1)

    # ------------------------------------------------------------------
    # Shutdown
    # ------------------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            self._closed = True
            watchers = [w for kinds in self._tree.values()
                        for group in kinds.values() for w in group]
        for w in watchers:
            with w.cond:
                w.closed = True
                w.cond.notify_all()
        with self._index_cond:
            self._index_cond.notify_all()
            listeners = list(self._index_listeners)
        for fn in listeners:
            fn(self.apply_index)

    # ------------------------------------------------------------------
    # Stats
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        return {
            "watchers": self.watchers,
            "deltas": self.deltas,
            "watch_shed": self.shed,
            "flips": self.flips,
            "truncated_frames": self.truncated_frames,
            "apply_index": self.apply_index,
        }
