"""Mixed read/write/watch serving benchmark (one shared driver for
``consul-tpu serve-bench --mixed`` and bench.py's ``serving_mixed``
phase).

Drives the three serving classes against one sim-attached plane in
interleaved rounds at a fixed R:W:Watch ratio: each round executes one
read batch (QueryBatcher), the round's share of writes (WriteBatcher),
and one snapshot flip (``sim.publish_serving``) whose delta fan-out
feeds the registered watchers. Per-class numbers are reported from
in-class time — each class's q/s is its op count over the wall time
spent inside that class's launches — with p50/p99 over the per-launch
latencies, all under one stable JSON shape:

``{"ratio", "read": {count, qps_per_chip, p50_ms, p99_ms},
   "write": {...}, "watch": {flips, deliveries, watchers, ...}}``
"""

from __future__ import annotations

import random
import time


def parse_ratio(spec: str) -> tuple[int, int, int]:
    """``"90:9:1"`` -> (90, 9, 1); read share must be positive."""
    parts = [int(x) for x in str(spec).split(":")]
    if len(parts) != 3 or min(parts) < 0 or parts[0] <= 0:
        raise ValueError(
            f"--mixed wants R:W:WATCH with positive reads, got {spec!r}")
    return parts[0], parts[1], parts[2]


def _pcts(samples) -> tuple[float, float]:
    lats = sorted(samples)
    if not lats:
        return 0.0, 0.0
    p50 = lats[len(lats) // 2]
    p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))]
    return round(p50 * 1e3, 3), round(p99 * 1e3, 3)


def run_mixed(sim, plane, *, ratio: str = "90:9:1", rounds: int = 16,
              read_batch: int = 256, watchers: int = 8,
              seed: int = 0) -> dict:
    """Run the mixed workload; returns the stable result dict. The
    plane must already be sim-attached with writes
    (``plane.attach_writes``); executables are warmed (one read batch,
    one write batch, one flip + diff) before the timed rounds, the
    compile-ledger discipline of every bench phase."""
    from consul_tpu.ops import deltas
    from consul_tpu.serving import MODE_NEAREST

    r, w_share, watch_share = parse_ratio(ratio)
    n = sim.cfg.n
    rng = random.Random(seed)
    write_batch = max(1, round(read_batch * w_share / r))
    # Watch class: `watchers` registered watchers fed by one flip per
    # round — the watch share scales how many service keys they spread
    # over (more share = denser fan-out), floor one watcher.
    n_watchers = max(1, watchers if watch_share else 1)
    svc_width = max(plane.num_services, 1)
    hooks = [plane.watch.register("service", i % svc_width)
             for i in range(n_watchers)]
    kv_hook = plane.watch.register("kv_prefix", "bench/")

    def read_ops():
        return [(MODE_NEAREST, rng.randrange(n), -1)
                for _ in range(read_batch)]

    def write_ops():
        ops = []
        for _ in range(write_batch):
            roll = rng.random()
            node = rng.randrange(n)
            if roll < 0.5:
                ops.append((deltas.OP_REGISTER, node,
                            rng.randrange(svc_width)))
            elif roll < 0.75:
                slot = plane.keys.slot_for(
                    f"bench/{rng.randrange(64)}", create=True)
                ops.append((deltas.OP_KV_PUT, slot, rng.randrange(1000)))
            else:
                ops.append((deltas.OP_DEREGISTER, node, -1))
        return ops

    # Warm every executable out of the timed region.
    plane.batcher.execute(read_ops())
    plane.writes.execute(write_ops())
    sim.publish_serving()
    plane.batcher.latencies_s.clear()
    plane.writes.latencies_s.clear()

    read_t = write_t = watch_t = 0.0
    reads = writes = 0
    flip_lats = []
    deliveries0 = plane.watch.deltas
    t_all = time.perf_counter()
    for _ in range(max(1, rounds)):
        t0 = time.perf_counter()
        plane.batcher.execute(read_ops())
        read_t += time.perf_counter() - t0
        reads += read_batch

        t0 = time.perf_counter()
        plane.writes.execute(write_ops())
        write_t += time.perf_counter() - t0
        writes += write_batch

        t0 = time.perf_counter()
        sim.publish_serving()   # flip + diff kernel + watch fan-out
        dt = time.perf_counter() - t0
        watch_t += dt
        flip_lats.append(dt)
    wall = time.perf_counter() - t_all
    deliveries = plane.watch.deltas - deliveries0

    rp50, rp99 = _pcts(plane.batcher.latencies_s)
    wp50, wp99 = _pcts(plane.writes.latencies_s)
    fp50, fp99 = _pcts(flip_lats)
    for h in hooks:
        plane.watch.unregister(h)
    plane.watch.unregister(kv_hook)
    return {
        "ratio": f"{r}:{w_share}:{watch_share}",
        "rounds": rounds,
        "wall_s": round(wall, 3),
        "apply_index": plane.apply_index,
        "read": {
            "count": reads,
            "qps_per_chip": round(reads / read_t, 1) if read_t else 0.0,
            "p50_ms": rp50, "p99_ms": rp99,
        },
        "write": {
            "count": writes,
            "qps_per_chip": round(writes / write_t, 1) if write_t
            else 0.0,
            "p50_ms": wp50, "p99_ms": wp99,
            "rejected": plane.writes.rejected,
            "shed": plane.writes.shed,
        },
        "watch": {
            "watchers": n_watchers + 1,
            "flips": len(flip_lats),
            "deliveries": deliveries,
            "qps_per_chip": round(deliveries / watch_t, 1) if watch_t
            else 0.0,
            "p50_ms": fp50, "p99_ms": fp99,
        },
    }
