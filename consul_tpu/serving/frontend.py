"""Async serving frontend: ONE event loop in front of the batchers.

The threaded serving tier parks every in-flight request on its own
``threading.Event`` (QueryBatcher/WriteBatcher ``submit``,
WatchPlane ``wait_index``) — correct, but one Python thread per parked
waiter is the host-tier ceiling ROADMAP names. This module is the
refactor that removes it: an :class:`AsyncFrontend` runs a single
asyncio event loop on ONE owned thread and multiplexes thousands of
logically-blocking requests as futures — reads and writes coalesce on
the loop into the same padded bucketed batches the threaded path
builds (``QueryBatcher.execute`` / ``WriteBatcher.execute``, so both
paths share one kernel and one result contract), and blocking-query
waiters park as loop timers woken by the WatchPlane's index-listener
seam instead of condition-variable threads.

The threaded park-and-pump path is preserved untouched;
``tests/test_frontend.py`` pins parity between the two (identical
results for the same mixed workload, strictly fewer live threads on
the async side).

An optional asyncio HTTP listener (:meth:`AsyncFrontend.serve_http`)
serves the real wire surface over the same loop — ``/v1/kv``,
``/v1/catalog/nodes``, ``/v1/health/service`` with ``?index=`` +
``?wait=`` blocking queries answered with ``X-Consul-Index`` under the
exact ``agent/http.py`` ``parse_blocking`` contract — so an external
multi-process client swarm (``gameday/swarm.py``) can drive it over
sockets. Documented narrowings: KV values are the device plane's one
i32 word per key, node/service addressing is the sim's integer
labels, and a PUT acknowledged under raft reports the provisional
``proposed`` status until the commit pump lands it (the committed
index is observable via a subsequent blocking read).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import threading
from typing import Optional
from urllib.parse import parse_qs, urlparse

from consul_tpu.obs import trace as obs_trace
from consul_tpu.serving.batcher import (ServingClosedError,
                                        ServingOverloadError)

_MAX_BODY = 1 << 20


class AsyncFrontend:
    """One event loop multiplexing reads/writes/blocking queries in
    front of a write-attached :class:`ServingPlane`.

    Every ``submit_*`` call is thread-safe and returns a
    ``concurrent.futures.Future`` immediately; a caller that wants the
    threaded-path blocking shape just calls ``.result()``. The point
    is that N in-flight requests cost N future objects and ONE loop
    thread — not N parked threads."""

    def __init__(self, plane, max_wait_s: Optional[float] = None,
                 max_batch: Optional[int] = None):
        self.plane = plane
        self.max_wait_s = (float(max_wait_s) if max_wait_s is not None
                           else plane.batcher.max_wait_s)
        self.max_batch = (int(max_batch) if max_batch is not None
                          else plane.batcher.max_batch)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._closed = False
        # Pending request queues — touched ONLY from the loop thread.
        self._reads: list = []        # (mode, src, arg, future)
        self._writes: list = []       # (op, target, arg, future)
        self._read_timer = None
        self._write_timer = None
        # Index waiters: {future: (min_index, timer)} — loop thread only.
        self._index_waiters: dict = {}
        self._listening = False
        self._server = None
        # Counters (mirrored into the plane's sink).
        self.reads = 0
        self.writes = 0
        self.batches = 0
        self.http_requests = 0
        self.inflight_peak = 0

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "AsyncFrontend":
        if self._thread is not None:
            return self
        self._loop = asyncio.new_event_loop()
        # The frontend's ONE owned thread — tracked on self and joined
        # in close(), the discipline lint rule TH113 enforces for the
        # serving/gameday host tier.
        self._thread = threading.Thread(
            target=self._run_loop, name="serving-frontend", daemon=True)
        self._thread.start()
        self._started.wait(5.0)
        watch = getattr(self.plane, "watch", None)
        if watch is not None:
            watch.add_index_listener(self._on_index)
        return self

    def _run_loop(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.call_soon(self._started.set)
        self._loop.run_forever()

    def close(self) -> None:
        """Idempotent: unhook the index listener, fail every pending
        future with ServingClosedError, stop the loop, join the one
        owned thread."""
        if self._closed:
            return
        self._closed = True
        watch = getattr(self.plane, "watch", None)
        if watch is not None:
            watch.remove_index_listener(self._on_index)
        loop = self._loop
        if loop is None:
            return
        done = threading.Event()

        def _shutdown():
            err = ServingClosedError("async frontend closed")
            for *_x, fut in self._reads + self._writes:
                if not fut.done():
                    fut.set_exception(err)
            self._reads, self._writes = [], []
            for fut, (_mi, timer) in list(self._index_waiters.items()):
                timer.cancel()
                if not fut.done():
                    fut.set_exception(err)
            self._index_waiters.clear()
            if self._server is not None:
                self._server.close()
            # Retire live connection coroutines before stopping the
            # loop — a pending task destroyed with the loop warns at
            # GC time and can leak its socket.
            tasks = list(asyncio.all_tasks(loop))
            for t in tasks:
                t.cancel()

            async def _finish():
                await asyncio.gather(*tasks, return_exceptions=True)
                loop.stop()
                done.set()

            loop.create_task(_finish())

        loop.call_soon_threadsafe(_shutdown)
        done.wait(5.0)
        if self._thread is not None:
            self._thread.join(5.0)

    @property
    def closed(self) -> bool:
        return self._closed

    def owned_threads(self) -> int:
        """Live threads this frontend owns (the parity test's bound)."""
        return 1 if self._thread is not None and self._thread.is_alive() \
            else 0

    # ------------------------------------------------------------------
    # Submission (thread-safe; futures resolve on the loop)
    # ------------------------------------------------------------------
    def _ensure_open(self):
        if self._closed or self._thread is None:
            raise ServingClosedError(
                "async frontend is not running (call start())")

    def submit_read(self, mode: int, src: int, arg: int = -1
                    ) -> concurrent.futures.Future:
        """Enqueue one read; the future resolves to a QueryResult.
        Reads coalesce for up to ``max_wait_s`` (or until a max batch
        fills) and run as ONE bucketed kernel via
        ``QueryBatcher.execute`` — the same executable the threaded
        pump uses."""
        self._ensure_open()
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._loop.call_soon_threadsafe(
            self._enqueue_read, (int(mode), int(src), int(arg), fut))
        return fut

    def submit_write(self, op: int, target: int, arg: int = -1
                     ) -> concurrent.futures.Future:
        """Enqueue one write; the future resolves to a WriteResult.
        Admission control mirrors the WriteBatcher contract (same
        ``max_pending`` bound, same policy, same sink counters) except
        that a rejection surfaces ON the future rather than at the
        submit call — the caller is not parked, so there is no
        synchronous raise point."""
        self._ensure_open()
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._loop.call_soon_threadsafe(
            self._enqueue_write, (int(op), int(target), int(arg), fut))
        return fut

    def wait_index(self, min_index: int = 0, wait_s: float = 10.0
                   ) -> concurrent.futures.Future:
        """The blocking-query primitive as a future: resolves to the
        apply index once it exceeds ``min_index`` (immediately when it
        already does), or at the wait deadline — same floor contract
        as ``WatchPlane.wait_index`` (never below ``min_index``, never
        below 1), with the waiter parked as a loop timer instead of a
        thread."""
        self._ensure_open()
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._loop.call_soon_threadsafe(
            self._enqueue_index_wait, int(min_index), float(wait_s), fut)
        return fut

    # -- convenience verbs (sim addressing, mirror ServingPlane's) ------
    def kv_put(self, key: str, value: int) -> concurrent.futures.Future:
        from consul_tpu.ops import deltas as deltas_mod

        slot = self.plane.keys.slot_for(key, create=True)
        if slot < 0:
            fut: concurrent.futures.Future = concurrent.futures.Future()
            fut.set_exception(ServingOverloadError(
                f"kv slot table full ({self.plane.keys.slots} slots)"))
            return fut
        return self.submit_write(deltas_mod.OP_KV_PUT, slot, int(value))

    def register(self, node: int, service: int) -> concurrent.futures.Future:
        from consul_tpu.ops import deltas as deltas_mod

        return self.submit_write(deltas_mod.OP_REGISTER, node, service)

    def nearest(self, src: int, service: int = -1
                ) -> concurrent.futures.Future:
        from consul_tpu.ops import serving as kernels

        return self.submit_read(kernels.MODE_NEAREST, src, service)

    def catalog_nodes(self, service: int = -1) -> concurrent.futures.Future:
        from consul_tpu.ops import serving as kernels

        return self.submit_read(kernels.MODE_CATALOG, 0, service)

    def health_nodes(self, service: int = -1) -> concurrent.futures.Future:
        from consul_tpu.ops import serving as kernels

        return self.submit_read(kernels.MODE_HEALTH, 0, service)

    # ------------------------------------------------------------------
    # Loop-side machinery
    # ------------------------------------------------------------------
    def _note_inflight(self):
        inflight = (len(self._reads) + len(self._writes)
                    + len(self._index_waiters))
        if inflight > self.inflight_peak:
            self.inflight_peak = inflight

    def _enqueue_read(self, item) -> None:
        self._reads.append(item)
        self._note_inflight()
        if len(self._reads) >= self.max_batch:
            self._flush_reads()
        elif self._read_timer is None:
            self._read_timer = self._loop.call_later(
                self.max_wait_s, self._flush_reads)

    def _enqueue_write(self, item) -> None:
        wb = self.plane.writes
        if wb is None:
            item[3].set_exception(RuntimeError(
                "plane has no write path (attach_writes first)"))
            return
        if len(self._writes) >= wb.max_pending:
            sink = getattr(self.plane, "sink", None)
            if wb.policy == "reject":
                wb.rejected += 1
                if sink is not None:
                    sink.incr_counter("sim.serving.rejected", 1)
                item[3].set_exception(ServingOverloadError(
                    f"write queue full ({wb.max_pending} pending, "
                    "policy=reject)"))
                return
            from consul_tpu.serving.writes import WriteResult

            shed = self._writes.pop(0)
            wb.shed += 1
            if sink is not None:
                sink.incr_counter("sim.serving.shed", 1)
            if not shed[3].done():
                shed[3].set_result(
                    WriteResult(applied=False, index=0, status="shed"))
        self._writes.append(item)
        self._note_inflight()
        if len(self._writes) >= wb.max_batch:
            self._flush_writes()
        elif self._write_timer is None:
            self._write_timer = self._loop.call_later(
                self.max_wait_s, self._flush_writes)

    def _flush_reads(self) -> None:
        if self._read_timer is not None:
            self._read_timer.cancel()
            self._read_timer = None
        batch, self._reads = self._reads, []
        if not batch:
            return
        self.batches += 1
        self.reads += len(batch)
        sink = getattr(self.plane, "sink", None)
        if sink is not None:
            sink.incr_counter("sim.serving.frontend_reads", len(batch))
            sink.incr_counter("sim.serving.frontend_batches", 1)
        with obs_trace.span("frontend.read_flush", cat="serving",
                            args={"n": len(batch)}):
            try:
                results = self.plane.batcher.execute(
                    [(m, s, a) for m, s, a, _f in batch])
            except Exception as e:  # noqa: BLE001 — fan the error out
                for *_x, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                return
        for (*_x, fut), res in zip(batch, results):
            if not fut.done():
                fut.set_result(res)

    def _flush_writes(self) -> None:
        if self._write_timer is not None:
            self._write_timer.cancel()
            self._write_timer = None
        batch, self._writes = self._writes, []
        if not batch:
            return
        self.batches += 1
        self.writes += len(batch)
        sink = getattr(self.plane, "sink", None)
        if sink is not None:
            sink.incr_counter("sim.serving.frontend_writes", len(batch))
            sink.incr_counter("sim.serving.frontend_batches", 1)
        with obs_trace.span("frontend.write_flush", cat="serving",
                            args={"n": len(batch)}):
            try:
                results = self.plane.writes.execute(
                    [(o, t, a) for o, t, a, _f in batch])
            except Exception as e:  # noqa: BLE001
                for *_x, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                return
        for (*_x, fut), res in zip(batch, results):
            if not fut.done():
                fut.set_result(res)

    def _enqueue_index_wait(self, min_index: int, wait_s: float,
                            fut) -> None:
        watch = self.plane.watch
        cur = watch.apply_index if watch is not None else 0
        if watch is None or cur > min_index:
            fut.set_result(max(cur, min_index, 1))
            return

        def _expire():
            self._index_waiters.pop(fut, None)
            if not fut.done():
                fut.set_result(max(watch.apply_index, min_index, 1))

        timer = self._loop.call_later(max(0.0, wait_s), _expire)
        self._index_waiters[fut] = (min_index, timer)
        self._note_inflight()

    def _on_index(self, index: int) -> None:
        """WatchPlane index-listener: hop onto the loop and wake every
        waiter the new index (or a plane close) releases."""
        loop = self._loop
        if loop is None or self._closed:
            return
        try:
            loop.call_soon_threadsafe(self._wake_index_waiters, index)
        except RuntimeError:
            pass  # loop already stopped under close()

    def _wake_index_waiters(self, index: int) -> None:
        watch = self.plane.watch
        closed = watch is None or watch._closed
        for fut, (min_index, timer) in list(self._index_waiters.items()):
            if closed or index > min_index:
                timer.cancel()
                del self._index_waiters[fut]
                if not fut.done():
                    fut.set_result(max(index, min_index, 1))

    # ------------------------------------------------------------------
    # HTTP listener (the swarm-facing wire surface)
    # ------------------------------------------------------------------
    def serve_http(self, host: str = "127.0.0.1", port: int = 0
                   ) -> tuple[str, int]:
        """Start an asyncio HTTP/1.1 listener on the frontend's loop;
        returns the bound (host, port). Requests multiplex on the SAME
        event loop as every future above — a thousand parked blocking
        queries are a thousand coroutines, zero extra threads."""
        self._ensure_open()
        fut: concurrent.futures.Future = concurrent.futures.Future()

        async def _start():
            server = await asyncio.start_server(
                self._serve_conn, host=host, port=port)
            self._server = server
            fut.set_result(server.sockets[0].getsockname()[:2])

        asyncio.run_coroutine_threadsafe(_start(), self._loop)
        got = fut.result(10.0)
        self._listening = True
        return got[0], got[1]

    async def _serve_conn(self, reader, writer) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    method, target, _proto = line.decode().split()
                except ValueError:
                    return
                clen = 0
                keep = True
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    name, _, val = h.decode().partition(":")
                    if name.strip().lower() == "content-length":
                        clen = min(int(val.strip()), _MAX_BODY)
                    if name.strip().lower() == "connection" and \
                            val.strip().lower() == "close":
                        keep = False
                body = await reader.readexactly(clen) if clen else b""
                status, payload, hdrs = await self._route(
                    method.upper(), target, body)
                data = json.dumps(payload).encode()
                head = [f"HTTP/1.1 {status} X",
                        "Content-Type: application/json",
                        f"Content-Length: {len(data)}"]
                head += [f"{k}: {v}" for k, v in hdrs.items()]
                head.append("Connection: keep-alive" if keep
                            else "Connection: close")
                writer.write(("\r\n".join(head) + "\r\n\r\n").encode()
                             + data)
                await writer.drain()
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            try:
                writer.close()
            except OSError:
                pass

    async def _route(self, method: str, target: str, body: bytes
                     ) -> tuple[int, object, dict]:
        from consul_tpu.agent.http import parse_blocking

        self.http_requests += 1
        sink = getattr(self.plane, "sink", None)
        if sink is not None:
            sink.incr_counter("sim.serving.frontend_http", 1)
        u = urlparse(target)
        q = {k: v[-1] for k, v in parse_qs(u.query,
                                           keep_blank_values=True).items()}
        parts = [p for p in u.path.split("/") if p]
        try:
            min_index, wait_s = parse_blocking(q)
        except ValueError:
            return 400, {"error": "bad index/wait"}, {}
        try:
            return await self._dispatch(method, parts, q, body,
                                        min_index, wait_s)
        except (ServingClosedError, ServingOverloadError) as e:
            return 503, {"error": str(e)}, {}
        except (ValueError, KeyError) as e:
            return 400, {"error": str(e)}, {}
        except Exception as e:  # noqa: BLE001 — never drop the conn
            return 500, {"error": f"internal: {e!r}"}, {}

    async def _dispatch(self, method, parts, q, body, min_index, wait_s):
        if parts[:2] == ["v1", "agent"] and parts[2:] == ["self"]:
            return 200, {"Config": {"NodeName": "serving-frontend"},
                         "Stats": {"apply_index":
                                   self.plane.apply_index}}, {}
        if "index" in q:
            # The blockingQuery contract: park (as a loop timer) until
            # the flip index passes the caller's, then serve the read.
            idx = await asyncio.wrap_future(
                self.wait_index(min_index, wait_s))
        else:
            idx = max(self.plane.apply_index, 1)
        hdrs = {"X-Consul-Index": str(idx)}
        if parts[:2] == ["v1", "kv"] and len(parts) >= 3:
            key = "/".join(parts[2:])
            if method == "GET":
                row = self.plane.kv_get(key)
                if row is None:
                    return 404, None, hdrs
                return 200, [row], {"X-Consul-Index":
                                    str(max(row["ModifyIndex"], idx))}
            if method == "PUT":
                val = int(body or b"0")
                res = await asyncio.wrap_future(self.kv_put(key, val))
                ok = bool(res.applied) or res.status == "proposed"
                if res.index > 0:
                    hdrs["X-Consul-Index"] = str(res.index)
                return 200, ok, hdrs
        if parts[:3] == ["v1", "catalog", "nodes"] and method == "GET":
            res = await asyncio.wrap_future(
                self.catalog_nodes(int(q.get("service", -1))))
            return 200, self._rows(res), hdrs
        if parts[:3] == ["v1", "catalog", "register"] and method == "PUT":
            doc = json.loads(body or b"{}")
            res = await asyncio.wrap_future(self.register(
                int(doc.get("Node", 0)), int(doc.get("Service", 0))))
            if res.index > 0:
                hdrs["X-Consul-Index"] = str(res.index)
            return 200, bool(res.applied) or res.status == "proposed", hdrs
        if parts[:3] == ["v1", "health", "service"] and len(parts) == 4 \
                and method == "GET":
            service = int(parts[3])
            if "near" in q:
                res = await asyncio.wrap_future(
                    self.nearest(int(q["near"]), service))
            else:
                res = await asyncio.wrap_future(self.health_nodes(service))
            return 200, self._rows(res), hdrs
        return 404, {"error": f"no route {method} /{'/'.join(parts)}"}, {}

    @staticmethod
    def _rows(res) -> list:
        return [{"Node": int(res.ids[j]), "RTT": float(res.rtts[j])}
                for j in range(min(res.count, len(res.ids)))
                if int(res.ids[j]) >= 0]

    # ------------------------------------------------------------------
    # Stats
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        return {
            "frontend_reads": self.reads,
            "frontend_writes": self.writes,
            "frontend_batches": self.batches,
            "frontend_http": self.http_requests,
            "frontend_inflight_peak": self.inflight_peak,
            "frontend_threads": self.owned_threads(),
        }
