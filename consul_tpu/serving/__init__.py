"""Device-resident query-serving plane.

Batched NearestN / health / catalog / distance reads straight from the
simulation tensors: a :class:`QueryBatcher` packs concurrent requests
into fixed-shape bucketed batches, each batch runs as one masked top-k
kernel (``ops/serving.py``) against a double-buffered device snapshot
(:class:`ServingPlane`), and results fan back out to waiters. Host
``server/rtt.py`` remains the documented reference implementation —
the device path is pinned to it by the golden-parity suite.

The write-side twin (``ServingPlane.attach_writes``): a
:class:`WriteBatcher` coalesces catalog/KV/session writes into
fixed-shape batches applied on device between flips (``ops/deltas.py``,
monotone raft-style apply index), and a :class:`WatchPlane` serves
blocking queries and watches as device-computed deltas between
consecutive snapshot flips. Both batchers run bounded queues with
drop/shed admission control; ``ServingPlane.close()`` wakes every
parked waiter with :class:`ServingClosedError`.
"""

from consul_tpu.ops.serving import (MODE_CATALOG, MODE_DIST, MODE_HEALTH,
                                    MODE_NEAREST, MODE_NOOP, Snapshot)
from consul_tpu.serving.batcher import (QueryBatcher, QueryResult,
                                        ServingClosedError,
                                        ServingOverloadError)
from consul_tpu.serving.frontend import AsyncFrontend
from consul_tpu.serving.plane import NearestResult, ServingPlane
from consul_tpu.serving.watch import Watcher, WatchEvent, WatchPlane
from consul_tpu.serving.writes import (KeyTable, WriteBatcher,
                                       WriteResult)

__all__ = [
    "MODE_CATALOG", "MODE_DIST", "MODE_HEALTH", "MODE_NEAREST", "MODE_NOOP",
    "AsyncFrontend", "KeyTable", "NearestResult", "QueryBatcher",
    "QueryResult",
    "ServingClosedError", "ServingOverloadError", "ServingPlane",
    "Snapshot", "Watcher", "WatchEvent", "WatchPlane", "WriteBatcher",
    "WriteResult",
]
