"""Device-resident query-serving plane.

Batched NearestN / health / catalog / distance reads straight from the
simulation tensors: a :class:`QueryBatcher` packs concurrent requests
into fixed-shape bucketed batches, each batch runs as one masked top-k
kernel (``ops/serving.py``) against a double-buffered device snapshot
(:class:`ServingPlane`), and results fan back out to waiters. Host
``server/rtt.py`` remains the documented reference implementation —
the device path is pinned to it by the golden-parity suite.
"""

from consul_tpu.ops.serving import (MODE_CATALOG, MODE_DIST, MODE_HEALTH,
                                    MODE_NEAREST, MODE_NOOP, Snapshot)
from consul_tpu.serving.batcher import QueryBatcher, QueryResult
from consul_tpu.serving.plane import NearestResult, ServingPlane

__all__ = [
    "MODE_CATALOG", "MODE_DIST", "MODE_HEALTH", "MODE_NEAREST", "MODE_NOOP",
    "NearestResult", "QueryBatcher", "QueryResult", "ServingPlane",
    "Snapshot",
]
