"""QueryBatcher: collect concurrent read requests into fixed-shape
padded batches and run each batch as one device kernel.

Batch sizes are bucketed (default 1/8/64/512) so every batch reuses one
of a handful of XLA executables — the same memoization discipline as
``models/cluster.py``'s runner cache. A request that arrives alone pays
one small-bucket launch; requests that arrive together share a launch,
and padding slots run as MODE_NOOP (count 0, no ids), their cost
surfaced through the ``sim.serving.padded_slots`` counter and the
``padding_waste_pct`` stat.

Concurrency model: there is no background thread to manage (nothing to
leak at shutdown — the lesson of the agent cache's refresh plane).
``submit()`` parks the caller up to ``max_wait_s``; whoever's wait
expires first pumps EVERY pending request into one batch and fans the
results back to the other waiters. ``execute()`` is the synchronous
path for callers that already hold a whole batch (bench, row sorting).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import NamedTuple, Optional, Sequence

import numpy as np

from consul_tpu.analysis import ledger
from consul_tpu.obs import trace as obs_trace
from consul_tpu.ops import serving as kernels


class ServingClosedError(RuntimeError):
    """The serving plane (or one of its batchers) has been closed:
    parked waiters are woken with this, and new submits are rejected
    with it — the ``agent/cache.py`` CacheClosedError discipline."""


class ServingOverloadError(RuntimeError):
    """Admission control rejected a submit: the bounded pending queue
    is full and the batcher's policy is ``reject`` (callers retry with
    backoff; the ``shed_oldest`` policy drops the oldest waiter
    instead and admits the new one)."""


class QueryResult(NamedTuple):
    """One query's answer: ``ids[i]``/``rtts[i]`` for i < count are the
    result rows (node indices and estimated RTT seconds, +inf for
    eligible-but-unknown coordinates); slots at and past ``count`` hold
    id -1 / rtt +inf. ``tick`` is the snapshot tick the answer is
    consistent as of."""

    ids: np.ndarray    # [k] i32
    rtts: np.ndarray   # [k] f32
    count: int
    tick: int


class _Waiter:
    __slots__ = ("mode", "src", "arg", "done", "result", "error")

    def __init__(self, mode: int, src: int, arg: int):
        self.mode = mode
        self.src = src
        self.arg = arg
        self.done = threading.Event()
        self.result: Optional[QueryResult] = None
        self.error: Optional[Exception] = None


class QueryBatcher:
    """Packs (mode, src, arg) queries into padded bucketed batches and
    executes them against ``plane.snapshot()`` via the memoized kernel.
    """

    def __init__(self, plane, k: int = 16,
                 buckets: Sequence[int] = (1, 8, 64, 512),
                 max_wait_s: float = 0.002):
        if not buckets:
            raise ValueError("need at least one batch bucket")
        self.plane = plane
        self.k = int(k)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.max_batch = self.buckets[-1]
        self.max_wait_s = float(max_wait_s)
        self._lock = ledger.make_lock("QueryBatcher._lock")
        self._pending: list[_Waiter] = []
        self._closed = False
        # Plain-int counters mirror the sink emissions so stats() works
        # without a sink attached.
        self.batches = 0
        self.queries = 0
        self.padded_slots = 0
        self.latencies_s: deque[float] = deque(maxlen=4096)

    # ------------------------------------------------------------------
    # Synchronous batched path
    # ------------------------------------------------------------------
    def execute(self, queries: Sequence[tuple[int, int, int]]
                ) -> list[QueryResult]:
        """Run a caller-assembled batch; oversize inputs are chunked at
        the largest bucket. One kernel launch + one device_get per
        chunk."""
        out: list[QueryResult] = []
        for i in range(0, len(queries), self.max_batch):
            out.extend(self._run_batch(queries[i:i + self.max_batch]))
        return out

    def _bucket(self, b: int) -> int:
        for cap in self.buckets:
            if cap >= b:
                return cap
        return self.max_batch

    def _run_batch(self, queries: Sequence[tuple[int, int, int]]
                   ) -> list[QueryResult]:
        import jax

        snap = self.plane.snapshot()
        t0 = time.perf_counter()
        b = len(queries)
        bucket = self._bucket(b)
        mode = np.full(bucket, kernels.MODE_NOOP, dtype=np.int32)
        src = np.zeros(bucket, dtype=np.int32)
        arg = np.full(bucket, -1, dtype=np.int32)
        for j, (m, s, a) in enumerate(queries):
            mode[j] = m
            src[j] = s
            arg[j] = a
        dm, ds, da = jax.device_put((mode, src, arg))
        kernel = getattr(self.plane, "kernel", None)
        kernel = kernel() if kernel is not None else kernels.kernel_for(self.k)
        ids, rtts, count, tick = kernel(snap, dm, ds, da)
        h_ids, h_rtts, h_count, h_tick = jax.device_get(
            (ids, rtts, count, tick))

        pad = bucket - b
        # execute() runs on caller threads concurrently with pump();
        # the telemetry counters need the lock (TH114) — taken after
        # the device_get so transfers never sit in the critical section
        with self._lock:
            self.latencies_s.append(time.perf_counter() - t0)
            self.batches += 1
            self.queries += b
            self.padded_slots += pad
        sink = getattr(self.plane, "sink", None)
        if sink is not None:
            sink.incr_counter("sim.serving.batches", 1)
            sink.incr_counter("sim.serving.queries", b)
            if pad:
                sink.incr_counter("sim.serving.padded_slots", pad)

        tick_i = int(h_tick)
        return [QueryResult(h_ids[j], h_rtts[j], int(h_count[j]), tick_i)
                for j in range(b)]

    # ------------------------------------------------------------------
    # Concurrent submit/fan-out path
    # ------------------------------------------------------------------
    def submit(self, mode: int, src: int, arg: int = -1,
               timeout_s: float = 10.0) -> QueryResult:
        """Enqueue one query and block for its result. Concurrent
        submitters coalesce: each parks up to ``max_wait_s`` and the
        first to time out (or to fill the largest bucket) pumps the
        whole pending set as one batch, fanning results back."""
        w = _Waiter(int(mode), int(src), int(arg))
        with self._lock:
            if self._closed:
                raise ServingClosedError("serving plane is closed")
            self._pending.append(w)
            full = len(self._pending) >= self.max_batch
        if full:
            self.pump()
        deadline = time.monotonic() + timeout_s
        while not w.done.wait(self.max_wait_s):
            if time.monotonic() >= deadline:
                raise TimeoutError("serving query timed out")
            self.pump()
        if w.error is not None:
            raise w.error
        assert w.result is not None
        return w.result

    def pump(self) -> int:
        """Drain pending waiters (up to one max bucket) into one batch;
        returns how many were served."""
        with self._lock:
            batch = self._pending[:self.max_batch]
            del self._pending[:len(batch)]
        if not batch:
            return 0
        with obs_trace.span("serving.query_pump", cat="serving",
                            args={"n": len(batch)}):
            results = self._run_batch(
                [(w.mode, w.src, w.arg) for w in batch])
        for w, r in zip(batch, results):
            w.result = r
            w.done.set()
        return len(batch)

    # ------------------------------------------------------------------
    # Shutdown (the agent/cache.py close discipline: wake every parked
    # waiter, reject every new submit)
    # ------------------------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        """Idempotent: mark closed, fail parked waiters with
        :class:`ServingClosedError` (never leave a thread parked on a
        plane that will not pump again), reject new submits."""
        with self._lock:
            self._closed = True
            pending, self._pending = self._pending, []
        for w in pending:
            w.error = ServingClosedError("serving plane closed while "
                                         "query was pending")
            w.done.set()

    # ------------------------------------------------------------------
    # Stats
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        lats = sorted(self.latencies_s)
        if lats:
            p50 = lats[len(lats) // 2]
            p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))]
        else:
            p50 = p99 = 0.0
        slots = self.queries + self.padded_slots
        return {
            "batches": self.batches,
            "queries": self.queries,
            "padded_slots": self.padded_slots,
            "padding_waste_pct": round(100.0 * self.padded_slots
                                       / max(1, slots), 2),
            "p50_batch_ms": round(p50 * 1e3, 3),
            "p99_batch_ms": round(p99 * 1e3, 3),
        }
