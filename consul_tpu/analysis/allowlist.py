"""The checked-in lint allowlist: every exemption is reviewable.

Format (``analysis/allowlist.toml``)::

    [[allow]]
    rule = "TH103"                          # required
    path = "consul_tpu/models/cluster.py"   # required, repo-relative
    symbol = "Simulation.run"               # optional: enclosing def
    line = 123                              # optional: exact line pin
    reason = "host-tier chunk timing"       # required, non-empty

Matching prefers ``symbol`` over ``line`` — symbols survive line
drift, so entries stay valid across unrelated edits. An entry that
matches nothing is reported as *unused* and fails the tier-1 gate:
the allowlist can only shrink or stay justified, never rot.

Python 3.10 has no ``tomllib``, and the container must not grow deps,
so this module carries a parser for exactly the TOML subset above
(comments, ``[[allow]]`` table arrays, string/int/bool values). It
delegates to ``tomllib`` when the interpreter provides it.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

try:  # Python >= 3.11
    import tomllib as _tomllib
except ImportError:  # Python 3.10: the subset parser below
    _tomllib = None


class AllowlistError(ValueError):
    """Malformed allowlist file (syntax or schema)."""


@dataclasses.dataclass
class AllowEntry:
    rule: str
    path: str
    reason: str
    symbol: Optional[str] = None
    line: Optional[int] = None
    hits: int = 0

    def matches(self, finding) -> bool:
        if self.rule != finding.rule or self.path != finding.path:
            return False
        if self.symbol is not None:
            sym = finding.symbol
            if sym != self.symbol and not sym.startswith(
                    self.symbol + "."):
                return False
        if self.line is not None and self.line != finding.line:
            return False
        return True


class Allowlist:
    def __init__(self, entries: Iterable[AllowEntry]):
        self.entries = list(entries)

    def match(self, finding) -> Optional[AllowEntry]:
        """First entry suppressing ``finding`` (marking it used)."""
        for e in self.entries:
            if e.matches(finding):
                e.hits += 1
                return e
        return None

    def unused(self) -> list:
        return [e for e in self.entries if e.hits == 0]


def load_allowlist(path: str) -> Allowlist:
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    return parse_allowlist(text, where=path)


def parse_allowlist(text: str, where: str = "<allowlist>") -> Allowlist:
    data = (_tomllib.loads(text) if _tomllib is not None
            else _parse_toml_subset(text, where))
    entries = []
    for i, raw in enumerate(data.get("allow", [])):
        if not isinstance(raw, dict):
            raise AllowlistError(f"{where}: [[allow]] #{i + 1} is not "
                                 "a table")
        unknown = set(raw) - {"rule", "path", "symbol", "line", "reason"}
        if unknown:
            raise AllowlistError(
                f"{where}: [[allow]] #{i + 1} has unknown keys "
                f"{sorted(unknown)}")
        for req in ("rule", "path", "reason"):
            if not isinstance(raw.get(req), str) or not raw[req].strip():
                raise AllowlistError(
                    f"{where}: [[allow]] #{i + 1} needs a non-empty "
                    f"string {req!r} — every exemption carries its "
                    "justification")
        line = raw.get("line")
        if line is not None and not isinstance(line, int):
            raise AllowlistError(
                f"{where}: [[allow]] #{i + 1}: line must be an integer")
        entries.append(AllowEntry(
            rule=raw["rule"], path=raw["path"].replace("\\", "/"),
            reason=raw["reason"], symbol=raw.get("symbol"), line=line))
    return Allowlist(entries)


def _parse_toml_subset(text: str, where: str) -> dict:
    tables: list = []
    current: Optional[dict] = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[allow]]":
            current = {}
            tables.append(current)
            continue
        if line.startswith("["):
            raise AllowlistError(
                f"{where}:{lineno}: only [[allow]] tables are "
                f"supported, got {line!r}")
        if "=" not in line:
            raise AllowlistError(
                f"{where}:{lineno}: expected 'key = value', got "
                f"{line!r}")
        if current is None:
            raise AllowlistError(
                f"{where}:{lineno}: key/value outside an [[allow]] "
                "table")
        key, _, value = line.partition("=")
        current[key.strip()] = _parse_value(value.strip(), where, lineno)
    return {"allow": tables}


def _parse_value(value: str, where: str, lineno: int):
    # strip trailing comments outside strings
    if value.startswith(("\"", "'")):
        quote = value[0]
        end = value.find(quote, 1)
        while end != -1 and value[end - 1] == "\\":
            end = value.find(quote, end + 1)
        if end == -1:
            raise AllowlistError(
                f"{where}:{lineno}: unterminated string")
        tail = value[end + 1:].strip()
        if tail and not tail.startswith("#"):
            raise AllowlistError(
                f"{where}:{lineno}: trailing junk after string: "
                f"{tail!r}")
        return value[1:end].replace("\\\"", "\"").replace("\\\\", "\\")
    value = value.split("#", 1)[0].strip()
    if value in ("true", "false"):
        return value == "true"
    try:
        return int(value)
    except ValueError:
        raise AllowlistError(
            f"{where}:{lineno}: unsupported value {value!r} (strings "
            "must be quoted)") from None
