"""Trace-hygiene static analysis for the jitted gossip core.

The paper's premise is that SWIM/serf/Vivaldi run as *one* compiled
scan on device — every silent recompile, implicit host<->device
transfer, or accidental dtype widening erodes the <60 s / 1M-node
target. This package enforces the device/host tier boundary
mechanically, in two layers:

- **Static** (this module + :mod:`engine` / :mod:`rules` /
  :mod:`callgraph`): an AST lint pass (stdlib ``ast``, no new deps)
  over the device tier. Trace reachability is computed from the real
  trace entry points (``jax.jit`` / ``lax.scan`` / ``shard_map`` /
  ``vmap`` call sites), so "host sync inside traced code" means
  *reachable from the jitted scan*, not "mentions numpy somewhere".
  Findings carry file:line + rule id; exemptions live only in the
  checked-in ``analysis/allowlist.toml`` with a mandatory reason.
  Run it as ``consul-tpu lint`` (exit 1 on unallowlisted findings) or
  through the tier-1 gate test (tests/test_analysis.py).

- **Runtime** (:mod:`guards`, imported lazily — it needs jax, the
  static layer does not): ``jax.transfer_guard`` wrappers and the
  process-wide :class:`~consul_tpu.analysis.guards.CompileLedger`
  built on ``jax.monitoring``, which the compile-count pins in
  tests/test_counters.py, test_chaos.py and test_runtime.py share.

Rule ids (one-line rationale per id in COVERAGE.md):

==========  ==========================================================
TH101       implicit scalar host sync (``.item()``/``.tolist()``/
            ``int()``/``float()``/``bool()``) inside traced code
TH102       host transfer API (``np.asarray``/``jax.device_get``/...)
            inside traced code
TH103       impure host stdlib (``time``/``random``/``datetime``) in a
            device-tier module
TH104       ``jnp`` array constructor without an explicit dtype in a
            device-tier module
TH105       swallowed exception (bare/broad ``except`` + ``pass``)
            anywhere in the package
TH106       mutable default argument anywhere in the package
TH107       module-level mutable state read inside traced code
TH108       host-tier retry loop with a bare constant ``time.sleep``
            and no bound/backoff anywhere in the package
TH109       data-dependent scatter in traced code
TH110       sharding-less device placement in a mesh-handling path
TH111       hand-widened packed state field in traced code
TH112       wall-clock subtraction used as a duration
TH113       unbounded thread spawn in host serving/gameday tiers
TH114       inconsistently guarded attribute write (guarded-by
            inference over per-class lock inventories)
TH115       lock-order cycle / non-reentrant re-acquire (static
            inter-procedural acquired-while-holding graph)
TH116       ``Condition.wait()`` outside a while-predicate loop
TH117       blocking call (device transfer, socket/file I/O,
            no-timeout ``Queue.get``, subprocess) under a held lock
==========  ==========================================================

TH114-TH117 are the host-tier concurrency rules
(:mod:`~consul_tpu.analysis.concurrency`); their runtime twin is the
:class:`~consul_tpu.analysis.ledger.LockLedger` — a monkeypatch-free
``threading`` shim (same idiom as CompileLedger) that traces real
acquisitions at test time, asserts the observed order graph acyclic,
and drives a seeded interleaving fuzzer.
"""

from consul_tpu.analysis.allowlist import (Allowlist, AllowlistError,
                                           load_allowlist)
from consul_tpu.analysis.engine import (Finding, LintReport,
                                        default_allowlist_path,
                                        lint_package, lint_sources,
                                        package_lock_graph)
from consul_tpu.analysis.ledger import LockLedger, LockLedgerError
from consul_tpu.analysis.rules import RULES

__all__ = [
    "Allowlist", "AllowlistError", "Finding", "LintReport",
    "LockLedger", "LockLedgerError", "RULES",
    "default_allowlist_path", "lint_package", "lint_sources",
    "load_allowlist", "package_lock_graph",
]
