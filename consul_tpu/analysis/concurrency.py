"""Host-tier lock-discipline pass (TH114-TH117).

The reference Consul leans on ``go test -race``; this rebuild's host
tier is ~33 ``threading`` lock sites with no race tooling. This pass
rides the engine's :class:`~consul_tpu.analysis.engine.ModuleIndex`
and closes that gap statically:

- **Per-class lock inventory**: attributes assigned
  ``threading.Lock()`` / ``RLock()`` / ``Condition()`` (or the
  :mod:`consul_tpu.analysis.ledger` factory equivalents), plus
  module-level and function-local locks.  A ``Condition(self._lock)``
  is recorded as an *alias* of the lock it wraps, so holding either
  counts as holding both.

- **TH114 — guarded-by inference**: for every lock-owning class, each
  write to a plain ``self.attr`` is classified by the set of class
  locks held (lexically, plus the guard a private method *inherits*
  when every internal call site holds the same lock).  An attribute
  written both under a lock and without one is inconsistently guarded;
  an unguarded read-modify-write (``self.x += 1``,
  ``self.xs.append(...)``) in a class that owns a Lock/RLock is a lost
  update waiting for a second thread.  ``__init__``/``__new__`` are
  exempt (no concurrent publication yet).

- **TH115 — lock-ordering cycles**: a global digraph of "acquired B
  while holding A" edges, collected lexically from nested ``with``
  blocks and inter-procedurally through call summaries (a call made
  under a lock contributes every lock the callee may acquire).  Any
  cycle is a potential deadlock; nesting a non-reentrant lock inside
  itself is reported directly.

- **TH116 — Condition.wait without a predicate loop**: ``cond.wait()``
  must sit inside a ``while`` that re-checks its predicate (spurious
  wakeups, stolen wakeups); ``wait_for`` carries its own loop and is
  always fine.

- **TH117 — blocking call under a lock**: device transfers
  (``jax.device_get``/``device_put``/``jnp.*``/``block_until_ready``),
  socket and file I/O, zero-timeout ``Queue.get()``, ``time.sleep``
  and ``subprocess`` executed while any lock is held serialize every
  other acquirer behind host-side latency.

Documented narrowings (COVERAGE.md "Concurrency analysis"): writes
through subscripts (``self.d[k] = v``) and attribute chains
(``self.a.b += 1``) are not tracked; cross-object lock identity is
only unified when the attribute name is a package-unique lock
(``write_lock``); generator-based ``with store.transaction():`` holds
are invisible; lock-*ish* names (containing ``lock``/``mutex``/
``cond``) that cannot be resolved participate in held-ness (TH117)
but never in order edges (TH115).  The dynamic
:class:`~consul_tpu.analysis.ledger.LockLedger` covers the runtime
side of the same contracts.
"""

from __future__ import annotations

import ast
from typing import Optional

# Calls that *create* a lock. Values: lock kind.
LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "consul_tpu.analysis.ledger.make_lock": "lock",
    "consul_tpu.analysis.ledger.make_rlock": "rlock",
    "consul_tpu.analysis.ledger.make_condition": "condition",
    "consul_tpu.analysis.guards.make_lock": "lock",
    "consul_tpu.analysis.guards.make_rlock": "rlock",
    "consul_tpu.analysis.guards.make_condition": "condition",
}

# Container-mutating method names treated as writes to the receiver.
MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "add", "discard",
    "setdefault", "rotate", "sort", "reverse",
})

# Blocking calls by resolved dotted fqname.
BLOCKING_FQ = frozenset({
    "jax.device_get", "jax.device_put", "jax.block_until_ready",
    "time.sleep", "socket.create_connection", "socket.create_server",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
})
# Resolved-prefix blocking families: any jnp constructor/transfer.
BLOCKING_FQ_PREFIXES = ("jax.numpy.",)
# Blocking calls by bare attribute name (socket methods, device sync).
BLOCKING_ATTRS = frozenset({
    "sendall", "recv", "recv_into", "recvfrom", "accept",
    "block_until_ready",
})

_LOCKISH = ("lock", "mutex", "cond")


def _is_lockish(name: str) -> bool:
    low = name.lower()
    return any(tok in low for tok in _LOCKISH)


def _receiver_attr(node):
    """('self'|'cls', attr) for a plain ``self.X`` / ``cls.X``."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id in ("self", "cls"):
        return node.value.id, node.attr
    return None


def _dotted_tail(node) -> Optional[str]:
    """Last segment of a dotted Name/Attribute chain, else None."""
    while isinstance(node, ast.Attribute):
        tail = node.attr
        node = node.value
        if isinstance(node, (ast.Name, ast.Attribute)):
            if isinstance(node, ast.Name):
                return tail
            continue
        return None
    if isinstance(node, ast.Name):
        return node.id
    return None


class _ClassInfo:
    def __init__(self, modname: str, qual: str, node: ast.ClassDef):
        self.modname = modname
        self.qual = qual                  # dotted, e.g. "Outer.Inner"
        self.node = node
        self.locks: dict = {}             # attr -> kind
        self.aliases: dict = {}           # cond attr -> wrapped lock attr
        self.methods: dict = {}           # name -> FunctionDef node

    def key_of(self, attr: str) -> str:
        return f"{self.modname}.{self.qual}.{self.canonical(attr)}"

    def canonical(self, attr: str) -> str:
        seen = set()
        while attr in self.aliases and attr not in seen:
            seen.add(attr)
            attr = self.aliases[attr]
        return attr


class _FnInfo:
    def __init__(self, mod, qual: str, node, cls: Optional[_ClassInfo]):
        self.mod = mod
        self.qual = qual                  # module-local dotted qualname
        self.fq = f"{mod.modname}.{qual}"
        self.node = node
        self.cls = cls
        self.local_locks: dict = {}       # local/param name -> kind
        self.acquired: set = set()        # canonical keys taken lexically
        self.edges: list = []             # (held_key, taken_key, node)
        self.self_deadlocks: list = []    # (key, node)
        self.writes: list = []            # (attr, heldset, node, kind)
        self.calls: list = []             # (target, heldkeys, node)
        self.self_calls: list = []        # (name, class_locks_held, node)
        self.blockers: list = []          # (desc, node, heldkeys)
        self.waits: list = []             # (node, has_loop, is_wait_for)


class _Pass:
    """Whole-package state: inventories then per-function walks."""

    def __init__(self, modules):
        self.modules = modules
        self.classes: dict = {}           # (modname, qual) -> _ClassInfo
        self.module_locks: dict = {}      # modname -> {name: kind}
        self.cond_attr_names: set = set()  # all condition attr names
        self.attr_owners: dict = {}       # lock attr name -> [class keys]
        self.infos: dict = {}             # fq -> _FnInfo
        self.findings: list = []

    # -- pass 1: inventory ----------------------------------------------
    def inventory(self):
        for mod in self.modules:
            locks = {}
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    kind = self._factory_kind(mod, stmt.value, None)
                    if kind:
                        locks[stmt.targets[0].id] = kind
            self.module_locks[mod.modname] = locks
            self._collect_classes(mod, mod.tree, prefix="")
        for cls in self.classes.values():
            for attr, kind in cls.locks.items():
                if kind == "condition":
                    self.cond_attr_names.add(attr)
                base = cls.canonical(attr)
                self.attr_owners.setdefault(attr, []).append(
                    (f"{cls.modname}.{cls.qual}.{base}", cls.locks[base]))

    def _factory_kind(self, mod, value, fn_node) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        fq = mod.resolve(value.func, fn_node)
        return LOCK_FACTORIES.get(fq) if fq else None

    def _collect_classes(self, mod, tree, prefix: str):
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, ast.ClassDef):
                qual = f"{prefix}{node.name}"
                cls = _ClassInfo(mod.modname, qual, node)
                self.classes[(mod.modname, qual)] = cls
                for stmt in node.body:
                    # class-body locks (the CompileLedger idiom)
                    if isinstance(stmt, ast.Assign) \
                            and len(stmt.targets) == 1 \
                            and isinstance(stmt.targets[0], ast.Name):
                        kind = self._factory_kind(mod, stmt.value, None)
                        if kind:
                            cls.locks[stmt.targets[0].id] = kind
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        cls.methods[stmt.name] = stmt
                        self._scan_lock_attrs(mod, cls, stmt)
                self._collect_classes(mod, node, prefix=qual + ".")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested classes inside functions: out of scope
            else:
                self._collect_classes(mod, node, prefix=prefix)

    def _scan_lock_attrs(self, mod, cls: _ClassInfo, meth):
        for node in ast.walk(meth):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            recv = _receiver_attr(node.targets[0])
            if recv is None:
                continue
            kind = self._factory_kind(mod, node.value, meth)
            if not kind:
                continue
            cls.locks[recv[1]] = kind
            if kind == "condition" and isinstance(node.value, ast.Call):
                args = list(node.value.args)
                for kw in node.value.keywords:
                    if kw.arg == "lock":
                        args.append(kw.value)
                for arg in args:
                    wrapped = _receiver_attr(arg)
                    if wrapped is not None:
                        cls.aliases[recv[1]] = wrapped[1]
                        break

    # -- pass 2: function walks -----------------------------------------
    def walk_functions(self):
        fn_to_class: dict = {}
        for cls in self.classes.values():
            for meth in cls.methods.values():
                fn_to_class[id(meth)] = cls
        for mod in self.modules:
            for qual, fn in mod.functions.items():
                cls = fn_to_class.get(id(fn))
                info = _FnInfo(mod, qual, fn, cls)
                self.infos[info.fq] = info
                self._collect_local_locks(mod, info)
                for stmt in fn.body:
                    self._walk(info, stmt, held=(), loops=())

    def _collect_local_locks(self, mod, info: _FnInfo):
        args = info.node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            if _is_lockish(a.arg):
                info.local_locks[a.arg] = "lock"
        for stmt in info.node.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                kind = self._factory_kind(mod, stmt.value, info.node)
                if kind:
                    info.local_locks[stmt.targets[0].id] = kind

    def _lock_key(self, info: _FnInfo, expr):
        """(key, kind) for a with-subject, (None, None) if not a lock.
        key '?' marks an unresolvable lock-ish expression: it counts as
        held (TH117) but contributes no order edges (TH115)."""
        recv = _receiver_attr(expr)
        if recv is not None and info.cls is not None \
                and recv[1] in info.cls.locks:
            base = info.cls.canonical(recv[1])
            return info.cls.key_of(recv[1]), info.cls.locks[base]
        if isinstance(expr, ast.Name):
            if expr.id in info.local_locks:
                return (f"{info.mod.modname}.{info.qual}.{expr.id}",
                        info.local_locks[expr.id])
            fq = info.mod.resolve(expr, info.node)
            if fq:
                modname, _, name = fq.rpartition(".")
                if name in self.module_locks.get(modname, {}):
                    return fq, self.module_locks[modname][name]
        tail = _dotted_tail(expr)
        if tail is None:
            return None, None
        # a package-unique lock attribute unifies cross-object holds
        # (self.plane.write_lock in writes.py IS ServingPlane.write_lock)
        owners = self.attr_owners.get(tail, ())
        if len(owners) == 1 and recv is None:
            return owners[0]
        if _is_lockish(tail):
            return "?", "lock"
        return None, None

    def _class_locks_held(self, info: _FnInfo, held) -> frozenset:
        if info.cls is None:
            return frozenset()
        prefix = f"{info.cls.modname}.{info.cls.qual}."
        return frozenset(k[len(prefix):] for k in held
                         if k != "?" and k.startswith(prefix)
                         and k[len(prefix):] in info.cls.locks)

    def _walk(self, info: _FnInfo, node, held, loops):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # nested defs are walked as their own units
        if isinstance(node, (ast.With, ast.AsyncWith)):
            taken = []
            for item in node.items:
                key, kind = self._lock_key(info, item.context_expr)
                if key is None:
                    continue
                if key != "?":
                    if key in held and kind == "lock":
                        info.self_deadlocks.append((key, item.context_expr))
                    for h in held:
                        if h != "?" and h != key:
                            info.edges.append((h, key, item.context_expr))
                    info.acquired.add(key)
                taken.append(key)
            inner = held + tuple(taken)
            for item in node.items:
                self._walk(info, item.context_expr, held, loops)
            for stmt in node.body:
                self._walk(info, stmt, inner, loops)
            return
        if isinstance(node, ast.While):
            self._walk(info, node.test, held, loops)
            for stmt in node.body + node.orelse:
                self._walk(info, stmt, held, loops + (node,))
            return
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            kind = "rmw" if isinstance(node, ast.AugAssign) else "assign"
            for t in targets:
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                for el in elts:
                    recv = _receiver_attr(el)
                    if recv is not None:
                        info.writes.append(
                            (recv[1], self._class_locks_held(info, held),
                             el, kind))
            self._walk(info, node.value, held, loops)
            return
        if isinstance(node, ast.Call):
            self._visit_call(info, node, held, loops)
        for child in ast.iter_child_nodes(node):
            self._walk(info, child, held, loops)

    def _visit_call(self, info: _FnInfo, node: ast.Call, held, loops):
        func = node.func
        # self.attr.mutator(...) is a write to self.attr
        if isinstance(func, ast.Attribute) and func.attr in MUTATORS:
            recv = _receiver_attr(func.value)
            if recv is not None and info.cls is not None \
                    and recv[1] not in info.cls.locks:
                info.writes.append(
                    (recv[1], self._class_locks_held(info, held),
                     node, "mutate"))
        # Condition.wait discipline
        if isinstance(func, ast.Attribute) \
                and func.attr in ("wait", "wait_for") \
                and self._is_condition_recv(info, func.value):
            info.waits.append((node, bool(loops),
                               func.attr == "wait_for"))
        # blocking-call census
        desc = self._blocking_desc(info, node)
        if desc is not None and held:
            info.blockers.append((desc, node, held))
        elif desc is not None:
            info.blockers.append((desc, node, ()))
        # call-graph edges for summaries (self.m() — NOT self.attr.m())
        if isinstance(func, ast.Attribute):
            recv = _receiver_attr(func)
            if recv is not None and info.cls is not None \
                    and func.attr in info.cls.methods:
                info.self_calls.append(
                    (func.attr, self._class_locks_held(info, held), node))
                info.calls.append(
                    (f"{info.cls.modname}.{info.cls.qual}.{func.attr}",
                     held, node))
                return
        fq = info.mod.resolve(func, info.node)
        if fq:
            info.calls.append((fq, held, node))

    def _is_condition_recv(self, info: _FnInfo, recv) -> bool:
        r = _receiver_attr(recv)
        if r is not None:
            if info.cls is not None and r[1] in info.cls.locks:
                return info.cls.locks[info.cls.canonical(r[1])] == \
                    "condition"
            return r[1] in self.cond_attr_names
        if isinstance(recv, ast.Name):
            if recv.id in info.local_locks:
                return info.local_locks[recv.id] == "condition"
            return False
        tail = _dotted_tail(recv)
        return tail is not None and tail in self.cond_attr_names

    def _blocking_desc(self, info: _FnInfo, node: ast.Call
                       ) -> Optional[str]:
        func = node.func
        if isinstance(func, ast.Name) and func.id == "open" \
                and info.mod.resolve(func, info.node) is None:
            return "open()"
        fq = info.mod.resolve(func, info.node)
        if fq:
            if fq in BLOCKING_FQ:
                return fq
            if any(fq.startswith(p) for p in BLOCKING_FQ_PREFIXES):
                return fq
        if isinstance(func, ast.Attribute):
            if func.attr in BLOCKING_ATTRS:
                return f".{func.attr}()"
            # queue-style blocking get: zero args, no timeout
            if func.attr == "get" and not node.args and not node.keywords:
                return ".get() with no timeout"
        return None

    # -- analyses -------------------------------------------------------
    def _finding(self, info: _FnInfo, node, rule: str, message: str):
        from consul_tpu.analysis.engine import Finding

        self.findings.append(Finding(
            rule=rule, path=info.mod.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            symbol=info.qual, message=message))

    def run_th114(self):
        by_class: dict = {}
        for info in self.infos.values():
            if info.cls is not None:
                by_class.setdefault(id(info.cls.node), []).append(info)
        for infos in by_class.values():
            self._th114_class(infos[0].cls, infos)

    def _th114_class(self, cls: _ClassInfo, infos):
        if not cls.locks:
            return
        has_real_lock = any(k in ("lock", "rlock")
                            for k in cls.locks.values())
        guard = self._inherited_guards(cls, infos)
        writes: dict = {}   # attr -> [(eff_guard, node, kind, info)]
        for info in infos:
            name = info.qual.rsplit(".", 1)[-1]
            if name in ("__init__", "__new__"):
                continue
            inherited = guard.get(name, frozenset())
            for attr, held, node, kind in info.writes:
                if attr in cls.locks:
                    continue
                eff = frozenset(cls.canonical(a) for a in held) | inherited
                writes.setdefault(attr, []).append((eff, node, kind, info))
        for attr, ws in sorted(writes.items()):
            guarded = sorted({lk for eff, *_ in ws for lk in eff})
            unguarded = [(node, kind, info) for eff, node, kind, info in ws
                         if not eff]
            flagged = set()
            if guarded and unguarded:
                for node, kind, info in unguarded:
                    flagged.add(id(node))
                    self._finding(
                        info, node, "TH114",
                        f"attribute 'self.{attr}' is written under "
                        f"'self.{guarded[0]}' elsewhere in "
                        f"{cls.qual} but written here with no lock "
                        "held — inconsistently guarded state")
            if not has_real_lock:
                continue
            lock_names = sorted(a for a, k in cls.locks.items()
                                if k in ("lock", "rlock"))
            for node, kind, info in unguarded:
                if kind in ("rmw", "mutate") and id(node) not in flagged:
                    self._finding(
                        info, node, "TH114",
                        f"unguarded read-modify-write of 'self.{attr}' "
                        f"in {cls.qual}, which guards its state with "
                        f"'self.{lock_names[0]}' — a concurrent writer "
                        "loses updates; hold the lock (or allowlist a "
                        "documented single-writer seam)")

    def _inherited_guards(self, cls: _ClassInfo, infos) -> dict:
        """method name -> lock set every internal call site holds.
        Public methods (and dunders) anchor at the empty set; private
        methods start at the full lock set and shrink to the greatest
        fixpoint over their observed call sites."""
        sites: dict = {}
        for info in infos:
            caller = info.qual.rsplit(".", 1)[-1]
            for name, held, _node in info.self_calls:
                sites.setdefault(name, []).append(
                    (caller, frozenset(cls.canonical(a) for a in held)))
        all_locks = frozenset(cls.canonical(a) for a in cls.locks)
        guard = {}
        for name in cls.methods:
            private = name.startswith("_") and not (
                name.startswith("__") and name.endswith("__"))
            guard[name] = all_locks if (private and sites.get(name)) \
                else frozenset()
        for _ in range(len(cls.methods) + 2):
            changed = False
            for name, slist in sites.items():
                if name not in guard or not guard[name]:
                    continue
                new = None
                for caller, held in slist:
                    eff = held | guard.get(caller, frozenset())
                    new = eff if new is None else (new & eff)
                new = new or frozenset()
                if new != guard[name]:
                    guard[name] = new
                    changed = True
            if not changed:
                break
        return guard

    def _summaries(self):
        """Fixpoint (acquires, blocking) closure over the call graph."""
        acquires = {fq: set(i.acquired) for fq, i in self.infos.items()}
        blocking = {fq: {d for d, _n, _h in i.blockers}
                    for fq, i in self.infos.items()}
        for _ in range(64):
            changed = False
            for fq, info in self.infos.items():
                for target, _held, _node in info.calls:
                    if target == fq or target not in self.infos:
                        continue
                    if not acquires[target] <= acquires[fq]:
                        acquires[fq] |= acquires[target]
                        changed = True
                    if not blocking[target] <= blocking[fq]:
                        blocking[fq] |= blocking[target]
                        changed = True
            if not changed:
                break
        return acquires, blocking

    def run_th115_th117(self):
        acquires, blocking = self._summaries()
        edges: dict = {}   # (src, dst) -> (info, node)
        lock_kind = {}
        for cls in self.classes.values():
            for attr, kind in cls.locks.items():
                lock_kind[cls.key_of(attr)] = cls.locks[cls.canonical(attr)]
        for modname, locks in self.module_locks.items():
            for name, kind in locks.items():
                lock_kind[f"{modname}.{name}"] = kind
        for info in self.infos.values():
            for key, node in info.self_deadlocks:
                self._finding(
                    info, node, "TH115",
                    f"'{key}' is re-acquired while already held — a "
                    "non-reentrant Lock self-deadlocks here")
            for src, dst, node in info.edges:
                edges.setdefault((src, dst), (info, node))
            # interprocedural: a call made under a lock drags in every
            # lock (and blocker) the callee may reach
            for target, held, node in info.calls:
                if not held or target not in self.infos:
                    continue
                real = [h for h in held if h != "?"]
                for h in real:
                    for k in acquires.get(target, ()):
                        if k == h:
                            if lock_kind.get(k) == "lock":
                                self._finding(
                                    info, node, "TH115",
                                    f"call into '{target}' while holding "
                                    f"'{h}', which it re-acquires — a "
                                    "non-reentrant Lock self-deadlocks")
                            continue
                        edges.setdefault((h, k), (info, node))
                blocked = blocking.get(target, ())
                if blocked:
                    self._finding(
                        info, node, "TH117",
                        f"call into '{target}' while holding "
                        f"{_fmt_locks(held)} — it performs blocking work "
                        f"({sorted(blocked)[0]}); move the call outside "
                        "the critical section")
            for desc, node, held in info.blockers:
                if held:
                    self._finding(
                        info, node, "TH117",
                        f"blocking call {desc} while holding "
                        f"{_fmt_locks(held)} — every other acquirer "
                        "serializes behind it; hoist it out of the "
                        "critical section")
        self._cycles(edges)
        self._edge_list = sorted(
            (src, dst, i.mod.relpath, getattr(n, "lineno", 0))
            for (src, dst), (i, n) in edges.items())

    def _cycles(self, edges: dict):
        adj: dict = {}
        for (src, dst), _site in edges.items():
            adj.setdefault(src, set()).add(dst)
        seen_cycles = set()
        for start in sorted(adj):
            path, on_path = [], set()

            def dfs(node):
                if node in on_path:
                    cyc = tuple(path[path.index(node):] + [node])
                    key = frozenset(cyc)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        info, site = edges[(cyc[0], cyc[1])]
                        self._finding(
                            info, site, "TH115",
                            "potential deadlock: lock-order cycle "
                            + " -> ".join(f"'{c}'" for c in cyc)
                            + " — two threads taking these locks in "
                            "opposite orders block forever")
                    return
                if node in path_seen:
                    return
                path_seen.add(node)
                path.append(node)
                on_path.add(node)
                for nxt in sorted(adj.get(node, ())):
                    dfs(nxt)
                path.pop()
                on_path.discard(node)

            path_seen: set = set()
            dfs(start)

    def run_th116(self):
        for info in self.infos.values():
            for node, has_loop, is_wait_for in info.waits:
                if is_wait_for or has_loop:
                    continue
                self._finding(
                    info, node, "TH116",
                    "Condition.wait() outside a while-predicate loop — "
                    "spurious and stolen wakeups make a bare wait "
                    "return with the predicate still false; use "
                    "'while not pred: cond.wait(...)' or wait_for()")


def _fmt_locks(held) -> str:
    real = sorted(h for h in held if h != "?")
    if real:
        return "'" + "', '".join(real) + "'"
    return "a lock"


def run_concurrency(modules) -> list:
    """All TH114-TH117 findings for a set of ModuleIndexes."""
    p = _Pass(modules)
    p.inventory()
    p.walk_functions()
    p.run_th114()
    p.run_th116()
    p.run_th115_th117()
    return p.findings


def lock_order_edges(modules) -> list:
    """The inferred lock-ordering graph: sorted
    ``(src_lock, dst_lock, path, line)`` tuples, where ``dst`` was
    acquired while ``src`` was held. ``consul-tpu lint --verbose``
    prints these as dot-ish text so TH115 findings are explainable."""
    p = _Pass(modules)
    p.inventory()
    p.walk_functions()
    p.run_th114()
    p.run_th116()
    p.run_th115_th117()
    return p._edge_list
