"""Runtime guard rails for the compiled gossip core.

Two teeth, both monkeypatch-free:

- :class:`CompileLedger`: a process-wide compile counter built on
  ``jax.monitoring``. XLA emits one
  ``/jax/core/compile/backend_compile_duration`` event per executable
  it builds (in-process jit-cache hits are silent; persistent-cache
  hits fire the event too but are netted out via the paired
  ``cache_hits`` counter), so the ledger sees every real compile in
  the process — jit, scan bodies, eager dispatch fallbacks — without
  wrapping or patching anything. Tests pin steady-state
  behaviour with ``ledger.expect(0)`` around a repeated call pattern;
  a silent recompile (weak-type drift, shape leak, new donation
  signature) fails loudly with the observed delta.

- :func:`no_transfers`: ``jax.transfer_guard("disallow")`` scoped as a
  context manager. Inside it, any *implicit* host<->device transfer —
  a stray Python scalar entering an eager op, an un-jitted ``jnp``
  constructor, a numpy argument to a jitted call — raises. Explicit
  escapes (``jax.device_get`` / ``jax.device_put``) stay allowed,
  which is exactly the tier discipline the lint rules prescribe: all
  boundary crossings are spelled out, at the chunk boundary.

The third tooth lives in :mod:`consul_tpu.analysis.ledger` and is
re-exported here: :class:`LockLedger`, the lock-discipline twin of
CompileLedger — host modules build their locks through
``make_lock``/``make_rlock``/``make_condition`` and get plain
``threading`` primitives in production, traced shims under a test
ledger. It stays in its own jax-free module so the host tier can
import the factories without dragging jax in.

This module needs jax and is therefore *not* imported by the static
lint layer (``consul_tpu.analysis`` stays importable without jax).
"""

from __future__ import annotations

import contextlib
import threading

import jax

from consul_tpu.analysis.ledger import (  # noqa: F401 (re-export)
    LockLedger, LockLedgerError, blocking, make_condition, make_lock,
    make_rlock)

# The monitoring event XLA's compile path records once per executable
# actually compiled (jax 0.4.x: pxla/dispatch both route through it).
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# Recorded by compiler.compile_or_get_cached on a persistent-cache
# deserialization. jax wraps that whole call in the COMPILE_EVENT
# timer, so a cache hit fires BOTH events even though XLA built
# nothing — the ledger nets hits out so it counts real builds. With
# the persistent cache disabled (the tier-1 default) no hit events
# fire and the arithmetic is a no-op.
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"


class CompileLedgerError(AssertionError):
    """An ``expect()`` window saw a different number of compiles."""


class CompileLedger:
    """Process-wide compile counter.

    One ``jax.monitoring`` listener is registered for the whole
    process the first time a ledger is built; every instance reads the
    same underlying counter, so ledgers are cheap handles, not
    stateful subscriptions. Typical use::

        led = CompileLedger()
        sim.run(64)                 # warm every (chunk, metrics) shape
        with led.expect(0):         # steady state: memo must hold
            sim.run(64)
    """

    _lock = threading.Lock()
    _count = 0
    _hits = 0
    _registered = False

    def __init__(self):
        cls = type(self)
        with cls._lock:
            if not cls._registered:
                jax.monitoring.register_event_duration_secs_listener(
                    cls._on_event)
                jax.monitoring.register_event_listener(cls._on_plain_event)
                cls._registered = True

    @classmethod
    def _on_event(cls, event: str, duration: float, **kwargs):
        if event == COMPILE_EVENT:
            with cls._lock:
                cls._count += 1

    @classmethod
    def _on_plain_event(cls, event: str, **kwargs):
        if event == CACHE_HIT_EVENT:
            with cls._lock:
                cls._hits += 1

    # -- reads ----------------------------------------------------------
    @property
    def total(self) -> int:
        """Executables actually BUILT process-wide since first
        registration: backend-compile events net of persistent-cache
        hits (a hit deserializes — jax still fires the compile timer
        around it, but no compilation happened). This is what makes
        ``prewarm → run`` pinnable at ``expect(0)``: the run's dispatch
        loads the prewarmed executable from the cache instead of
        building it."""
        with type(self)._lock:
            return type(self)._count - type(self)._hits

    def snapshot(self) -> int:
        return self.total

    def delta(self, since: int) -> int:
        return self.total - since

    # -- the pin --------------------------------------------------------
    @contextlib.contextmanager
    def expect(self, n: int, what: str = ""):
        """Assert exactly ``n`` compiles happen inside the block."""
        start = self.total
        yield self
        got = self.delta(start)
        if got != n:
            label = f" ({what})" if what else ""
            raise CompileLedgerError(
                f"expected exactly {n} compile(s){label}, observed "
                f"{got} — a cached executable was silently rebuilt "
                "(or a new one traced) inside the pinned window")


@contextlib.contextmanager
def no_transfers():
    """Forbid implicit host<->device transfers inside the block.

    Explicit ``jax.device_get`` / ``jax.device_put`` still work —
    the point is that every boundary crossing is *written down*.
    Compile executables outside the block first: tracing constants is
    legitimately transfer-heavy, steady-state execution must not be.
    """
    with jax.transfer_guard("disallow"):
        yield
