"""Trace reachability: which function defs run inside compiled code.

Seeds are the real trace entry points — any function (or lambda, or
``functools.partial`` of one) passed to ``jax.jit`` / ``jax.vmap`` /
``jax.lax.scan`` / ``shard_map`` / friends — plus defs carrying an
explicit ``# lint: traced`` pragma for the few hand-offs the static
pass cannot follow (e.g. ``staticmethod`` driver hooks). From a seed,
reachability propagates through every function *referenced* in a
traced body (called directly, handed to ``partial``/``lax.cond``, or
named as a parameter default that the body then calls), across module
boundaries via the import maps.

The result intentionally over-approximates a little (a function both
traced and called on the host is treated as traced — its host uses
must then also be hygienic) and under-approximates where Python gets
too dynamic (``self.method`` dispatch); the pragma closes those gaps
explicitly and reviewably.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet

# Callables whose function-valued arguments enter tracing. Matched on
# resolved dotted fqnames; the shard_map entries cover both the jax
# spellings and this repo's version-portability shim.
TRACE_WRAPPERS = frozenset({
    "jax.jit", "jax.pmap", "jax.vmap", "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat", "jax.custom_jvp", "jax.custom_vjp",
    "jax.lax.scan", "jax.lax.cond", "jax.lax.while_loop",
    "jax.lax.fori_loop", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan", "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "consul_tpu.parallel.mesh.shard_map",
    "jax.numpy.vectorize", "jax.named_call",
})

TRACED_PRAGMA = "lint: traced"
HOST_PRAGMA = "lint: host"


def _pragma_on_def(mod, node, pragma: str) -> bool:
    line = getattr(node, "lineno", 0)
    if 0 < line <= len(mod.lines):
        return pragma in mod.lines[line - 1]
    return False


def _function_refs(mod, func_node, expr):
    """Yield (fqname or local node) for every function reference inside
    ``expr``: dotted paths resolving somewhere, lambdas, and partial
    targets. Used for wrapper arguments."""
    if isinstance(expr, ast.Lambda):
        yield expr
        return
    if isinstance(expr, ast.Call):
        fn = mod.resolve(expr.func, func_node)
        if fn and fn.rsplit(".", 1)[-1] == "partial":
            for a in expr.args:
                yield from _function_refs(mod, func_node, a)
            return
    fq = mod.resolve(expr, func_node)
    if fq is not None:
        yield fq


class _RefCollector(ast.NodeVisitor):
    """Collect, inside one traced function's body, every reference that
    could pull another function into the trace: dotted paths in call
    position or argument position, parameter-default targets, nested
    defs and lambdas that are referenced."""

    def __init__(self, mod, func_node):
        self.mod = mod
        self.func_node = func_node
        self.refs: set = set()          # fqname strings
        self.local_nodes: set = set()   # nested def/lambda AST nodes
        # parameter name -> default expression (followed when the
        # parameter is referenced: `step_fn=swim.step_counted`)
        self.param_defaults: dict = {}
        args = getattr(func_node, "args", None)
        if args is not None:
            pos = args.posonlyargs + args.args
            for a, d in zip(pos[len(pos) - len(args.defaults):],
                            args.defaults):
                self.param_defaults[a.arg] = d
            for a, d in zip(args.kwonlyargs, args.kw_defaults):
                if d is not None:
                    self.param_defaults[a.arg] = d

    def _add_expr(self, expr):
        if isinstance(expr, (ast.Lambda, ast.FunctionDef)):
            self.local_nodes.add(id(expr))
            return
        fq = self.mod.resolve(expr, self.func_node)
        if fq is not None:
            self.refs.add(fq)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            if node.id in self.param_defaults:
                self._add_expr(self.param_defaults[node.id])
            else:
                self._add_expr(node)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if isinstance(node.ctx, ast.Load):
            self._add_expr(node)
            if self.mod.resolve(node, self.func_node) is not None:
                # a resolved dotted path is handled as a whole; don't
                # re-resolve its prefix (`swim` alone)
                return
        self.generic_visit(node)

    def visit_Lambda(self, node):
        self.local_nodes.add(id(node))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        # Nested defs inside a traced function are traced: they only
        # exist to be closed over by the compiled program.
        self.local_nodes.add(id(node))
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def traced_functions(modules) -> Dict[str, FrozenSet[int]]:
    """Map module name -> frozenset of id(func node) for every
    function/lambda definition reachable from a trace entry point."""
    # fqname -> (module, func node) for every def in every module
    def_index = {}
    for m in modules:
        for qual, node in m.functions.items():
            def_index[f"{m.modname}.{qual}"] = (m, node)

    # node-id keyed structures need the actual node; keep a lookup
    node_by_id = {}
    for m in modules:
        for node in ast.walk(m.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                node_by_id[id(node)] = node

    traced: dict = {m.modname: set() for m in modules}
    work: list = []

    def mark(mod, node):
        if _pragma_on_def(mod, node, HOST_PRAGMA):
            return  # explicitly host-tier: never traced
        if id(node) not in traced[mod.modname]:
            traced[mod.modname].add(id(node))
            work.append((mod, node))

    def mark_fq(fq: str):
        hit = def_index.get(fq)
        if hit is not None:
            mark(*hit)

    # -- seeds ----------------------------------------------------------
    for m in modules:
        for node in ast.walk(m.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _pragma_on_def(m, node, TRACED_PRAGMA):
                mark(m, node)
            if not isinstance(node, ast.Call):
                continue
            encl = _enclosing_function(m, node)
            fq = m.resolve(node.func, encl)
            if fq not in TRACE_WRAPPERS:
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                for ref in _function_refs(m, encl, arg):
                    if isinstance(ref, str):
                        mark_fq(ref)
                    else:
                        mark(m, ref)

    # -- propagation ----------------------------------------------------
    while work:
        mod, node = work.pop()
        coll = _RefCollector(mod, node if not isinstance(node, ast.Lambda)
                             else _nearest_def(mod, node))
        for child in ast.iter_child_nodes(node):
            coll.visit(child)
        for fq in coll.refs:
            mark_fq(fq)
        for nid in coll.local_nodes:
            inner = node_by_id.get(nid)
            if inner is not None:
                mark(mod, inner)

    return {name: frozenset(ids) for name, ids in traced.items()}


def _enclosing_function(mod, node):
    """The innermost def lexically containing ``node`` (None at module
    level). Computed lazily via a parent walk over the module tree the
    first time it is needed."""
    parents = getattr(mod, "_parents", None)
    if parents is None:
        parents = {}
        for parent in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(parent):
                parents[id(child)] = parent
        mod._parents = parents
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parents.get(id(cur))
    return None


def _nearest_def(mod, lam):
    return _enclosing_function(mod, lam)
