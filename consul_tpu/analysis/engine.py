"""Lint engine: module indexing, name resolution, orchestration.

The engine parses every module once into a :class:`ModuleIndex`
(imports, function table, lexical nesting), hands the set to
:mod:`callgraph` to compute which function definitions are reachable
from a trace entry point, then runs the :mod:`rules` visitors with
that reachability in hand. Everything is stdlib ``ast`` — linting
never imports the code under analysis (and never imports jax), so
``consul-tpu lint`` stays instant and safe to run anywhere.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable, Optional

from consul_tpu.analysis import allowlist as allowlist_mod
from consul_tpu.analysis import callgraph, concurrency, rules

# Directories (relative to the package) whose modules form the device
# tier: code in them is presumed to build or run inside compiled
# programs, so the module-scoped rules (TH103/TH104) apply everywhere
# in them, not just in provably-traced functions.
DEVICE_TIER_DIRS = ("models", "ops", "parallel", "chaos")

PACKAGE = "consul_tpu"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation. ``symbol`` is the enclosing def's dotted
    qualname ('' at module level) — the stable handle allowlist
    entries use, so exemptions survive line drift."""

    rule: str
    path: str     # repo-relative, forward slashes
    line: int
    col: int
    symbol: str
    message: str

    def format(self) -> str:
        where = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}{where}: {self.message}")


@dataclasses.dataclass
class LintReport:
    """What one lint run produced, already split by the allowlist."""

    findings: list          # unallowlisted Finding, file/line ordered
    suppressed: list        # (Finding, entry) pairs the allowlist ate
    unused_entries: list    # allowlist entries that matched nothing
    n_files: int

    @property
    def clean(self) -> bool:
        return not self.findings


class ModuleIndex:
    """Everything the rules and the callgraph need to know about one
    parsed module, computed in a single AST walk."""

    def __init__(self, relpath: str, source: str, tree: ast.Module):
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.modname = _modname_of(relpath)
        self.device_tier = _is_device_tier(relpath)
        self.lines = source.splitlines()
        # alias -> imported module fqname ("np" -> "numpy")
        self.import_map: dict = {}
        # local name -> "module.attr" fqname from `from m import a`
        self.from_map: dict = {}
        # dotted qualname -> FunctionDef/AsyncFunctionDef node
        self.functions: dict = {}
        # id(func node) -> qualname (includes lambdas, as "<lambda>")
        self.qualname_of: dict = {}
        # id(func node) -> id(enclosing func node) (lexical nesting)
        self.parent_of: dict = {}
        # id(func node) -> {local name: value AST} for simple
        # `x = <expr>` statements in that function's immediate body
        # (callgraph follows x when x is referenced from traced code)
        self.local_bindings: dict = {}
        # module-level names bound to mutable literals (TH107)
        self.mutable_globals: set = set()
        self._index()

    def _index(self):
        for node in self.tree.body:
            _collect_mutable_global(node, self.mutable_globals)
        self.local_bindings[None] = _simple_bindings(self.tree.body)
        stack: list = []

        def visit(node):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_map[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
                    if a.asname:
                        self.import_map[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name != "*":
                        self.from_map[a.asname or a.name] = \
                            f"{node.module}.{a.name}"
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                name = getattr(node, "name", "<lambda>")
                qual = ".".join(
                    [q for q, _ in stack] + [name])
                self.qualname_of[id(node)] = qual
                self.parent_of[id(node)] = id(stack[-1][1]) if stack \
                    else None
                if not isinstance(node, ast.Lambda):
                    self.functions[qual] = node
                    self.local_bindings[id(node)] = \
                        _simple_bindings(node.body)
                stack.append((name, node))
                for child in ast.iter_child_nodes(node):
                    visit(child)
                stack.pop()
                return
            if isinstance(node, ast.ClassDef):
                stack.append((node.name, node))
                for child in ast.iter_child_nodes(node):
                    visit(child)
                stack.pop()
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        for node in self.tree.body:
            visit(node)

    # -- name resolution ------------------------------------------------
    def resolve(self, node, func_node=None) -> Optional[str]:
        """Best-effort dotted fqname of a Name/Attribute expression
        ("jnp.zeros" -> "jax.numpy.zeros", "swim.step_counted" ->
        "consul_tpu.models.swim.step_counted"). ``func_node`` scopes
        the lookup through enclosing functions' simple local bindings.
        None when the expression isn't a static dotted path."""
        parts = _dotted_parts(node)
        if not parts:
            return None
        head, rest = parts[0], parts[1:]
        base = self._resolve_head(head, func_node)
        if base is None:
            return None
        return ".".join([base] + rest) if rest else base

    def _resolve_head(self, head: str, func_node) -> Optional[str]:
        # Walk lexically outward through simple local bindings first.
        fid = id(func_node) if func_node is not None else None
        while True:
            bound = self.local_bindings.get(fid, {}).get(head)
            if bound is not None:
                inner = _dotted_parts(bound)
                if inner and inner[0] != head:
                    resolved = self.resolve(bound, None)
                    if resolved:
                        return resolved
                return None  # bound to a non-path expression: opaque
            if fid is None:
                break
            fid = self.parent_of.get(fid)
        if head in self.from_map:
            return self.from_map[head]
        if head in self.import_map:
            return self.import_map[head]
        if head in self.functions:
            return f"{self.modname}.{head}"
        return None


def _dotted_parts(node) -> Optional[list]:
    parts: list = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    if isinstance(node, ast.Call):
        # see through functools.partial(f, ...) to f
        fn = _dotted_parts(node.func)
        if fn and fn[-1] == "partial" and node.args:
            return _dotted_parts(node.args[0])
    return None


def _simple_bindings(body) -> dict:
    out = {}
    for stmt in body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            out[stmt.targets[0].id] = stmt.value
    return out


def _collect_mutable_global(node, acc: set):
    if isinstance(node, (ast.Assign, ast.AnnAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        value = node.value
        if value is not None and _is_mutable_literal(value):
            for t in targets:
                if isinstance(t, ast.Name):
                    acc.add(t.id)


def _is_mutable_literal(node) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("list", "dict", "set", "bytearray",
                                "defaultdict", "deque")
    return False


def _modname_of(relpath: str) -> str:
    mod = relpath.replace("\\", "/")
    if mod.endswith(".py"):
        mod = mod[:-3]
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


def _is_device_tier(relpath: str) -> bool:
    parts = relpath.replace("\\", "/").split("/")
    return (len(parts) >= 2 and parts[0] == PACKAGE
            and parts[1] in DEVICE_TIER_DIRS)


def default_allowlist_path() -> str:
    return os.path.join(os.path.dirname(__file__), "allowlist.toml")


def _iter_py_files(paths: Iterable[str], root: str):
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            yield full
        else:
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git"))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def _build_modules(sources: dict):
    """Parse sources into ModuleIndexes; syntax errors become TH000."""
    modules = []
    findings = []
    for relpath in sorted(sources):
        src = sources[relpath]
        try:
            tree = ast.parse(src, filename=relpath)
        except SyntaxError as e:
            findings.append(Finding(
                rule="TH000", path=relpath, line=e.lineno or 0,
                col=e.offset or 0, symbol="",
                message=f"syntax error: {e.msg}"))
            continue
        modules.append(ModuleIndex(relpath, src, tree))
    return modules, findings


def _read_sources(paths, root: Optional[str]):
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sources = {}
    for full in _iter_py_files(paths, root):
        rel = os.path.relpath(full, root).replace(os.sep, "/")
        with open(full, "r", encoding="utf-8") as f:
            sources[rel] = f.read()
    return sources


def lint_sources(sources: dict, allowlist=None) -> LintReport:
    """Lint in-memory sources: {repo-relative path: source text}.
    The unit tests drive this; ``lint_package`` is the on-disk
    wrapper. ``allowlist`` is an :class:`Allowlist` or None."""
    modules, findings = _build_modules(sources)

    traced = callgraph.traced_functions(modules)
    for mod in modules:
        findings.extend(rules.run_rules(mod, traced.get(mod.modname,
                                                        frozenset())))
    findings.extend(concurrency.run_concurrency(modules))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    if allowlist is None:
        allowlist = allowlist_mod.Allowlist(())
    kept, suppressed = [], []
    for f in findings:
        entry = allowlist.match(f)
        if entry is None:
            kept.append(f)
        else:
            suppressed.append((f, entry))
    return LintReport(findings=kept, suppressed=suppressed,
                      unused_entries=allowlist.unused(),
                      n_files=len(sources))


def lint_package(paths=(PACKAGE,), root: Optional[str] = None,
                 allowlist_path: Optional[str] = None,
                 use_allowlist: bool = True) -> LintReport:
    """Lint on-disk trees. ``paths`` are files or directories relative
    to ``root`` (default: the repo root inferred as the parent of this
    package). The checked-in allowlist applies unless disabled."""
    sources = _read_sources(paths, root)
    allowlist = None
    if use_allowlist:
        path = allowlist_path or default_allowlist_path()
        if os.path.exists(path):
            allowlist = allowlist_mod.load_allowlist(path)
    return lint_sources(sources, allowlist)


def package_lock_graph(paths=(PACKAGE,), root: Optional[str] = None):
    """The inferred lock-ordering graph for on-disk trees: sorted
    ``(src_lock, dst_lock, path, line)`` tuples (``consul-tpu lint
    --verbose`` renders these as dot-ish text)."""
    modules, _ = _build_modules(_read_sources(paths, root))
    return concurrency.lock_order_edges(modules)
