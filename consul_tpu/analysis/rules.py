"""The trace-hygiene rules. Each rule is one method on the visitor;
``RULES`` documents every id for the CLI and COVERAGE.md.

Scoping model: ``traced`` rules (TH101/TH102/TH107) fire only inside
function definitions the callgraph proved reachable from a trace entry
point — host-tier driver code in the same file is untouched.
``device-module`` rules (TH103/TH104) fire anywhere in a device-tier
module (models/ ops/ parallel/ chaos/). ``package`` rules
(TH105/TH106/TH108/TH112) fire everywhere. ``host-serving`` rules
(TH113) fire in the host serving tiers (serving/ server/ gameday/).
"""

from __future__ import annotations

import ast

RULES = {
    "TH101": "implicit scalar host sync in traced code — .item()/"
             ".tolist()/int()/float()/bool() on a traced value blocks "
             "the device stream and breaks the compiled scan",
    "TH102": "host transfer API in traced code — np.asarray/np.array/"
             "jax.device_get/device_put/block_until_ready inside a "
             "jitted function forces a device round-trip per trace",
    "TH103": "impure host stdlib (time/random/datetime) in a device-"
             "tier module — wall clocks and host RNG are invisible to "
             "XLA and silently freeze at trace time",
    "TH104": "jnp array constructor without an explicit dtype in a "
             "device-tier module — default promotion widens dtypes "
             "and forks executables between platforms",
    "TH105": "bare/broad except swallowing errors — a silent pass "
             "hides device failures the sentinels exist to surface",
    "TH106": "mutable default argument — shared mutable state leaks "
             "across calls and across traces",
    "TH107": "module-level mutable state read inside traced code — "
             "the value is baked at trace time and silently goes "
             "stale (or recompiles) when mutated",
    "TH108": "host-tier retry loop with a bare constant time.sleep "
             "and no bound/backoff — an unbounded while around a "
             "fixed sleep spins forever on a wedged dependency and "
             "synchronizes retry storms across workers",
    "TH109": "data-dependent scatter (.at[idx].add/set/...) in traced "
             "code — XLA lowers it to a serialized HLO scatter on TPU "
             "(the dense [N, E] update the fused serf core exists to "
             "avoid); use one-hot matmul/gather shapes or the "
             "collective reduce-scatter helper",
    "TH110": "sharding-less device placement (jax.device_put without a "
             "sharding / jnp.asarray) in a mesh-handling host path — "
             "the array lands committed to a single device (or "
             "replicated), and every sharded program that consumes it "
             "pays a reshard or fails the multi-chip parity contract; "
             "place node-axis data with NamedSharding(mesh, "
             "node_spec(...)) (parallel/shard_step.place)",
    "TH111": "hand-widened packed state field in traced code — an "
             ".astype(<wide dtype>) reaching directly into a packed "
             "StateLayout field (meta/flags/view_inc/susp_delta/"
             "*_delta) bypasses the one codec (models/layout.unpack) "
             "and silently drops its sentinels, tick anchors, and fp8 "
             "scale; unpack the whole state instead",
    "TH112": "time.time() used to compute a duration — subtracting "
             "wall-clock reads measures NTP steps and clock slews, "
             "not elapsed time; spans and latency math must use "
             "time.perf_counter()/time.monotonic() (genuine "
             "wall-clock-timestamp sites are allowlisted)",
    "TH113": "unbounded threading.Thread spawn in host-tier serving/"
             "gameday code — a thread per connection or blocking "
             "query grows without limit under churny load (the "
             "failure mode the async frontend exists to kill); keep "
             "a handle that is join()ed, drain it through a joined "
             "container, or hand the work to the event-loop frontend "
             "(intentional sites are allowlisted with their bound)",
    # TH114-TH117 are the host-tier lock-discipline rules; they run as
    # a whole-package pass in analysis/concurrency.py (they need the
    # cross-module lock graph), not through run_rules below.
    "TH114": "inconsistently guarded attribute write — an attribute "
             "written under `with self._lock` elsewhere (or a "
             "read-modify-write in a lock-owning class) is written "
             "here with no lock held; two threads interleaving the "
             "read and the write lose updates (single-writer seams "
             "are allowlisted with their external bound)",
    "TH115": "lock-order cycle / non-reentrant re-acquire — the "
             "static acquired-while-holding graph (nested `with` "
             "blocks plus calls made under a lock) contains a cycle, "
             "so two threads taking the locks in opposite orders "
             "deadlock; or a plain Lock is re-acquired while held",
    "TH116": "Condition.wait() outside a while-predicate loop — "
             "spurious and stolen wakeups make a bare wait() return "
             "with the predicate still false; re-check in a while "
             "loop or use wait_for()",
    "TH117": "blocking call under a lock — jax.device_get/device_put/"
             "jnp.*, socket/file I/O, no-timeout Queue.get, "
             "time.sleep or subprocess executed while a lock is held "
             "serializes every other acquirer behind host latency "
             "(measured, externally bounded sites are allowlisted)",
    "TH118": "Pallas interpret mode hardcoded on — a truthy-literal "
             "interpret= reaching pl.pallas_call (directly or through "
             "a kernel builder), or an interpret parameter DEFAULTING "
             "truthy, ships the Python interpreter twin to TPU: a "
             "silent ~100x perf cliff with no error. Thread "
             "pallas_gossip.default_interpret() instead; the one "
             "marked test/debug entry point is allowlisted",
}

# TH101: int()/float()/bool() arguments considered static (config
# plumbing, shape math) — these never hold device values.
_STATIC_ROOTS = frozenset({"cfg", "self", "len", "n", "k", "chunk"})

# TH102: the host-boundary APIs that must not appear under a trace.
_TRANSFER_CALLS = frozenset({
    "numpy.asarray", "numpy.array", "numpy.frombuffer",
    "numpy.ascontiguousarray", "numpy.copyto", "numpy.save",
    "numpy.load", "jax.device_get", "jax.device_put",
})
_TRANSFER_METHODS = frozenset({"block_until_ready",
                               "copy_to_host_async"})

# TH103: host-impure stdlib modules banned from the device tier.
_IMPURE_MODULES = frozenset({"time", "random", "datetime"})

# TH104: jax.numpy constructors that take a dtype, with the positional
# index the dtype may appear at.
_DTYPE_CTORS = {
    "jax.numpy.array": 1,
    "jax.numpy.zeros": 1,
    "jax.numpy.ones": 1,
    "jax.numpy.empty": 1,
    "jax.numpy.full": 2,
    "jax.numpy.arange": 3,
}

_SCALAR_CASTS = frozenset({"int", "float", "bool"})

# TH109: the indexed-update methods that lower to HLO scatter when the
# index is a traced array.
_SCATTER_OPS = frozenset({"add", "set", "max", "min", "mul", "multiply"})

# TH110: a host function is "mesh-handling" when it takes a mesh
# parameter, reads a .mesh attribute, or builds one via these
# constructors — the scope where a sharding-less placement silently
# breaks the multi-chip layout.
_MESH_CTORS = frozenset({"elastic_mesh", "make_mesh", "default_mesh"})

# TH110: the jnp constructors that materialize host data on a device
# with no way to say where (asarray/array take no sharding argument).
_UNSHARDED_CTORS = frozenset({"jax.numpy.asarray", "jax.numpy.array"})

# TH111: fields that exist ONLY on the packed StateLayout
# (models/layout.py PackedSimState) — touching one means the code is
# holding a packed state. The packed encoding is a codec, not just
# narrow dtypes: susp_delta/next_probe_delta/pending_fail_delta are
# tick-anchored with saturation sentinels, meta is a bitfield, and the
# latency lanes carry an fp8 scale. A hand-spelled widening cast
# reproduces none of that.
_PACKED_ONLY_FIELDS = frozenset({
    "flags", "meta", "view_inc", "susp_delta", "next_probe_delta",
    "pending_fail_delta",
})

# TH111: the wide dtypes a hand-widening cast lands on.
_WIDE_DTYPES = frozenset({
    "int32", "int64", "uint32", "uint64", "float32", "float64",
})


# TH113: the host serving tiers where a per-request thread spawn is a
# capacity bug, not a style choice — the threaded HTTP/RPC surfaces,
# the async frontend, and the game-day harness/swarm drivers.
_TH113_PREFIXES = ("consul_tpu/serving/", "consul_tpu/server/",
                   "consul_tpu/gameday/")


def run_rules(mod, traced_ids) -> list:
    v = _RuleVisitor(mod, traced_ids)
    v.visit(mod.tree)
    if mod.relpath.startswith(_TH113_PREFIXES):
        v.findings.extend(_run_th113(mod))
    v.findings.extend(_run_th118(mod))
    return v.findings


def _run_th113(mod) -> list:
    """Unbounded ``threading.Thread`` spawns in a host-serving module.

    Boundedness is a whole-module property (spawned in ``start``,
    joined in ``close``), so this runs as its own two-pass walk:

    1. Collect every join drain — ``X.join(...)`` marks the spelled
       receiver ``X`` (a name or a ``self`` attribute) as a joined
       handle, and ``for t in C: t.join()`` marks the container ``C``
       as join-drained.
    2. Every ``threading.Thread(...)`` constructor is then judged by
       what happens to the handle: assigned to a joined name, or
       appended into a join-drained container → bounded; assigned to
       an unjoined name, chained straight into ``.start()``, or
       passed/stored anywhere opaque → a finding.
    """
    from consul_tpu.analysis.engine import Finding

    joined: set = set()
    drained: set = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join":
            joined.add(ast.unparse(node.func.value))
        if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            loop_var = node.target.id
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call) \
                        and isinstance(inner.func, ast.Attribute) \
                        and inner.func.attr == "join" \
                        and isinstance(inner.func.value, ast.Name) \
                        and inner.func.value.id == loop_var:
                    drained.add(ast.unparse(node.iter))
                    break

    parents: dict = {}
    for p in ast.walk(mod.tree):
        for c in ast.iter_child_nodes(p):
            parents[c] = p

    def _symbol(node) -> str:
        names = []
        cur = node
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
        return ".".join(reversed(names))

    findings = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and mod.resolve(node.func, None) == "threading.Thread"):
            continue
        parent = parents.get(node)
        if isinstance(parent, ast.Assign):
            if any(ast.unparse(t) in joined for t in parent.targets):
                continue
            shape = (f"handle {ast.unparse(parent.targets[0])} is "
                     "never join()ed")
        elif isinstance(parent, ast.Call) \
                and isinstance(parent.func, ast.Attribute) \
                and parent.func.attr == "append":
            container = ast.unparse(parent.func.value)
            if container in drained or container in joined:
                continue
            shape = f"container {container} is never join-drained"
        elif isinstance(parent, ast.Attribute) and parent.attr == "start":
            shape = "spawned and started with no handle kept"
        else:
            shape = "handle escapes without a visible join"
        findings.append(Finding(
            rule="TH113", path=mod.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            symbol=_symbol(node),
            message=f"unbounded thread spawn — {shape}; under churny "
                    "serving load this grows the thread count without "
                    "limit: join the handle, drain it through a joined "
                    "container, or use the async frontend's event loop"))
    return findings


# TH118: the Pallas kernel launch, and the prefix marking calls that
# forward an interpret= kwarg down to one (the repo's kernel builders).
_TH118_PALLAS_CALL = "jax.experimental.pallas.pallas_call"
_TH118_INTERNAL_PREFIX = "consul_tpu."


def _run_th118(mod) -> list:
    """Truthy-literal ``interpret=`` reaching a Pallas kernel launch.

    ``pl.pallas_call(..., interpret=True)`` runs the kernel body under
    the Python interpret evaluator — correct everywhere, needed on CPU,
    and a silent ~100x perf cliff if it ships to TPU (no error, no
    warning, just a Mosaic kernel that never compiles). Three shapes
    fire:

    1. A call resolving to ``jax.experimental.pallas.pallas_call``
       with a truthy-literal ``interpret=``.
    2. A call resolving into ``consul_tpu.*`` (the kernel builders,
       which forward ``interpret`` verbatim into the launch) with a
       truthy-literal ``interpret=``.
    3. A function definition whose ``interpret`` parameter DEFAULTS
       truthy — every caller who forgets the kwarg ships the
       interpreter.

    Non-literal values stay quiet by construction: threading
    ``pallas_gossip.default_interpret()`` (the backend probe) is
    exactly the sanctioned idiom. The one sanctioned truthy literal —
    the marked test/debug entry ``interpret_tick`` — is carried by the
    allowlist, not by the rule."""
    from consul_tpu.analysis.engine import Finding

    parents: dict = {}
    for p in ast.walk(mod.tree):
        for c in ast.iter_child_nodes(p):
            parents[c] = p

    def _symbol(node) -> str:
        names = []
        cur = node
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
        return ".".join(reversed(names))

    def _truthy_literal(node) -> bool:
        return isinstance(node, ast.Constant) and bool(node.value)

    findings = []

    def _emit(node, message):
        # A def-shaped finding anchors to the function itself: its own
        # name IS the allowlistable symbol.
        sym = _symbol(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sym = f"{sym}.{node.name}" if sym else node.name
        findings.append(Finding(
            rule="TH118", path=mod.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            symbol=sym, message=message))

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            kw = next((k for k in node.keywords
                       if k.arg == "interpret"), None)
            if kw is None or not _truthy_literal(kw.value):
                continue
            fq = mod.resolve(node.func, None)
            if fq == _TH118_PALLAS_CALL:
                _emit(node, "pl.pallas_call(..., interpret=True) — the "
                            "interpret evaluator hardcoded into the "
                            "launch ships a ~100x perf cliff to TPU; "
                            "thread pallas_gossip.default_interpret()")
            elif fq is not None \
                    and fq.startswith(_TH118_INTERNAL_PREFIX):
                _emit(node, f"interpret=True forwarded into {fq} — a "
                            "kernel built here runs interpreted on "
                            "every backend, TPU included; thread "
                            "pallas_gossip.default_interpret() (test/"
                            "debug entries are allowlisted by symbol)")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            pos = a.posonlyargs + a.args
            for arg, default in zip(pos[len(pos) - len(a.defaults):],
                                    a.defaults):
                if arg.arg == "interpret" and _truthy_literal(default):
                    _emit(node, f"def {node.name}(... interpret="
                                "True ...) — an interpret parameter "
                                "defaulting truthy ships the evaluator "
                                "to every caller who forgets the "
                                "kwarg; default False (or "
                                "default_interpret()) and make tests "
                                "opt in explicitly")
            for arg, default in zip(a.kwonlyargs, a.kw_defaults):
                if arg.arg == "interpret" and default is not None \
                        and _truthy_literal(default):
                    _emit(node, f"def {node.name}(*, interpret=True) "
                                "— an interpret parameter defaulting "
                                "truthy ships the evaluator to every "
                                "caller who forgets the kwarg; "
                                "default False (or default_interpret()"
                                ") and make tests opt in explicitly")
    return findings


class _RuleVisitor(ast.NodeVisitor):
    def __init__(self, mod, traced_ids):
        from consul_tpu.analysis.engine import Finding
        self._Finding = Finding
        self.mod = mod
        self.traced_ids = traced_ids
        self.findings: list = []
        self._scope: list = []  # (qualname segment, is_traced)
        # Parallel stack of "this function handles a mesh" flags
        # (TH110). Kept separate from _scope: its 2-tuples are
        # unpacked at every _symbol()/_in_trace() call site.
        self._mesh_scope: list = []
        # Depth of enclosing `with jax.ensure_compile_time_eval():`
        # blocks — the canonical static-at-trace idiom. Host syncs in
        # them run once at trace time, so TH101/TH102 stay quiet.
        self._compile_time_depth = 0
        # Names proven concrete by an `isinstance(x, jax.core.Tracer)`
        # guard (the non-Tracer branch) — int(x) there is host math.
        self._proven_static: set = set()
        # Per-scope sets of names assigned from time.time() (TH112):
        # a later subtraction over one of them is a wall-clock
        # duration. Stack-shaped like _scope; lookups see enclosing
        # scopes so a closure over a wall stamp still fires.
        self._walltime_scope: list = [set()]

    # -- helpers --------------------------------------------------------
    def _emit(self, rule: str, node, message: str):
        self.findings.append(self._Finding(
            rule=rule, path=self.mod.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            symbol=self._symbol(), message=message))

    def _symbol(self) -> str:
        return ".".join(s for s, _ in self._scope)

    def _in_trace(self) -> bool:
        return any(t for _, t in self._scope)

    def _in_mesh_scope(self) -> bool:
        return any(self._mesh_scope)

    # -- scope tracking -------------------------------------------------
    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        self._scope.append((node.name, id(node) in self.traced_ids))
        self._mesh_scope.append(_touches_mesh(node, self.mod))
        self._walltime_scope.append(set())
        for dec in node.decorator_list:
            self.visit(dec)
        self.visit(node.args)
        self._visit_body(node.body)
        self._scope.pop()
        self._mesh_scope.pop()
        self._walltime_scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._scope.append(("<lambda>", id(node) in self.traced_ids))
        self._mesh_scope.append(False)  # inherits via any()
        self._walltime_scope.append(set())
        self.generic_visit(node)
        self._scope.pop()
        self._mesh_scope.pop()
        self._walltime_scope.pop()

    def visit_ClassDef(self, node):
        self._scope.append((node.name, False))
        self._mesh_scope.append(False)
        self._walltime_scope.append(set())
        self.generic_visit(node)
        self._scope.pop()
        self._mesh_scope.pop()
        self._walltime_scope.pop()

    # -- static-at-trace idioms the trace rules must respect ------------
    def visit_With(self, node):
        static = any(
            isinstance(item.context_expr, ast.Call)
            and self.mod.resolve(item.context_expr.func, None)
            == "jax.ensure_compile_time_eval"
            for item in node.items)
        if static:
            self._compile_time_depth += 1
        self.generic_visit(node)
        if static:
            self._compile_time_depth -= 1

    def visit_If(self, node):
        kept = self._guarded_if(node)
        if kept:
            self._proven_static.discard(kept)

    def _guarded_if(self, node):
        """Visit an If statement. When its test is a Tracer guard and
        the tracer branch terminates (early return/raise), returns the
        guarded name so the caller can keep it proven-static for the
        rest of the enclosing block; otherwise None."""
        guarded = _tracer_guard_name(node.test, self.mod)
        if guarded is None:
            self.generic_visit(node)
            return None
        name, tracer_is_body = guarded
        for test_child in ast.iter_child_nodes(node.test):
            self.visit(test_child)
        tracer_branch = node.body if tracer_is_body else node.orelse
        static_branch = node.orelse if tracer_is_body else node.body
        for stmt in tracer_branch:
            self.visit(stmt)
        added = name not in self._proven_static
        if added:
            self._proven_static.add(name)
        for stmt in static_branch:
            self.visit(stmt)
        if added and tracer_is_body and _terminates(tracer_branch):
            return name
        if added:
            self._proven_static.discard(name)
        return None

    def _visit_body(self, stmts):
        """Visit a statement block, extending a Tracer guard's
        proven-static scope past an early-returning guard:
        ``if isinstance(x, Tracer): return dyn(x)`` makes ``x``
        concrete for every following sibling statement."""
        keep = []
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                kept = self._guarded_if(stmt)
                if kept:
                    keep.append(kept)
            else:
                self.visit(stmt)
        for name in keep:
            self._proven_static.discard(name)

    # -- TH105: swallowed exceptions ------------------------------------
    def visit_ExceptHandler(self, node):
        broad = node.type is None or (
            isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException"))
        silent = all(
            isinstance(s, ast.Pass)
            or (isinstance(s, ast.Expr)
                and isinstance(s.value, ast.Constant)
                and s.value.value is Ellipsis)
            for s in node.body)
        if broad and silent:
            what = ast.unparse(node.type) if node.type else "bare"
            self._emit("TH105", node,
                       f"{what} except with a silent pass swallows "
                       "errors — narrow the exception or handle it")
        self.generic_visit(node)

    # -- TH106: mutable defaults ----------------------------------------
    def _check_defaults(self, node):
        from consul_tpu.analysis.engine import _is_mutable_literal
        args = node.args
        for d in list(args.defaults) + [d for d in args.kw_defaults
                                        if d is not None]:
            if _is_mutable_literal(d):
                self._emit("TH106", d,
                           f"mutable default {ast.unparse(d)!r} is "
                           "shared across calls — default to None")

    # -- call-shaped rules ----------------------------------------------
    def visit_Call(self, node):
        fq = self.mod.resolve(node.func, None)
        in_trace = self._in_trace()

        if in_trace:
            self._rule_th101(node, fq)
            self._rule_th102(node, fq)
            self._rule_th109(node)
            self._rule_th111(node)
        elif self._in_mesh_scope():
            self._rule_th110(node, fq)
        if self.mod.device_tier:
            self._rule_th104(node, fq)
        self.generic_visit(node)

    def _rule_th101(self, node, fq):
        if self._compile_time_depth:
            return  # ensure_compile_time_eval: runs once, at trace time
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("item", "tolist") \
                and not node.args:
            self._emit("TH101", node,
                       f".{node.func.attr}() forces a device->host "
                       "sync inside traced code")
            return
        if isinstance(node.func, ast.Name) \
                and node.func.id in _SCALAR_CASTS and len(node.args) == 1:
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in self._proven_static:
                return  # isinstance(x, Tracer) guard proved x concrete
            if not _is_static_expr(arg):
                self._emit(
                    "TH101", node,
                    f"{node.func.id}({ast.unparse(arg)}) on a traced "
                    "value host-syncs inside traced code — use "
                    "jnp casts/astype instead")

    def _rule_th102(self, node, fq):
        if self._compile_time_depth:
            return  # ensure_compile_time_eval: runs once, at trace time
        if fq in _TRANSFER_CALLS:
            self._emit("TH102", node,
                       f"{fq} inside traced code forces a host "
                       "round-trip per trace — keep transfers at the "
                       "chunk boundary (jax.device_get on the host "
                       "tier)")
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _TRANSFER_METHODS:
            self._emit("TH102", node,
                       f".{node.func.attr}() inside traced code "
                       "blocks the device stream")

    def _rule_th104(self, node, fq):
        idx = _DTYPE_CTORS.get(fq)
        if idx is None:
            return
        if any(k.arg == "dtype" for k in node.keywords):
            return
        if len(node.args) > idx:
            return  # dtype passed positionally
        name = fq.rsplit(".", 1)[-1]
        self._emit("TH104", node,
                   f"jnp.{name}(...) without an explicit dtype — "
                   "default promotion differs across platforms; spell "
                   "the dtype")

    def _rule_th109(self, node):
        """``x.at[idx].add(v)`` (or set/max/min/mul/multiply) inside
        traced code, where ``idx`` is not a compile-time-static index
        expression. A static index (``.at[..., 0].set``, ``.at[3:5]``)
        lowers to a dynamic-update-slice — cheap and vectorized; a
        traced index lowers to HLO scatter, which TPUs serialize
        row-by-row. The serf hot path deliberately has zero of these
        (one-hot matmuls and top-k gathers instead, models/serf.py);
        this rule keeps new ones from creeping back in. Deliberate
        scatters (collective.sum_scatter_rows, whose scatter-add IS the
        reduce-scatter) are allowlisted by symbol."""
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr in _SCATTER_OPS):
            return
        sub = f.value
        if not (isinstance(sub, ast.Subscript)
                and isinstance(sub.value, ast.Attribute)
                and sub.value.attr == "at"):
            return
        if _static_index(sub.slice):
            return
        self._emit(
            "TH109", node,
            f".at[{ast.unparse(sub.slice)}].{f.attr}(...) with a "
            "traced index lowers to a serialized HLO scatter on TPU — "
            "reformulate as a one-hot matmul / gather, or route "
            "through the collective reduce-scatter helper")

    def _rule_th110(self, node, fq):
        """Sharding-less device placement in a mesh-handling host
        function. ``jax.device_put(x)`` with no sharding/device
        argument commits to device 0; ``jnp.asarray``/``jnp.array``
        materialize wherever the default device is (and cannot say
        otherwise — they take no sharding). Either way a node-axis
        array built next to a mesh lands mis-placed, and the first
        sharded program that consumes it pays a full reshard (or, for
        a committed input, fails with an incompatible-devices error).
        The fix is the one placement rule every sharded path shares:
        ``NamedSharding(mesh, node_spec(leaf, n))`` via
        ``parallel/shard_step.place``. Deliberate scalar/replicated
        conversions are allowlisted by symbol with their reason."""
        if fq == "jax.device_put":
            if len(node.args) >= 2 or any(
                    k.arg in ("device", "sharding") for k in node.keywords):
                return  # placement is spelled out
            self._emit(
                "TH110", node,
                "jax.device_put without an explicit sharding in a "
                "mesh-handling host path commits the array to a single "
                "device — place it with NamedSharding(mesh, "
                "node_spec(...)) (parallel/shard_step.place)")
        elif fq in _UNSHARDED_CTORS:
            name = fq.rsplit(".", 1)[-1]
            self._emit(
                "TH110", node,
                f"jnp.{name}(...) in a mesh-handling host path cannot "
                "express a sharding — a node-axis array lands on the "
                "default device and every sharded consumer pays a "
                "reshard; build host-side (numpy) and place via "
                "parallel/shard_step.place")

    def _rule_th111(self, node):
        """``<expr over a packed-only field>.astype(<wide dtype>)``
        inside traced code. The packed StateLayout (models/layout.py)
        is a codec: its delta fields are tick-anchored with saturation
        sentinels (susp_delta 65535 = no suspicion), ``meta`` is a
        status/tx/perm bitfield, and the fp8 lanes carry a x256 scale.
        A widening cast spelled at a use site reproduces none of that
        — it decodes the representation without the codec, which reads
        plausibly and corrupts silently (a suspicion that never
        expires, a deadline off by the tick anchor). The one sanctioned
        decode path is ``models/layout.unpack``; its own widening
        casts are the codec and are allowlisted by symbol."""
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "astype"
                and node.args):
            return
        target = self._dtype_of(node.args[0])
        if target not in _WIDE_DTYPES:
            return
        field = next(
            (x.attr for x in ast.walk(f.value)
             if isinstance(x, ast.Attribute)
             and x.attr in _PACKED_ONLY_FIELDS), None)
        if field is None:
            return
        self._emit(
            "TH111", node,
            f"packed state field {field!r} hand-widened with "
            f".astype({ast.unparse(node.args[0])}) — the packed layout "
            "is a codec (sentinels, tick anchors, fp8 scale); decode "
            "through models/layout.unpack instead")

    def _dtype_of(self, node):
        """Best-effort dtype name of an ``astype`` argument: the tail
        of a resolved dotted path (jnp.int32 -> 'int32') or a string
        literal ('int32'). None for anything opaque."""
        fq = self.mod.resolve(node, None)
        if fq:
            return fq.rsplit(".", 1)[-1]
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return None

    # -- TH108: unbounded host retry loops ------------------------------
    def visit_While(self, node):
        self._rule_th108(node)
        self.generic_visit(node)

    def _rule_th108(self, node):
        """A ``while`` that paces itself with a fixed ``time.sleep``
        but carries no bound: no comparison in the loop test (deadline
        or attempt counter), no ``while not done:`` stop flag, and no
        comparison-gated escape in the body. The canonical offender::

            while True:
                if ping():      # a probe, not a bound
                    break
                time.sleep(5)

        — liveness depends entirely on the dependency coming back.
        Bounded shapes (``while time.monotonic() < deadline``,
        ``for _ in range(retries)``, ``if attempt > max: raise``) and
        variable sleeps (a computed backoff) stay quiet."""
        if any(isinstance(t, ast.Compare) for t in ast.walk(node.test)):
            return  # deadline / attempt comparison bounds the loop
        test = node.test
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return  # `while not done:` — an externally-set stop flag
        sleep = _bare_sleep(node.body, self.mod)
        if sleep is None:
            return
        if _bounded_escape(node.body):
            return
        self._emit(
            "TH108", sleep,
            f"retry loop sleeps a fixed {ast.unparse(sleep.args[0])}s "
            "with no bound or backoff — a wedged dependency spins this "
            "forever; bound the attempts (deadline compare or max "
            "retries) and back off with jitter")

    # -- TH112: wall-clock durations ------------------------------------
    def visit_Assign(self, node):
        is_wall = isinstance(node.value, ast.Call) \
            and self.mod.resolve(node.value.func, None) == "time.time"
        for t in node.targets:
            if isinstance(t, ast.Name):
                if is_wall:
                    self._walltime_scope[-1].add(t.id)
                else:
                    self._walltime_scope[-1].discard(t.id)
        self.generic_visit(node)

    def visit_BinOp(self, node):
        if isinstance(node.op, ast.Sub):
            self._rule_th112(node)
        self.generic_visit(node)

    def _rule_th112(self, node):
        """A subtraction with a ``time.time()`` read on either side —
        directly (``time.time() - t0``) or through a name assigned
        from it in an enclosing scope (``t0 = time.time() ...
        t1 - t0``). Wall clocks step under NTP and slew continuously,
        so the difference is not elapsed time; every span/latency/
        timeout measurement must use ``time.perf_counter()`` or
        ``time.monotonic()``. Genuine wall-clock timestamp arithmetic
        (e.g. age against a file mtime, which IS wall-clock) is
        allowlisted by symbol with its reason."""
        def _is_wall(n):
            if isinstance(n, ast.Call) \
                    and self.mod.resolve(n.func, None) == "time.time":
                return True
            return isinstance(n, ast.Name) \
                and any(n.id in s for s in self._walltime_scope)

        if _is_wall(node.left) or _is_wall(node.right):
            self._emit(
                "TH112", node,
                f"{ast.unparse(node)!s} computes a duration from "
                "time.time() — wall clocks step (NTP) and slew, so "
                "this is not elapsed time; use time.perf_counter() "
                "or time.monotonic() for spans and latency math")

    # -- TH103 / TH107: name-shaped rules -------------------------------
    def visit_Attribute(self, node):
        if self.mod.device_tier and isinstance(node.ctx, ast.Load):
            parts = []
            base = node
            while isinstance(base, ast.Attribute):
                parts.append(base.attr)
                base = base.value
            if isinstance(base, ast.Name):
                root = self.mod.import_map.get(base.id)
                if root in _IMPURE_MODULES:
                    self._emit(
                        "TH103", node,
                        f"{root}.{'.'.join(reversed(parts))} in a "
                        "device-tier module — host clocks/RNG freeze "
                        "at trace time; thread ticks/keys through the "
                        "state instead")
        self.generic_visit(node)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            if self.mod.device_tier:
                fq = self.mod.from_map.get(node.id)
                if fq is not None and fq.split(".")[0] in _IMPURE_MODULES:
                    self._emit(
                        "TH103", node,
                        f"{fq} in a device-tier module — host "
                        "clocks/RNG freeze at trace time")
            if self._in_trace() and node.id in self.mod.mutable_globals:
                self._emit(
                    "TH107", node,
                    f"module-level mutable {node.id!r} read inside "
                    "traced code — its contents bake into the "
                    "executable at trace time")
        self.generic_visit(node)


def _touches_mesh(node, mod) -> bool:
    """Is this function a mesh-handling host path (TH110 scope)? True
    when it takes a parameter named ``mesh``, reads any ``.mesh``
    attribute, or calls a mesh constructor (elastic_mesh / make_mesh /
    default_mesh). Nested defs are scanned too — a helper closure
    inside a mesh function inherits the scope via the visitor stack
    anyway, so the over-approximation only widens the same net."""
    args = node.args
    names = [a.arg for a in args.args + args.posonlyargs
             + args.kwonlyargs]
    if args.vararg is not None:
        names.append(args.vararg.arg)
    if args.kwarg is not None:
        names.append(args.kwarg.arg)
    if "mesh" in names:
        return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "mesh" \
                and isinstance(sub.ctx, ast.Load):
            return True
        if isinstance(sub, ast.Call):
            fq = mod.resolve(sub.func, None)
            if fq is not None and fq.rsplit(".", 1)[-1] in _MESH_CTORS:
                return True
    return False


def _sub_blocks(stmt):
    """The nested statement blocks of one compound statement that the
    SAME iteration executes — if/try/with arms. New scopes and nested
    loops are deliberately excluded: their sleeps and breaks pace the
    inner construct, not the loop TH108 is judging."""
    if isinstance(stmt, ast.If):
        yield stmt.body
        yield stmt.orelse
    elif isinstance(stmt, ast.Try):
        yield stmt.body
        for h in stmt.handlers:
            yield h.body
        yield stmt.orelse
        yield stmt.finalbody
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        yield stmt.body


def _bare_sleep(stmts, mod):
    """The first ``time.sleep(<constant>)`` expression statement in a
    loop body (recursing through if/try/with, not into nested scopes or
    loops). Resolved through the module's import map, so aliases
    (``from time import sleep``, ``import time as t``) are caught; a
    variable argument (a computed backoff) does not match."""
    for s in stmts:
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
            call = s.value
            if mod.resolve(call.func, None) == "time.sleep" \
                    and call.args \
                    and isinstance(call.args[0], ast.Constant):
                return call
        for blk in _sub_blocks(s):
            found = _bare_sleep(blk, mod)
            if found is not None:
                return found
    return None


def _bounded_escape(stmts, top: bool = True) -> bool:
    """Does a loop body guarantee a bound? True for an unconditional
    top-level break/return/raise, or an ``if`` whose test COMPARES
    something (a deadline, an attempt counter) and whose branch
    escapes. An ``if probe(): break`` does NOT count — that is the
    pattern under judgment: the escape exists but nothing bounds how
    long the loop waits for it."""
    for s in stmts:
        if top and isinstance(s, (ast.Break, ast.Return, ast.Raise)):
            return True
        if isinstance(s, ast.If):
            gated = any(isinstance(t, ast.Compare)
                        for t in ast.walk(s.test))
            escapes = any(
                isinstance(x, (ast.Break, ast.Return, ast.Raise))
                for blk in (s.body, s.orelse)
                for st in blk for x in ast.walk(st))
            if gated and escapes:
                return True
            if _bounded_escape(s.body, top=False) \
                    or _bounded_escape(s.orelse, top=False):
                return True
        elif isinstance(s, ast.Try):
            # The try body runs unconditionally; handlers/else do not.
            if _bounded_escape(s.body, top) \
                    or _bounded_escape(s.finalbody, top) \
                    or _bounded_escape(s.orelse, top=False) \
                    or any(_bounded_escape(h.body, top=False)
                           for h in s.handlers):
                return True
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            if _bounded_escape(s.body, top):
                return True
    return False


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _tracer_guard_name(test, mod):
    """Recognise ``isinstance(x, ...Tracer)`` (or its negation) as an
    If test. Returns ``(name, tracer_branch_is_body)`` or None. In the
    non-Tracer branch ``x`` is a concrete Python value, so ``int(x)``
    there is plain host math, not a device sync."""
    negated = False
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        test, negated = test.operand, True
    if not (isinstance(test, ast.Call)
            and isinstance(test.func, (ast.Name, ast.Attribute))
            and len(test.args) == 2
            and isinstance(test.args[0], ast.Name)):
        return None
    if mod.resolve(test.func, None) != "isinstance" and not (
            isinstance(test.func, ast.Name)
            and test.func.id == "isinstance"):
        return None
    cls = mod.resolve(test.args[1], None)
    if cls is None or not cls.rsplit(".", 1)[-1].endswith("Tracer"):
        return None
    return test.args[0].id, not negated


def _static_index(node) -> bool:
    """True when an ``.at[...]`` index is compile-time static —
    constants (including Ellipsis/None), negative constants, slices
    with static bounds, and tuples of those. These lower to
    (dynamic-)update-slice, not scatter, so TH109 stays quiet."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp):
        return _static_index(node.operand)
    if isinstance(node, ast.Slice):
        return all(p is None or _static_index(p)
                   for p in (node.lower, node.upper, node.step))
    if isinstance(node, ast.Tuple):
        return all(_static_index(e) for e in node.elts)
    return False


def _is_static_expr(node) -> bool:
    """True when an int()/float()/bool() argument is clearly host-side
    static: literals, len()/ord() results, config plumbing rooted at
    cfg/self, UPPER_CASE constants, and arithmetic over those. These
    shapes must NOT fire TH101 (the known false positives the tests
    pin)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return (node.id in _STATIC_ROOTS or node.id.isupper()
                or node.id.startswith("n_"))
    if isinstance(node, ast.Attribute):
        base = node
        while isinstance(base, ast.Attribute):
            base = base.value
        return isinstance(base, ast.Name) and base.id in _STATIC_ROOTS
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) and node.func.id == "len":
            return True  # len() is a Python int whatever the argument
        return (isinstance(node.func, ast.Name)
                and node.func.id in ("ord", "round", "min", "max")
                and all(_is_static_expr(a) for a in node.args))
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    return False
