"""LockLedger: the dynamic half of the lock-discipline pass.

The static rules (TH114-TH117 in :mod:`consul_tpu.analysis.concurrency`)
reason about code; this module watches the locks actually taken at test
time. It is deliberately monkeypatch-free, mirroring the CompileLedger
idiom in :mod:`consul_tpu.analysis.guards`: production modules build
their locks through :func:`make_lock` / :func:`make_rlock` /
:func:`make_condition`, which return *plain* ``threading`` primitives
unless a :class:`LockLedger` is installed — zero overhead outside
tests, full acquisition tracing inside them.

While installed, the ledger records, per acquisition: lock name, thread,
and the stack of ledger locks that thread already holds. From those it
maintains the observed lock-order graph ("B taken while A held") and
checks it for cycles *as edges appear*, so an AB/BA inversion is caught
on the first run that exercises both sides — no actual deadlock needed.
:meth:`blocking` brackets known-slow work (device transfers, socket
I/O) and records a violation if any ledger lock is held across it — the
runtime twin of TH117.

``fuzz(seed)`` arms a seeded-schedule perturber: each blocking acquire
first sleeps a deterministic pseudo-random sliver (up to ~250us drawn
from ``random.Random(seed)``), widening race windows so seeded runs
explore different interleavings while staying reproducible.

This module must stay importable without jax (the static lint layer
imports nothing from here at runtime, but tests and host modules do).
"""

from __future__ import annotations

import threading
import time
import random


class LockLedgerError(AssertionError):
    """A lock-discipline violation observed at runtime."""


class _Held(threading.local):
    def __init__(self):
        self.stack = []


class LockLedger:
    """Records real lock acquisitions and asserts discipline.

    Usage (see the ``lock_ledger`` fixture in tests/conftest.py)::

        ledger = LockLedger()
        with ledger:              # or ledger.install() / .uninstall()
            ledger.fuzz(seed=3)   # optional schedule perturbation
            ... exercise code built on make_lock()/make_condition() ...
        ledger.assert_clean()
    """

    _active = None
    _active_guard = threading.Lock()

    def __init__(self):
        self._guard = threading.Lock()
        self._held = _Held()
        self.acquisitions = []   # (lock_name, thread_name, held_tuple)
        self.edges = {}          # (src, dst) -> first (thread, heldrepr)
        self.violations = []     # human-readable strings
        self._rng = None
        self._max_jitter_s = 0.0

    # -- install / uninstall -------------------------------------------
    def install(self):
        cls = type(self)
        with cls._active_guard:
            if cls._active is not None and cls._active is not self:
                raise LockLedgerError("another LockLedger is installed")
            cls._active = self
        return self

    def uninstall(self):
        cls = type(self)
        with cls._active_guard:
            if cls._active is self:
                cls._active = None

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- seeded-schedule fuzzing ---------------------------------------
    def fuzz(self, seed, max_jitter_us=250):
        """Arm deterministic acquisition jitter drawn from ``seed``."""
        self._rng = random.Random(seed)
        self._max_jitter_s = max_jitter_us / 1e6
        return self

    # -- hooks called by the shim primitives ---------------------------
    def _before_acquire(self, name, blocking_acquire):
        held = list(self._held.stack)
        if self._rng is not None and blocking_acquire:
            time.sleep(self._rng.random() * self._max_jitter_s)
        if not blocking_acquire or name in held:
            # try-locks add no order constraint; reentrant re-acquires
            # (RLock) add no new edge either
            return
        new_edges = [(h, name) for h in held
                     if h != name and (h, name) not in self.edges]
        if not new_edges:
            return
        with self._guard:
            for edge in new_edges:
                if edge not in self.edges:
                    self.edges[edge] = (
                        threading.current_thread().name, tuple(held))
                    cyc = self._find_cycle_locked(edge[0])
                    if cyc:
                        self.violations.append(
                            "lock-order cycle observed: "
                            + " -> ".join(repr(c) for c in cyc))

    def _after_acquire(self, name, acquired):
        if not acquired:
            return
        self._held.stack.append(name)
        with self._guard:
            self.acquisitions.append(
                (name, threading.current_thread().name,
                 tuple(self._held.stack[:-1])))

    def _after_release(self, name):
        stack = self._held.stack
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    # -- TH117 runtime twin --------------------------------------------
    def blocking(self, what):
        """Context manager flagging ``what`` if entered under a lock."""
        return _BlockingRegion(self, what)

    # -- inspection / assertions ---------------------------------------
    def order_edges(self):
        """Sorted observed (held_lock, then_acquired) pairs."""
        with self._guard:
            return sorted(self.edges)

    def _find_cycle_locked(self, start):
        adj = {}
        for src, dst in self.edges:
            adj.setdefault(src, []).append(dst)
        path, on_path = [], set()

        def dfs(node):
            if node in on_path:
                return path[path.index(node):] + [node]
            if node in visited:
                return None
            visited.add(node)
            path.append(node)
            on_path.add(node)
            for nxt in sorted(adj.get(node, ())):
                found = dfs(nxt)
                if found:
                    return found
            path.pop()
            on_path.discard(node)
            return None

        visited = set()
        return dfs(start)

    def find_cycle(self):
        with self._guard:
            for src, _dst in sorted(self.edges):
                cyc = self._find_cycle_locked(src)
                if cyc:
                    return cyc
        return None

    def assert_acyclic(self):
        cyc = self.find_cycle()
        if cyc:
            raise LockLedgerError(
                "observed lock-order graph has a cycle: "
                + " -> ".join(repr(c) for c in cyc))

    def assert_clean(self):
        """No violations, acyclic order graph, nothing still held."""
        if self.violations:
            raise LockLedgerError(
                "%d lock-discipline violation(s):\n  " % len(self.violations)
                + "\n  ".join(self.violations))
        self.assert_acyclic()
        if self._held.stack:
            raise LockLedgerError(
                "locks still held at ledger teardown: %r"
                % (self._held.stack,))


class _BlockingRegion:
    def __init__(self, ledger, what):
        self.ledger = ledger
        self.what = what

    def __enter__(self):
        held = list(self.ledger._held.stack)
        if held:
            with self.ledger._guard:
                self.ledger.violations.append(
                    "blocking region %r entered while holding %r"
                    % (self.what, held))
        return self

    def __exit__(self, *exc):
        return False


class _LedgerLock:
    """threading.Lock/RLock shim reporting to the installed ledger."""

    def __init__(self, name, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking=True, timeout=-1):
        ledger = LockLedger._active
        if ledger is not None:
            ledger._before_acquire(self.name, blocking and timeout == -1)
        got = self._inner.acquire(blocking, timeout)
        if ledger is not None:
            ledger._after_acquire(self.name, got)
        return got

    def release(self):
        self._inner.release()
        ledger = LockLedger._active
        if ledger is not None:
            ledger._after_release(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # threading.Condition duck-types its lock through these three; by
    # NOT defining _release_save/_acquire_restore/_is_owned we force
    # Condition onto its acquire/release fallbacks, which route through
    # the shim above — so waits stay visible to the ledger.
    def locked(self):
        return self._inner.locked() if hasattr(self._inner, "locked") \
            else False

    def __repr__(self):
        return "<LedgerLock %s %r>" % (self.name, self._inner)


def make_lock(name):
    """A ``threading.Lock`` — wrapped if a LockLedger is installed."""
    if LockLedger._active is None:
        return threading.Lock()
    return _LedgerLock(name, threading.Lock())


def make_rlock(name):
    """A ``threading.RLock`` — wrapped if a LockLedger is installed."""
    if LockLedger._active is None:
        return threading.RLock()
    return _LedgerLock(name, threading.RLock())


def make_condition(name, lock=None):
    """A ``threading.Condition`` over ``lock`` (shim-aware).

    When a ledger is active and no lock is given, the condition is
    built over a fresh ledger lock so waits/notifies are traced.
    """
    if LockLedger._active is None:
        return threading.Condition(lock)
    if lock is None:
        lock = _LedgerLock(name, threading.Lock())
    return threading.Condition(lock)


def blocking(what):
    """Mark a known-blocking region (device transfer, socket I/O).

    No-op unless a LockLedger is installed; under one, entering the
    region with any ledger lock held records a TH117-shaped violation.
    """
    ledger = LockLedger._active
    if ledger is None:
        return _NullRegion()
    return ledger.blocking(what)


class _NullRegion:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
