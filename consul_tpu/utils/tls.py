"""TLS for the HTTP/RPC boundary: contexts + a development CA.

The reference's ``tlsutil.Configurator`` builds hot-reloadable TLS
configs for RPC/HTTP/gossip from CA + cert/key material, with
``VerifyIncoming``/``VerifyOutgoing`` gates (reference tlsutil/config.go),
and auto-encrypt provisions client certs from the server CA (reference
agent/consul/auto_encrypt*.go). This module is that surface at the
size this framework needs:

  - :class:`Configurator` — owns cert/key/CA paths, builds server and
    client ``ssl.SSLContext`` objects, and hot-reloads material in
    place (``update``), so running listeners pick up rotated certs on
    the next handshake — the reference's reload contract;
  - :func:`dev_ca` — a self-signed CA + server certificate generator
    (the ``consul tls cert create`` developer flow), built on the
    ``cryptography`` package the keyring already uses.

Gossip-layer encryption is separate and symmetric (wire/keyring.py),
exactly as in the reference.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
from typing import Optional

# Optional dependency: the Configurator / client_ctx surface is pure
# stdlib ``ssl``; only generating development material (dev_ca) needs
# the ``cryptography`` package.
try:
    import cryptography  # noqa: F401
    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover — crypto-less environment
    HAVE_CRYPTOGRAPHY = False


def _san(hostname: str):
    """IP SAN when the hostname parses as an address (v4 or v6), DNS
    SAN otherwise."""
    from cryptography import x509

    try:
        return x509.IPAddress(ipaddress.ip_address(hostname))
    except ValueError:
        return x509.DNSName(hostname)


def dev_ca(dir_path: str, hostname: str = "127.0.0.1") -> dict[str, str]:
    """Generate a CA plus a server cert/key signed by it (the
    ``consul tls ca create`` / ``tls cert create`` developer flow).
    Returns paths: {ca, cert, key}."""
    if not HAVE_CRYPTOGRAPHY:
        raise RuntimeError(
            "dev_ca requires the 'cryptography' package")
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    os.makedirs(dir_path, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)

    def name(cn):
        return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])

    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(name("consul-tpu dev CA"))
        .issuer_name(name("consul-tpu dev CA"))
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )
    srv_key = ec.generate_private_key(ec.SECP256R1())
    srv_cert = (
        x509.CertificateBuilder()
        .subject_name(name(hostname))
        .issuer_name(ca_cert.subject)
        .public_key(srv_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(
            x509.SubjectAlternativeName([_san(hostname)]),
            critical=False,
        )
        .sign(ca_key, hashes.SHA256())
    )
    paths = {"ca": os.path.join(dir_path, "ca.pem"),
             "cert": os.path.join(dir_path, "server.pem"),
             "key": os.path.join(dir_path, "server.key")}
    with open(paths["ca"], "wb") as f:
        f.write(ca_cert.public_bytes(serialization.Encoding.PEM))
    with open(paths["cert"], "wb") as f:
        f.write(srv_cert.public_bytes(serialization.Encoding.PEM))
    with open(paths["key"], "wb") as f:
        f.write(srv_key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()))
    return paths


class Configurator:
    """tlsutil.Configurator: build server/client contexts from the
    current material; ``update`` swaps material in place so running
    listeners serve the new cert on the next handshake."""

    def __init__(self, cert: str, key: str, ca: Optional[str] = None,
                 verify_incoming: bool = False):
        if verify_incoming and not ca:
            # The reference treats VerifyIncoming without a CA as a hard
            # config error — never a silent security downgrade
            # (tlsutil/config.go).
            raise ValueError("verify_incoming requires a CA file")
        self.ca = ca
        self.verify_incoming = verify_incoming
        self._server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        self.update(cert, key)

    def update(self, cert: str, key: str):
        """Hot-reload cert material (tlsutil reload contract): the
        existing server context object — already attached to running
        listeners — loads the new chain."""
        self.cert, self.key = cert, key
        self._server_ctx.load_cert_chain(cert, key)
        if self.ca and self.verify_incoming:
            self._server_ctx.load_verify_locations(self.ca)
            self._server_ctx.verify_mode = ssl.CERT_REQUIRED

    def incoming_ctx(self) -> ssl.SSLContext:
        """Server-side context (IncomingHTTPSConfig)."""
        return self._server_ctx

    def outgoing_ctx(self) -> ssl.SSLContext:
        """Client-side context verifying against the CA
        (OutgoingRPCConfig with VerifyOutgoing); presents this node's
        own cert so a VerifyIncoming peer accepts us."""
        return client_ctx(self.ca, cert=self.cert, key=self.key)


def client_ctx(ca: Optional[str], cert: Optional[str] = None,
               key: Optional[str] = None) -> ssl.SSLContext:
    """One shared recipe for outgoing RPC/HTTPS contexts (tlsutil
    OutgoingRPCConfig): verify the server against ``ca``, optionally
    present a client cert for VerifyIncoming servers. Hostname checks
    stay off — names are node ids, not DNS names."""
    ctx = ssl.create_default_context(cafile=ca) if ca \
        else ssl.create_default_context()
    ctx.check_hostname = False
    if cert:
        ctx.load_cert_chain(cert, key)
    return ctx
