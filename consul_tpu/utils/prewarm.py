"""AOT compile prewarm: build every chunk-program signature off the
critical path (ROADMAP item 2 — the 387.5 s serf cold start at 1M).

``jit(...).lower(avals).compile()`` compiles a program WITHOUT running
it, from abstract ``jax.ShapeDtypeStruct`` arguments that carry the
real arrays' shapes, dtypes AND shardings. Routed through the
persistent compilation cache (utils/compile_cache.py), the compiled
executable lands on disk keyed by its HLO fingerprint; a later process
that builds the same simulation — same (n, kind, chunk, mesh shape,
chaos shape) signature, same seed-derived topology (the topology
tables are trace-time constants, so the seed is part of the program
identity) — deserializes it instead of recompiling. A warm 1M serf
start then records ``compile_s ~ 0`` (trace + cache read) in bench
JSON, and the compile ledger (analysis/guards.py) pins steady state to
zero backend compiles: persistent-cache loads don't fire the
backend_compile event.

The prewarm builds REAL Simulation objects (cheap next to the compile
it avoids) rather than synthesizing avals by hand: that is the only
way to guarantee the fingerprint matches what ``run``/``chaos``/bench
will execute.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import jax

from consul_tpu.utils import compile_cache


def _abstract(tree):
    """ShapeDtypeStruct pytree mirroring ``tree``'s shapes, dtypes and
    shardings — the avals ``.lower()`` compiles against."""

    def one(leaf):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sh = getattr(leaf, "sharding", None)
            # Only mesh placements are part of the program identity.
            # Single-device leaves stay unspecified, exactly as the
            # real call sees its uncommitted inputs — mixing a pinned
            # SingleDeviceSharding with mesh shardings would both fail
            # to lower and fingerprint a program nobody runs.
            if not isinstance(sh, jax.sharding.NamedSharding):
                sh = None
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)
        return leaf

    return jax.tree.map(one, tree)


def prewarm_simulation(sim, chunk: int, with_metrics: bool) -> None:
    """AOT-compile one chunk-runner signature for ``sim`` exactly as
    ``Simulation.run(ticks, chunk, with_metrics)`` would bind it —
    same memoized program (models/cluster._chunk_runner), same mesh,
    same chaos shape, same raft arming — without advancing any
    state. With the raft tier armed the donated state aval is the
    ``(model_state, RaftState)`` pair the runner binds."""
    from consul_tpu.chaos import schedule as chaos_mod
    from consul_tpu.models import cluster

    raft_cfg = getattr(sim, "_raft_cfg", None)
    jitted = cluster._chunk_runner(
        sim.cfg, sim.topo, chunk, with_metrics,
        step_fn=type(sim)._step_fn, swim_of=type(sim)._swim_of,
        chaos_key=chaos_mod.static_key_of(sim.chaos),
        sentinel=sim.sentinel, mesh=sim.mesh,
        layout=getattr(sim, "layout", "dense"),
        raft=raft_cfg,
        kernel=getattr(sim, "kernel", "xla"),
    )
    state_aval = (_abstract(sim.state) if raft_cfg is None
                  else (_abstract(sim.state), _abstract(sim.raft.state)))
    jitted.lower(
        _abstract(sim.world), _abstract(sim.chaos),
        state_aval, _abstract(sim.base_key),
    ).compile()


def _mesh_shape(mesh) -> Optional[list]:
    if mesh is None:
        return None
    return [int(mesh.shape[a]) for a in mesh.axis_names]


def prewarm(ns: Sequence[int], kinds: Sequence[str] = ("swim",),
            chunks: Sequence[int] = (64,),
            metrics_modes: Sequence[bool] = (False, True),
            mesh=None, device_count: Optional[int] = None, n_dc: int = 1,
            chaos: bool = False, seed: int = 0, view_degree: int = 16,
            sentinel: bool = False, cache_dir: Optional[str] = None,
            layout: str = "dense", family: str = "circulant",
            family_param: float = 0.0, sweep: int = 0,
            sweep_chunk: int = 32, raft_groups: int = 0,
            raft_peers: int = 5, kernel: str = "xla") -> dict:
    """Compile every (n, kind, chunk, mesh-shape, chaos-shape, layout)
    signature into the persistent compile cache and return a JSON-ready
    summary: the signatures compiled, cache hit/miss movement, and wall
    time. ``mesh`` overrides the per-``n`` default
    (parallel/mesh.default_mesh over the visible devices, honoring
    ``device_count``/``n_dc``); ``chaos=True`` additionally compiles
    the chaos-enabled program for the default one-partition schedule
    shape (the ``consul-tpu chaos`` / bench chaos-phase signature).

    ``view_degree``/``seed``/``family``/``family_param`` must match the
    run being warmed — they change the seed-derived topology constants
    and with them the program fingerprint (the signature key documented
    in COVERAGE.md). ``sweep=S`` additionally compiles the S-scenario
    vmapped sweep program (chaos/sweep.py) at ``sweep_chunk`` — that
    one is topology-as-argument, so a single family warms every family
    of the same shape. ``raft_groups=R`` (with ``raft_peers``) arms the
    batched raft tier before compiling, warming the raft-carrying
    program a ``consul-tpu run --raft-groups R`` binds.
    ``kernel="pallas"`` warms the Pallas packed-native tick program
    (ops/pallas_gossip.py) instead of the XLA scan body — a different
    executable, so the flag is part of the signature key exactly like
    ``layout``.
    """
    from consul_tpu import chaos as chaos_api
    from consul_tpu.config import SimConfig, clamp_view_degree
    from consul_tpu.models.cluster import SerfSimulation, Simulation
    from consul_tpu.parallel import mesh as pmesh

    if cache_dir:
        compile_cache.enable(cache_dir)
    else:
        compile_cache.maybe_enable_from_env()
    classes = {"swim": Simulation, "serf": SerfSimulation}
    for kind in kinds:
        if kind not in classes:
            raise ValueError(f"unknown kind {kind!r} (swim|serf)")

    before = compile_cache.stats()
    t_start = time.perf_counter()
    signatures = []
    for n in ns:
        m = mesh if mesh is not None else pmesh.default_mesh(
            n, device_count=device_count, n_dc=n_dc)
        for kind in kinds:
            cfg = SimConfig(n=n, view_degree=clamp_view_degree(n, view_degree),
                            topo_family=family, topo_param=family_param)
            sim = classes[kind](cfg, seed=seed, sentinel=sentinel, mesh=m,
                                layout=layout, kernel=kernel)
            if raft_groups > 0:
                sim.set_raft(raft_groups, peers=raft_peers)
            schedules = [None]
            if chaos:
                schedules.append([chaos_api.Partition(
                    start=4, stop=16, side_a=slice(0, max(1, n // 3)))])
            for sched in schedules:
                sim.set_chaos(sched)
                for chunk in chunks:
                    for with_metrics in metrics_modes:
                        t0 = time.perf_counter()
                        prewarm_simulation(sim, chunk, with_metrics)
                        signatures.append({
                            "n": int(n), "kind": kind, "chunk": int(chunk),
                            "mesh": _mesh_shape(m),
                            "with_metrics": bool(with_metrics),
                            "chaos": sched is not None,
                            "layout": layout,
                            "kernel": kernel,
                            "family": family,
                            "raft_groups": int(raft_groups),
                            "wall_s": round(time.perf_counter() - t0, 3),
                        })
            if sweep > 0:
                from consul_tpu.chaos import sweep as sweep_mod

                sim.set_chaos(None)
                t0 = time.perf_counter()
                sweep_mod.prewarm_sweep(
                    sim, sweep_mod.scenario_grid(n, sweep),
                    chunk=sweep_chunk)
                signatures.append({
                    "n": int(n), "kind": kind, "chunk": int(sweep_chunk),
                    "mesh": _mesh_shape(m), "with_metrics": False,
                    "chaos": True, "layout": layout,
                    "family": "*",  # topology-as-argument: any family
                    "sweep": int(sweep),
                    "wall_s": round(time.perf_counter() - t0, 3),
                })
    return {
        "signatures": signatures,
        "compiled": len(signatures),
        "cache": compile_cache.stats_delta(before),
        "wall_s": round(time.perf_counter() - t_start, 3),
    }
