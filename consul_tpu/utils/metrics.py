"""Convergence and accuracy metrics for the simulated cluster.

These mirror what the reference's operators watch: membership agreement
(serf's convergence simulator outputs, reference lib/serf.go:21-25
comment), failure-detection latency, false-positive rate
(memberlist.health gauges, awareness.go:50), and Vivaldi accuracy
(serf.coordinate.adjustment-ms metrics, ping_delegate.go:71-81) — here
measurable exactly because the simulation owns the ground truth.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from consul_tpu.config import SimConfig
from consul_tpu.models.state import SimState
from consul_tpu.ops import merge, topology, vivaldi
from consul_tpu.ops.topology import World


class HealthMetrics(NamedTuple):
    agreement: jax.Array        # [] f32 — fraction of live-observer edges
                                # whose alive/dead belief matches truth
    false_positive: jax.Array   # [] f32 — live nodes believed dead/suspect
    undetected: jax.Array       # [] f32 — dead nodes still believed alive
    live_nodes: jax.Array       # [] i32


def health(cfg: SimConfig, topo, state: SimState) -> HealthMetrics:
    """Membership-agreement metrics over every (live observer, neighbor) edge."""
    active = state.alive_truth & ~state.left
    st = merge.key_status(state.view_key)
    subj_up = topology.gather_cols(topo, active)  # truth per edge subject
    believed_up = st == merge.ALIVE
    believed_down = (st == merge.DEAD) | (st == merge.LEFT)
    obs = active[:, None] & jnp.ones_like(st, bool)
    edges = jnp.maximum(jnp.sum(obs), 1)
    # Suspect counts as "not yet wrong" for false positives but as
    # disagreement for convergence (the reference's convergence window
    # is until states settle, not merely until suspicion).
    agree = obs & ((subj_up & believed_up) | (~subj_up & believed_down))
    fp = obs & subj_up & believed_down
    und = obs & ~subj_up & believed_up
    return HealthMetrics(
        agreement=jnp.sum(agree) / edges,
        false_positive=jnp.sum(fp) / edges,
        undetected=jnp.sum(und) / edges,
        live_nodes=jnp.sum(active).astype(jnp.int32),
    )


def vivaldi_rmse(cfg: SimConfig, world: World, state: SimState, key, samples: int = 4096):
    """RMSE of estimated vs true RTT over random live pairs, in seconds.

    The north-star accuracy metric (BASELINE.md): how well the learned
    coordinates predict the ground-truth latency model, the same
    question `consul rtt` answers from real coordinates (reference
    command/rtt/rtt.go, lib/rtt.go:13-19).
    """
    k1, k2 = jax.random.split(key)
    i = jax.random.randint(k1, (samples,), 0, cfg.n)
    j = jax.random.randint(k2, (samples,), 0, cfg.n)
    ok = (i != j) & state.alive_truth[i] & state.alive_truth[j]
    est = vivaldi.distance(
        state.viv.vec[i], state.viv.height[i], state.viv.adjustment[i],
        state.viv.vec[j], state.viv.height[j], state.viv.adjustment[j],
    )
    err = jnp.where(ok, est - topology.true_rtt(world, i, j), 0.0)
    denom = jnp.maximum(jnp.sum(ok), 1)
    return jnp.sqrt(jnp.sum(err * err) / denom)
