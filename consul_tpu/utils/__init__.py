"""Host-side utilities: convergence metrics, checkpointing, telemetry."""
