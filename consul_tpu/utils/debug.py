"""Debug bundle: the ``consul debug`` capture, TPU-side.

The reference CLI bundles metrics, host info, agent self-description,
heap/cpu profiles, and logs into a tarball (reference
command/debug/debug.go: captureStatic :299, captureDynamic :353;
pprof endpoints agent/http.go:304-309). The TPU equivalents:

  - static capture: agent self + members + coordinates + the
    go-metrics snapshot, fetched over the same HTTP API the reference
    uses (:func:`capture_static`);
  - dynamic capture: instead of pprof, a ``jax.profiler`` trace of the
    simulation's step program (:func:`capture_sim` with
    ``profile_ticks`` > 0) — the XLA-level truth about where the step
    spends its time, viewable in TensorBoard/Perfetto;
  - :func:`write_bundle` packs everything into one ``.tar.gz``.
"""

from __future__ import annotations

import io
import json
import os
import platform
import tarfile
import time
from typing import Optional


def _host_info() -> dict:
    """agent/debug/host.go:20-31 equivalent (no gopsutil here)."""
    info = {
        "Hostname": platform.node(),
        "OS": platform.system(),
        "Platform": platform.platform(),
        "Python": platform.python_version(),
        "CollectionTime": int(time.time() * 1e9),
    }
    try:
        import jax
        info["Jax"] = jax.__version__
        # Devices only when a backend ALREADY exists: the debug CLI is
        # a pure HTTP client and must never initialize one itself —
        # jax.devices() dials the device plugin, and on this
        # environment's remote-TPU relay that call can hang
        # indefinitely when the relay is wedged (measured: the whole
        # `consul-tpu debug` verb froze on this line).
        from jax._src import xla_bridge as _xb
        if getattr(_xb, "_backends", None):
            info["Devices"] = [str(d) for d in jax.devices()]
        else:
            info["Devices"] = "not initialized (host-side capture)"
    except Exception as e:  # noqa: BLE001 — host info must never fail
        info["JaxError"] = repr(e)
    return info


def capture_static(client) -> dict[str, dict]:
    """Fetch the static capture set over the HTTP API (the reference's
    captureStatic: self, metrics, members — debug.go:299-351)."""
    out: dict[str, dict] = {"host.json": _host_info()}

    def grab(name, fn):
        try:
            out[name] = fn()
        except Exception as e:  # noqa: BLE001 — partial bundles beat none
            out[name] = {"error": repr(e)}

    grab("self.json", client.agent.self_)
    grab("metrics.json", client.agent.metrics)
    grab("members.json", lambda: client.catalog.nodes()[0])
    grab("coordinates.json", lambda: client.coordinate.nodes()[0])
    # The combined node+services+checks dump the reference's debug and
    # UI read (internal_endpoint.go NodeDump via /v1/internal/ui/nodes).
    grab("node-dump.json", lambda: client.internal.node_dump()[0])
    # Day-2 raft/autopilot views (operator_raft_endpoint.go).
    grab("raft-configuration.json", client.operator.raft_get_configuration)
    grab("autopilot-config.json",
         client.operator.autopilot_get_configuration)
    grab("autopilot-health.json",
         client.operator.autopilot_server_health)
    # Round-5 control-plane surfaces. Token listings are ALREADY
    # secret-redacted by the endpoint, never re-fetched with secrets.
    grab("intentions.json", lambda: client.connect.intention_list()[0])
    grab("prepared-queries.json", lambda: client.query.list()[0])
    grab("acl-policies.json", client.acl.policy_list)
    grab("acl-tokens.json", client.acl.token_list)
    return out


def capture_sim(sim, profile_ticks: int = 0,
                trace_dir: Optional[str] = None) -> dict[str, dict]:
    """Capture a running simulation: config, health, telemetry — and,
    when ``profile_ticks`` > 0, a jax.profiler trace of that many ticks
    written under ``trace_dir`` (the pprof-profile equivalent)."""
    import dataclasses

    import jax

    from consul_tpu.utils import metrics as m

    out: dict[str, dict] = {"host.json": _host_info()}
    out["config.json"] = dataclasses.asdict(sim.cfg)
    swim_st = sim.swim_state  # works for bare-SWIM and serf drivers
    h = m.health(sim.cfg, sim.topo, swim_st)
    out["health.json"] = {
        "agreement": float(h.agreement),
        "false_positive": float(h.false_positive),
        "undetected": float(h.undetected),
        "live_nodes": int(h.live_nodes),
        "vivaldi_rmse_ms": float(sim.rmse()) * 1000.0,
        "tick": int(swim_st.t),
    }
    out["metrics.json"] = sim.sink.snapshot()
    # The flight recorder's view of this process: the host-span ring
    # (Chrome trace-event JSON, obs/trace.py) and — when the node lens
    # is armed — the recorded per-node timelines.
    from consul_tpu.obs import trace as obs_trace
    out["spans.json"] = obs_trace.get_tracer().to_json()
    if getattr(sim, "lens", None) is not None:
        out["lens.json"] = sim.lens.to_json()
    if profile_ticks > 0 and trace_dir:
        with jax.profiler.trace(trace_dir):
            sim.run(profile_ticks, with_metrics=False)
            jax.block_until_ready(sim.swim_state.view_key)
        out["profile.json"] = {"trace_dir": trace_dir,
                               "ticks": profile_ticks}
    return out


def write_bundle(path: str, files: dict[str, dict],
                 extra_dirs: Optional[list[str]] = None) -> str:
    """Pack captures (+ optional trace directories) into a .tar.gz —
    the debug.go tarball (:553-...)."""
    with tarfile.open(path, "w:gz") as tar:
        for name, payload in files.items():
            blob = json.dumps(payload, indent=2, default=str).encode()
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(blob))
        for d in extra_dirs or []:
            if os.path.isdir(d):
                tar.add(d, arcname=os.path.basename(d.rstrip("/")))
    return path
