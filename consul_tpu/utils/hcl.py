"""HCL (HashiCorp Configuration Language v1) subset parser.

The reference's config builder accepts HCL beside JSON (reference
agent/config/builder.go:1-200, vendor/github.com/hashicorp/hcl); every
published Consul example config is written in it. This module parses
the HCL1 subset those configs actually use into plain dicts:

  - ``key = value`` assignments (idents or quoted keys)
  - values: strings (with escapes), integers, floats, bools,
    lists ``[...]``, objects ``{ k = v ... }``
  - blocks ``name { ... }`` and labeled blocks
    ``service "web" { ... }`` (labels nest: ``a "b" "c" {}`` is
    ``{"a": {"b": {"c": {...}}}}`` — HCL1 object-key chaining)
  - repeated blocks/keys merge: objects deep-merge; anything else
    collects into a list (HCL1's ExpandShorthand semantics, the shape
    hcl.Decode gives Go)
  - comments: ``#``, ``//``, ``/* ... */``

Grammar-complete HCL (interpolation, heredocs) is out of scope — the
reference's *config files* never use those (interpolation arrived with
HCL2/Terraform, not Consul agent configs).
"""

from __future__ import annotations

import re
from typing import Any

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<comment>\#[^\n]*|//[^\n]*|/\*.*?\*/)
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<float>-?\d+\.\d+(?:[eE][+-]?\d+)?|-?\d+[eE][+-]?\d+)
  | (?P<int>-?\d+)
  | (?P<punct>[={}\[\],:])
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.-]*)
""", re.VERBOSE | re.DOTALL)

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\"}


class HCLError(ValueError):
    pass


def _tokenize(src: str):
    pos, line = 0, 1
    out = []
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise HCLError(f"line {line}: unexpected character {src[pos]!r}")
        kind = m.lastgroup
        text = m.group()
        line += text.count("\n")
        pos = m.end()
        if kind in ("ws", "comment"):
            continue
        out.append((kind, text, line))
    out.append(("eof", "", line))
    return out


def _unquote(s: str) -> str:
    body, out, i = s[1:-1], [], 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            nxt = body[i + 1]
            if nxt not in _ESCAPES:
                # Reject rather than silently mangle (Go's strconv
                # unquote errors on invalid escapes; dropping the
                # backslash would corrupt e.g. Windows paths).
                raise HCLError(f"invalid escape sequence \\{nxt} in {s}")
            out.append(_ESCAPES[nxt])
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


class _Parser:
    def __init__(self, tokens):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, kind, text=None):
        k, t, line = self.next()
        if k != kind or (text is not None and t != text):
            raise HCLError(
                f"line {line}: expected {text or kind}, got {t or k!r}")
        return t

    # -- values --------------------------------------------------------
    def value(self) -> Any:
        kind, text, line = self.next()
        if kind == "string":
            return _unquote(text)
        if kind == "int":
            return int(text)
        if kind == "float":
            return float(text)
        if kind == "ident":
            if text == "true":
                return True
            if text == "false":
                return False
            if text == "null":
                return None
            raise HCLError(f"line {line}: bare identifier {text!r} as value")
        if (kind, text) == ("punct", "["):
            return self.list_value()
        if (kind, text) == ("punct", "{"):
            return self.object_body(closing="}")
        raise HCLError(f"line {line}: unexpected {text or kind!r} in value")

    def list_value(self) -> list:
        out = []
        while True:
            kind, text, _ = self.peek()
            if (kind, text) == ("punct", "]"):
                self.next()
                return out
            out.append(self.value())
            kind, text, _ = self.peek()
            if (kind, text) == ("punct", ","):
                self.next()

    # -- objects / blocks ---------------------------------------------
    def object_body(self, closing=None) -> dict:
        out: dict[str, Any] = {}
        while True:
            kind, text, line = self.peek()
            if kind == "eof":
                if closing is None:
                    return out
                raise HCLError(f"line {line}: unexpected EOF, missing "
                               f"{closing!r}")
            if closing is not None and (kind, text) == ("punct", closing):
                self.next()
                return out
            if kind not in ("ident", "string"):
                raise HCLError(f"line {line}: expected a key, got "
                               f"{text or kind!r}")
            self.next()
            key = _unquote(text) if kind == "string" else text
            # Label chain: block "label" ["label2"...] { ... }
            labels = []
            while self.peek()[0] == "string":
                labels.append(_unquote(self.next()[1]))
            kind2, text2, line2 = self.peek()
            if (kind2, text2) == ("punct", "{"):
                self.next()
                val: Any = self.object_body(closing="}")
                for lbl in reversed(labels):
                    val = {lbl: val}
            elif labels:
                raise HCLError(
                    f"line {line2}: labeled key {key!r} must open a block")
            else:
                if (kind2, text2) in (("punct", "="), ("punct", ":")):
                    self.next()
                else:
                    raise HCLError(
                        f"line {line2}: expected '=' or block after {key!r}")
                val = self.value()
            _merge(out, key, val)
            kind3, text3, _ = self.peek()
            if (kind3, text3) == ("punct", ","):
                self.next()


def _merge(out: dict, key: str, val: Any) -> None:
    """HCL1 repeated-key semantics: objects deep-merge, everything else
    collects into a list."""
    if key not in out:
        out[key] = val
        return
    cur = out[key]
    if isinstance(cur, dict) and isinstance(val, dict):
        for k, v in val.items():
            _merge(cur, k, v)
    elif isinstance(cur, list) and not isinstance(val, list):
        cur.append(val)
    else:
        out[key] = [cur, val]


def parse(src: str) -> dict:
    """Parse HCL source into a dict (the shape hcl.Decode gives Go)."""
    return _Parser(_tokenize(src)).object_body()


def load(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return parse(f.read())
