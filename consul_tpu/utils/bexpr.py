"""Boolean filter expressions for API results (``?filter=``).

Mirrors the reference's go-bexpr filtering (reference agent/http.go
parseFilter → hashicorp/go-bexpr, wired into catalog/health/agent
listings): a small boolean expression language over result rows —

    Node == "web-1" and Service.Port != 80
    "prod" in Service.Tags
    Checks is not empty
    Node matches "web-[0-9]+"
    not (Status == critical or Status == warning)

Grammar (bexpr's): ``or`` over ``and`` over ``not`` over primaries;
primaries are parenthesised expressions, ``<selector> <op> <value>``,
``<value> in|not in <selector>``, and ``<selector> is [not] empty``.
Operators: ``==  !=  in  not in  contains  matches  not matches``.
Values are double-quoted, backtick-quoted, or bare words.

Selectors are dotted paths into the row (``Service.Tags``); this
framework's rows use snake_case keys while the reference's selectors
are Go field names, so lookup tries the selector verbatim, then its
snake_case form — both spellings work.
"""

from __future__ import annotations

import re
from typing import Any, Optional


class FilterError(ValueError):
    pass


# --- `matches` hardening (RE2 divergence, COVERAGE.md) ----------------
#
# go-bexpr matches via Go's regexp (RE2): guaranteed linear time. This
# port uses Python's backtracking `re`, where a hostile
# `?filter=... matches ...` pattern like (a+)+$ against a modest input
# is exponential — a one-request DoS on the HTTP tier. RE2 itself is
# not reimplementable here, so the exposure is closed structurally:
# pattern and matched-input lengths are capped, and the nested
# quantifier family (a repeat whose body contains another repeat) is
# rejected when the Filter compiles, before any row is evaluated.

try:  # Python 3.11+ spells the sre internals re._constants/_parser
    from re import _constants as _sre_c
    from re import _parser as _sre_p
except ImportError:  # Python <= 3.10
    import sre_constants as _sre_c
    import sre_parse as _sre_p

_RE_MAX_PATTERN = 256
_RE_MAX_INPUT = 4096
_REPEATS = (_sre_c.MAX_REPEAT, _sre_c.MIN_REPEAT)


def _sre_children(op, av):
    """Subpatterns nested inside one parsed sre node."""
    if op in _REPEATS:
        yield av[2]
    elif op is _sre_c.BRANCH:
        for alt in av[1]:
            yield alt
    elif op is _sre_c.SUBPATTERN:
        yield av[3]
    elif op in (_sre_c.ASSERT, _sre_c.ASSERT_NOT):
        yield av[1]


def _contains_repeat(sub) -> bool:
    for op, av in sub:
        if op in _REPEATS:
            return True
        if any(_contains_repeat(c) for c in _sre_children(op, av)):
            return True
    return False


def _nested_quantifier(sub) -> bool:
    for op, av in sub:
        for child in _sre_children(op, av):
            if op in _REPEATS and _contains_repeat(child):
                return True
            if _nested_quantifier(child):
                return True
    return False


def _check_pattern(pattern: str) -> None:
    """Raise FilterError for patterns the backtracking engine cannot
    match safely. Runs at Filter compile time (parse_primary), so a bad
    pattern is a 400 before any row is touched."""
    if len(pattern) > _RE_MAX_PATTERN:
        raise FilterError(
            f"regexp too long ({len(pattern)} > {_RE_MAX_PATTERN} chars)")
    try:
        parsed = _sre_p.parse(pattern)
    except re.error as e:
        raise FilterError(f"bad regexp {pattern!r}: {e}") from e
    if _nested_quantifier(parsed):
        raise FilterError(
            f"regexp {pattern!r} rejected: nested quantifiers risk "
            "catastrophic backtracking (RE2 divergence, COVERAGE.md)")


_TOKEN = re.compile(r"""
    \s*(?:
      (?P<lparen>\() | (?P<rparen>\)) |
      (?P<dquote>"(?:[^"\\]|\\.)*") |
      (?P<bquote>`[^`]*`) |
      (?P<badquote>["`]) |
      (?P<word>[^\s()"`]+)
    )""", re.VERBOSE)

_KEYWORDS = {"and", "or", "not", "in", "contains", "matches", "is",
             "empty", "==", "!="}


def _tokenize(src: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if m is None or m.end() == pos:
            if src[pos:].strip():
                raise FilterError(f"bad filter syntax at {src[pos:]!r}")
            break
        pos = m.end()
        if m.group("lparen"):
            out.append(("(", "("))
        elif m.group("rparen"):
            out.append((")", ")"))
        elif m.group("dquote"):
            raw = m.group("dquote")[1:-1]
            out.append(("value", re.sub(r"\\(.)", r"\1", raw)))
        elif m.group("bquote"):
            out.append(("value", m.group("bquote")[1:-1]))
        elif m.group("badquote"):
            # A lone quote means an unterminated string: refuse loudly
            # rather than comparing against a mangled literal.
            raise FilterError(
                f"unterminated string starting at {src[pos - 1:]!r}")
        else:
            w = m.group("word")
            out.append(("word", w))
    return out


def snake_case(name: str) -> str:
    """Public camel→snake helper (shared with the HTTP tier)."""
    return _snake(name)


def _snake(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0 and not name[i - 1].isupper():
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


def _lookup(row: Any, selector: str) -> tuple[bool, Any]:
    """(found, value) for a dotted path; tries the given spelling then
    snake_case per segment. A missing path is 'not found', never an
    error (bexpr evaluates missing fields as non-matching)."""
    cur = row
    for seg in selector.split("."):
        if isinstance(cur, dict):
            if seg in cur:
                cur = cur[seg]
                continue
            alt = _snake(seg)
            if alt in cur:
                cur = cur[alt]
                continue
            return False, None
        if isinstance(cur, (list, tuple)) and seg.isdigit():
            i = int(seg)
            if i >= len(cur):
                return False, None
            cur = cur[i]
            continue
        return False, None
    return True, cur


def _as_str(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return str(v)


def _eq(field: Any, value: str) -> bool:
    if isinstance(field, bool):
        return _as_str(field) == value.lower()
    if isinstance(field, (int, float)):
        try:
            return float(value) == float(field)
        except ValueError:
            return False
    return _as_str(field) == value


def _contains(field: Any, value: str) -> bool:
    if isinstance(field, (list, tuple)):
        return any(_eq(x, value) for x in field)
    if isinstance(field, dict):
        return value in field
    if isinstance(field, (str, bytes)):
        return value in _as_str(field)
    return False


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.pos = 0

    def peek(self) -> Optional[tuple[str, str]]:
        return self.toks[self.pos] if self.pos < len(self.toks) else None

    def next(self) -> tuple[str, str]:
        t = self.peek()
        if t is None:
            raise FilterError("unexpected end of filter")
        self.pos += 1
        return t

    def expect_value(self) -> str:
        kind, text = self.next()
        if kind not in ("word", "value"):
            raise FilterError(f"expected a value, got {text!r}")
        return text

    def parse(self):
        node = self.parse_or()
        if self.peek() is not None:
            raise FilterError(f"trailing tokens at {self.peek()[1]!r}")
        return node

    def parse_or(self):
        left = self.parse_and()
        while self.peek() and self.peek()[1] == "or":
            self.next()
            right = self.parse_and()
            left = ("or", left, right)
        return left

    def parse_and(self):
        left = self.parse_unary()
        while self.peek() and self.peek()[1] == "and":
            self.next()
            right = self.parse_unary()
            left = ("and", left, right)
        return left

    def parse_unary(self):
        t = self.peek()
        if t and t[1] == "not":
            self.next()
            return ("not", self.parse_unary())
        return self.parse_primary()

    def parse_primary(self):
        kind, text = self.next()
        if kind == "(":
            node = self.parse_or()
            k, _ = self.next()
            if k != ")":
                raise FilterError("missing )")
            return node
        if kind == ")":
            raise FilterError("unexpected )")
        # Either  <value> [not] in <selector>   or
        #         <selector> <op> ...
        nxt = self.peek()
        if nxt and nxt[1] == "in":
            self.next()
            sel = self.expect_value()
            return ("in", text, sel)
        if nxt and nxt[1] == "not" and self.pos + 1 < len(self.toks) \
                and self.toks[self.pos + 1][1] == "in":
            self.next()
            self.next()
            sel = self.expect_value()
            return ("not", ("in", text, sel))
        selector = text
        if kind == "value":
            raise FilterError(
                f"quoted value {text!r} must be followed by in/not in")
        k, op = self.next()
        if op in ("==", "!="):
            val = self.expect_value()
            node = ("eq", selector, val)
            return node if op == "==" else ("not", node)
        if op == "contains":
            return ("contains", selector, self.expect_value())
        if op == "matches":
            pat = self.expect_value()
            _check_pattern(pat)
            return ("matches", selector, pat)
        if op == "not":
            k2, op2 = self.next()
            if op2 == "matches":
                pat = self.expect_value()
                _check_pattern(pat)
                return ("not", ("matches", selector, pat))
            raise FilterError(f"bad operator 'not {op2}'")
        if op == "is":
            k2, w = self.next()
            if w == "empty":
                return ("empty", selector)
            if w == "not":
                k3, w2 = self.next()
                if w2 == "empty":
                    return ("not", ("empty", selector))
            raise FilterError("expected 'is [not] empty'")
        raise FilterError(f"unknown operator {op!r}")


def _eval(node, row) -> bool:
    op = node[0]
    if op == "and":
        return _eval(node[1], row) and _eval(node[2], row)
    if op == "or":
        return _eval(node[1], row) or _eval(node[2], row)
    if op == "not":
        return not _eval(node[1], row)
    if op == "eq":
        found, v = _lookup(row, node[1])
        return found and _eq(v, node[2])
    if op == "contains":
        found, v = _lookup(row, node[1])
        return found and _contains(v, node[2])
    if op == "in":
        found, v = _lookup(row, node[2])
        return found and _contains(v, node[1])
    if op == "matches":
        found, v = _lookup(row, node[1])
        if not found:
            return False
        try:
            # Input cap pairs with the compile-time pattern checks: even
            # a pathological bounded pattern only ever sees the first
            # _RE_MAX_INPUT chars of a field.
            return re.search(node[2], _as_str(v)[:_RE_MAX_INPUT]) is not None
        except re.error as e:
            raise FilterError(f"bad regexp {node[2]!r}: {e}") from e
    if op == "empty":
        found, v = _lookup(row, node[1])
        if not found:
            return True
        if v is None:
            return True
        if isinstance(v, (list, tuple, dict, str, bytes)):
            return len(v) == 0
        return False
    raise AssertionError(op)


class Filter:
    """Compiled filter: ``Filter('Port == 80').match(row)`` /
    ``.apply(rows)`` (the bexpr.Evaluator shape)."""

    def __init__(self, expression: str):
        self.expression = expression
        self._ast = _Parser(_tokenize(expression)).parse()

    def match(self, row: Any) -> bool:
        return _eval(self._ast, row)

    def apply(self, rows: list) -> list:
        return [r for r in rows if self.match(r)]


def apply_filter(expression: Optional[str], rows: list) -> list:
    """``rows`` unchanged when no expression; raises FilterError (→
    HTTP 400) on a bad one."""
    if not expression:
        return rows
    return Filter(expression).apply(rows)
