"""Telemetry: a go-metrics-style in-memory sink with reference metric
names, feeding ``/v1/agent/metrics`` and the debug bundle.

The reference fans go-metrics out to statsite/statsd/prometheus sinks
and ALWAYS attaches an in-memory sink exposed at ``/v1/agent/metrics``
(reference lib/telemetry.go, agent/http_register.go:39). This module is
that in-memory sink — gauges, counters, and samples with the reference
API shape (SetGauge/IncrCounter/AddSample/MeasureSince) and the
reference JSON schema on snapshot (armon/go-metrics InmemSink
DisplayMetrics: Timestamp/Gauges/Counters/Samples with
Count/Sum/Min/Max/Mean aggregates).

:func:`emit_sim_metrics` translates one simulation chunk boundary into
the metric names the reference's gossip stack emits — the TPU fold of
per-operation instrumentation onto the batched host boundary:

    memberlist.health.score      awareness gauge (awareness.go:50);
                                 the sim emits mean/max over all nodes
    memberlist.gossip            per-round wall time (state.go:518)
    serf.coordinate.adjustment-ms  |adjustment| sample in ms
                                   (ping_delegate.go:71-81)
    serf.coordinate.resets       Vivaldi NaN/Inf reset counter
                                 (client.go:228-231; the reference's
                                 serf.coordinate.rejected counts the
                                 same defensive path)
    sim.*                        the north-star convergence metrics
                                 (agreement / false-positive /
                                 undetected / rmse-ms / rounds-per-sec)

External sinks (statsd and friends) need sockets this framework does
not own; ``Sink.snapshot()`` returns the same JSON any consumer would
forward.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

# jax/numpy are imported lazily inside emit_sim_metrics: the Sink class
# is pure Python and agents (which only need the sink) must not pay for
# JAX import/backend init at startup.


# Bounded per-aggregate sample window for the percentile views. 512
# recent values bound memory like the go-metrics interval ring does;
# p50/p99 over the window is what the Prometheus summary lines expose.
_PCTL_WINDOW = 512


class _Aggregate:
    __slots__ = ("count", "total", "min", "max", "recent")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.recent = deque(maxlen=_PCTL_WINDOW)

    def add(self, v: float):
        self.count += 1
        self.total += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        self.recent.append(v)

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the bounded recent window."""
        if not self.recent:
            return 0.0
        vals = sorted(self.recent)
        return vals[min(len(vals) - 1, int(len(vals) * q))]

    def view(self, name: str) -> dict:
        mean = self.total / self.count if self.count else 0.0
        return {"Name": name, "Count": self.count, "Sum": self.total,
                "Min": self.min if self.count else 0.0,
                "Max": self.max if self.count else 0.0, "Mean": mean,
                "P50": self.percentile(0.5), "P99": self.percentile(0.99)}


class Sink:
    """In-memory metrics sink (armon/go-metrics InmemSink contract)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._gauges: dict[str, float] = {}
        self._counters: dict[str, _Aggregate] = {}
        self._samples: dict[str, _Aggregate] = {}

    # go-metrics API surface (names are dotted, like the wire form).
    def set_gauge(self, name: str, value: float):
        with self._lock:
            self._gauges[name] = float(value)

    def incr_counter(self, name: str, n: float = 1.0):
        with self._lock:
            self._counters.setdefault(name, _Aggregate()).add(float(n))

    def add_sample(self, name: str, value: float):
        with self._lock:
            self._samples.setdefault(name, _Aggregate()).add(float(value))

    def measure_since(self, name: str, t0: float):
        """MeasureSince: elapsed milliseconds sample (go-metrics)."""
        self.add_sample(name, (time.perf_counter() - t0) * 1000.0)

    # Read-side accessors (the host tier's read-through views — e.g.
    # RpcListener.metrics — poll these instead of keeping shadow dicts).
    def counter_sum(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            agg = self._counters.get(name)
            return agg.total if agg is not None else default

    def gauge_value(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def snapshot(self) -> dict:
        """The /v1/agent/metrics JSON shape (go-metrics
        DisplayMetrics)."""
        with self._lock:
            return {
                "Timestamp": time.strftime("%Y-%m-%d %H:%M:%S +0000 UTC",
                                           time.gmtime()),
                "Gauges": [{"Name": k, "Value": v}
                           for k, v in sorted(self._gauges.items())],
                "Counters": [agg.view(k) for k, agg in
                             sorted(self._counters.items())],
                "Samples": [agg.view(k) for k, agg in
                            sorted(self._samples.items())],
            }


def to_prometheus(snapshot: dict) -> str:
    """Render a DisplayMetrics snapshot in the Prometheus text
    exposition format (reference /v1/agent/metrics?format=prometheus,
    agent_endpoint.go:90 via promhttp). Metric names sanitize the
    go-metrics dotted names the Prometheus way (dots → underscores)."""
    def norm(name: str) -> str:
        return "".join(ch if ch.isalnum() or ch == "_" else "_"
                       for ch in name)

    # Distinct dotted names can sanitize to the same Prometheus name
    # ("serf.queue.Event-max" vs "serf.queue.Event.max"); a second
    # # TYPE line for an already-declared name is invalid exposition
    # format, so later collisions are skipped (keep first).
    seen: set[str] = set()
    lines: list[str] = []
    for g in snapshot.get("Gauges", []):
        n = norm(g["Name"])
        if n in seen:
            continue
        seen.add(n)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {float(g['Value'])}")
    for c in snapshot.get("Counters", []):
        n = norm(c["Name"])
        if n in seen:
            continue
        seen.add(n)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {float(c.get('Sum', c.get('Count', 0)))}")
    for s in snapshot.get("Samples", []):
        n = norm(s["Name"])
        if n in seen:
            continue
        seen.add(n)
        # Samples render as a summary — quantile lines (p50/p99 over
        # the bounded recent window) plus count + sum, the promhttp
        # convention for go-metrics samples.
        lines.append(f"# TYPE {n} summary")
        if "P50" in s:
            lines.append(f'{n}{{quantile="0.5"}} {float(s["P50"])}')
        if "P99" in s:
            lines.append(f'{n}{{quantile="0.99"}} {float(s["P99"])}')
        lines.append(f"{n}_count {float(s.get('Count', 0))}")
        lines.append(f"{n}_sum {float(s.get('Sum', 0.0))}")
    return "\n".join(lines) + "\n"


def emit_counter_deltas(sink: Sink, deltas: dict):
    """Fold one chunk's GossipCounters deltas (plain-int dict keyed by
    field name) into the sink under the reference metric names
    (models/counters.py METRIC_NAMES). Zero deltas are skipped so an
    idle plane leaves no counter rows behind."""
    from consul_tpu.models.counters import METRIC_NAMES

    for field, delta in deltas.items():
        if delta:
            sink.incr_counter(METRIC_NAMES[field], delta)


def emit_sim_metrics(state, sink: Sink,
                     health=None, rmse_s: Optional[float] = None,
                     rounds_per_sec: Optional[float] = None,
                     chunk_wall_s: Optional[float] = None,
                     chunk_ticks: Optional[int] = None,
                     serf_state=None,
                     queue_depth_warning: int = 0,
                     counters: Optional[dict] = None):
    """Record one chunk boundary's worth of reference-named metrics.

    One batched device→host fetch for the scalar reductions; the
    optional ``health``/``rmse_s`` reuse values the caller already
    computed (utils/metrics.py) rather than recomputing. ``counters``
    is the chunk's GossipCounters delta dict (already host-side ints),
    folded in via :func:`emit_counter_deltas`."""
    import jax.numpy as jnp
    import numpy as np

    aw = state.awareness
    live = state.alive_truth & ~state.left
    live_f = live.astype(jnp.float32)
    parts = [
        jnp.sum(jnp.where(live, aw, 0)).astype(jnp.float32),
        jnp.max(jnp.where(live, aw, 0)).astype(jnp.float32),
        jnp.sum(live_f),
        jnp.sum(jnp.abs(state.viv.adjustment) * live_f) * 1000.0,
        jnp.sum(state.viv.resets).astype(jnp.float32),
    ]
    if serf_state is not None:
        occ = jnp.sum((serf_state.ev_key != 0) & live[:, None], axis=1)
        parts += [jnp.sum(occ).astype(jnp.float32),
                  jnp.max(occ).astype(jnp.float32)]
    scalars = np.asarray(jnp.stack(parts))
    n_live = float(scalars[2])
    denom = max(n_live, 1.0)  # divide-by-zero clamp only
    sink.set_gauge("memberlist.health.score", float(scalars[0]) / denom)
    sink.set_gauge("memberlist.health.score.max", float(scalars[1]))
    sink.set_gauge("serf.members.alive", n_live)
    sink.add_sample("serf.coordinate.adjustment-ms",
                    float(scalars[3]) / denom)
    sink.set_gauge("serf.coordinate.resets", float(scalars[4]))
    if chunk_wall_s is not None and chunk_ticks:
        # Per-gossip-round wall time (memberlist.gossip MeasureSince).
        sink.add_sample("memberlist.gossip",
                        chunk_wall_s * 1000.0 / chunk_ticks)
    if rounds_per_sec is not None:
        sink.set_gauge("sim.gossip_rounds_per_sec", rounds_per_sec)
    if health is not None:
        sink.set_gauge("sim.agreement", float(health.agreement))
        sink.set_gauge("sim.false_positive", float(health.false_positive))
        sink.set_gauge("sim.undetected", float(health.undetected))
    if rmse_s is not None:
        sink.set_gauge("sim.vivaldi_rmse_ms", rmse_s * 1000.0)
    if counters is not None:
        emit_counter_deltas(sink, counters)
    if serf_state is not None:
        # serf.queue.Event sample (checkQueueDepth, serf/serf.go:
        # 1627-1648): per-live-node occupied broadcast-queue slots. The
        # reference samples one node's queue length every 30 s; the sim
        # folds the whole cluster into mean + max at the chunk boundary,
        # and a FULL per-node queue is the warning condition (the
        # reference's 128-message level folded onto the sim's
        # event_queue_slots capacity).
        q_sum, q_max = float(scalars[5]), float(scalars[6])
        sink.add_sample("serf.queue.Event", q_sum / denom)
        sink.set_gauge("serf.queue.Event.max", q_max)
        warn_at = min(queue_depth_warning, serf_state.ev_key.shape[1]) \
            if queue_depth_warning else 0
        if warn_at and q_max >= warn_at:
            import logging

            from consul_tpu.utils.logger import LOGGER_NAME
            logging.getLogger(LOGGER_NAME + ".serf").warning(
                "serf: Event queue depth: %d", int(q_max)
            )
