"""Single-flight lock for the one real TPU chip.

Two JAX processes touching the TPU backend at once deadlock — and in
this environment killing the second client can wedge the device relay
for *everyone* (observed repeatedly; see BASELINE.md provenance notes).
So every process that may initialize the TPU backend takes this lock
first: the benchmark driver (bench.py), ad-hoc measurement scripts,
anything. The lock is advisory but is the only thing standing between
a working tunnel and a wedged one, so honor it.

Design: a lockfile containing JSON ``{"pid": ..., "started": ...,
"what": ...}`` created with O_EXCL. A lock whose owner pid is gone is
stale and is broken atomically (rename-away then unlink, so two
breakers cannot both win). No jax imports here — the module must be
importable by the bench parent, which never touches jax.
"""

from __future__ import annotations

import errno
import json
import os
import time

# Outside the repo so a `git clean`/checkout never deletes a live lock.
LOCK_PATH = os.environ.get("CONSUL_TPU_LOCK", "/tmp/consul_tpu_device.lock")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _read(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def holder(path: str = LOCK_PATH):
    """The live holder's info dict, or None if unheld/stale."""
    info = _read(path)
    if info is None:
        return None
    pid = info.get("pid")
    if isinstance(pid, int) and _pid_alive(pid):
        return info
    return None


_UNPARSEABLE_GRACE_S = 30.0


def _break_stale(path: str) -> bool:
    """Atomically remove a stale lockfile. Returns True if removed.

    An unparseable lockfile is treated as LIVE within a grace window
    (it may be another acquirer's moment-of-creation) and stale only
    after it — never steal a lock that might just be young.
    """
    info = _read(path)
    if info is None:
        try:
            age = time.time() - os.path.getmtime(path)
        except OSError:
            return not os.path.exists(path)  # vanished: gone is gone
        if age < _UNPARSEABLE_GRACE_S:
            return False
    else:
        pid = info.get("pid")
        if isinstance(pid, int) and _pid_alive(pid):
            return False
    tomb = f"{path}.stale.{os.getpid()}"
    try:
        os.rename(path, tomb)  # only one breaker wins the rename
    except OSError:
        return not os.path.exists(path)
    # Check-then-rename race: between our read and the rename, another
    # breaker may have removed the stale lock AND a new holder acquired
    # — in which case we just renamed a LIVE lock. Verify the tomb holds
    # what we judged stale; if not, put it back and report failure.
    entombed = _read(tomb)
    same = (entombed == info) or (
        entombed is not None and info is not None
        and entombed.get("pid") == info.get("pid")
        and entombed.get("started") == info.get("started")
    )
    if not same:
        # Put the live lock back WITHOUT clobbering: link fails with
        # EEXIST if yet another acquirer has taken the path meanwhile
        # (renaming over it would hand two processes the lock).
        try:
            os.link(tomb, path)
        except OSError:
            pass  # someone holds the path; the entombed holder loses
        try:
            os.unlink(tomb)
        except OSError:
            pass
        return False
    try:
        os.unlink(tomb)
    except OSError:
        pass
    return True


def try_acquire(what: str = "?", wait_s: float = 0.0,
                path: str = LOCK_PATH) -> str:
    """Take the lock: "acquired", "busy", or "error:<detail>".

    ``wait_s``: how long to poll for a live holder to finish. Stale
    locks are broken immediately regardless. The lockfile is created
    complete via link-into-place, so no acquirer ever observes an
    empty lock and mistakes it for stale.
    """
    deadline = time.monotonic() + wait_s
    tmp = f"{path}.new.{os.getpid()}"
    while True:
        try:
            with open(tmp, "w") as f:
                json.dump({"pid": os.getpid(), "started": time.time(),
                           "what": what}, f)
            try:
                os.link(tmp, path)  # atomic: fails if the lock exists
                return "acquired"
            except FileExistsError:
                pass
            finally:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        except OSError as e:
            return f"error:{e!r}"
        if _break_stale(path):
            continue
        if time.monotonic() >= deadline:
            return "busy"
        time.sleep(min(5.0, max(0.1, deadline - time.monotonic())))


def acquire(what: str = "?", wait_s: float = 0.0, path: str = LOCK_PATH):
    """Bool convenience wrapper over :func:`try_acquire`."""
    return try_acquire(what, wait_s, path) == "acquired"


def release(path: str = LOCK_PATH) -> None:
    info = _read(path)
    if info and info.get("pid") == os.getpid():
        try:
            os.unlink(path)
        except OSError:
            pass


class held:
    """Context manager: ``with held("bench"):`` — raises RuntimeError
    with the holder's info if the lock cannot be taken in time."""

    def __init__(self, what: str = "?", wait_s: float = 0.0,
                 path: str = LOCK_PATH):
        self.what, self.wait_s, self.path = what, wait_s, path

    def __enter__(self):
        if not acquire(self.what, self.wait_s, self.path):
            raise RuntimeError(
                f"TPU lock busy: {holder(self.path)!r} (path {self.path})")
        return self

    def __exit__(self, *exc):
        release(self.path)
        return False
