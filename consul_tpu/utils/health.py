"""Check-status severity ordering, shared by every rollup.

The reference encodes this precedence wherever check statuses are
aggregated (structs' check status precedence; agent/checks/alias.go
worst-of; ui_endpoint.go summaries): passing < warning < critical,
and any unrecognized status ranks as critical.
"""

from __future__ import annotations

_ORDER = {"passing": 0, "warning": 1}


def severity(status: str) -> int:
    return _ORDER.get(status, 2)


def worst_status(statuses) -> str:
    """The most severe of ``statuses`` (an empty set is passing —
    reference alias.go:150-158: no checks at all means passing)."""
    worst = "passing"
    for s in statuses:
        if severity(s) > severity(worst):
            worst = s
    return worst
