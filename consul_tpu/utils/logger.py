"""Logging infrastructure: gated writer, rotating logfile, monitor tap.

Mirrors the reference logger package (reference logger/): a **gated
writer** that buffers all output until the logging system is fully
configured, then flushes and passes through (logger/gated_writer.go —
exists so early startup lines are not lost or misrouted); a size-based
**rotating logfile** (logger/logfile.go); and **log streaming** for
``/v1/agent/monitor`` (agent/http_register.go:38 + logger/log_writer.go:
a ring of recent lines plus live tailing for attached watchers).

Built over stdlib ``logging`` — handlers, not a parallel framework.
"""

from __future__ import annotations

import io
import logging
import os
import threading
from typing import Optional

LOGGER_NAME = "consul_tpu"


class GatedWriter(io.TextIOBase):
    """Buffer writes until flushed open (logger/gated_writer.go): early
    startup output is retained, then replayed into the real stream the
    moment configuration completes."""

    def __init__(self, target):
        self.target = target
        self._buf: list[str] = []
        self._open = False
        self._lock = threading.Lock()

    def write(self, s: str) -> int:
        with self._lock:
            if self._open:
                return self.target.write(s)
            self._buf.append(s)
            return len(s)

    def flush_open(self):
        """Release the gate: replay the buffer, pass through from now."""
        with self._lock:
            for s in self._buf:
                self.target.write(s)
            self._buf.clear()
            self._open = True

    def flush(self):
        if self._open:
            self.target.flush()


class RotatingFileHandler(logging.Handler):
    """Size-rotated logfile (logger/logfile.go: rotate at max_bytes,
    keep ``backups`` rotated files)."""

    def __init__(self, path: str, max_bytes: int = 1 << 20,
                 backups: int = 3):
        super().__init__()
        self.path = path
        self.max_bytes = max_bytes
        self.backups = backups
        self._fh = open(path, "a", encoding="utf-8")

    def emit(self, record: logging.LogRecord):
        line = self.format(record) + "\n"
        self._fh.write(line)
        self._fh.flush()
        if self._fh.tell() >= self.max_bytes:
            self.rotate()

    def rotate(self):
        self._fh.close()
        for i in range(self.backups - 1, 0, -1):
            src, dst = f"{self.path}.{i}", f"{self.path}.{i + 1}"
            if os.path.exists(src):
                os.replace(src, dst)
        if self.backups > 0:
            os.replace(self.path, f"{self.path}.1")
        else:
            os.unlink(self.path)
        self._fh = open(self.path, "a", encoding="utf-8")

    def close(self):
        self._fh.close()
        super().close()


class MonitorHandler(logging.Handler):
    """The /v1/agent/monitor tap (logger/log_writer.go): a bounded ring
    of recent lines plus a condition for live long-polling."""

    def __init__(self, capacity: int = 512):
        super().__init__()
        self.capacity = capacity
        self._lines: list[tuple[int, str]] = []
        self._seq = 0
        self._cond = threading.Condition()

    def emit(self, record: logging.LogRecord):
        with self._cond:
            self._seq += 1
            self._lines.append((self._seq, self.format(record)))
            del self._lines[:-self.capacity]
            self._cond.notify_all()

    _LEVELS = ("DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL")
    _ALIASES = {"TRACE": "DEBUG", "WARN": "WARNING", "ERR": "ERROR"}

    def tail(self, min_seq: int = 0, wait_s: float = 0.0,
             level: str = "") -> tuple[int, list[str]]:
        """Lines after ``min_seq`` (blocking up to ``wait_s`` for new
        ones), filtered at-or-above ``level`` — the monitor endpoint's
        ?loglevel semantics, accepting consul-conventional names
        (warn/err) as well as Python's."""
        import time

        deadline = time.monotonic() + wait_s
        with self._cond:
            while self._seq <= min_seq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            out = [line for seq, line in self._lines if seq > min_seq]
            if level:
                name = self._ALIASES.get(level.upper(), level.upper())
                if name in self._LEVELS:
                    allowed = self._LEVELS[self._LEVELS.index(name):]
                    out = [l for l in out
                           if any(f"[{a}]" in l for a in allowed)]
            return self._seq, out


_FORMAT = "%(asctime)s [%(levelname)s] %(name)s: %(message)s"


def setup(level: str = "INFO", log_file: Optional[str] = None,
          max_bytes: int = 1 << 20, backups: int = 3,
          stream=None, monitor_capacity: int = 512):
    """Configure the framework logger (the logger/ setup flow): a gated
    stream writer (released once handlers are attached), optional
    rotating file, and the monitor tap. Returns (logger, monitor,
    gate)."""
    log = logging.getLogger(LOGGER_NAME)
    log.setLevel(level.upper())
    for h in list(log.handlers):
        log.removeHandler(h)
        h.close()  # reconfigure must not leak file descriptors
    fmt = logging.Formatter(_FORMAT)

    import sys

    gate = GatedWriter(stream if stream is not None else sys.stderr)
    sh = logging.StreamHandler(gate)
    sh.setFormatter(fmt)
    log.addHandler(sh)

    if log_file:
        fh = RotatingFileHandler(log_file, max_bytes, backups)
        fh.setFormatter(fmt)
        log.addHandler(fh)

    monitor = MonitorHandler(monitor_capacity)
    monitor.setFormatter(fmt)
    log.addHandler(monitor)

    gate.flush_open()
    return log, monitor, gate
