"""Checkpoint / resume for simulation state.

The reference persists three independent things (SURVEY.md §5): the serf
snapshot (append-only member-event log replayed on restart for fast
rejoin, reference serf/snapshot.go:59-431), raft snapshots of every FSM
table (reference agent/consul/fsm/fsm.go:134-152), and operator snapshot
archives (reference snapshot/archive.go:99-170, tar+SHA256).

The TPU-native equivalent collapses all of that into one mechanism: the
entire cluster *is* a pytree of device arrays, so a checkpoint is one
streamed file and resume is reload + continue ticking. Integrity is
guarded the way the operator archive does it (reference
snapshot/archive.go:143-170): a SHA-256 digest over the payload, stored
in the manifest, verified on restore; the header itself is guarded by a
magic number, a bounded length, and clean corruption errors.

File layout (FORMAT_VERSION 2)::

    b"CTPU"  | manifest_len (8 LE bytes) | manifest JSON | raw leaf bytes

Leaves are written in pytree order as contiguous little-endian buffers;
their names/shapes/dtypes live in the manifest, so restore validates
the template *before* reading any array and streams one leaf at a time
(peak extra memory = the largest leaf, not 3x the checkpoint).

Works on any pytree of arrays (SimState, SerfState, federation states);
restore takes a template with the same structure (an ``init()`` result).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, BinaryIO

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.obs import trace as obs_trace

MAGIC = b"CTPU"
FORMAT_VERSION = 2
_MAX_MANIFEST = 64 << 20


def _leaf_names(tree: Any) -> list[str]:
    paths_and_leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(path) for path, _ in paths_and_leaves]


def _host_leaves(state: Any) -> list[np.ndarray]:
    out = []
    for leaf in jax.tree.leaves(state):
        # device_get is the explicit boundary crossing (legal under
        # transfer_guard "disallow"); asarray then only normalizes
        # host scalars.
        arr = np.asarray(jax.device_get(leaf))
        if not arr.flags.c_contiguous:
            # ascontiguousarray promotes 0-d to 1-d; restore the shape.
            arr = np.ascontiguousarray(arr).reshape(arr.shape)
        out.append(arr)
    return out


def _partition_specs(state: Any) -> list:
    """Per-leaf PartitionSpec manifest (pytree order): each entry is
    the leaf's axis-name list (``["nodes", None]``-style, JSON-clean)
    when the leaf carries a :class:`jax.sharding.NamedSharding`, else
    None. The payload itself is always the globally-gathered view
    (:func:`_host_leaves`), so this records how the SOURCE run was
    laid out — the provenance an elastic resume uses to re-shard the
    same logical partitioning onto whatever mesh the surviving
    devices support (runtime/harness.restore_placed)."""
    specs = []
    for leaf in jax.tree.leaves(state):
        spec = getattr(getattr(leaf, "sharding", None), "spec", None)
        if spec is None:
            specs.append(None)
            continue
        axes = []
        for a in spec:
            if a is None or isinstance(a, str):
                axes.append(a)
            else:  # a tuple of axis names (multi-axis partitioning)
                axes.append([str(x) for x in a])
        specs.append(axes)
    return specs


@obs_trace.traced("ckpt.save", cat="io")
def save(path: str, state: Any, meta: Any = None) -> str:
    """Write ``state`` (any pytree of arrays) to ``path``. Returns the
    payload's hex SHA-256 digest. Crash-safe: fsync before the atomic
    rename, so a torn write can never replace a good checkpoint.

    ``meta`` (optional, JSON-serializable) rides in the manifest under
    the ``meta`` key — run provenance the resilient harness needs to
    resume correctly (ticks done, chaos-schedule tick offset, schedule
    digest; consul_tpu/runtime/policy.py). Readable without touching
    the payload via :func:`read_manifest`."""
    names = _leaf_names(state)
    leaves = _host_leaves(state)

    # Pass 1: digest the payload (leaf-at-a-time; no full buffering).
    h = hashlib.sha256()
    for arr in leaves:
        h.update(arr.data)
    digest = h.hexdigest()

    manifest = {
        "format_version": FORMAT_VERSION,
        "n_leaves": len(leaves),
        "names": names,
        "shapes": [list(a.shape) for a in leaves],
        "dtypes": [str(a.dtype) for a in leaves],
        "sha256": digest,
        # Mesh-shape provenance, not a restore requirement: the
        # payload is the gathered global view either way, so any
        # device count can restore it (FORMAT_VERSION unchanged —
        # old readers ignore the extra key).
        "partition_spec": _partition_specs(state),
    }
    if meta is not None:
        manifest["meta"] = meta
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        mjson = json.dumps(manifest).encode()
        f.write(MAGIC)
        f.write(len(mjson).to_bytes(8, "little"))
        f.write(mjson)
        for arr in leaves:  # pass 2: stream the payload
            f.write(arr.data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic, like the serf snapshotter's rename
    return digest


def _read_header(f: BinaryIO) -> dict:
    """Shared header parser: magic + bounded length-prefixed JSON.
    Raises a clean ValueError on any corruption in the header region."""
    magic = f.read(len(MAGIC))
    if magic != MAGIC:
        raise ValueError(f"not a checkpoint (magic {magic!r} != {MAGIC!r})")
    mlen = int.from_bytes(f.read(8), "little")
    if not 0 < mlen <= _MAX_MANIFEST:
        raise ValueError(f"corrupt checkpoint header (manifest length {mlen})")
    try:
        manifest = json.loads(f.read(mlen))
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise ValueError(f"corrupt checkpoint manifest: {e}") from e
    if manifest.get("format_version") != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {manifest.get('format_version')} != "
            f"{FORMAT_VERSION}"
        )
    missing = {"sha256", "names", "n_leaves", "shapes", "dtypes"} - set(manifest)
    if missing:
        raise ValueError(
            f"corrupt checkpoint manifest: missing fields {sorted(missing)}"
        )
    return manifest


def read_manifest(path: str) -> dict:
    with open(path, "rb") as f:
        return _read_header(f)


def read_meta(path: str) -> Any:
    """The run-provenance ``meta`` the save embedded (or None). Header-
    only read — cheap enough to probe every candidate resume point."""
    return read_manifest(path).get("meta")


def read_partition_spec(path: str) -> Any:
    """The per-leaf PartitionSpec manifest the save recorded (or None
    for checkpoints written before FORMAT_VERSION 2 grew the key).
    Header-only read; see :func:`_partition_specs` for the encoding."""
    return read_manifest(path).get("partition_spec")


def state_layout_digest(state: Any, n: int) -> str:
    """Stable digest of a state pytree's LAYOUT: leaf paths, dtypes,
    and shapes with the node axis abstracted to ``N`` (so the digest is
    shape-family, not instance). Two states with the same digest are
    field-for-field restorable into each other; a digest change means
    the program's state schema moved (a new field, a packed dtype, a
    reshaped buffer — e.g. the fused-serf refactor narrowed ev_origin
    to i16, and the packed StateLayout re-encodes the whole SWIM
    plane) and a checkpoint across the change must be either widened
    (:func:`restore_widened`, when the saved schema is the dense twin
    of the running packed one) or refused, never shape-crashed into."""
    parts = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        shape = tuple("N" if d == n else int(d)
                      for d in getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        parts.append(f"{jax.tree_util.keystr(path)}:{dtype}:{shape}")
    joined = "|".join(sorted(parts))
    return hashlib.sha256(joined.encode()).hexdigest()[:16]


def restore_widened(path: str, dense_template: Any, widen, n: int, *,
                    verify: bool = True) -> tuple:
    """Widen-on-load: restore a checkpoint written by the PRE-PACKING
    dense program into a packed-layout run. ``dense_template`` is the
    dense twin of the running state (models.layout.unpack_state of it);
    ``widen`` converts the restored dense pytree into the running
    layout (models.layout.pack_state). Returns ``(state, provenance)``
    where provenance records both layout digests — the audit trail
    that distinguishes a widened resume from a native one."""
    state = restore(path, dense_template, verify=verify)
    out = widen(state)
    return out, {
        "widened_from": state_layout_digest(dense_template, n),
        "widened_to": state_layout_digest(out, n),
    }


@obs_trace.traced("ckpt.restore", cat="io")
def restore(path: str, template: Any, *, verify: bool = True) -> Any:
    """Load a checkpoint into the structure of ``template`` (an
    ``init()``-produced pytree). Structure/shape/dtype mismatches and
    payload corruption raise before any tick runs."""
    with open(path, "rb") as f:
        manifest = _read_header(f)

        t_leaves, treedef = jax.tree.flatten(template)
        if len(t_leaves) != manifest["n_leaves"]:
            raise ValueError(
                f"checkpoint has {manifest['n_leaves']} leaves, template has "
                f"{len(t_leaves)} — config/structure mismatch "
                f"(saved names: {manifest['names'][:4]}…)"
            )
        t_names = _leaf_names(template)
        if t_names != manifest["names"]:
            diffs = [
                f"{saved!r} vs template {now!r}"
                for saved, now in zip(manifest["names"], t_names)
                if saved != now
            ]
            raise ValueError(
                "checkpoint field names do not match the template (fields "
                f"renamed/reordered since the save?): {diffs[:3]}"
            )
        for name, tleaf, shape, dtype in zip(
            t_names, t_leaves, manifest["shapes"], manifest["dtypes"]
        ):
            tarr = jnp.asarray(tleaf)
            if tuple(shape) != tuple(tarr.shape) or dtype != str(tarr.dtype):
                raise ValueError(
                    f"leaf {name}: checkpoint {dtype}{list(shape)} vs "
                    f"template {tarr.dtype}{list(tarr.shape)} — was the "
                    f"checkpoint written with a different SimConfig?"
                )

        # Stream the payload one leaf at a time, hashing as we go.
        h = hashlib.sha256()
        arrays = []
        for shape, dtype in zip(manifest["shapes"], manifest["dtypes"]):
            nbytes = int(np.dtype(dtype).itemsize * int(np.prod(shape or [1])))
            raw = f.read(nbytes)
            if len(raw) != nbytes:
                raise ValueError("checkpoint payload truncated")
            h.update(raw)
            arrays.append(np.frombuffer(raw, dtype=dtype).reshape(shape))

    if verify and h.hexdigest() != manifest["sha256"]:
        raise ValueError(
            f"checkpoint payload digest mismatch: {h.hexdigest()[:12]}… != "
            f"{manifest['sha256'][:12]}… (corrupt or truncated)"
        )
    return jax.tree.unflatten(treedef, [jnp.asarray(a) for a in arrays])
