"""Checkpoint / resume for simulation state.

The reference persists three independent things (SURVEY.md §5): the serf
snapshot (append-only member-event log replayed on restart for fast
rejoin, reference serf/snapshot.go:59-431), raft snapshots of every FSM
table (reference agent/consul/fsm/fsm.go:134-152), and operator snapshot
archives (reference snapshot/archive.go:99-170, tar+SHA256).

The TPU-native equivalent collapses all of that into one mechanism: the
entire cluster *is* a pytree of device arrays, so a checkpoint is a
single batched device→host transfer written as one ``.npz`` archive with
a manifest — and resume is reload + continue ticking. Integrity is
guarded the way the operator archive does it: a SHA-256 digest over the
payload stored alongside (reference snapshot/archive.go:143-170).

Works on any pytree of arrays (SimState, SerfState, federation states);
restore takes a template with the same structure (an ``init()`` result)
so shapes/dtypes are validated before any tick runs.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

_MANIFEST = "__manifest__"
FORMAT_VERSION = 1


def _leaf_names(tree: Any) -> list[str]:
    paths_and_leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [jax.tree_util.keystr(path) for path, _ in paths_and_leaves]


def save(path: str, state: Any) -> str:
    """Write ``state`` (any pytree of arrays) to ``path`` as an npz
    archive with a JSON manifest + SHA-256 payload digest. Returns the
    hex digest."""
    names = _leaf_names(state)
    leaves = jax.tree.leaves(state)
    arrays = {f"leaf_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}

    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()
    digest = hashlib.sha256(payload).hexdigest()

    manifest = {
        "format_version": FORMAT_VERSION,
        "n_leaves": len(leaves),
        "names": names,
        "shapes": [list(a.shape) for a in arrays.values()],
        "dtypes": [str(a.dtype) for a in arrays.values()],
        "sha256": digest,
    }
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        # Manifest first (length-prefixed JSON), then the npz payload —
        # the same "metadata then stream" layout as the operator archive.
        mjson = json.dumps(manifest).encode()
        f.write(len(mjson).to_bytes(8, "little"))
        f.write(mjson)
        f.write(payload)
    os.replace(tmp, path)  # atomic, like the snapshotter's rename
    return digest


def read_manifest(path: str) -> dict:
    with open(path, "rb") as f:
        mlen = int.from_bytes(f.read(8), "little")
        return json.loads(f.read(mlen))


def restore(path: str, template: Any, *, verify: bool = True) -> Any:
    """Load a checkpoint into the structure of ``template`` (an
    ``init()``-produced pytree). Shape/dtype mismatches and payload
    corruption raise before any tick runs."""
    with open(path, "rb") as f:
        mlen = int.from_bytes(f.read(8), "little")
        manifest = json.loads(f.read(mlen))
        payload = f.read()

    if manifest.get("format_version") != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {manifest.get('format_version')} != {FORMAT_VERSION}"
        )
    if verify:
        digest = hashlib.sha256(payload).hexdigest()
        if digest != manifest["sha256"]:
            raise ValueError(
                f"checkpoint payload digest mismatch: {digest[:12]}… != "
                f"{manifest['sha256'][:12]}… (corrupt or truncated)"
            )

    t_leaves, treedef = jax.tree.flatten(template)
    if len(t_leaves) != manifest["n_leaves"]:
        raise ValueError(
            f"checkpoint has {manifest['n_leaves']} leaves, template has "
            f"{len(t_leaves)} — config/structure mismatch "
            f"(saved names: {manifest['names'][:4]}…)"
        )
    t_names = _leaf_names(template)
    if t_names != manifest["names"]:
        diffs = [
            f"{saved!r} vs template {now!r}"
            for saved, now in zip(manifest["names"], t_names)
            if saved != now
        ]
        raise ValueError(
            "checkpoint field names do not match the template (fields "
            f"renamed/reordered since the save?): {diffs[:3]}"
        )
    with np.load(io.BytesIO(payload)) as z:
        new_leaves = []
        for i, (tleaf, name) in enumerate(zip(t_leaves, manifest["names"])):
            arr = z[f"leaf_{i}"]
            tarr = jnp.asarray(tleaf)
            if tuple(arr.shape) != tuple(tarr.shape) or str(arr.dtype) != str(
                tarr.dtype
            ):
                raise ValueError(
                    f"leaf {name}: checkpoint {arr.dtype}{list(arr.shape)} vs "
                    f"template {tarr.dtype}{list(tarr.shape)} — was the "
                    f"checkpoint written with a different SimConfig?"
                )
            new_leaves.append(jnp.asarray(arr))
    return jax.tree.unflatten(treedef, new_leaves)
