"""Persistent XLA compilation cache plumbing (ROADMAP item 2).

The serf north-star program costs ~387 s of XLA compile on a cold
process (BENCH_r05). jax ships a persistent compilation cache —
``jax_compilation_cache_dir`` — that serializes every compiled
executable to disk keyed on (HLO, compile options, backend version),
so the SECOND cold process deserializes in ~0 s instead of recompiling.
This module is the one switch for it:

- :func:`enable` points jax at a directory (created if missing) and
  drops the min-size/min-time thresholds so even small test programs
  cache (the default 1 s floor would skip everything but the north
  star itself).
- :func:`maybe_enable_from_env` wires the ``CONSUL_TPU_COMPILE_CACHE``
  environment variable; the CLI/bench ``--compile-cache DIR`` flag
  calls :func:`enable` directly.
- :func:`stats` reports hit/miss counts observed process-wide via
  ``jax.monitoring`` (the CompileLedger idiom, analysis/guards.py) so
  bench JSON can record *provenance*: a ``compile_s`` next to
  ``{"hits": 8, "misses": 0}`` is a warm-from-disk number, not a
  measured compile.

No jax import happens at module load beyond the top-level ``import
jax`` this package already pays everywhere device-side.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import jax

ENV_VAR = "CONSUL_TPU_COMPILE_CACHE"

# Events the jax 0.4.x compilation-cache path records (compiler.py /
# compilation_cache.py): one per executable looked up.
HIT_EVENT = "/jax/compilation_cache/cache_hits"
MISS_EVENT = "/jax/compilation_cache/cache_misses"

_lock = threading.Lock()
_state = {"dir": None, "hits": 0, "misses": 0, "registered": False}


def _on_event(event: str, **kwargs):
    if event == HIT_EVENT:
        with _lock:
            _state["hits"] += 1
    elif event == MISS_EVENT:
        with _lock:
            _state["misses"] += 1


def _register_listener():
    with _lock:
        if _state["registered"]:
            return
        _state["registered"] = True
    jax.monitoring.register_event_listener(_on_event)


def enable(directory: str) -> str:
    """Turn the persistent compilation cache on, rooted at
    ``directory`` (created if missing). Returns the absolute path.
    Idempotent; re-pointing at a new directory is allowed."""
    path = os.path.abspath(directory)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything: the defaults skip executables under 1 s of
    # compile / tiny byte sizes, which would exclude every program in
    # the test tier and make hit/miss provenance unobservable there.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # jax initializes its cache state AT MOST ONCE, on the first compile
    # (_initialize_cache latches _cache_initialized). Any device constant
    # materialized before this call — e.g. a module-level jnp scalar in
    # an imported model — has already latched the cache OFF for the whole
    # process, and setting the config above is then a silent no-op.
    # reset_cache() returns it to the pristine state so the next compile
    # re-reads the config we just set.
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _jax_cc,
        )
        _jax_cc.reset_cache()
    except (ImportError, AttributeError):  # pragma: no cover - old jax
        pass
    _register_listener()
    with _lock:
        _state["dir"] = path
    return path


def maybe_enable_from_env(environ=os.environ) -> Optional[str]:
    """Enable the cache iff ``CONSUL_TPU_COMPILE_CACHE`` is set and
    non-empty; returns the directory or None. Call sites: bench main(),
    CLI local-run subcommands."""
    directory = environ.get(ENV_VAR, "").strip()
    if not directory:
        return None
    return enable(directory)


def enabled() -> bool:
    with _lock:
        return _state["dir"] is not None


def cache_dir() -> Optional[str]:
    with _lock:
        return _state["dir"]


def stats() -> dict:
    """Provenance snapshot for bench JSON ``compile_s`` entries:
    ``{"enabled": bool, "dir": str|None, "hits": int, "misses": int}``.
    Counts are process-wide since the cache was first enabled."""
    with _lock:
        return {
            "enabled": _state["dir"] is not None,
            "dir": _state["dir"],
            "hits": _state["hits"],
            "misses": _state["misses"],
        }


def stats_delta(before: dict) -> dict:
    """The hit/miss movement since a :func:`stats` snapshot — what one
    bench phase's compiles resolved to."""
    now = stats()
    return {
        "enabled": now["enabled"],
        "dir": now["dir"],
        "hits": now["hits"] - before.get("hits", 0),
        "misses": now["misses"] - before.get("misses", 0),
    }
