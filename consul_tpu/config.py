"""Protocol configuration for the simulated gossip fabric.

The knob names and default values mirror the reference so published
Serf/Consul timing defaults transfer 1:1:
  - memberlist LAN/WAN/Local profiles:
      reference vendor/github.com/hashicorp/memberlist/config.go:231-300
  - Vivaldi tuning factors:
      reference vendor/github.com/hashicorp/serf/coordinate/config.go:59-70

Wall-clock intervals are mapped onto a single global tick cadence
(``tick_ms``, default 200 ms = the LAN gossip interval): gossip fires every
tick, probes every ``probe_interval_ms / tick_ms`` ticks, push-pull every
``push_pull_interval_ms / tick_ms`` ticks scaled by ``push_pull_scale(n)``.
Timers (probe ack deadlines, suspicion timers) become per-node deadline
arrays compared against the global tick counter.
"""

from __future__ import annotations

import dataclasses
import math


def to_ticks(ms: float, tick_ms: float) -> int:
    """Convert a wall-clock interval to whole ticks (minimum 1).

    Rounds up so a quantized interval is never shorter than specified —
    a probe timeout of 500 ms on a 200 ms tick must wait 3 ticks, not 2.
    """
    return max(1, math.ceil(ms / tick_ms))


_ticks = to_ticks  # internal alias used by the config properties below


def clamp_view_degree(n: int, view_degree: int) -> int:
    """Clamp a requested partial-view degree to a valid value for ``n``.

    The sparse view is a symmetric circulant: every offset ``d`` pairs
    with ``n - d``, so a sparse degree must be even (ops/topology.py
    rejects odd degrees at build time). An explicit odd request is an
    error — silently rounding a user's choice would hide a config typo —
    but the *cap* at ``n - 2`` rounds down to the nearest even value so
    small clusters under a wide default (e.g. n=17 with view_degree=16)
    still build. 0 always means the complete graph.
    """
    if view_degree < 0:
        raise ValueError(f"view_degree must be >= 0, got {view_degree}")
    if view_degree == 0:
        return 0
    if view_degree % 2 != 0:
        raise ValueError(
            f"view_degree must be even: the sparse view pairs every "
            f"offset d with n-d (symmetric circulant, ops/topology.py), "
            f"got {view_degree} — use {view_degree - 1} or "
            f"{view_degree + 1}")
    if view_degree >= n - 1:
        return view_degree  # SimConfig.degree falls back to dense
    capped = min(view_degree, n - 2)
    if capped % 2 != 0:
        capped -= 1
    return max(capped, 0)


@dataclasses.dataclass(frozen=True)
class GossipConfig:
    """SWIM / gossip protocol knobs (reference memberlist/config.go).

    All ``*_ms`` values are wall-clock milliseconds in the simulated
    cluster's frame; the tick mapping derives integer tick counts.
    """

    # -- time base ---------------------------------------------------------
    tick_ms: int = 200

    # -- failure detector (reference config.go:241-249) --------------------
    probe_interval_ms: int = 1000
    probe_timeout_ms: int = 500
    indirect_checks: int = 3
    awareness_max: int = 8

    # -- suspicion (Lifeguard; reference config.go:243-244) ----------------
    suspicion_mult: int = 4
    suspicion_max_timeout_mult: int = 6

    # -- dissemination (reference config.go:242,251-253) -------------------
    retransmit_mult: int = 4
    gossip_interval_ms: int = 200
    gossip_nodes: int = 3
    gossip_to_the_dead_ms: int = 30_000

    # -- anti-entropy (reference config.go:245) ----------------------------
    push_pull_interval_ms: int = 30_000

    # -- vectorization capacity knobs (no reference analogue; these bound
    #    the fixed-shape replacements for Go's unbounded structures) -------
    # Per-node broadcast queue slots (replaces the btree
    # TransmitLimitedQueue, reference memberlist/queue.go:14-28).
    queue_slots: int = 8
    # Messages piggybacked per gossip send (models the 1400-byte UDP
    # budget, reference memberlist/state.go:541 / config.go:265).
    piggyback_msgs: int = 3

    # ---------------------------------------------------------------------
    @classmethod
    def lan(cls, **overrides) -> "GossipConfig":
        """Reference DefaultLANConfig (memberlist/config.go:231-267)."""
        return cls(**overrides)

    @classmethod
    def wan(cls, **overrides) -> "GossipConfig":
        """Reference DefaultWANConfig (memberlist/config.go:272-283)."""
        kw = dict(
            tick_ms=500,
            suspicion_mult=6,
            push_pull_interval_ms=60_000,
            probe_timeout_ms=3_000,
            probe_interval_ms=5_000,
            gossip_nodes=4,
            gossip_interval_ms=500,
            gossip_to_the_dead_ms=60_000,
        )
        kw.update(overrides)
        return cls(**kw)

    @classmethod
    def local(cls, **overrides) -> "GossipConfig":
        """Reference DefaultLocalConfig (memberlist/config.go:288-300)."""
        kw = dict(
            tick_ms=100,
            indirect_checks=1,
            retransmit_mult=2,
            suspicion_mult=3,
            push_pull_interval_ms=15_000,
            probe_timeout_ms=200,
            probe_interval_ms=1000,
            gossip_interval_ms=100,
            gossip_to_the_dead_ms=15_000,
        )
        kw.update(overrides)
        return cls(**kw)

    # -- derived tick counts ----------------------------------------------
    @property
    def probe_period_ticks(self) -> int:
        return _ticks(self.probe_interval_ms, self.tick_ms)

    @property
    def probe_timeout_ticks(self) -> int:
        return _ticks(self.probe_timeout_ms, self.tick_ms)

    @property
    def gossip_period_ticks(self) -> int:
        return _ticks(self.gossip_interval_ms, self.tick_ms)

    @property
    def gossip_to_the_dead_ticks(self) -> int:
        return _ticks(self.gossip_to_the_dead_ms, self.tick_ms)

    def push_pull_period_ticks(self, n: int) -> int:
        """Push-pull cadence scaled by cluster size.

        Mirrors pushPullScale (reference memberlist/util.go:89-97): the
        interval multiplies by ceil(log2(n) - log2(32)) + 1 above 32 nodes.
        """
        from consul_tpu.ops import scaling

        base = _ticks(self.push_pull_interval_ms, self.tick_ms)
        return base * int(scaling.push_pull_scale(n))


@dataclasses.dataclass(frozen=True)
class SerfConfig:
    """Serf-layer knobs (reference serf/config.go:246-289, lib/serf.go).

    The fixed-capacity ``*_slots``/``*_ring`` sizes replace Go's unbounded
    per-node queues and buffers (eventBroadcasts / recent-event buffers,
    reference serf/serf.go + delegate.go:19-282) with static shapes.
    """

    # Per-node user-event/query broadcast queue slots (replaces the
    # serf event TransmitLimitedQueue, serf/serf.go eventBroadcasts).
    event_queue_slots: int = 8
    # Events piggybacked per gossip send (models the UDP byte budget
    # split across the serf queues, serf/delegate.go GetBroadcasts).
    piggyback_events: int = 2
    # Recent-event dedup buffer per node, in **Lamport-time buckets**
    # (reference buffers the last EventBuffer=512 ltimes keyed by
    # ``ltime % size``, serf/serf.go:1258-1357 + config.go:158). Events
    # older than the window are rejected as stale, never redelivered.
    seen_ring: int = 16
    # Distinct origins remembered per Lamport-time bucket (the reference
    # keeps an unbounded per-ltime name list; this is the fixed-shape
    # bound — >width concurrent same-ltime events per bucket drop).
    seen_width: int = 4
    # Dynamic queue-depth limit knobs (reference serf/serf.go:1612-1648
    # getQueueMax/checkQueueDepth; Consul raises MinQueueDepth to 4096,
    # reference lib/serf.go:26-28). The scaled limit max(2N, min) bounds
    # *host-side* queues (wire/bridge.py seam buffers); the warning
    # threshold feeds the serf.queue.* telemetry samples.
    min_queue_depth: int = 4096
    max_queue_depth: int = 0
    # The reference warns when one node's queue holds 128 messages; the
    # sim's per-node capacity is event_queue_slots, so the effective
    # warning level is min(this, event_queue_slots) — a full queue warns.
    queue_depth_warning: int = 128
    # Query response timeout multiplier (reference serf/config.go
    # QueryTimeoutMult=16; timeout = mult * log10(N+1) * gossip_interval,
    # serf/serf.go DefaultQueryTimeout).
    query_timeout_mult: int = 16
    # Concurrent outstanding queries per origin (the reference keeps
    # per-query QueryResponse state, serf/query.go — unbounded; this is
    # the fixed-shape bound. A query opened past the cap evicts the
    # origin's oldest-deadline slot).
    query_slots: int = 4
    # Duplicate query responses relayed through this many other members
    # for redundancy under packet loss (reference QueryParam.RelayFactor,
    # serf/query.go:31-33, relayResponse serf.go:244-...; default 0).
    query_relay_factor: int = 0
    # Failed members are remembered (and eligible for reconnect) this
    # long before being reaped from member lists (reference
    # serf/config.go:277 ReconnectTimeout=24h).
    reconnect_timeout_ms: int = 24 * 3600 * 1000
    # Left members linger this long before reaping (reference
    # serf/config.go TombstoneTimeout=24h).
    tombstone_timeout_ms: int = 24 * 3600 * 1000
    # A leaving node keeps gossiping this long so its leave intent
    # propagates before it goes quiet (reference lib/serf.go:21-25
    # LeavePropagateDelay=3s, sized for >99.99% of 100k nodes).
    leave_propagate_delay_ms: int = 3000


@dataclasses.dataclass(frozen=True)
class VivaldiConfig:
    """Vivaldi coordinate tuning (reference serf/coordinate/config.go:59-70)."""

    dimensionality: int = 8
    vivaldi_error_max: float = 1.5
    vivaldi_ce: float = 0.25
    vivaldi_cc: float = 0.25
    adjustment_window_size: int = 20
    height_min: float = 10.0e-6
    latency_filter_size: int = 3
    gravity_rho: float = 150.0


@dataclasses.dataclass(frozen=True)
class RaftConfig:
    """Device raft tier shape (models/raft.py + ops/raft_ops.py): R
    independent ``groups`` of ``peers`` voters each, stepped as [R, P]
    tensors inside the jitted scan. Frozen + hashable so it joins the
    chunk-runner memo key exactly like ``chaos_key``/``sentinel`` —
    ``None`` (raft off) is the byte-identical pre-raft program.

    Timing constants default to the host tier's (server/raft.py
    HEARTBEAT_TICKS / ELECTION_TICKS_MIN / ELECTION_TICKS_MAX) so the
    two tiers argue about the same protocol. ``window`` is the bounded
    on-device log: at most ``window`` entries per group per run — the
    no-InstallSnapshot narrowing documented in COVERAGE.md."""

    groups: int = 4
    peers: int = 5
    window: int = 32
    heartbeat_ticks: int = 2
    election_ticks_min: int = 10
    election_ticks_max: int = 20

    def __post_init__(self):
        if self.groups < 1:
            raise ValueError(f"raft groups must be >= 1, got {self.groups}")
        if self.peers < 1:
            raise ValueError(f"raft peers must be >= 1, got {self.peers}")
        if self.window < 2:
            raise ValueError(f"raft window must be >= 2, got {self.window}")
        if self.heartbeat_ticks < 1:
            raise ValueError("raft heartbeat_ticks must be >= 1")
        if not (self.heartbeat_ticks < self.election_ticks_min
                <= self.election_ticks_max):
            raise ValueError(
                "need heartbeat_ticks < election_ticks_min <= "
                "election_ticks_max, got "
                f"{self.heartbeat_ticks}/{self.election_ticks_min}/"
                f"{self.election_ticks_max}")

    @property
    def quorum(self) -> int:
        return self.peers // 2 + 1


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Top-level simulation parameters for one simulated datacenter."""

    n: int = 1024                      # number of simulated nodes
    gossip: GossipConfig = dataclasses.field(default_factory=GossipConfig)
    vivaldi: VivaldiConfig = dataclasses.field(default_factory=VivaldiConfig)
    serf: SerfConfig = dataclasses.field(default_factory=SerfConfig)

    # Partial-view degree: each node maintains membership views of at most
    # ``view_degree`` neighbors. 0 means the complete graph (each node
    # views every other node, like a real memberlist member map — only
    # feasible for small n; the >=100k configs must bound this).
    view_degree: int = 0

    # Sparse-view graph family (consul_tpu/topo/families.py registry).
    # Every family emits a symmetric circulant offset set, so the
    # roll-based delivery machinery is family-independent; "circulant"
    # reproduces the original sampling bit-for-bit. Ignored when the
    # view is dense (view_degree == 0).
    topo_family: str = "circulant"
    # One per-family shape parameter; 0.0 selects the family default
    # (smallworld: rewire probability 0.2, hier: 8 datacenters,
    # expander: 32 candidate draws). circulant ignores it.
    topo_param: float = 0.0

    # Ground-truth latency model: nodes are planted in a Vivaldi-style
    # space; RTT(i,j) = euclidean distance + per-node access-link height,
    # plus lognormal jitter. Units: milliseconds.
    world_diameter_ms: float = 50.0    # spread of planted coordinates
    world_dims: int = 3                # intrinsic dimensionality of truth
    height_ms_min: float = 0.1
    height_ms_max: float = 2.0
    rtt_jitter_frac: float = 0.05      # lognormal sigma on each sample
    packet_loss: float = 0.0           # iid drop probability per message

    @property
    def degree(self) -> int:
        """Effective neighbor-table width K (N-1 for complete graph).
        A configured partial view at least as wide as the cluster falls
        back to the complete graph — a 20-server WAN pool under the
        LAN's view_degree=32 tracks everyone, like the reference's
        member map would."""
        if self.view_degree == 0 or self.view_degree >= self.n - 1:
            return self.n - 1
        return self.view_degree
