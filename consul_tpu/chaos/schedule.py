"""Declarative fault schedules compiled to device tensors.

The reference survives partitions, asymmetric links, and churn because
SWIM + Lifeguard were designed against exactly those faults — but a
simulator that can only express one global iid ``packet_loss`` scalar
cannot reproduce any of the headline behaviors (partition heal via
push-pull and probe acks, awareness under asymmetric loss, suspicion
scaling during churn). This module is the fault model, stated once:

  host description              device form (one ChaosSchedule pytree)
  ---------------------------------------------------------------------
  Partition(start, stop, A)  -> part_start/stop [P] + part_side [N, P]
  LinkLoss(start, stop,      -> ll_start/stop/fwd/rev [L] +
    A, B, fwd, rev)             ll_a/ll_b [N, L]
  ChurnWave(start, stop,     -> cw_start/stop/period/down [C] +
    nodes, period, down)        cw_mask [N, C]
  Degrade(start, stop,       -> dg_start/stop/tx/rx [D] +
    nodes, tx, rx)              dg_mask [N, D]

The schedule enters the jitted scan as a program ARGUMENT (like the
world, models/cluster.py): schedules with the same slot counts
(:func:`static_key_of`) share one XLA executable, and shifting every
start/stop by the current tick (:func:`shift_schedule`) changes only
values, never shapes — ``run_scenario`` replays a relative schedule at
any point of a warm simulation without recompiling. ``None`` / an empty
schedule short-circuits at trace time (a Python branch on the static
slot counts), so the no-chaos program is byte-identical to today's step
and the compile-count pin holds.

Per-message semantics: every delivery leg in the step functions keeps
its existing uniform draw and only the *threshold* changes. A leg
src -> dst survives with probability

  (1 - base_loss) * q_tx(src) * q_rx(dst)
    * prod_l (1 - fwd_l)^[src in A_l][dst in B_l]
    * (1 - rev_l)^[src in B_l][dst in A_l]

and is additionally cut entirely when src and dst sit on different
sides of any active Partition (:func:`pair_ok`). With every entry
inactive the threshold degenerates to ``base_loss`` — the plain
``cfg.packet_loss`` model.

Node-axis leaves carry the node dimension FIRST, so under ``shard_map``
they shard with the state (parallel/shard_step.py ``node_spec``); the
per-entry scalars replicate. All per-node evaluation (:func:`node_terms`,
:func:`down_at`) therefore works on whatever row block the leaves hold —
the same code runs single-chip and sharded.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.parallel import collective as coll

# Slot-count caps: partition colors and link-side bitmasks ride the
# probe plane's packed f32 gather (models/swim.py), which is exact only
# below 2^24; 20 bits leaves headroom for the SLO status packing.
MAX_PARTITIONS = 20
MAX_LINKS = 20
# Raft events share one [K]-slot lane (kind discriminator), no packing
# constraint — the cap just bounds the per-tick mask reduction.
MAX_RAFT_EVENTS = 20


# ----------------------------------------------------------------------
# Host-side schedule entries.
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Partition:
    """Full partition over [start, stop): nodes in ``side_a`` can only
    reach each other; everyone else forms side B. Models the network
    split the reference heals via push-pull + probe acks after the
    partition lifts (memberlist state.go pushPullNode / probeNode)."""

    start: int
    stop: int
    side_a: object  # node ids, bool mask, or slice


@dataclasses.dataclass(frozen=True)
class LinkLoss:
    """Extra loss on the A->B direction (``fwd``) and independently on
    B->A (``rev``) over [start, stop) — the asymmetric-link fault
    Lifeguard's awareness/nack machinery exists for."""

    start: int
    stop: int
    a: object
    b: object
    fwd: float
    rev: float = 0.0


@dataclasses.dataclass(frozen=True)
class ChurnWave:
    """Kill/revive pulses: over [start, stop) the masked nodes are down
    whenever ``(t - start) mod period < down_ticks``. ``period=0`` means
    one pulse spanning the whole window. Revives are warm (the node
    keeps its views and rejoins by announcing a bumped incarnation,
    models/state.py revive)."""

    start: int
    stop: int
    nodes: object
    period: int = 0
    down_ticks: int = 0


@dataclasses.dataclass(frozen=True)
class Degrade:
    """Slow/lossy nodes over [start, stop): every leg they send loses an
    extra ``tx_loss`` fraction, every leg they receive an extra
    ``rx_loss`` — the flaky-member fault that drives the node's own
    Lifeguard awareness up (failed probe cycles + missing nacks)."""

    start: int
    stop: int
    nodes: object
    tx_loss: float = 0.0
    rx_loss: float = 0.0


@dataclasses.dataclass(frozen=True)
class RaftKill:
    """Freeze raft peer ``peer`` of group ``group`` over [start, stop):
    it neither acts nor sends nor receives (ops/raft_ops.chaos_masks).
    ``peer=-1`` targets whoever LEADS the group at each tick — the
    leader-kill drill; ``group=-1`` hits every group. A killed leader
    keeps its role while down, so on revive it is deposed by the next
    higher-term AppendEntries it hears (the stale-leader probe)."""

    start: int
    stop: int
    group: int = -1
    peer: int = -1


@dataclasses.dataclass(frozen=True)
class RaftPartition:
    """Split a raft group's peers at ``cut`` over [start, stop): seats
    ``p < cut`` and ``p >= cut`` cannot exchange raft messages. A
    minority-side leader keeps emitting heartbeats into the void while
    the majority elects around it — the classic stale-read hazard the
    quorum commit rule exists for."""

    start: int
    stop: int
    cut: int
    group: int = -1


@dataclasses.dataclass(frozen=True)
class RaftStorm:
    """Total in-group message blackout over [start, stop): every timer
    expires with no vote ever delivered, so terms climb in lockstep and
    the election storm resolves only after the window lifts — the
    split-vote convergence scenario for the sweep plane."""

    start: int
    stop: int
    group: int = -1


# ----------------------------------------------------------------------
# The compiled device pytree.
# ----------------------------------------------------------------------

class ChaosSchedule(NamedTuple):
    """Tick-indexed fault schedule as device tensors. Per-entry scalars
    are [slots]; node masks are [N, slots] (node axis first — shards
    with the state under shard_map)."""

    part_start: jax.Array  # [P] i32
    part_stop: jax.Array   # [P] i32
    part_side: jax.Array   # [N, P] bool — True = side A
    ll_start: jax.Array    # [L] i32
    ll_stop: jax.Array     # [L] i32
    ll_fwd: jax.Array      # [L] f32 — extra loss A->B
    ll_rev: jax.Array      # [L] f32 — extra loss B->A
    ll_a: jax.Array        # [N, L] bool
    ll_b: jax.Array        # [N, L] bool
    cw_start: jax.Array    # [C] i32
    cw_stop: jax.Array     # [C] i32
    cw_period: jax.Array   # [C] i32
    cw_down: jax.Array     # [C] i32
    cw_mask: jax.Array     # [N, C] bool
    dg_start: jax.Array    # [D] i32
    dg_stop: jax.Array     # [D] i32
    dg_tx: jax.Array       # [D] f32
    dg_rx: jax.Array       # [D] f32
    dg_mask: jax.Array     # [N, D] bool
    # Raft lane (ops/raft_ops.chaos_masks): one [K] slot set with a
    # kind discriminator instead of per-family [N, slots] masks — raft
    # groups are addressed by global group id, not node id, so these
    # replicate under shard_map and the group-id comparison localizes.
    rk_kind: jax.Array     # [K] i32 (RK_KILL/RK_PARTITION/RK_STORM)
    rk_group: jax.Array    # [K] i32, -1 = every group
    rk_arg: jax.Array      # [K] i32 (kill: peer|-1=leader; part: cut)
    rk_start: jax.Array    # [K] i32
    rk_stop: jax.Array     # [K] i32


class NodeTerms(NamedTuple):
    """Per-node chaos terms at one tick, the transportable form: five
    per-node scalars that ride rolls/gathers to wherever a pairwise
    check happens (src terms at the receiver, dst terms at the sender).
    ``color`` is the partition-side bitfield — two nodes can talk iff
    their colors are equal. ``a_bits``/``b_bits`` mark membership of
    the active LinkLoss sides; ``q_tx``/``q_rx`` are the Degrade
    survival products."""

    color: jax.Array   # [N] i32
    a_bits: jax.Array  # [N] i32
    b_bits: jax.Array  # [N] i32
    q_tx: jax.Array    # [N] f32
    q_rx: jax.Array    # [N] f32


def _as_mask(nodes, n: int) -> np.ndarray:
    if isinstance(nodes, slice):
        m = np.zeros(n, bool)
        m[nodes] = True
        return m
    a = np.asarray(nodes)
    if a.dtype == np.bool_:
        if a.shape != (n,):
            raise ValueError(f"bool mask must be [{n}], got {a.shape}")
        return a.copy()
    m = np.zeros(n, bool)
    m[a.astype(np.int64)] = True
    return m


def _check_window(e, kind: str):
    if not (0 <= e.start < e.stop):
        raise ValueError(f"{kind} needs 0 <= start < stop, got "
                         f"[{e.start}, {e.stop})")


def _check_rate(v: float, what: str):
    if not (0.0 <= v <= 1.0):
        raise ValueError(f"{what} must be in [0, 1], got {v}")


def compile_schedule(n: int, events: Sequence = ()) -> ChaosSchedule:
    """Compile host-side schedule entries into one device pytree.
    Start/stop ticks are relative to whatever origin the caller later
    picks (:func:`shift_schedule` rebases them onto a live tick)."""
    parts = [e for e in events if isinstance(e, Partition)]
    links = [e for e in events if isinstance(e, LinkLoss)]
    churn = [e for e in events if isinstance(e, ChurnWave)]
    degr = [e for e in events if isinstance(e, Degrade)]
    rafts = [e for e in events
             if isinstance(e, (RaftKill, RaftPartition, RaftStorm))]
    known = len(parts) + len(links) + len(churn) + len(degr) + len(rafts)
    if known != len(list(events)):
        raise TypeError("events must be Partition/LinkLoss/ChurnWave/"
                        "Degrade/RaftKill/RaftPartition/RaftStorm")
    if len(parts) > MAX_PARTITIONS:
        raise ValueError(f"at most {MAX_PARTITIONS} Partition entries")
    if len(links) > MAX_LINKS:
        raise ValueError(f"at most {MAX_LINKS} LinkLoss entries")
    if len(rafts) > MAX_RAFT_EVENTS:
        raise ValueError(f"at most {MAX_RAFT_EVENTS} raft events")

    for e in parts:
        _check_window(e, "Partition")
    for e in links:
        _check_window(e, "LinkLoss")
        _check_rate(e.fwd, "LinkLoss.fwd")
        _check_rate(e.rev, "LinkLoss.rev")
    for e in churn:
        _check_window(e, "ChurnWave")
        if e.period < 0 or e.down_ticks < 0:
            raise ValueError("ChurnWave period/down_ticks must be >= 0")
    for e in degr:
        _check_window(e, "Degrade")
        _check_rate(e.tx_loss, "Degrade.tx_loss")
        _check_rate(e.rx_loss, "Degrade.rx_loss")
    for e in rafts:
        _check_window(e, type(e).__name__)
        if isinstance(e, RaftPartition) and e.cut < 1:
            raise ValueError("RaftPartition.cut must be >= 1")

    def i32(xs):
        return jnp.asarray(np.asarray(xs, np.int32))

    def f32(xs):
        return jnp.asarray(np.asarray(xs, np.float32))

    def masks(entries, pick):
        cols = [_as_mask(pick(e), n) for e in entries]
        out = np.stack(cols, axis=1) if cols else np.zeros((n, 0), bool)
        return jnp.asarray(out)

    # A ChurnWave without an explicit period is one pulse covering the
    # whole window: period = down = the window length.
    cw_period = [e.period if e.period > 0 else e.stop - e.start
                 for e in churn]
    cw_down = [e.down_ticks if e.period > 0 else e.stop - e.start
               for e in churn]

    # Kind codes match ops/raft_ops RK_KILL/RK_PARTITION/RK_STORM.
    rk_kind = [{RaftKill: 1, RaftPartition: 2, RaftStorm: 3}[type(e)]
               for e in rafts]
    rk_arg = [e.peer if isinstance(e, RaftKill)
              else e.cut if isinstance(e, RaftPartition) else 0
              for e in rafts]

    return ChaosSchedule(
        part_start=i32([e.start for e in parts]),
        part_stop=i32([e.stop for e in parts]),
        part_side=masks(parts, lambda e: e.side_a),
        ll_start=i32([e.start for e in links]),
        ll_stop=i32([e.stop for e in links]),
        ll_fwd=f32([e.fwd for e in links]),
        ll_rev=f32([e.rev for e in links]),
        ll_a=masks(links, lambda e: e.a),
        ll_b=masks(links, lambda e: e.b),
        cw_start=i32([e.start for e in churn]),
        cw_stop=i32([e.stop for e in churn]),
        cw_period=i32(cw_period),
        cw_down=i32(cw_down),
        cw_mask=masks(churn, lambda e: e.nodes),
        dg_start=i32([e.start for e in degr]),
        dg_stop=i32([e.stop for e in degr]),
        dg_tx=f32([e.tx_loss for e in degr]),
        dg_rx=f32([e.rx_loss for e in degr]),
        dg_mask=masks(degr, lambda e: e.nodes),
        rk_kind=i32(rk_kind),
        rk_group=i32([e.group for e in rafts]),
        rk_arg=i32(rk_arg),
        rk_start=i32([e.start for e in rafts]),
        rk_stop=i32([e.stop for e in rafts]),
    )


def empty(n: int) -> ChaosSchedule:
    return compile_schedule(n, ())


def is_empty(sched: ChaosSchedule) -> bool:
    """Trace-time emptiness: slot counts are static shapes, so callers
    branch in Python and an empty schedule compiles to exactly the
    schedule-free program."""
    return (
        sched.part_start.shape[0] == 0
        and sched.ll_start.shape[0] == 0
        and sched.cw_start.shape[0] == 0
        and sched.dg_start.shape[0] == 0
        and sched.rk_kind.shape[0] == 0
    )


def static_key_of(sched: Optional[ChaosSchedule]):
    """Shape fingerprint for executable-cache memo keys: schedules of
    the same slot counts trace to the same program; None/empty is the
    schedule-free program."""
    if sched is None or is_empty(sched):
        return None
    return ("chaos", sched.part_start.shape[0], sched.ll_start.shape[0],
            sched.cw_start.shape[0], sched.dg_start.shape[0],
            sched.rk_kind.shape[0])


def digest_of(sched: Optional[ChaosSchedule]) -> str:
    """Content fingerprint of a compiled schedule (hex SHA-256 over
    every leaf's bytes, in field order). Rides in checkpoint
    provenance (consul_tpu/runtime): a resumed chaos run must replay
    the remaining schedule bit-identically, so a checkpoint written
    under a DIFFERENT schedule is refused at resume rather than
    silently continuing a different experiment. ``None``/empty digests
    to the stable sentinel ``"none"``."""
    if sched is None or is_empty(sched):
        return "none"
    import hashlib

    h = hashlib.sha256()
    for leaf in sched:
        arr = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def shift_schedule(sched: ChaosSchedule, dt) -> ChaosSchedule:
    """Rebase every start/stop by ``dt`` ticks — values only, shapes
    unchanged, so a relative schedule replays at any live tick without
    recompiling (run_scenario's offset)."""
    dt = jnp.asarray(dt, jnp.int32)
    return sched._replace(
        part_start=sched.part_start + dt, part_stop=sched.part_stop + dt,
        ll_start=sched.ll_start + dt, ll_stop=sched.ll_stop + dt,
        cw_start=sched.cw_start + dt, cw_stop=sched.cw_stop + dt,
        dg_start=sched.dg_start + dt, dg_stop=sched.dg_stop + dt,
        rk_start=sched.rk_start + dt, rk_stop=sched.rk_stop + dt,
    )


# ----------------------------------------------------------------------
# Per-tick evaluation (jit/shard_map safe).
# ----------------------------------------------------------------------

def node_terms(sched: ChaosSchedule, t) -> NodeTerms:
    """Evaluate the schedule at tick ``t`` down to the five per-node
    transport scalars. Works on whatever row block the [N, slots]
    leaves hold (local block under shard_map)."""
    t = jnp.asarray(t, jnp.int32)
    nloc = sched.part_side.shape[0]
    n_p = sched.part_start.shape[0]
    n_l = sched.ll_start.shape[0]
    n_d = sched.dg_start.shape[0]

    if n_p:
        p_act = (t >= sched.part_start) & (t < sched.part_stop)
        w = jnp.int32(1) << jnp.arange(n_p, dtype=jnp.int32)
        color = jnp.sum(
            jnp.where(sched.part_side & p_act[None, :], w[None, :], 0),
            axis=1,
        ).astype(jnp.int32)
    else:
        color = jnp.zeros((nloc,), jnp.int32)

    if n_l:
        l_act = (t >= sched.ll_start) & (t < sched.ll_stop)
        wl = jnp.int32(1) << jnp.arange(n_l, dtype=jnp.int32)
        a_bits = jnp.sum(
            jnp.where(sched.ll_a & l_act[None, :], wl[None, :], 0), axis=1
        ).astype(jnp.int32)
        b_bits = jnp.sum(
            jnp.where(sched.ll_b & l_act[None, :], wl[None, :], 0), axis=1
        ).astype(jnp.int32)
    else:
        a_bits = jnp.zeros((nloc,), jnp.int32)
        b_bits = jnp.zeros((nloc,), jnp.int32)

    if n_d:
        d_act = (t >= sched.dg_start) & (t < sched.dg_stop)
        on = sched.dg_mask & d_act[None, :]
        q_tx = jnp.prod(
            jnp.where(on, 1.0 - sched.dg_tx[None, :], 1.0), axis=1
        )
        q_rx = jnp.prod(
            jnp.where(on, 1.0 - sched.dg_rx[None, :], 1.0), axis=1
        )
    else:
        q_tx = jnp.ones((nloc,), jnp.float32)
        q_rx = jnp.ones((nloc,), jnp.float32)

    return NodeTerms(color, a_bits, b_bits, q_tx, q_rx)


def down_at(sched: ChaosSchedule, t) -> jax.Array:
    """[N] bool — which nodes a ChurnWave holds down at tick ``t``."""
    nloc = sched.part_side.shape[0]
    if sched.cw_start.shape[0] == 0:
        return jnp.zeros((nloc,), bool)
    t = jnp.asarray(t, jnp.int32)
    act = (t >= sched.cw_start) & (t < sched.cw_stop)
    phase = (t - sched.cw_start) % jnp.maximum(sched.cw_period, 1)
    down = act & (phase < sched.cw_down)
    return jnp.any(sched.cw_mask & down[None, :], axis=1)


def fault_started(sched: ChaosSchedule, t) -> jax.Array:
    """[] bool — has any reachability fault (Partition/ChurnWave) begun
    by tick ``t``? Gates the time-to-heal accumulator: heal time only
    counts after a fault existed and lifted."""
    t = jnp.asarray(t, jnp.int32)
    started = jnp.zeros((), bool)
    if sched.part_start.shape[0]:
        started = started | jnp.any(sched.part_start <= t)
    if sched.cw_start.shape[0]:
        started = started | jnp.any(sched.cw_start <= t)
    return started


# ----------------------------------------------------------------------
# Pairwise deliverability.
# ----------------------------------------------------------------------

def _link_survival(sched: ChaosSchedule, src: NodeTerms,
                   dst: NodeTerms) -> jax.Array:
    n_l = sched.ll_start.shape[0]
    q = jnp.ones_like(src.q_tx)
    if n_l == 0:
        return q
    fwd_hit = src.a_bits & dst.b_bits
    rev_hit = src.b_bits & dst.a_bits
    for li in range(n_l):  # static, small — unrolled compare-selects
        bit = jnp.int32(1 << li)
        q = q * jnp.where((fwd_hit & bit) != 0, 1.0 - sched.ll_fwd[li], 1.0)
        q = q * jnp.where((rev_hit & bit) != 0, 1.0 - sched.ll_rev[li], 1.0)
    return q


def _survival(sched: ChaosSchedule, src: NodeTerms, dst: NodeTerms):
    return src.q_tx * dst.q_rx * _link_survival(sched, src, dst)


def pair_ok(sched: ChaosSchedule, src: NodeTerms, dst: NodeTerms, u,
            base_loss: float, round_trip: bool = False) -> jax.Array:
    """One delivery leg src -> dst against an existing uniform draw
    ``u``: survives iff the pair shares a partition side and ``u``
    clears the combined loss threshold (base iid loss composed with the
    chaos survival product). ``round_trip=True`` composes both
    directions' survival onto the one draw — the step's direct-probe
    and push-pull legs model the ping+ack round trip with a single
    uniform, and chaos keeps that draw (and therefore the empty-schedule
    trajectory) unchanged."""
    q = _survival(sched, src, dst)
    if round_trip:
        q = q * _survival(sched, dst, src)
    p = 1.0 - (1.0 - base_loss) * q
    return (src.color == dst.color) & (u >= p)


# ----------------------------------------------------------------------
# Transport helpers.
# ----------------------------------------------------------------------

def pack_terms(terms: NodeTerms):
    """The five per-node scalars as uint32 columns for
    ``collective.roll_many`` (floats travel by bit-pattern)."""
    return [
        terms.color.astype(jnp.uint32),
        terms.a_bits.astype(jnp.uint32),
        terms.b_bits.astype(jnp.uint32),
        jax.lax.bitcast_convert_type(terms.q_tx, jnp.uint32),
        jax.lax.bitcast_convert_type(terms.q_rx, jnp.uint32),
    ]


def unpack_terms(cols) -> NodeTerms:
    c, a, b, qt, qr = cols
    return NodeTerms(
        color=c.astype(jnp.int32),
        a_bits=a.astype(jnp.int32),
        b_bits=b.astype(jnp.int32),
        q_tx=jax.lax.bitcast_convert_type(qt.astype(jnp.uint32), jnp.float32),
        q_rx=jax.lax.bitcast_convert_type(qr.astype(jnp.uint32), jnp.float32),
    )


def roll_terms(terms: NodeTerms, shift) -> NodeTerms:
    """Terms of the node ``shift`` seats back along the ring, at every
    row: one packed exchange (collective.roll semantics — roll by
    ``+off[j]`` lands the in-column-j sender's terms at the receiver,
    by ``-off[c]`` the column-c target's terms at the prober)."""
    return unpack_terms(coll.roll_many(pack_terms(terms), shift))


def shard_once(x):
    """Zero a replicated global indicator on every shard but 0: the
    sharded counter reduction psums over the node axis, which would
    multiply a replicated scalar by the shard count."""
    ctx = coll.current()
    if ctx is None:
        return x
    keep = jax.lax.axis_index(ctx.axis_name) == 0
    return jnp.where(keep, x, jnp.zeros_like(x))
