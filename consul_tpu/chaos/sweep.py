"""Vmapped adversarial scenario sweeps over program-argument topologies.

Two compounding ideas, both about amortizing XLA executables:

**Program-argument topology.** The standard chunk runner bakes the
topology tables into the program (trace-time static roll shifts —
models/cluster.py ``_topo_key``), which is the right call for a single
long run but means every graph family costs a fresh compile. The sweep
runner instead passes ``off``/``rcol``/``inv`` as *traced inputs* and
rebuilds the ``Topology`` NamedTuple inside the jit: the roll sites in
models/swim.py and ops/topology.py detect the traced offsets
(``isinstance(off, jax.core.Tracer)``) and emit dynamic-shift rolls
(parallel/collective.py handles both). Result: every same-shape family
in consul_tpu/topo/families.py shares ONE executable — stronger than
one-per-family, and what makes a 4-family Pareto table cheap.

**Vmapped scenario axis.** The chaos engine already compiles fault
schedules to tick-indexed tensors that enter the program as arguments
(chaos/schedule.py). Stacking S same-shape schedules on a leading
scenario axis and ``jax.vmap``-ing the chunk body over (schedule,
state) runs dozens of Partition/ChurnWave/Degrade parameterizations in
ONE executable launch, with per-scenario SLO counters
(first-suspect/confirm/heal/false-deaths — models/counters.py chaos_*)
reduced on device and fetched in a single [fields, S] transfer.

Parity contract: per-tick keys are ``fold_in(base_key, t)`` — a
function of the tick alone, not the scenario — so scenario ``s`` of a
sweep consumes exactly the randomness the same schedule would consume
in a solo :meth:`Simulation.run_scenario` replay from the same formed
state; the SLO counters match the K independent runs *exactly*
(tests/test_sweep.py, single-device and sharded).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.chaos import schedule as chaos_mod
from consul_tpu.config import SimConfig, clamp_view_degree
from consul_tpu.models import counters as counters_mod
from consul_tpu.ops import topology
from consul_tpu.parallel import mesh as pmesh

# Estimated wire bytes for the Pareto bandwidth axis, mirroring the
# reference msgpack encodings the 1400-byte UDP budget is divided by
# (memberlist state.go/util.go): a compound-message frame per packet
# plus ~33 encoded bytes per piggybacked alive/suspect/dead message.
PACKET_OVERHEAD_BYTES = 12
MSG_BYTES = 33

# Process-wide memo for sweep runners, the chaos/sweep analogue of
# models/cluster._RUNNER_CACHE. Keyed on *shape only* — the family
# enters through runtime tensors, never the key — so families share.
_SWEEP_CACHE: dict = {}


def _shape_cfg(cfg: SimConfig) -> SimConfig:
    """The family-free canonical config the sweep program is traced
    with: the step math never reads ``topo_family``/``topo_param``
    (only make_topology does), so erasing them from the memo key is
    what lets same-shape families share one executable."""
    return dataclasses.replace(cfg, topo_family="circulant", topo_param=0.0)


def _sweep_runner(cfg: SimConfig, chunk: int, n_scen: int, chaos_key,
                  step_fn, swim_of, mesh, raft=None):
    """One compiled sweep program:
    ``run(world, off, rcol, inv, scheds, states, base_key) ->
    (states, counters)`` with states/scheds stacked on a leading
    scenario axis and counters returned as [S]-leaf pytrees. ``cfg``
    must be the canonical family-free config (:func:`_shape_cfg`).

    With ``raft`` (a config.RaftConfig) the state slot is the
    ``(model_state, RaftState)`` pair — both scenario-stacked — and the
    counters pair up as ``(GossipCounters, RaftCounters)``: each
    scenario lane steps its own raft tier against its own schedule's
    RaftKill/RaftPartition/RaftStorm windows, which is how election
    storms and leader kills become sweepable adversarial parameters.
    Single-device only (run_sweep raises on mesh+raft — documented
    narrowing)."""
    memo = ("sweep", cfg, chunk, n_scen, chaos_key, step_fn, swim_of,
            pmesh.mesh_key(mesh), raft)
    hit = _SWEEP_CACHE.get(memo)
    if hit is not None:
        return hit

    if mesh is not None:
        from consul_tpu.parallel import shard_step

        jitted = shard_step.make_sharded_sweep_runner(
            cfg, mesh, chunk, step_fn=step_fn, swim_of=swim_of)
        _SWEEP_CACHE[memo] = jitted
        return jitted

    if raft is not None:
        from consul_tpu.ops import raft_ops

    def one(topo, world, sched, state, base_key):
        if raft is not None:
            state, rst = state
        ticks = swim_of(state).t + jnp.arange(chunk, dtype=jnp.int32)
        tick_keys = jax.vmap(
            lambda t: jax.random.fold_in(base_key, t))(ticks)

        def body(carry, tick_key):
            if raft is not None:
                (st, rst), (cnt, rcnt) = carry
            else:
                st, cnt = carry
            if raft is not None:
                t_pre = swim_of(st).t
            st, c = step_fn(cfg, topo, world, st, tick_key, sched,
                            sentinel=False)
            cnt = counters_mod.add(cnt, c)
            if raft is not None:
                rst, rc = raft_ops.tick(raft, rst, t_pre, tick_key,
                                        sched=sched)
                return ((st, rst),
                        (cnt, raft_ops.counters_add(rcnt, rc))), ()
            return (st, cnt), ()

        if raft is not None:
            carry0 = ((state, rst),
                      (counters_mod.zeros(), raft_ops.counters_zeros()))
        else:
            carry0 = (state, counters_mod.zeros())
        (state, cnt), _ = jax.lax.scan(body, carry0, tick_keys)
        return state, cnt

    def run(world, off, rcol, inv, scheds, states, base_key):
        topo = topology.Topology(
            n=cfg.n, dense=False, off=off, rcol=rcol, inv=inv)
        return jax.vmap(
            lambda sc, st: one(topo, world, sc, st, base_key)
        )(scheds, states)

    jitted = jax.jit(run, donate_argnums=(5,))
    _SWEEP_CACHE[memo] = jitted
    return jitted


def _check_sim(sim):
    if sim.topo.dense:
        raise ValueError(
            "chaos sweeps need the sparse view (view_degree > 0): "
            "topology families only differ there — pass --view-degree "
            "(an even K, e.g. 16)")
    if getattr(sim, "layout", "dense") != "dense":
        raise ValueError("chaos sweeps run on the dense state layout")


def _compile_scenarios(sim, scenarios, ticks, settle):
    """Compile + shape-check + rebase the scenario schedules onto the
    sim's live tick (values only, exactly like run_scenario)."""
    if not scenarios:
        raise ValueError("empty scenario sweep")
    scheds = [chaos_mod.compile_schedule(sim.cfg.n, ev) for ev in scenarios]
    keys = {chaos_mod.static_key_of(s) for s in scheds}
    if len(keys) != 1 or None in keys:
        raise ValueError(
            "sweep scenarios must share one schedule shape so they can "
            f"stack into one executable; got shapes {sorted(map(str, keys))}"
            " — pad the short ones with no-op entries (empty node slices"
            " / zero loss rates)")
    if ticks is None:
        stops = [int(e.stop) for ev in scenarios for e in ev]
        ticks = (max(stops) if stops else 0) + settle
    t0 = sim._tick()
    scheds = [chaos_mod.shift_schedule(s, t0) for s in scheds]
    stack = jax.tree.map(lambda *ls: jnp.stack(ls), *scheds)
    return stack, len(scheds), ticks, chaos_mod.static_key_of(scheds[0])


def _stack_states(sim, n_scen: int):
    return jax.tree.map(
        lambda l: jnp.stack([l] * n_scen), sim.state)


def run_sweep(sim, scenarios, *, ticks=None, chunk: int = 32,
              settle: int = 64):
    """Run S fault scenarios against ``sim``'s current state in one
    vmapped executable; returns a list of S per-scenario dicts
    ``{"slo": ..., "counters": ..., "ticks": ...}`` in input order.

    ``scenarios`` is a sequence of event lists (Partition/LinkLoss/
    ChurnWave/Degrade, plus RaftKill/RaftPartition/RaftStorm when the
    sim's raft tier is armed), all compiling to the same slot shape
    (chaos/schedule.static_key_of). Each runs on its own copy of the
    state — ``sim`` itself is not advanced — with start/stop rebased
    onto the live tick, for ``ticks`` ticks (default: global max stop
    + ``settle``). Counter semantics match
    :meth:`Simulation.run_scenario` exactly (the parity pin).

    With ``sim.set_raft(...)`` armed, every scenario lane also steps a
    copy of the live RaftState and each result dict gains a ``raft``
    entry: per-group terms/leaders/commit after the scenario plus the
    scenario's RaftCounters deltas — how many elections a kill window
    forced, how far a storm burned through terms. Raft sweeps are
    single-device only (a mesh sweep with raft armed raises — the
    documented narrowing; group-sharded raft lives in the chunk
    runner, parallel/shard_step.py)."""
    from consul_tpu.models import cluster

    _check_sim(sim)
    raft_cfg = getattr(sim, "_raft_cfg", None)
    if raft_cfg is not None and sim.mesh is not None:
        raise ValueError(
            "raft-armed sweeps are single-device only: clear the mesh "
            "or set_raft(None) before run_sweep (documented narrowing)")
    sched_stack, n_scen, ticks, chaos_key = _compile_scenarios(
        sim, scenarios, ticks, settle)
    states = _stack_states(sim, n_scen)
    cfg = _shape_cfg(sim.cfg)
    topo = sim.topo
    if sim.mesh is not None:
        from consul_tpu.parallel import shard_step

        sched_stack = shard_step.place_sweep(
            sim.mesh, sched_stack, cfg.n)
        states = shard_step.place_sweep(sim.mesh, states, cfg.n)
    if raft_cfg is not None:
        rst0 = sim.raft.take_state()
        states = (states, jax.tree.map(
            lambda l: jnp.stack([l] * n_scen), rst0))

    totals = None
    remaining = ticks
    while remaining > 0:
        c = min(chunk, remaining)
        runner = _sweep_runner(cfg, c, n_scen, chaos_key,
                               type(sim)._step_fn, type(sim)._swim_of,
                               sim.mesh, raft=raft_cfg)
        states, cnt = runner(sim.world, topo.off, topo.rcol, topo.inv,
                             sched_stack, states, sim.base_key)
        totals = (cnt if totals is None
                  else jax.tree.map(jnp.add, totals, cnt))
        remaining -= c

    raft_rows = None
    if raft_cfg is not None:
        from consul_tpu.ops import raft_ops

        states, rst_stack = states
        totals, rtotals = totals
        # One batched transfer for the raft plane: the vmapped summary
        # plus the [fields, S] counter matrix.
        summ, rvals = jax.device_get((
            jax.vmap(raft_ops.summary)(rst_stack),
            raft_ops.counters_stack(rtotals)))
        term_g, leader_g, commit_g, cc = summ
        raft_rows = []
        for s in range(n_scen):
            raft_rows.append({
                "terms": [int(x) for x in term_g[s]],
                "leaders": [int(x) for x in leader_g[s]],
                "commit": [int(x) for x in commit_g[s]],
                "committed_clients": [int(x) for x in cc[s]],
                "counters": {f: int(rvals[i][s]) for i, f in
                             enumerate(raft_ops.FIELDS)},
            })

    # One batched [fields, S] device->host transfer for the whole sweep.
    vals = jax.device_get(counters_mod.stack(totals))
    sim.sink.incr_counter("sim.sweep.runs", 1)
    sim.sink.incr_counter("sim.sweep.scenarios", n_scen)
    results = []
    for s in range(n_scen):
        deltas = {f: int(vals[i][s])
                  for i, f in enumerate(counters_mod.FIELDS)}
        slo = {cluster.SLO_KEYS[f]: deltas[f] for f in cluster.SLO_KEYS}
        row = {"slo": slo, "counters": deltas, "ticks": ticks}
        if raft_rows is not None:
            row["raft"] = raft_rows[s]
        results.append(row)
    return results


def prewarm_sweep(sim, scenarios, *, ticks=None, chunk: int = 32,
                  settle: int = 64) -> None:
    """AOT-compile every sweep executable :func:`run_sweep` would bind
    for (sim shape, S, chunk, ticks) — including the tail-remainder
    chunk when ``chunk`` does not divide ``ticks`` — from abstract
    state avals, no state advanced. Routed through the persistent
    compile cache when enabled (utils/compile_cache.py), like
    utils/prewarm.prewarm_simulation."""
    from consul_tpu.utils.prewarm import _abstract

    _check_sim(sim)
    raft_cfg = getattr(sim, "_raft_cfg", None)
    if raft_cfg is not None and sim.mesh is not None:
        raise ValueError(
            "raft-armed sweeps are single-device only: clear the mesh "
            "or set_raft(None) before prewarm_sweep")
    sched_stack, n_scen, ticks, chaos_key = _compile_scenarios(
        sim, scenarios, ticks, settle)
    cfg = _shape_cfg(sim.cfg)
    if sim.mesh is not None:
        from consul_tpu.parallel import shard_step

        sched_stack = shard_step.place_sweep(sim.mesh, sched_stack, cfg.n)
        states = _abstract(shard_step.place_sweep(
            sim.mesh, _stack_states(sim, n_scen), cfg.n))
    else:
        states = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct((n_scen,) + l.shape, l.dtype),
            sim.state)
    if raft_cfg is not None:
        states = (states, jax.tree.map(
            lambda l: jax.ShapeDtypeStruct((n_scen,) + l.shape, l.dtype),
            sim.raft.state))
    topo = sim.topo
    chunk_sizes = sorted({min(chunk, ticks), ticks % chunk or chunk})
    for c in chunk_sizes:
        runner = _sweep_runner(cfg, c, n_scen, chaos_key,
                               type(sim)._step_fn, type(sim)._swim_of,
                               sim.mesh, raft=raft_cfg)
        runner.lower(
            _abstract(sim.world), _abstract(topo.off), _abstract(topo.rcol),
            _abstract(topo.inv), _abstract(sched_stack), states,
            _abstract(sim.base_key),
        ).compile()


# ---------------------------------------------------------------------------
# Scenario generators: the search space of the worst-case plane.

def scenario_grid(n: int, count: int, *, start: int = 4):
    """``count`` partition scenarios over a (fraction x duration) grid —
    all one Partition slot, so the whole grid stacks into one sweep."""
    fracs = [0.1, 0.2, 0.3, 0.45]
    durs = [8, 12, 16, 24]
    out = []
    for i in range(count):
        fr = fracs[i % len(fracs)]
        du = durs[(i // len(fracs)) % len(durs)]
        out.append([chaos_mod.Partition(
            start=start, stop=start + du,
            side_a=slice(0, max(1, int(n * fr))))])
    return out


def scenario_random(n: int, count: int, seed: int = 0, *, start: int = 4,
                    max_dur: int = 24):
    """``count`` seeded random compound scenarios, each one Partition +
    one ChurnWave + one Degrade slot (no-op entries keep the shape
    uniform when a draw lands at zero intensity)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(count):
        fr = float(rng.uniform(0.05, 0.45))
        du = int(rng.integers(6, max_dur + 1))
        churn = int(n * float(rng.uniform(0.0, 0.2)))
        tx_loss = float(rng.uniform(0.0, 0.5))
        out.append([
            chaos_mod.Partition(start=start, stop=start + du,
                                side_a=slice(0, max(1, int(n * fr)))),
            chaos_mod.ChurnWave(start=start, stop=start + du,
                                nodes=slice(0, churn)),
            chaos_mod.Degrade(start=start, stop=start + du,
                              nodes=slice(0, max(1, n // 10)),
                              tx_loss=tx_loss),
        ])
    return out


def worst_case(results):
    """Index of the worst scenario: slowest heal, then most false
    deaths, then slowest detection — the argmax the sweep plane
    searches for."""
    def severity(r):
        s = r["slo"]
        return (s["time_to_heal"], s["false_positive_deaths"],
                s["time_to_first_suspect"])

    return max(range(len(results)), key=lambda i: severity(results[i]))


# ---------------------------------------------------------------------------
# Pareto table: bandwidth vs convergence per family.

def wire_bytes_per_tick_node(counters: dict, ticks: int, n: int) -> float:
    """Estimated gossip-plane wire bytes per tick per node over a
    scenario window (the Pareto bandwidth axis): packets pay the
    compound-frame overhead, each piggybacked message its encoded
    size."""
    total = (counters["gossip_tx"] * PACKET_OVERHEAD_BYTES
             + counters["gossip_msgs_tx"] * MSG_BYTES)
    return float(total) / float(max(1, ticks) * n)


def pareto_table(per_family: dict) -> list:
    """Rank family summaries on (bytes/tick/node, worst time-to-heal).
    Adds ``dominated_by`` to each row (standard Pareto dominance:
    <= on both axes, < on at least one). Rows sort by bytes."""
    rows = [dict(family=fam, **d) for fam, d in per_family.items()]
    for r in rows:
        r["dominated_by"] = sorted(
            o["family"] for o in rows
            if o["family"] != r["family"]
            and o["bytes_per_tick_node"] <= r["bytes_per_tick_node"]
            and o["time_to_heal_worst"] <= r["time_to_heal_worst"]
            and (o["bytes_per_tick_node"] < r["bytes_per_tick_node"]
                 or o["time_to_heal_worst"] < r["time_to_heal_worst"]))
    return sorted(rows, key=lambda r: r["bytes_per_tick_node"])


def strict_dominators(per_family: dict, baseline: str = "circulant"):
    """Families strictly better than ``baseline`` on BOTH axes (the
    acceptance bar: lower bytes AND faster worst-case heal)."""
    base = per_family.get(baseline)
    if base is None:
        return []
    return sorted(
        fam for fam, d in per_family.items()
        if fam != baseline
        and d["bytes_per_tick_node"] < base["bytes_per_tick_node"]
        and d["time_to_heal_worst"] < base["time_to_heal_worst"])


def family_sweep(sim, scenarios, *, ticks=None, chunk: int = 32,
                 settle: int = 64) -> dict:
    """Sweep one formed sim and fold the results into a JSON-ready
    per-family summary row (the Pareto table input)."""
    from consul_tpu.topo import spectral_gap

    results = run_sweep(sim, scenarios, ticks=ticks, chunk=chunk,
                        settle=settle)
    ticks_run = results[0]["ticks"]
    n = sim.cfg.n
    byt = [wire_bytes_per_tick_node(r["counters"], ticks_run, n)
           for r in results]
    heal = [r["slo"]["time_to_heal"] for r in results]
    wi = worst_case(results)
    return {
        "degree": sim.topo.degree,
        "spectral_gap": round(
            spectral_gap(np.asarray(sim.topo.off), n), 6),
        "bytes_per_tick_node": round(float(np.mean(byt)), 3),
        "time_to_heal_worst": int(max(heal)),
        "time_to_heal_mean": round(float(np.mean(heal)), 2),
        "worst_scenario": int(wi),
        "worst_slo": dict(results[wi]["slo"]),
        "scenarios": [
            {"bytes_per_tick_node": round(float(b), 3), **r["slo"]}
            for b, r in zip(byt, results)
        ],
    }


def bench_pareto(*, n: int, degree: int, scenarios: int,
                 families=("circulant", "expander", "smallworld", "hier"),
                 seed: int = 0, form_ticks: int = 64, chunk: int = 32,
                 settle: int = 64, mode: str = "grid",
                 sweep_seed: int = 0, serf: bool = False,
                 mesh=None) -> dict:
    """The bench.py ``topology`` phase body (also reused by
    ``consul-tpu chaos --sweep``): form one sim per family at equal
    degree, run the same S-scenario sweep against each — every family
    reuses ONE sweep executable (program-argument topology) — and emit
    the bandwidth-vs-convergence Pareto table."""
    from consul_tpu.models import cluster

    cls = cluster.SerfSimulation if serf else cluster.Simulation
    scens = (scenario_grid(n, scenarios) if mode == "grid"
             else scenario_random(n, scenarios, seed=sweep_seed))
    per_family = {}
    for fam in families:
        cfg = SimConfig(n=n, view_degree=clamp_view_degree(n, degree),
                        topo_family=fam)
        sim = cls(cfg, seed=seed, mesh=mesh)
        sim.run(form_ticks, chunk=chunk, with_metrics=False)
        per_family[fam] = family_sweep(sim, scens, chunk=chunk,
                                       settle=settle)
    return {
        "n": int(n),
        "degree": int(degree),
        "scenario_count": int(scenarios),
        "mode": mode,
        "families": list(families),
        "pareto": pareto_table(per_family),
        "dominates_default": strict_dominators(per_family),
    }
