"""Device-side chaos engine: declarative fault schedules compiled to
tick-indexed device tensors, threaded through the jitted SWIM/serf scan
as a program argument (see chaos/schedule.py)."""

from consul_tpu.chaos.schedule import (  # noqa: F401
    MAX_LINKS,
    MAX_PARTITIONS,
    ChaosSchedule,
    ChurnWave,
    Degrade,
    LinkLoss,
    NodeTerms,
    Partition,
    compile_schedule,
    down_at,
    empty,
    fault_started,
    is_empty,
    node_terms,
    pack_terms,
    pair_ok,
    roll_terms,
    shard_once,
    shift_schedule,
    static_key_of,
    unpack_terms,
)
