"""Device-side chaos engine: declarative fault schedules compiled to
tick-indexed device tensors, threaded through the jitted SWIM/serf scan
as a program argument (see chaos/schedule.py).

``consul_tpu.chaos.sweep`` (the vmapped scenario-sweep plane) loads
lazily: it imports models/cluster.py, which imports this package for
the schedule types — eager re-export here would close the cycle."""

from consul_tpu.chaos.schedule import (  # noqa: F401
    MAX_LINKS,
    MAX_PARTITIONS,
    MAX_RAFT_EVENTS,
    ChaosSchedule,
    ChurnWave,
    Degrade,
    LinkLoss,
    NodeTerms,
    Partition,
    RaftKill,
    RaftPartition,
    RaftStorm,
    compile_schedule,
    down_at,
    empty,
    fault_started,
    is_empty,
    node_terms,
    pack_terms,
    pair_ok,
    roll_terms,
    shard_once,
    shift_schedule,
    static_key_of,
    unpack_terms,
)


def __getattr__(name):  # PEP 562: lazy, cycle-free sweep export
    if name == "sweep":
        import consul_tpu.chaos.sweep as _sweep

        return _sweep
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
