"""The command-line interface.

Mirrors the reference CLI surface (reference command/, 30+ subcommands
registered via command/registry.go:16-27) for the subsystems this
framework implements:

  members          catalog membership + serf health    (command/members)
  rtt              coordinate distance between nodes   (command/rtt/rtt.go:40)
  kv get|put|delete|list|export|import                 (command/kv)
  catalog nodes|services                               (command/catalog)
  info             agent + leadership info             (command/info)
  services register|deregister                         (command/services)
  sessions list                                        (command/acl… session)
  snapshot save|restore                                (command/snapshot)
  join             route a client agent onto servers   (command/join)
  leave            graceful leave + shutdown           (command/leave)
  acl              bootstrap / policy / token CRUD     (command/acl)
  intention        create|get|list|delete|match|check  (command/intention)
  connect ca       get-config|set-config               (command/connect/ca)
  event fire|list / watch / force-leave / debug
  operator raft list-peers|remove-peer                 (command/operator)
  operator autopilot get-config|set-config|health
  maint            node/service maintenance mode       (command/maint)
  keyring          gossip key install/use/remove/list  (command/keyring)
  monitor          stream agent logs                   (command/monitor)
  reload           trigger a config reload             (command/reload)
  version          print the version                   (command/version)
  tls create       dev CA + server cert                (command/tls)
  validate         config file validation              (command/validate)
  chaos            compiled fault-schedule scenarios   (consul_tpu/chaos)
  trace            flight-record a local run           (consul_tpu/obs)
  lock             run a command under a KV lock       (command/lock)
  exec             remote execution via KV + events    (command/exec)

All commands speak to a running agent's HTTP API (like the reference,
which routes every subcommand through the api client), selected by
``--http-addr`` / ``CONSUL_TPU_HTTP_ADDR``.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import sys

from consul_tpu.api import APIError, Client
from consul_tpu.server.rtt import compute_distance


def make_client(args) -> Client:
    host, _, port = args.http_addr.rpartition(":")
    return Client(host or "127.0.0.1", int(port),
                  token=getattr(args, "token", "")
                  or os.environ.get("CONSUL_TPU_TOKEN", ""))


def cmd_version(client: Client, args) -> int:
    # One version source: the package (reference command/version reads
    # the build's version package).
    from consul_tpu import __version__
    print(f"consul-tpu v{__version__}")
    print("Protocol: consul-capability framework, TPU-native backend")
    return 0


def cmd_members(client: Client, args) -> int:
    if getattr(args, "wan", False):
        # Reference `consul members -wan`: the WAN server pool.
        try:
            rows = client.agent.members(wan=True)
        except APIError as e:
            print(f"error: {e.body.get('error', e) if isinstance(e.body, dict) else e}",
                  file=sys.stderr)
            return 1
        print(f"{'Node':<24} {'DC':<8} Status")
        for m in rows:
            print(f"{m['Name']:<24} {m['Tags'].get('dc', ''):<8} "
                  f"{m['Status']}")
        return 0
    nodes, _ = client.catalog.nodes()
    checks, _ = client.health.state("any")
    by_node = {}
    for c in checks:
        if c["check_id"] == "serfHealth":
            by_node[c["node"]] = c["status"]
    print(f"{'Node':<20} {'Address':<16} Status")
    for n in nodes:
        status = {"passing": "alive", "critical": "failed"}.get(
            by_node.get(n["node"], ""), "unknown")
        print(f"{n['node']:<20} {n['address']:<16} {status}")
    return 0


def cmd_rtt(client: Client, args) -> int:
    # reference command/rtt/rtt.go: estimate RTT between two nodes from
    # their coordinates (LAN by default; -wan reads the WAN server
    # coordinates, addressed as <node>.<dc> or just <dc>).
    if args.wan:
        by_node = {}
        for dcrow in client.coordinate.datacenters():
            for c in dcrow.get("coordinates", []):
                by_node[c["node"]] = c["coord"]
                # A bare DC name resolves to its first server.
                by_node.setdefault(dcrow["datacenter"], c["coord"])
    else:
        coords, _ = client.coordinate.nodes()
        by_node = {c["node"]: c["coord"] for c in coords
                   if not c.get("segment")}
    node2 = args.node2 or args.node1
    a, b = by_node.get(args.node1), by_node.get(node2)
    if a is None or b is None:
        missing = args.node1 if a is None else node2
        print(f"error: no coordinate for {missing!r}", file=sys.stderr)
        return 1
    d = compute_distance(a, b)
    print(f"Estimated {args.node1} <-> {node2} rtt: {d * 1000:.3f} ms")
    return 0


def cmd_kv(client: Client, args) -> int:
    if args.kv_cmd == "get":
        row, _ = client.kv.get(args.key)
        if row is None:
            print(f"error: key {args.key!r} not found", file=sys.stderr)
            return 1
        sys.stdout.write(row["Value"].decode(errors="replace"))
        if not row["Value"].endswith(b"\n"):
            sys.stdout.write("\n")
        return 0
    if args.kv_cmd == "put":
        value = args.value.encode() if args.value is not None else \
            sys.stdin.buffer.read()
        ok = client.kv.put(args.key, value,
                           cas=args.cas, flags=args.flags)
        if not ok:
            print("error: put failed (CAS conflict?)", file=sys.stderr)
            return 1
        print(f"Success! Data written to: {args.key}")
        return 0
    if args.kv_cmd == "delete":
        client.kv.delete(args.key, recurse=args.recurse)
        print(f"Success! Deleted key{'s under' if args.recurse else ''}: "
              f"{args.key}")
        return 0
    if args.kv_cmd == "list":
        for k in client.kv.keys(args.key or ""):
            print(k)
        return 0
    if args.kv_cmd == "export":
        # Reference `consul kv export`: a JSON array of
        # {key, flags, value(base64)} rows for the prefix.
        rows = client.kv.list(args.key or "")
        print(json.dumps([{
            "key": r["Key"], "flags": r.get("Flags", 0),
            "value": base64.b64encode(r["Value"]).decode(),
        } for r in rows], indent=2))
        return 0
    if args.kv_cmd == "import":
        # Reference `consul kv import`: reads the export format from
        # a file or stdin.
        try:
            if args.file and args.file != "-":
                with open(args.file, encoding="utf-8") as f:
                    raw = f.read()
            else:
                raw = sys.stdin.read()
            rows = json.loads(raw)
            if not isinstance(rows, list) or not all(
                    isinstance(e, dict) and "key" in e for e in rows):
                raise ValueError(
                    "import expects a JSON array of {key, flags, value}")
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        for e in rows:
            client.kv.put(e["key"], base64.b64decode(e.get("value", "")),
                          flags=int(e.get("flags", 0)))
        print(f"Imported {len(rows)} entries")
        return 0
    raise AssertionError(args.kv_cmd)


def cmd_catalog(client: Client, args) -> int:
    if args.catalog_cmd == "nodes":
        nodes, _ = client.catalog.nodes(near=args.near or "")
        print(f"{'Node':<20} Address")
        for n in nodes:
            print(f"{n['node']:<20} {n['address']}")
        return 0
    if args.catalog_cmd == "services":
        services, _ = client.catalog.services()
        for name, tags in sorted(services.items()):
            print(name + (f"  [{', '.join(tags)}]" if tags else ""))
        return 0
    if args.catalog_cmd == "datacenters":
        for dc in client.catalog.datacenters():
            print(dc)
        return 0
    raise AssertionError(args.catalog_cmd)


def cmd_info(client: Client, args) -> int:
    self_info = client.agent.self_()
    print(f"agent:\n\tnode = {self_info['Config']['NodeName']}")
    print(f"consensus:\n\tleader = {client.status.leader()}")
    print(f"\tpeers = {', '.join(client.status.peers())}")
    return 0


def cmd_services(client: Client, args) -> int:
    if args.services_cmd == "register":
        client.agent.service_register(
            args.name, service_id=args.id or "", port=args.port,
            tags=args.tag or [], check_ttl=args.ttl or "")
        print(f"Registered service: {args.name}")
        return 0
    if args.services_cmd == "deregister":
        client.agent.service_deregister(args.id or args.name)
        print(f"Deregistered service: {args.id or args.name}")
        return 0
    raise AssertionError(args.services_cmd)


def cmd_sessions(client: Client, args) -> int:
    sessions, _ = client.session.list()
    for s in sessions:
        print(f"{s['id']}  node={s['node']}  ttl={s.get('ttl_s', 0)}s")
    return 0


def cmd_snapshot(client: Client, args) -> int:
    if args.snapshot_cmd == "save":
        snap, _, _ = client._call("GET", "/v1/snapshot")
        with open(args.file, "w") as f:
            json.dump(snap, f)
        print(f"Saved snapshot (index {snap['index']}) to {args.file}")
        return 0
    if args.snapshot_cmd == "restore":
        with open(args.file) as f:
            body = f.read().encode()
        client._call("PUT", "/v1/snapshot", None, body)
        print(f"Restored snapshot from {args.file}")
        return 0
    if args.snapshot_cmd == "inspect":
        # Reference `consul snapshot inspect`: offline summary of a
        # saved archive — index + per-table row counts, no server
        # needed.
        with open(args.file) as f:
            snap = json.load(f)
        print(f"Index:  {snap.get('index')}")
        tables = snap.get("tables", {})
        width = max((len(t) for t in tables), default=5)
        print(f"{'Table':<{width}}  Rows")
        for name in sorted(tables):
            print(f"{name:<{width}}  {len(tables[name])}")
        return 0
    raise AssertionError(args.snapshot_cmd)


def cmd_event(client: Client, args) -> int:
    """User events (reference command/event: fire via the agent)."""
    if args.event_cmd == "fire":
        payload = (args.payload or "").encode()
        out, _, _ = client._call("PUT", f"/v1/event/fire/{args.name}",
                                 {}, payload)
        print(f"Event ID: {out['ID']}")
        return 0
    out, _, _ = client._call("GET", "/v1/event/list",
                             {"name": args.name or None})
    for e in out:
        print(f"{e['LTime']:>6}  {e['Name']}  {e['ID']}")
    return 0


def cmd_watch(client: Client, args) -> int:
    """One-shot or looped watch (reference command/watch over
    api/watch): prints the JSON result each time the index moves."""
    from consul_tpu.api import watch as make_watch

    params = {}
    for kv in args.param or []:
        k, _, v = kv.partition("=")
        params[k] = v
    required = {"key": ["key"], "service": ["service"],
                "agent_service": ["service_id"],
                "connect_leaf": ["service"]}.get(args.type, [])
    missing = [r for r in required if r not in params]
    if missing:
        print(f"watch --type {args.type} requires --param "
              + " ".join(f"{m}=..." for m in missing), file=sys.stderr)
        return 1
    fired = {"n": 0}

    def handler(index, result):
        fired["n"] += 1
        print(json.dumps({"Index": index, "Result": result}, default=str))

    plan = make_watch(client, args.type, handler, **params)
    rounds = args.rounds if args.rounds else (1 if args.once else 0)
    if rounds:
        for _ in range(rounds):
            plan.run_once(wait=args.wait)
    else:  # pragma: no cover — interactive loop
        plan.run(wait=args.wait)
    return 0 if fired["n"] else 1


def cmd_join(client: Client, args) -> int:
    """Join the addressed agent to a server set (reference
    command/join; here the wire-tier verb re-aiming a client agent's
    connection pool at runtime)."""
    try:
        ok = client.agent.join(args.address)
    except APIError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"Successfully joined {args.address}" if ok
          else f"error: join {args.address} failed")
    return 0 if ok else 1


def cmd_acl(client: Client, args) -> int:
    """ACL management (reference command/acl: bootstrap, policy and
    token CRUD against /v1/acl/*)."""
    if args.acl_cmd == "bootstrap":
        try:
            tok = client.acl.bootstrap()
        except APIError as e:
            print(f"error: {e.body.get('error', e)}", file=sys.stderr)
            return 1
        print(f"AccessorID:   {tok['AccessorID']}")
        print(f"SecretID:     {tok['SecretID']}")
        print(f"Description:  {tok['Description']}")
        return 0
    if args.acl_cmd == "policy":
        if args.policy_cmd == "create":
            rules = args.rules
            if rules.startswith("@"):
                with open(rules[1:]) as f:
                    rules = f.read()
            p = client.acl.policy_create(args.name, rules,
                                         args.description)
            print(f"Created policy {p['Name']} ({p['ID']})")
            return 0
        if args.policy_cmd == "read":
            p = client.acl.policy_read(args.name)
            if p is None:
                print(f"error: policy {args.name!r} not found",
                      file=sys.stderr)
                return 1
            print(json.dumps(p, indent=2))
            return 0
        if args.policy_cmd == "delete":
            ok = client.acl.policy_delete(args.name)
            print(f"Deleted policy {args.name}" if ok else "error")
            return 0 if ok else 1
        if args.policy_cmd == "list":
            for p in client.acl.policy_list():
                print(f"{p['Name']:<24} {p['Description']}")
            return 0
    if args.acl_cmd == "token":
        if args.token_cmd == "create":
            t = client.acl.token_create(
                args.description,
                args.policy_name or [])
            print(f"AccessorID:   {t['AccessorID']}")
            print(f"SecretID:     {t['SecretID']}")
            print(f"Policies:     "
                  f"{', '.join(p['Name'] for p in t['Policies'])}")
            return 0
        if args.token_cmd == "read":
            t = client.acl.token_read(args.id)
            if t is None:
                print(f"error: token {args.id!r} not found",
                      file=sys.stderr)
                return 1
            print(json.dumps(t, indent=2))
            return 0
        if args.token_cmd == "delete":
            ok = client.acl.token_delete(args.id)
            print(f"Deleted token {args.id}" if ok else "error")
            return 0 if ok else 1
        if args.token_cmd == "list":
            for t in client.acl.token_list():
                pols = ", ".join(p["Name"] for p in t["Policies"])
                print(f"{t['AccessorID']}  [{pols}] {t['Description']}")
            return 0
    raise AssertionError(args.acl_cmd)


def cmd_connect(client: Client, args) -> int:
    """Connect CA management (reference command/connect/ca:
    get-config / set-config)."""
    if args.connect_cmd == "ca" and args.ca_cmd == "get-config":
        print(json.dumps(client.connect.ca_get_config(), indent=2))
        return 0
    if args.connect_cmd == "ca" and args.ca_cmd == "set-config":
        try:
            with open(args.config_file, encoding="utf-8") as f:
                cfg = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        client.connect.ca_set_config(cfg)
        print("Configuration updated!")
        return 0
    raise AssertionError(args.connect_cmd)


def cmd_intention(client: Client, args) -> int:
    """Intention management (reference command/intention: create,
    get, delete, match, check)."""
    if args.intention_cmd == "create":
        action = "deny" if args.deny else "allow"
        iid = client.connect.intention_create(args.source, args.destination,
                                              action)
        print(f"Created: {args.source} => {args.destination} "
              f"({action}) [{iid}]")
        return 0
    if args.intention_cmd == "list":
        rows, _ = client.connect.intention_list()
        for x in rows:
            print(f"{x['ID']}  {x['SourceName']} => "
                  f"{x['DestinationName']} ({x['Action']})")
        return 0
    if args.intention_cmd == "get":
        x = client.connect.intention_get(args.id)
        if x is None:
            print(f"error: intention {args.id!r} not found",
                  file=sys.stderr)
            return 1
        print(json.dumps(x, indent=2))
        return 0
    if args.intention_cmd == "delete":
        ok = client.connect.intention_delete(args.id)
        print("Deleted" if ok else "error")
        return 0 if ok else 1
    if args.intention_cmd == "match":
        for x in client.connect.intention_match(args.name, args.by):
            print(f"{x['SourceName']} => {x['DestinationName']} "
                  f"({x['Action']})")
        return 0
    if args.intention_cmd == "check":
        allowed = client.connect.intention_check(args.source,
                                                 args.destination)
        print("Allowed" if allowed else "Denied")
        return 0 if allowed else 2
    raise AssertionError(args.intention_cmd)


def cmd_tls(client: Client, args) -> int:
    """Development TLS material (reference command/tls: ca create /
    cert create) — a CA plus a server cert signed by it."""
    from consul_tpu.utils.tls import dev_ca
    paths = dev_ca(args.dir, hostname=args.hostname)
    for k in ("ca", "cert", "key"):
        print(f"{k}: {paths[k]}")
    return 0


def cmd_leave(client: Client, args) -> int:
    """Graceful leave (reference command/leave → /v1/agent/leave):
    the agent deregisters and its runtime shuts down."""
    ok = client.agent.leave()
    print("Graceful leave complete" if ok else "error: leave failed")
    return 0 if ok else 1


def cmd_force_leave(client: Client, args) -> int:
    """Force a failed member out (reference command/forceleave →
    agent ForceLeave → serf.RemoveFailedNode)."""
    out, _, _ = client._call("PUT", f"/v1/agent/force-leave/{args.node}", {})
    print(f"Force-leave {args.node}: {'ok' if out else 'no-op'}")
    return 0


def cmd_operator(client: Client, args) -> int:
    """Operator subcommands (reference command/operator raft,
    command/operator autopilot)."""
    if args.operator_cmd == "raft" and args.raft_cmd == "list-peers":
        cfg = client.operator.raft_get_configuration()
        if not any(s["leader"] for s in cfg["servers"]):
            print("error: no cluster leader", file=sys.stderr)
            return 1
        for s in cfg["servers"]:
            role = "leader" if s["leader"] else (
                "follower" if s["voter"] else "non-voter")
            print(f"{s['id']:<12} {s['address']:<16} {role}")
        return 0
    if args.operator_cmd == "raft" and args.raft_cmd == "remove-peer":
        try:
            client.operator.raft_remove_peer(args.id)
        except APIError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        print(f"Removed peer with id {args.id!r}")
        return 0
    if args.operator_cmd == "autopilot" and args.autopilot_cmd == "health":
        # Reference `consul operator autopilot ...` health view
        # (api/operator_autopilot.go AutopilotServerHealth).
        h = client.operator.autopilot_server_health()
        print(f"Healthy: {h['Healthy']}  "
              f"FailureTolerance: {h['FailureTolerance']}")
        for s in h["Servers"]:
            role = "leader" if s["Leader"] else (
                "voter" if s["Voter"] else "non-voter")
            state = "healthy" if s["Healthy"] else (
                f"unhealthy ({s['Reason']})")
            print(f"{s['Name']:<12} {role:<10} {state}")
        return 0 if h["Healthy"] else 1
    if args.operator_cmd == "autopilot" and args.autopilot_cmd == "get-config":
        cfg = client.operator.autopilot_get_configuration()
        for k in sorted(cfg):
            print(f"{k} = {cfg[k]}")
        return 0
    if args.operator_cmd == "autopilot" and args.autopilot_cmd == "set-config":
        cfg = client.operator.autopilot_get_configuration()
        # Read-modify-write under CAS (reference operator autopilot
        # set-config uses AutopilotCASConfiguration): a concurrent
        # set-config loses loudly instead of silently reverting fields.
        cas = cfg.pop("modify_index", 0)
        if args.cleanup_dead_servers is not None:
            cfg["cleanup_dead_servers"] = \
                args.cleanup_dead_servers == "true"
        if args.server_stabilization_ticks is not None:
            cfg["server_stabilization_ticks"] = \
                args.server_stabilization_ticks
        if args.max_trailing_logs is not None:
            cfg["max_trailing_logs"] = args.max_trailing_logs
        ok = client.operator.autopilot_set_configuration(cfg, cas=cas)
        print("Configuration updated!" if ok else "error: CAS failed "
              "(config changed concurrently — retry)")
        return 0 if ok else 1
    raise AssertionError(args.operator_cmd)


def cmd_maint(client: Client, args) -> int:
    """Maintenance mode toggle (reference command/maint)."""
    enable = not args.disable
    if args.service:
        ok = client.agent.service_maintenance(
            args.service, enable, args.reason or "")
        what = f"service {args.service}"
    else:
        ok = client.agent.maintenance(enable, args.reason or "")
        what = "node"
    verb = "enabled" if enable else "disabled"
    print(f"Maintenance mode {verb} for {what}" if ok else "error")
    return 0 if ok else 1


def cmd_keyring(client: Client, args) -> int:
    """Cluster gossip-keyring management (reference command/keyring →
    operator keyring serf queries)."""
    try:
        if args.list:
            for pool in client.operator.keyring_list():
                for key, holders in sorted(pool.get("Keys", {}).items()):
                    print(f"  {key} [{holders}/{pool.get('NumNodes', '?')}]")
            return 0
        if args.install:
            ok = client.operator.keyring_install(args.install)
        elif args.use:
            ok = client.operator.keyring_use(args.use)
        elif args.remove:
            ok = client.operator.keyring_remove(args.remove)
        else:
            print("one of -list/-install/-use/-remove required",
                  file=sys.stderr)
            return 1
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"error: {e}", file=sys.stderr)
        return 1
    print("ok" if ok else "error")
    return 0 if ok else 1


def cmd_monitor(client: Client, args) -> int:
    """Stream agent logs (reference command/monitor →
    /v1/agent/monitor long-poll loop)."""
    index = 0
    rounds = 0
    while args.rounds == 0 or rounds < args.rounds:
        out, meta, _ = client._call(
            "GET", "/v1/agent/monitor",
            {"index": index or None, "wait": args.wait})
        for line in out or []:
            print(line)
        # Never regress to a non-blocking cursor: an idle tap at seq 0
        # must long-poll (?index=1), not busy-spin the HTTP loop.
        index = max(meta.index, 1)
        rounds += 1
    return 0


def cmd_validate(client: Client, args) -> int:
    """Validate a config file (reference command/validate)."""
    from consul_tpu import config_loader

    try:
        config_loader.load([args.path])
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"Config validation failed: {e}", file=sys.stderr)
        return 1
    print(f"Configuration file {args.path} is valid!")
    return 0


def cmd_lock(client: Client, args) -> int:
    """Run a shell command under a KV lock (reference command/lock:
    acquire, exec child, release)."""
    import subprocess

    from consul_tpu.api import Lock

    lock = Lock(client, args.prefix)
    if not lock.acquire(retries=args.retries):
        print("lock acquisition failed", file=sys.stderr)
        return 1
    try:
        return subprocess.call(args.command, shell=True)
    finally:
        lock.release()


def cmd_exec(client: Client, args) -> int:
    """Remote execution over KV + events (reference command/exec →
    agent/remote_exec.go semantics via rexec.py)."""
    from consul_tpu import rexec

    result = rexec.submit(client, args.node, args.command,
                          wait_s=args.timeout, target=args.target or "")
    for node, r in sorted(result.items()):
        out = r.get("output", b"")
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        print(f"{node}: {out.rstrip()} (exit {r.get('exit')})")
    print(f"{len(result)} node(s) responded")
    return 0 if result and all(
        r.get("exit") == 0 for r in result.values()) else 1


def cmd_config(client: Client, args) -> int:
    """reference command/config: write/read/list/delete centralized
    config entries through /v1/config."""
    if args.config_cmd == "write":
        if args.file == "-":
            doc = json.load(sys.stdin)
        else:
            with open(args.file, encoding="utf-8") as f:
                doc = json.load(f)
        try:
            kind, name = doc.pop("Kind"), doc.pop("Name")
        except KeyError as e:
            print(f"config write: entry is missing required field {e}",
                  file=sys.stderr)
            return 1
        ok = client.config.set(kind, name, doc, cas=args.cas)
        if not ok:
            print("config write failed (cas mismatch)", file=sys.stderr)
            return 1
        print(f"Config entry written: {kind}/{name}")
    elif args.config_cmd == "read":
        entry, _ = client.config.get(args.kind, args.name)
        if entry is None:
            print(f"config entry {args.kind}/{args.name} not found",
                  file=sys.stderr)
            return 1
        print(json.dumps(entry, indent=2))
    elif args.config_cmd == "list":
        entries, _ = client.config.list(args.kind)
        for e in entries:
            print(f"{e['Kind']}/{e['Name']}")
    elif args.config_cmd == "delete":
        ok = client.config.delete(args.kind, args.name, cas=args.cas)
        if not ok:
            print("config delete failed (cas mismatch)", file=sys.stderr)
            return 1
        print(f"Config entry deleted: {args.kind}/{args.name}")
    return 0


def cmd_reload(client: Client, args) -> int:
    """Trigger a config reload (reference command/reload)."""
    try:
        out, _, _ = client._call("PUT", "/v1/agent/reload")
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"error: {e}", file=sys.stderr)
        return 1
    print("Configuration reload triggered"
          + (f" (applied: {', '.join(out['Applied'])})"
             if out.get("Applied") else " (no safe-reloadable changes)"))
    return 0


def cmd_debug(client: Client, args) -> int:
    """Capture a debug bundle over the HTTP API (reference
    command/debug/debug.go captureStatic)."""
    from consul_tpu.utils import debug as debug_mod

    files = debug_mod.capture_static(client)
    path = debug_mod.write_bundle(args.output, files)
    print(f"Saved debug bundle ({len(files)} captures) to {path}")
    return 0


def _mesh_from_args(args, n: int):
    """Default-mesh selection for the local-run subcommands: the
    largest elastic mesh over the visible devices whenever more than
    one is visible — multi-chip is the DEFAULT headline path — with
    ``--devices``/``--n-dc`` as explicit overrides (``--devices 1``
    pins single-device execution)."""
    from consul_tpu.parallel import mesh as pmesh

    return pmesh.default_mesh(
        n, device_count=getattr(args, "devices", None),
        n_dc=getattr(args, "n_dc", 1) or 1)


def _plan_from_args(args, cfg, kind: str, mesh):
    """MemoryBudget resolution for the local-run subcommands. Returns
    None when the legacy dense path applies (no --layout/--budget
    given) so runs that never asked for planning are byte-identical to
    before the planner existed. A population that exceeds the
    per-device budget over the mesh replans single-device — that is
    the cohort-streamed regime (models/cluster.StreamedSimulation)."""
    layout = getattr(args, "layout", None) or "dense"
    budget = getattr(args, "budget", None)
    if layout == "dense" and budget is None:
        return None
    from consul_tpu.runtime import membudget

    chunk = getattr(args, "chunk", None)
    try:
        return membudget.plan(cfg, kind, layout=layout,
                              budget=budget or "auto", mesh=mesh,
                              chunk=chunk)
    except ValueError:
        if mesh is None or getattr(mesh, "size", 1) <= 1:
            raise
        return membudget.plan(cfg, kind, layout=layout,
                              budget=budget or "auto", mesh=None,
                              chunk=chunk)


def _build_sim(args):
    """Build the simulation a local-run subcommand drives, honoring the
    MemoryBudget plan when --layout/--budget ask for one. Returns
    ``(sim, plan)``; ``plan`` is None on the legacy dense path, and
    ``plan.streamed`` means ``sim`` is a StreamedSimulation (cohorts
    through one device, no mesh/sentinel/serving)."""
    from consul_tpu.config import SimConfig, clamp_view_degree
    from consul_tpu.models.cluster import (SerfSimulation, Simulation,
                                           StreamedSerfSimulation,
                                           StreamedSimulation)
    from consul_tpu.utils import compile_cache

    if getattr(args, "compile_cache", None):
        compile_cache.enable(args.compile_cache)
    else:
        compile_cache.maybe_enable_from_env()
    # clamp_view_degree fails fast on an odd degree (the symmetric
    # circulant constraint) and keeps the n-2 cap even — the old
    # min(view_degree, n - 2) could produce an odd degree that
    # make_topology rejected only after the argv had long scrolled by.
    try:
        vd = clamp_view_degree(args.n, args.view_degree)
    except ValueError as e:
        print(f"--view-degree: {e}", file=sys.stderr)
        raise SystemExit(2)
    cfg = SimConfig(n=args.n, view_degree=vd,
                    topo_family=getattr(args, "family", "circulant"),
                    topo_param=getattr(args, "family_param", 0.0))
    kind = "serf" if args.serf else "swim"
    mesh = _mesh_from_args(args, args.n)
    plan = _plan_from_args(args, cfg, kind, mesh)
    kernel = getattr(args, "kernel", "xla") or "xla"
    if kernel != "xla":
        from consul_tpu.ops import pallas_gossip

        try:
            pallas_gossip.validate_kernel(
                kernel, plan.layout if plan else "dense")
        except ValueError as e:
            print(f"--kernel: {e}", file=sys.stderr)
            raise SystemExit(2)
    if plan is not None and plan.streamed:
        if kernel != "xla":
            print("--kernel: cohort-streamed runs drive the XLA scan "
                  "body; drop --kernel or shrink n under the budget",
                  file=sys.stderr)
            raise SystemExit(2)
        if int(getattr(args, "lens", 0) or 0):
            print("--lens: the node lens needs a resident population; "
                  "cohort-streamed runs cannot record it",
                  file=sys.stderr)
            raise SystemExit(2)
        if int(getattr(args, "raft_groups", 0) or 0):
            print("--raft-groups: the raft tier rides the resident "
                  "chunk scan; cohort-streamed runs cannot arm it",
                  file=sys.stderr)
            raise SystemExit(2)
        scls = StreamedSerfSimulation if args.serf else StreamedSimulation
        sim = scls(cfg, cohort_n=plan.cohort_n, seed=args.seed,
                   layout=plan.layout, chunk=plan.chunk)
        return sim, plan
    cls = SerfSimulation if args.serf else Simulation
    sim = cls(cfg, seed=args.seed, mesh=mesh,
              layout=plan.layout if plan else "dense", kernel=kernel)
    lens_n = int(getattr(args, "lens", 0) or 0)
    if lens_n:
        if mesh is not None:
            print("--lens: the node lens is single-device; drop the "
                  "mesh flags to use it", file=sys.stderr)
            raise SystemExit(2)
        sim.set_lens(lens_n)
    raft_groups = int(getattr(args, "raft_groups", 0) or 0)
    if raft_groups:
        sim.set_raft(raft_groups,
                     peers=int(getattr(args, "raft_peers", 5)))
    if getattr(args, "prewarm", False):
        from consul_tpu.utils import prewarm as prewarm_mod

        chunk = getattr(args, "chunk", 32)
        for with_metrics in (False, True):
            prewarm_mod.prewarm_simulation(sim, chunk, with_metrics)
    return sim, plan


def _export_trace(args, sim=None):
    """Write the flight-recorder artifact (obs/trace.py Chrome
    trace-event JSON; the armed lens's node timelines merge in) when
    the run asked for one via ``--trace-dir``. Returns the artifact
    path, or None when tracing was not requested."""
    tdir = getattr(args, "trace_dir", None)
    if not tdir:
        return None
    from consul_tpu.obs import trace as obs_trace

    extra = None
    lens = getattr(sim, "lens", None) if sim is not None else None
    if lens is not None:
        extra = lens.to_trace_events()
    return obs_trace.get_tracer().export(
        os.path.join(tdir, "trace.json"), extra_events=extra)


def _ckpt_policy(args, sim, default_tag: str):
    """The checkpoint policy the local-run subcommands share — None
    when the user gave no --ckpt-dir (no resume point, same as before
    this knob existed)."""
    if not getattr(args, "ckpt_dir", None):
        return None
    from consul_tpu.runtime import CheckpointPolicy

    return CheckpointPolicy(
        directory=args.ckpt_dir,
        tag=args.ckpt_tag or default_tag,
        every_ticks=args.ckpt_every_ticks,
        min_interval_s=args.ckpt_interval_s,
        sink=sim.sink,
    )


def _run_resilient_cmd(args, sim, events, ticks, extra: dict) -> int:
    """Drive one local simulation through runtime.run_resilient and
    print a single JSON line. SIGTERM mid-run saves a resume point and
    exits 75 (EX_TEMPFAIL: rerunning the same command continues the
    trajectory); a tripped invariant sentinel exits 2 with the
    violation and diagnostic-checkpoint path in the JSON."""
    from consul_tpu.runtime import (Preempted, SentinelViolation,
                                    run_resilient)

    if getattr(args, "dcn_retry_max", None) is not None:
        # The process-wide LinkPolicy default: any DCN federation this
        # run builds inherits the bound (parallel/dcn).
        import dataclasses as _dc

        from consul_tpu.parallel import dcn as dcn_mod

        dcn_mod.DEFAULT_LINK_POLICY = _dc.replace(
            dcn_mod.DEFAULT_LINK_POLICY, retry_max=args.dcn_retry_max)

    policy = _ckpt_policy(
        args, sim, f"{args.cmd}_{args.n}_seed{args.seed}")
    try:
        report = run_resilient(
            sim, ticks, chunk=args.chunk, events=events, policy=policy,
            sentinel=args.sentinel,
            sentinel_dump_dir=args.sentinel_dump_dir,
            heartbeat_s=args.heartbeat_s or None,
            elastic=args.elastic)
    except Preempted as e:
        print(json.dumps(dict(extra, **e.report.to_json())))
        return 75
    except SentinelViolation as e:
        print(json.dumps(dict(
            extra, sentinel_tripped=True, violation_mask=e.mask,
            violations={k: int(v) for k, v in e.deltas.items() if v},
            diagnostic_checkpoint=e.dump_path)))
        return 2
    out = dict(extra, ticks=report.ticks_done, slo=report.slo,
               counters=report.counters,
               resumed_from_tick=report.resumed_from_tick,
               ckpt_failures=report.ckpt_failures,
               reshards=report.reshards,
               hang_status=report.hang_status)
    if getattr(sim, "raft", None) is not None:
        sim.raft.pump()
        out["raft"] = dict(sim.raft.summary(),
                           counters=sim.raft.counters_snapshot())
    trace_path = _export_trace(args, sim)
    if trace_path:
        out["trace"] = trace_path
    print(json.dumps(out))
    return 0


def cmd_chaos(args) -> int:
    """Run a compiled fault-schedule scenario (consul_tpu/chaos) on a
    local in-process simulation and print the on-device convergence SLO
    counters as one JSON line. No running agent is needed — like the
    ``agent`` subcommand this path is special-cased in main() and
    imports jax lazily so the HTTP-client commands stay light.

    Drives runtime.run_resilient: with ``--ckpt-dir`` the scenario
    survives preemption (SIGTERM saves, rerun resumes bit-identically —
    the chaos schedule is rebased to the ORIGINAL start tick recorded
    in the checkpoint); ``--sentinel`` arms the on-device invariant
    validator."""
    from consul_tpu import chaos as chaos_mod

    n = args.n
    if args.sweep > 0:
        return _cmd_chaos_sweep(args)

    def frac_nodes(frac):
        return slice(0, max(1, int(n * frac)))

    events = []
    for spec in args.partition or []:
        start, stop, frac = spec.split(",")
        events.append(chaos_mod.Partition(
            start=int(start), stop=int(stop),
            side_a=frac_nodes(float(frac))))
    for spec in args.link_loss or []:
        f = spec.split(",")
        na = max(1, int(n * float(f[2])))
        nb = max(1, int(n * float(f[3])))
        events.append(chaos_mod.LinkLoss(
            start=int(f[0]), stop=int(f[1]),
            a=slice(0, na), b=slice(na, na + nb),
            fwd=float(f[4]), rev=float(f[5]) if len(f) > 5 else 0.0))
    for spec in args.churn or []:
        start, stop, frac = spec.split(",")
        events.append(chaos_mod.ChurnWave(
            start=int(start), stop=int(stop),
            nodes=frac_nodes(float(frac))))
    for spec in args.degrade or []:
        f = spec.split(",")
        events.append(chaos_mod.Degrade(
            start=int(f[0]), stop=int(f[1]),
            nodes=frac_nodes(float(f[2])),
            tx_loss=float(f[3]),
            rx_loss=float(f[4]) if len(f) > 4 else 0.0))
    raft_events = []
    for spec in args.raft_kill or []:
        f = spec.split(",")
        raft_events.append(chaos_mod.RaftKill(
            start=int(f[0]), stop=int(f[1]),
            group=int(f[2]) if len(f) > 2 else -1,
            peer=int(f[3]) if len(f) > 3 else -1))
    for spec in args.raft_partition or []:
        f = spec.split(",")
        raft_events.append(chaos_mod.RaftPartition(
            start=int(f[0]), stop=int(f[1]), cut=int(f[2]),
            group=int(f[3]) if len(f) > 3 else -1))
    for spec in args.raft_storm or []:
        f = spec.split(",")
        raft_events.append(chaos_mod.RaftStorm(
            start=int(f[0]), stop=int(f[1]),
            group=int(f[2]) if len(f) > 2 else -1))
    if raft_events and not getattr(args, "raft_groups", 0):
        print("--raft-kill/--raft-partition/--raft-storm act on the "
              "raft tier; arm it with --raft-groups R", file=sys.stderr)
        return 2
    events.extend(raft_events)
    if not events:
        # Default scenario: the acceptance-style 70/30 partition-heal.
        events = [chaos_mod.Partition(
            start=4, stop=16, side_a=frac_nodes(0.3))]

    sim, plan = _build_sim(args)
    ticks = max(int(e.stop) for e in events) + args.settle
    extra = {"n": n}
    if plan is not None:
        extra["memory_plan"] = plan.to_dict()
    if plan is not None and plan.streamed:
        # Beyond-budget population: form, then replay the schedule
        # inside every cohort (shifted past formation — the streamed
        # driver has no harness to rebase it). The resilient-harness
        # knobs (checkpoint/sentinel) don't apply to this path.
        import dataclasses as _dc

        sim.run(args.form_ticks)
        sim.set_chaos([_dc.replace(e, start=e.start + args.form_ticks,
                                   stop=e.stop + args.form_ticks)
                       for e in events])
        summary = sim.run(ticks)
        out = dict(extra, **summary, streamed=True,
                   counters=sim.counters_snapshot())
        trace_path = _export_trace(args, sim)
        if trace_path:
            out["trace"] = trace_path
        print(json.dumps(out))
        return 0
    sim.run(args.form_ticks, chunk=args.chunk, with_metrics=False)
    return _run_resilient_cmd(args, sim, events, ticks, extra)


def _cmd_chaos_sweep(args) -> int:
    """``consul-tpu chaos --sweep S``: run S scenario parameterizations
    per family in ONE vmapped executable each (chaos/sweep.py) and
    print the per-family worst cases plus the bandwidth-vs-convergence
    Pareto table as one JSON line. Same-shape families share a single
    program — the topology tables travel as program arguments — so the
    whole table costs one compile per (n, degree, S, chunk)."""
    from consul_tpu.chaos import sweep as sweep_mod
    from consul_tpu.topo import FAMILIES

    if args.families:
        if args.families.strip() == "all":
            families = [f for f in sorted(FAMILIES)
                        if f != "hier" or args.n % 8 == 0]
        else:
            families = [f.strip() for f in args.families.split(",")
                        if f.strip()]
    else:
        families = [args.family]
    unknown = [f for f in families if f not in FAMILIES]
    if unknown:
        print(f"--families: unknown famil{'ies' if len(unknown) > 1 else 'y'}"
              f" {', '.join(unknown)}; registered: "
              f"{', '.join(sorted(FAMILIES))}", file=sys.stderr)
        return 2

    scens = (sweep_mod.scenario_grid(args.n, args.sweep)
             if args.sweep_mode == "grid"
             else sweep_mod.scenario_random(args.n, args.sweep,
                                            seed=args.sweep_seed))
    per_family = {}
    for fam in families:
        fam_args = argparse.Namespace(**vars(args))
        fam_args.family = fam
        sim, _plan = _build_sim(fam_args)
        sim.run(args.form_ticks, chunk=args.chunk, with_metrics=False)
        per_family[fam] = sweep_mod.family_sweep(
            sim, scens, chunk=args.chunk, settle=args.settle)
    print(json.dumps({
        "n": args.n,
        "sweep": args.sweep,
        "mode": args.sweep_mode,
        "families": families,
        "pareto": sweep_mod.pareto_table(per_family),
        "dominates_default": sweep_mod.strict_dominators(per_family),
    }))
    return 0


def cmd_gameday(args) -> int:
    """Run the federated game-day soak (consul_tpu/gameday) locally and
    print the single SLO verdict as one JSON line. Like ``chaos`` this
    is special-cased in main() and imports jax lazily — no running
    agent is needed; the harness builds its own simulation, arms the
    raft tier, composes Partition+ChurnWave+RaftKill on the compiled
    schedule, and drives sustained mixed traffic through the chosen
    host frontend while a DCN federation leg and the watcher tree run
    alongside.

    SIGTERM mid-soak saves a resume point at the last drained phase
    boundary (when --resume-dir is set) and exits 75 (EX_TEMPFAIL) —
    rerunning the same command continues from the last completed
    phase. Exit 0 = SLO pass, 1 = SLO fail."""
    from consul_tpu.gameday import GamedayConfig, run_gameday
    from consul_tpu.runtime.policy import SignalTrap

    cfg = GamedayConfig(
        n=args.n, seed=args.seed, view_degree=args.view_degree,
        watchers=args.watchers, watch_queue=args.watch_queue,
        ratio=args.ratio, read_batch=args.read_batch,
        raft_groups=args.raft_groups, raft_peers=args.raft_peers,
        dcn_islands=args.dcn_islands, frontend=args.frontend,
        warmup_ticks=args.warmup_ticks,
        ticks_per_round=args.ticks_per_round,
        steady_rounds=args.steady_rounds,
        fault_rounds=args.fault_rounds, heal_rounds=args.heal_rounds,
        drain_rounds=args.drain_rounds,
        partition_frac=args.partition_frac, churn_frac=args.churn_frac,
        swarm_procs=args.swarm_procs, swarm_requests=args.swarm_requests,
        resume_dir=args.resume_dir)
    say = (lambda rec: print(json.dumps(rec), file=sys.stderr)) \
        if args.verbose else None
    with SignalTrap() as trap:
        verdict = run_gameday(cfg, trap=trap, emit=say)
    print(json.dumps(verdict))
    if trap.fired is not None:
        return 75
    return 0 if verdict.get("pass") else 1


def cmd_run(args) -> int:
    """Advance a plain local simulation under the resilient harness
    (no fault schedule — ``chaos`` is the faulted variant) and print
    the run report as one JSON line. The kill -9 / resume quickstart in
    the README drives this subcommand.

    With ``--layout``/``--budget`` the MemoryBudget planner
    (runtime/membudget.py) picks the state layout and chunk; a
    population beyond the per-device budget runs cohort-streamed
    (models/cluster.StreamedSimulation) and the JSON carries
    ``streamed: true`` plus the plan under ``memory_plan``."""
    sim, plan = _build_sim(args)
    extra = {"n": args.n}
    if plan is not None:
        extra["memory_plan"] = plan.to_dict()
    if plan is not None and plan.streamed:
        summary = sim.run(args.ticks)
        out = dict(extra, **summary, streamed=True,
                   counters=sim.counters_snapshot())
        trace_path = _export_trace(args, sim)
        if trace_path:
            out["trace"] = trace_path
        print(json.dumps(out))
        return 0
    return _run_resilient_cmd(args, sim, None, args.ticks, extra)


def cmd_prewarm(args) -> int:
    """AOT-compile every requested (n, kind, chunk, mesh-shape,
    chaos-shape) chunk-program signature into the persistent compile
    cache (utils/prewarm.py) and print a JSON summary — signatures
    compiled, cache hit/miss movement, wall_s. Run it off the critical
    path so a later ``consul-tpu run``/bench at the same signature
    starts with compile_s ~ 0."""
    from consul_tpu.utils import prewarm as prewarm_mod

    mesh = None
    if args.mesh:
        import jax

        from consul_tpu.parallel import mesh as pmesh

        dims = [int(x) for x in args.mesh.lower().split("x")]
        if len(dims) == 1:
            n_dc, per_dc = 1, dims[0]
        elif len(dims) == 2:
            n_dc, per_dc = dims
        else:
            print(f"--mesh {args.mesh!r}: want NODES or DCxNODES",
                  file=sys.stderr)
            return 2
        mesh = pmesh.make_mesh(jax.devices()[:n_dc * per_dc], n_dc=n_dc)
    summary = prewarm_mod.prewarm(
        ns=[int(x) for x in args.n.split(",") if x],
        kinds=tuple(x.strip() for x in args.kinds.split(",") if x.strip()),
        chunks=[int(x) for x in args.chunks.split(",") if x],
        mesh=mesh, device_count=args.devices, n_dc=args.n_dc,
        chaos=args.chaos, seed=args.seed, view_degree=args.view_degree,
        sentinel=args.sentinel, cache_dir=args.compile_cache,
        layout=args.layout, family=args.family,
        family_param=args.family_param, sweep=args.sweep,
        sweep_chunk=args.sweep_chunk, raft_groups=args.raft_groups,
        raft_peers=args.raft_peers, kernel=args.kernel,
    )
    print(json.dumps(summary))
    return 0


def cmd_serve_bench(args) -> int:
    """Benchmark the device serving plane against a local simulation:
    form a cluster, attach a ServingPlane, and drive batched NearestN
    queries through the QueryBatcher. Prints one JSON line with the
    same stable keys as bench.py's ``serving`` phase (queries/s/chip,
    p50/p99 batch latency, padding waste %). The kernel runs on one
    device, so per-chip and total throughput coincide."""
    import random as _random
    import time as _time

    sim, _ = _build_sim(args)
    sim.run(args.form_ticks, chunk=args.chunk, with_metrics=False)

    from consul_tpu.serving import MODE_NEAREST, ServingPlane

    # Plain serve-bench keeps the unlabeled plane; --mixed wants a
    # non-trivial service space for register churn + watch fan-out.
    services = args.services or (8 if args.mixed else 0)
    plane = ServingPlane(k=args.k, buckets=(args.batch,),
                         num_services=services)
    sim.attach_serving(plane, writes=bool(args.mixed),
                       kv_slots=args.kv_slots)
    rng = _random.Random(args.seed)

    if args.mixed:
        from consul_tpu.serving.mixed import run_mixed
        mixed = run_mixed(sim, plane, ratio=args.mixed,
                          rounds=args.mixed_rounds, read_batch=args.batch,
                          watchers=args.watchers, seed=args.seed)
        out = dict(plane.stats())
        out.update({"n": args.n, "k": args.k, "batch": args.batch,
                    "mixed": mixed})
        trace_path = _export_trace(args, sim)
        if trace_path:
            out["trace"] = trace_path
        print(json.dumps(out))
        return 0

    def make_batch(b: int):
        return [(MODE_NEAREST, rng.randrange(args.n), -1) for _ in range(b)]

    # Warm the bucket's executable so compilation never lands in the
    # timed region (the throughput() discipline), and drop its latency
    # sample so p50/p99 describe steady state only.
    plane.batcher.execute(make_batch(args.batch))
    plane.batcher.latencies_s.clear()
    total = 0
    t0 = _time.perf_counter()
    while total < args.queries:
        b = min(args.batch, args.queries - total)
        plane.batcher.execute(make_batch(b))
        total += b
    wall = _time.perf_counter() - t0
    out = dict(plane.stats())
    # Timed-region numbers win over the batcher's lifetime counters
    # (which include the warmup batch).
    out.update({"n": args.n, "k": args.k, "batch": args.batch,
                "queries": total, "wall_s": round(wall, 3),
                "queries_per_sec_per_chip": round(total / wall, 1)})
    trace_path = _export_trace(args, sim)
    if trace_path:
        out["trace"] = trace_path
    print(json.dumps(out))
    return 0


def cmd_trace(args) -> int:
    """Flight-record a short local run: arm the node lens, advance the
    simulation, and write the Perfetto-loadable trace artifact — host
    spans, XLA compile spans, per-chunk markers, and one counter
    timeline per sampled node in a single file. Prints one JSON line
    with the artifact path (load it at https://ui.perfetto.dev or
    chrome://tracing)."""
    sim, plan = _build_sim(args)
    trace = sim.run(args.ticks, chunk=args.chunk)
    path = _export_trace(args, sim)
    out = {
        "n": args.n,
        "ticks": args.ticks,
        "lens_ids": list(sim.lens.ids) if sim.lens is not None else [],
        "agreement": float(trace.agreement[-1]) if trace is not None
        else None,
        "trace": path,
    }
    print(json.dumps(out))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="consul-tpu",
        description="TPU-native Consul-capability framework CLI",
    )
    p.add_argument(
        "--http-addr",
        default=os.environ.get("CONSUL_TPU_HTTP_ADDR", "127.0.0.1:8500"),
    )
    p.add_argument(
        "--token", default="",
        help="ACL token (or CONSUL_TPU_TOKEN), sent as X-Consul-Token",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    ag = sub.add_parser(
        "agent", help="boot an agent (+in-process servers) from config")
    ag.add_argument("--config-file", default=None)
    ag.add_argument("--node", default=None, help="override node_name")
    ag.add_argument("--server", action="store_true", default=None)
    ag.add_argument("--http-port", type=int, default=None,
                    help="override http.port (0 = pick a free port)")
    ag.add_argument("--data-dir", default=None)

    def add_resilience_flags(sp):
        # Shared by the local-run subcommands (run / chaos): the
        # runtime harness knobs (consul_tpu/runtime).
        sp.add_argument("--ckpt-dir", default=None,
                        help="checkpoint directory; enables resume — "
                             "rerun the same command after a kill to "
                             "continue the trajectory bit-identically")
        sp.add_argument("--ckpt-tag", default=None,
                        help="checkpoint name (default: derived from "
                             "subcommand/n/seed)")
        sp.add_argument("--ckpt-interval-s", type=float, default=120.0,
                        help="minimum wall seconds between saves")
        sp.add_argument("--ckpt-every-ticks", type=int, default=0,
                        help="tick bound between save checks (0: wall "
                             "pacing only)")
        sp.add_argument("--sentinel", action="store_true",
                        help="arm the on-device invariant sentinels "
                             "(fail-fast on state corruption)")
        sp.add_argument("--sentinel-dump-dir", default=None,
                        help="where a sentinel trip dumps its "
                             "diagnostic checkpoint")
        sp.add_argument("--elastic", action="store_true",
                        help="place the run over the largest mesh the "
                             "surviving devices support, and re-shard "
                             "a resumed checkpoint onto it (chip-loss "
                             "survival: resume 8->4->1 devices)")
        sp.add_argument("--heartbeat-s", type=float, default=0.0,
                        help="per-chunk heartbeat deadline in seconds "
                             "(0: off) — a chunk that fails to finish "
                             "in time is classified mid-run-hang and "
                             "a diagnostic checkpoint of the last "
                             "completed state is written")
        sp.add_argument("--dcn-retry-max", type=int, default=None,
                        help="bound on consecutive DCN federation "
                             "link retries before a link is marked "
                             "degraded (parallel/dcn LinkPolicy)")
        sp.add_argument("--compile-cache", default=None, metavar="DIR",
                        help="persistent XLA compilation cache "
                             "directory (or CONSUL_TPU_COMPILE_CACHE):"
                             " a second cold process deserializes "
                             "executables instead of recompiling")

    def add_obs_flags(sp, lens_default: int = 0):
        """The flight-recorder knobs every local-run subcommand
        shares (consul_tpu/obs)."""
        sp.add_argument("--trace-dir", default=None, metavar="DIR",
                        help="write the Perfetto trace artifact "
                             "(host spans + XLA compiles + chunk "
                             "markers + lens timelines) under DIR")
        sp.add_argument("--lens", type=int, default=lens_default,
                        metavar="N",
                        help="record N evenly spaced nodes' per-tick "
                             "observables inside the compiled scan "
                             "(obs/lens.py; 0 = off, the byte-"
                             "identical pre-lens program)")

    def add_layout_flags(sp):
        # MemoryBudget planner knobs (runtime/membudget.py): the state
        # layout and the per-device byte budget that together decide
        # resident-vs-streamed and dense-vs-packed.
        sp.add_argument("--layout", choices=("auto", "dense", "packed"),
                        default="dense",
                        help="per-node state layout: dense f32/i32 "
                             "(golden reference, default), packed "
                             "(2.5x smaller at rest), or auto (planner "
                             "picks per the memory budget)")
        sp.add_argument("--budget", default=None, metavar="BYTES",
                        help="per-device memory budget ('auto' probes "
                             "the device, or e.g. '2GB'/'512MiB'); "
                             "populations beyond it stream as node "
                             "cohorts through one device")
        sp.add_argument("--kernel", choices=("xla", "pallas"),
                        default="xla",
                        help="tick execution engine: xla (scan body, "
                             "default) or pallas (packed-native fused "
                             "tick, ops/pallas_gossip.py; requires "
                             "--layout packed)")

    def add_family_flags(sp):
        # Topology-lab knobs (consul_tpu/topo): which view-graph family
        # generates the circulant offset set, and its one parameter
        # (expander: candidate draws; smallworld: rewire beta; hier:
        # datacenter count).
        sp.add_argument("--family", default="circulant",
                        help="view-graph family: circulant (default), "
                             "expander, smallworld, hier "
                             "(consul_tpu/topo/families.py)")
        sp.add_argument("--family-param", type=float, default=0.0,
                        help="family parameter (0 = family default: "
                             "expander 32 draws, smallworld beta 0.2, "
                             "hier 8 DCs)")

    def add_raft_flags(sp):
        # Batched device raft tier (models/raft.py, ops/raft_ops.py):
        # R consensus groups x P peers stepped inside the same jitted
        # scan, the commit point feeding the serving write path.
        sp.add_argument("--raft-groups", type=int, default=0,
                        metavar="R",
                        help="arm the batched raft server tier with R "
                             "groups (0 = off, the byte-identical "
                             "pre-raft program); the run report gains "
                             "per-group terms/leaders/commit + "
                             "consul.raft.* counters")
        sp.add_argument("--raft-peers", type=int, default=5,
                        metavar="P",
                        help="peers per raft group (odd; quorum "
                             "P//2+1)")

    def add_mesh_flags(sp):
        # Multi-chip placement knobs: by default the local-run
        # subcommands run over the largest elastic mesh the visible
        # devices support (parallel/mesh.default_mesh); these override.
        sp.add_argument("--devices", type=int, default=None,
                        help="number of devices to mesh over (default: "
                             "all visible; 1 pins single-device)")
        sp.add_argument("--n-dc", type=int, default=1,
                        help="fold a dc axis into the mesh: devices "
                             "arrange as a (dc, nodes) grid")
        sp.add_argument("--prewarm", action="store_true",
                        help="AOT-compile this run's chunk programs "
                             "into the persistent compile cache before "
                             "t0 (see the prewarm subcommand)")

    rn = sub.add_parser(
        "run",
        help="advance a local simulation under the resilient harness")
    rn.add_argument("--n", type=int, default=1024)
    rn.add_argument("--seed", type=int, default=0)
    rn.add_argument("--view-degree", type=int, default=16)
    add_family_flags(rn)
    rn.add_argument("--ticks", type=int, default=256)
    rn.add_argument("--chunk", type=int, default=32)
    rn.add_argument("--serf", action="store_true",
                    help="run the full serf step (event/query plane)")
    add_resilience_flags(rn)
    add_mesh_flags(rn)
    add_layout_flags(rn)
    add_obs_flags(rn)
    add_raft_flags(rn)

    tr = sub.add_parser(
        "trace",
        help="flight-record a short local run: host spans + XLA "
             "compiles + chunk markers + per-node lens timelines in "
             "one Perfetto-loadable trace file")
    tr.add_argument("--n", type=int, default=1024)
    tr.add_argument("--seed", type=int, default=0)
    tr.add_argument("--view-degree", type=int, default=16)
    add_family_flags(tr)
    tr.add_argument("--ticks", type=int, default=256)
    tr.add_argument("--chunk", type=int, default=32)
    tr.add_argument("--serf", action="store_true",
                    help="trace the full serf step (event/query plane)")
    # The lens is single-device; pin the default mesh off rather than
    # erroring on multi-chip hosts.
    tr.add_argument("--devices", type=int, default=1,
                    help=argparse.SUPPRESS)
    tr.add_argument("--trace-dir", default="traces", metavar="DIR",
                    help="artifact directory (default: ./traces)")
    tr.add_argument("--lens", type=int, default=8, metavar="N",
                    help="record N evenly spaced nodes' per-tick "
                         "observables inside the compiled scan "
                         "(obs/lens.py; 0 = off)")
    add_raft_flags(tr)

    sv = sub.add_parser(
        "serve-bench",
        help="benchmark the device serving plane (batched NearestN "
             "reads straight from the simulation tensors)")
    sv.add_argument("--n", type=int, default=4096)
    sv.add_argument("--seed", type=int, default=0)
    sv.add_argument("--view-degree", type=int, default=16)
    add_family_flags(sv)
    sv.add_argument("--form-ticks", type=int, default=64,
                    help="ticks to form the cluster before serving")
    sv.add_argument("--chunk", type=int, default=32)
    sv.add_argument("--queries", type=int, default=65536,
                    help="total queries to serve in the timed region")
    sv.add_argument("--batch", type=int, default=512,
                    help="batch bucket size (one XLA executable)")
    sv.add_argument("--k", type=int, default=8,
                    help="result width (top-k nearest per query)")
    sv.add_argument("--serf", action="store_true",
                    help="serve over the full serf simulation")
    sv.add_argument("--mixed", nargs="?", const="90:9:1", default=None,
                    metavar="R:W:WATCH",
                    help="run the mixed read/write/watch workload at "
                         "this ratio (flag alone = 90:9:1); attaches "
                         "the device write path and watch plane")
    sv.add_argument("--mixed-rounds", type=int, default=32,
                    help="interleaved rounds for --mixed")
    sv.add_argument("--services", type=int, default=0,
                    help="synthetic service label count for the plane "
                         "(0: unlabeled, or 8 under --mixed)")
    sv.add_argument("--kv-slots", type=int, default=256,
                    help="device KV slot capacity (--mixed)")
    sv.add_argument("--watchers", type=int, default=8,
                    help="registered service watchers (--mixed)")
    sv.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="persistent XLA compilation cache directory")
    add_mesh_flags(sv)
    add_obs_flags(sv)

    ch = sub.add_parser(
        "chaos",
        help="run a fault-schedule scenario locally, print SLO JSON")
    ch.add_argument("--n", type=int, default=1024)
    ch.add_argument("--seed", type=int, default=0)
    ch.add_argument("--view-degree", type=int, default=16)
    add_family_flags(ch)
    ch.add_argument("--form-ticks", type=int, default=64,
                    help="ticks to form the cluster before the faults")
    ch.add_argument("--chunk", type=int, default=32)
    ch.add_argument("--settle", type=int, default=64,
                    help="post-lift window for the heal probe")
    ch.add_argument("--serf", action="store_true",
                    help="run the full serf step (event/query plane)")
    ch.add_argument("--partition", action="append",
                    metavar="START,STOP,FRAC")
    ch.add_argument("--link-loss", action="append",
                    metavar="START,STOP,FRAC_A,FRAC_B,FWD[,REV]")
    ch.add_argument("--churn", action="append", metavar="START,STOP,FRAC")
    ch.add_argument("--degrade", action="append",
                    metavar="START,STOP,FRAC,TX[,RX]")
    ch.add_argument("--raft-kill", action="append",
                    metavar="START,STOP[,GROUP[,PEER]]",
                    help="freeze a raft peer for the window (group -1 "
                         "= every group, peer -1 = whoever leads at "
                         "each tick: the leader-kill drill); needs "
                         "--raft-groups")
    ch.add_argument("--raft-partition", action="append",
                    metavar="START,STOP,CUT[,GROUP]",
                    help="split a raft group's peers at seat CUT "
                         "(minority side cannot commit); needs "
                         "--raft-groups")
    ch.add_argument("--raft-storm", action="append",
                    metavar="START,STOP[,GROUP]",
                    help="total message blackout: every timeout fires, "
                         "no vote lands — a split-vote election storm "
                         "burning through terms; needs --raft-groups")
    ch.add_argument("--sweep", type=int, default=0, metavar="S",
                    help="run S scenario parameterizations in ONE "
                         "vmapped executable per family instead of one "
                         "scenario (chaos/sweep.py); prints the "
                         "bandwidth-vs-convergence Pareto table")
    ch.add_argument("--sweep-mode", choices=("grid", "random"),
                    default="grid",
                    help="scenario search: partition fraction x "
                         "duration grid, or seeded random compound "
                         "scenarios (partition+churn+degrade)")
    ch.add_argument("--sweep-seed", type=int, default=0,
                    help="rng seed for --sweep-mode random")
    ch.add_argument("--families", default=None, metavar="F1,F2,...",
                    help="comma list of view-graph families to sweep "
                         "(default: the single --family; 'all' = every "
                         "registered family that fits n)")
    add_resilience_flags(ch)
    add_mesh_flags(ch)
    add_layout_flags(ch)
    add_obs_flags(ch)
    add_raft_flags(ch)

    gd = sub.add_parser(
        "gameday",
        help="run the federated game-day soak (composed chaos + live "
             "traffic + watchers + DCN leg) and print the SLO verdict")
    gd.add_argument("--n", type=int, default=4096)
    gd.add_argument("--seed", type=int, default=0)
    gd.add_argument("--view-degree", type=int, default=16)
    gd.add_argument("--watchers", type=int, default=1024,
                    help="registered watchers on the reduction tree")
    gd.add_argument("--watch-queue", type=int, default=8,
                    help="per-watcher bounded delivery queue")
    gd.add_argument("--ratio", default="90:9:1", metavar="R:W:WATCH",
                    help="read:write:watch traffic mix per round")
    gd.add_argument("--read-batch", type=int, default=256)
    gd.add_argument("--raft-groups", type=int, default=4)
    gd.add_argument("--raft-peers", type=int, default=3)
    gd.add_argument("--dcn-islands", type=int, default=2,
                    help="DCN federation islands for the WAN leg "
                         "(0 skips the leg)")
    gd.add_argument("--frontend", choices=("threaded", "async"),
                    default="threaded",
                    help="host frontend the traffic goes through: the "
                         "lock-based threaded path or the one-event-"
                         "loop async frontend (serving/frontend.py)")
    gd.add_argument("--warmup-ticks", type=int, default=64)
    gd.add_argument("--ticks-per-round", type=int, default=32)
    gd.add_argument("--steady-rounds", type=int, default=4)
    gd.add_argument("--fault-rounds", type=int, default=6)
    gd.add_argument("--heal-rounds", type=int, default=4)
    gd.add_argument("--drain-rounds", type=int, default=4)
    gd.add_argument("--partition-frac", type=float, default=0.25,
                    help="fraction of nodes on the cut side of the "
                         "composed partition")
    gd.add_argument("--churn-frac", type=float, default=0.05,
                    help="fraction of nodes in the churn wave")
    gd.add_argument("--swarm-procs", type=int, default=0,
                    help="HTTP client swarm processes hammering the "
                         "async frontend's socket listener (0 = off; "
                         "needs --frontend async)")
    gd.add_argument("--swarm-requests", type=int, default=64,
                    help="requests per swarm process")
    gd.add_argument("--resume-dir", default=None, metavar="DIR",
                    help="preemption resume directory: SIGTERM saves "
                         "at the last drained phase boundary and exits "
                         "75; rerunning continues from there")
    gd.add_argument("--verbose", action="store_true",
                    help="stream per-phase progress JSON to stderr")

    pw = sub.add_parser(
        "prewarm",
        help="AOT-compile chunk programs into the persistent compile "
             "cache so a later run/bench starts with compile_s ~ 0")
    pw.add_argument("--n", default="4096",
                    help="comma-separated node counts")
    pw.add_argument("--kinds", default="swim",
                    help="comma list of step kinds: swim,serf")
    pw.add_argument("--chunks", default="32",
                    help="comma-separated scan chunk sizes")
    pw.add_argument("--mesh", default=None, metavar="[DCx]NODES",
                    help="device grid to compile for, e.g. 8 or 2x4 "
                         "(default: largest elastic mesh over the "
                         "visible devices)")
    pw.add_argument("--devices", type=int, default=None,
                    help="devices for the default mesh (1 = "
                         "single-device programs)")
    pw.add_argument("--n-dc", type=int, default=1)
    pw.add_argument("--chaos", action="store_true",
                    help="also compile the chaos-enabled program for "
                         "the default one-partition schedule shape")
    pw.add_argument("--sentinel", action="store_true",
                    help="compile the sentinel-armed programs")
    pw.add_argument("--seed", type=int, default=0,
                    help="must match the run being warmed (topology "
                         "constants are part of the program identity)")
    pw.add_argument("--view-degree", type=int, default=16)
    add_family_flags(pw)
    pw.add_argument("--sweep", type=int, default=0, metavar="S",
                    help="also compile the S-scenario vmapped sweep "
                         "program (chaos/sweep.py) — topology travels "
                         "as a program argument, so one warm covers "
                         "every same-shape family")
    pw.add_argument("--sweep-chunk", type=int, default=32)
    pw.add_argument("--raft-groups", type=int, default=0, metavar="R",
                    help="also arm the batched raft tier (R groups) "
                         "so the warmed program matches a "
                         "run --raft-groups R")
    pw.add_argument("--raft-peers", type=int, default=5, metavar="P")
    pw.add_argument("--layout", choices=("dense", "packed"),
                    default="dense",
                    help="state layout the warmed programs bind "
                         "(part of the program identity)")
    pw.add_argument("--kernel", choices=("xla", "pallas"),
                    default="xla",
                    help="tick engine the warmed programs bind (pallas "
                         "needs --layout packed; part of the program "
                         "identity like --layout)")
    pw.add_argument("--compile-cache", default=None, metavar="DIR",
                    help="persistent cache directory (or "
                         "CONSUL_TPU_COMPILE_CACHE)")

    mem_p = sub.add_parser("members", help="cluster members + health")
    mem_p.add_argument("-wan", action="store_true",
                       help="list the WAN server pool")

    rtt_p = sub.add_parser("rtt", help="estimate RTT between two nodes")
    rtt_p.add_argument("node1")
    rtt_p.add_argument("node2", nargs="?")
    rtt_p.add_argument("-wan", action="store_true",
                       help="use WAN server coordinates (<node>.<dc>)")

    kv_p = sub.add_parser("kv", help="KV store operations")
    kv_sub = kv_p.add_subparsers(dest="kv_cmd", required=True)
    g = kv_sub.add_parser("get")
    g.add_argument("key")
    pu = kv_sub.add_parser("put")
    pu.add_argument("key")
    pu.add_argument("value", nargs="?")
    pu.add_argument("--cas", type=int)
    pu.add_argument("--flags", type=int, default=0)
    d = kv_sub.add_parser("delete")
    d.add_argument("key")
    d.add_argument("--recurse", action="store_true")
    ls = kv_sub.add_parser("list")
    ls.add_argument("key", nargs="?")
    ex = kv_sub.add_parser("export")
    ex.add_argument("key", nargs="?")
    im = kv_sub.add_parser("import")
    im.add_argument("file", nargs="?", default="-")

    cat_p = sub.add_parser("catalog", help="catalog queries")
    cat_sub = cat_p.add_subparsers(dest="catalog_cmd", required=True)
    cn = cat_sub.add_parser("nodes")
    cn.add_argument("--near")
    cat_sub.add_parser("services")
    cat_sub.add_parser("datacenters")

    sub.add_parser("info", help="agent and consensus info")

    svc_p = sub.add_parser("services", help="agent service registration")
    svc_sub = svc_p.add_subparsers(dest="services_cmd", required=True)
    sr = svc_sub.add_parser("register")
    sr.add_argument("name")
    sr.add_argument("--id")
    sr.add_argument("--port", type=int, default=0)
    sr.add_argument("--tag", action="append")
    sr.add_argument("--ttl")
    sd = svc_sub.add_parser("deregister")
    sd.add_argument("name", nargs="?")
    sd.add_argument("--id")

    sub.add_parser("sessions", help="list sessions")

    snap_p = sub.add_parser("snapshot", help="save/restore server state")
    snap_sub = snap_p.add_subparsers(dest="snapshot_cmd", required=True)
    ss = snap_sub.add_parser("save")
    ss.add_argument("file")
    sr2 = snap_sub.add_parser("restore")
    sr2.add_argument("file")
    si = snap_sub.add_parser("inspect")
    si.add_argument("file")

    dbg = sub.add_parser("debug", help="capture a debug bundle")
    dbg.add_argument("--output", default="consul-tpu-debug.tar.gz")

    ev_p = sub.add_parser("event", help="fire or list user events")
    ev_sub = ev_p.add_subparsers(dest="event_cmd", required=True)
    ef = ev_sub.add_parser("fire")
    ef.add_argument("name")
    ef.add_argument("payload", nargs="?")
    el = ev_sub.add_parser("list")
    el.add_argument("name", nargs="?")

    w_p = sub.add_parser("watch", help="watch a view for changes")
    w_p.add_argument("--type", required=True,
                     choices=("key", "keyprefix", "services", "nodes",
                              "service", "checks", "event",
                              "agent_service", "connect_roots",
                              "connect_leaf"))
    w_p.add_argument("--param", action="append",
                     help="watch parameter key=value (e.g. key=config/db)")
    w_p.add_argument("--once", action="store_true")
    w_p.add_argument("--rounds", type=int, default=0)
    w_p.add_argument("--wait", default="10s")

    jn = sub.add_parser("join", help="join the agent to a server set")
    jn.add_argument("address", help="server RPC address host:port")

    fl = sub.add_parser("force-leave", help="force a failed member out")
    fl.add_argument("node")

    sub.add_parser("leave", help="gracefully leave and shut down the agent")
    sub.add_parser("version", help="print the version")
    tls_p = sub.add_parser("tls", help="create development TLS material")
    tls_sub = tls_p.add_subparsers(dest="tls_cmd", required=True)
    tc = tls_sub.add_parser("create", help="CA + server cert (dev flow)")
    tc.add_argument("-dir", default=".")
    tc.add_argument("-hostname", default="127.0.0.1")

    conn_p = sub.add_parser("connect", help="connect CA management")
    conn_sub = conn_p.add_subparsers(dest="connect_cmd", required=True)
    ca_p = conn_sub.add_parser("ca")
    ca_sub = ca_p.add_subparsers(dest="ca_cmd", required=True)
    ca_sub.add_parser("get-config")
    ca_sc = ca_sub.add_parser("set-config")
    ca_sc.add_argument("-config-file", required=True)

    ixn_p = sub.add_parser("intention", help="connect intentions")
    ixn_sub = ixn_p.add_subparsers(dest="intention_cmd", required=True)
    ic = ixn_sub.add_parser("create")
    ic.add_argument("source")
    ic.add_argument("destination")
    ic.add_argument("-deny", action="store_true")
    ixn_sub.add_parser("list")
    for verb in ("get", "delete"):
        vp = ixn_sub.add_parser(verb)
        vp.add_argument("id")
    im = ixn_sub.add_parser("match")
    im.add_argument("name")
    im.add_argument("-by", choices=["source", "destination"],
                    default="destination")
    ich = ixn_sub.add_parser("check")
    ich.add_argument("source")
    ich.add_argument("destination")

    acl_p = sub.add_parser("acl", help="ACL bootstrap / policies / tokens")
    acl_sub = acl_p.add_subparsers(dest="acl_cmd", required=True)
    acl_sub.add_parser("bootstrap")
    pol_p = acl_sub.add_parser("policy")
    pol_sub = pol_p.add_subparsers(dest="policy_cmd", required=True)
    pc = pol_sub.add_parser("create")
    pc.add_argument("-name", required=True)
    pc.add_argument("-rules", required=True,
                    help="rules document ('@file' reads a file)")
    pc.add_argument("-description", default="")
    for verb in ("read", "delete"):
        vp = pol_sub.add_parser(verb)
        vp.add_argument("-name", required=True)
    pol_sub.add_parser("list")
    tok_p = acl_sub.add_parser("token")
    tok_sub = tok_p.add_subparsers(dest="token_cmd", required=True)
    tc = tok_sub.add_parser("create")
    tc.add_argument("-description", default="")
    tc.add_argument("-policy-name", action="append", default=[])
    for verb in ("read", "delete"):
        vp = tok_sub.add_parser(verb)
        vp.add_argument("-id", required=True)
    tok_sub.add_parser("list")

    op_p = sub.add_parser("operator", help="operator tooling")
    op_sub = op_p.add_subparsers(dest="operator_cmd", required=True)
    raft_p = op_sub.add_parser("raft")
    raft_sub = raft_p.add_subparsers(dest="raft_cmd", required=True)
    raft_sub.add_parser("list-peers")
    rp = raft_sub.add_parser("remove-peer")
    rp.add_argument("-id", required=True)
    ap_p = op_sub.add_parser("autopilot")
    ap_sub = ap_p.add_subparsers(dest="autopilot_cmd", required=True)
    ap_sub.add_parser("get-config")
    ap_sub.add_parser("health")
    sc = ap_sub.add_parser("set-config")
    sc.add_argument("-cleanup-dead-servers", choices=["true", "false"],
                    default=None)
    sc.add_argument("-server-stabilization-ticks", type=int, default=None)
    sc.add_argument("-max-trailing-logs", type=int, default=None)

    mt = sub.add_parser("maint", help="toggle maintenance mode")
    mt.add_argument("-disable", action="store_true")
    mt.add_argument("-reason", default="")
    mt.add_argument("-service", default="")

    kr = sub.add_parser("keyring", help="gossip keyring management")
    kr.add_argument("-list", action="store_true")
    kr.add_argument("-install", default="")
    kr.add_argument("-use", default="")
    kr.add_argument("-remove", default="")

    mon = sub.add_parser("monitor", help="stream agent logs")
    mon.add_argument("--rounds", type=int, default=1,
                     help="long-poll rounds (0 = forever)")
    mon.add_argument("--wait", default="10s")

    va = sub.add_parser("validate", help="validate a config file")
    va.add_argument("path")

    ln = sub.add_parser(
        "lint",
        help="trace-hygiene static analysis over the device tier")
    ln.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the consul_tpu "
                         "package)")
    ln.add_argument("--allowlist", default=None,
                    help="allowlist TOML (default: the checked-in "
                         "analysis/allowlist.toml)")
    ln.add_argument("--no-allowlist", action="store_true",
                    help="report every finding, ignoring the allowlist")
    ln.add_argument("--verbose", action="store_true",
                    help="also print suppressed findings with the "
                         "allowlist reason that ate each one")

    lk = sub.add_parser("lock", help="run a command under a KV lock")
    lk.add_argument("prefix")
    lk.add_argument("command")
    lk.add_argument("--retries", type=int, default=10)

    ex = sub.add_parser("exec", help="remote execution via KV + events")
    ex.add_argument("command")
    ex.add_argument("--node", default="",
                    help="coordinating node (the submitting agent)")
    ex.add_argument("--target", default="",
                    help="only this node executes (default: all workers)")
    ex.add_argument("--timeout", type=float, default=5.0)

    sub.add_parser("reload", help="trigger a config reload")

    cfg_p = sub.add_parser("config", help="centralized config entries")
    cfg_sub = cfg_p.add_subparsers(dest="config_cmd", required=True)
    cw = cfg_sub.add_parser("write")
    cw.add_argument("file", help="JSON file with Kind/Name (or - for stdin)")
    cw.add_argument("--cas", type=int, default=None)
    cr = cfg_sub.add_parser("read")
    cr.add_argument("-kind", "--kind", required=True)
    cr.add_argument("-name", "--name", required=True)
    cl = cfg_sub.add_parser("list")
    cl.add_argument("-kind", "--kind", default="*")
    cd = cfg_sub.add_parser("delete")
    cd.add_argument("-kind", "--kind", required=True)
    cd.add_argument("-name", "--name", required=True)
    cd.add_argument("--cas", type=int, default=None)

    return p


COMMANDS = {
    "members": cmd_members, "rtt": cmd_rtt, "kv": cmd_kv,
    "catalog": cmd_catalog, "info": cmd_info, "services": cmd_services,
    "sessions": cmd_sessions, "snapshot": cmd_snapshot, "debug": cmd_debug,
    "event": cmd_event, "watch": cmd_watch, "join": cmd_join,
    "force-leave": cmd_force_leave, "leave": cmd_leave, "acl": cmd_acl,
    "intention": cmd_intention, "connect": cmd_connect,
    "version": cmd_version, "tls": cmd_tls,
    "operator": cmd_operator, "maint": cmd_maint, "keyring": cmd_keyring,
    "monitor": cmd_monitor, "validate": cmd_validate, "lock": cmd_lock,
    "exec": cmd_exec, "reload": cmd_reload, "config": cmd_config,
}


def cmd_agent(args) -> int:
    """Boot-from-config (reference command/agent/agent.go, main.go:19) —
    the one subcommand that IS an agent rather than talking to one."""
    from consul_tpu.agent import boot

    overrides = {}
    if args.node is not None:
        overrides["node_name"] = args.node
    if args.server:
        overrides["server"] = True
    if args.data_dir is not None:
        overrides["data_dir"] = args.data_dir
    if args.http_port is not None:
        overrides["http"] = {"host": "127.0.0.1", "port": args.http_port}
    return boot.run(args.config_file, overrides)


def cmd_lint(args) -> int:
    """Static trace-hygiene pass (consul_tpu/analysis). Pure stdlib
    ast — no jax import, no agent, instant anywhere."""
    from consul_tpu import analysis

    try:
        report = analysis.lint_package(
            paths=tuple(args.paths) if args.paths else ("consul_tpu",),
            allowlist_path=args.allowlist,
            use_allowlist=not args.no_allowlist)
    except analysis.AllowlistError as e:
        print(f"allowlist error: {e}", file=sys.stderr)
        return 2
    for f in report.findings:
        print(f.format())
    if args.verbose:
        for f, entry in report.suppressed:
            print(f"allowed: {f.format()}  [{entry.reason}]")
        edges = analysis.package_lock_graph(
            paths=tuple(args.paths) if args.paths else ("consul_tpu",))
        if edges:
            print("lock-order graph (dst acquired while src held):")
            for src, dst, path, line in edges:
                print(f'  "{src}" -> "{dst}"  // {path}:{line}')
    for entry in report.unused_entries:
        print(f"unused allowlist entry: {entry.rule} {entry.path}"
              f"{' ' + entry.symbol if entry.symbol else ''} — remove "
              f"it ({entry.reason})", file=sys.stderr)
    ok = not report.findings and not report.unused_entries
    print(f"{report.n_files} files: {len(report.findings)} finding(s), "
          f"{len(report.suppressed)} allowlisted, "
          f"{len(report.unused_entries)} unused entrie(s)")
    return 0 if ok else 1


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "lint":
        return cmd_lint(args)
    if args.cmd == "agent":
        return cmd_agent(args)
    if args.cmd == "chaos":
        return cmd_chaos(args)
    if args.cmd == "gameday":
        return cmd_gameday(args)
    if args.cmd == "run":
        return cmd_run(args)
    if args.cmd == "prewarm":
        return cmd_prewarm(args)
    if args.cmd == "serve-bench":
        return cmd_serve_bench(args)
    if args.cmd == "trace":
        return cmd_trace(args)
    client = make_client(args)
    try:
        return COMMANDS[args.cmd](client, args)
    except ConnectionError as e:
        print(f"error contacting agent at {args.http_addr}: {e}",
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
