// LZW codec (LSB bit order, 8-bit literals) — the compression used by
// memberlist's compressMsg payloads (reference memberlist/util.go:221-275,
// Go compress/lzw with lzw.LSB, litWidth 8).
//
// Semantics mirrored from the Go implementation: codes 0..255 are
// literals, 256 is CLEAR, 257 is EOF, new table entries start at 258;
// code width starts at 9 bits and grows when the next assigned code
// reaches the current width's capacity; when the table reaches code
// 4095 the encoder emits CLEAR and resets (so streams of any length
// work). The decoder tracks the same schedule, including the KwKwK
// (code == next unassigned entry) case.
//
// C ABI: bytes in, bytes out; returns the output length, -1 on corrupt
// input, -2 when the output buffer is too small (caller retries with a
// bigger buffer).

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kClear = 256;
constexpr uint32_t kEof = 257;
constexpr uint32_t kFirst = 258;
constexpr uint32_t kMaxCode = (1u << 12) - 1;  // 4095

struct BitWriter {
  uint8_t* out;
  long cap;
  long n = 0;
  uint64_t acc = 0;
  int bits = 0;
  bool overflow = false;

  void put(uint32_t code, int width) {
    acc |= static_cast<uint64_t>(code) << bits;
    bits += width;
    while (bits >= 8) {
      if (n >= cap) { overflow = true; return; }
      out[n++] = static_cast<uint8_t>(acc & 0xff);
      acc >>= 8;
      bits -= 8;
    }
  }
  void flush() {
    if (bits > 0) {
      if (n >= cap) { overflow = true; return; }
      out[n++] = static_cast<uint8_t>(acc & 0xff);
      acc = 0;
      bits = 0;
    }
  }
};

struct BitReader {
  const uint8_t* in;
  long len;
  long pos = 0;
  uint64_t acc = 0;
  int bits = 0;

  // Returns code or UINT32_MAX when the stream is exhausted.
  uint32_t get(int width) {
    while (bits < width) {
      if (pos >= len) return UINT32_MAX;
      acc |= static_cast<uint64_t>(in[pos++]) << bits;
      bits += 8;
    }
    uint32_t code = static_cast<uint32_t>(acc & ((1u << width) - 1));
    acc >>= width;
    bits -= width;
    return code;
  }
};

}  // namespace

extern "C" {

long lzw_compress(const uint8_t* in, long n, uint8_t* out, long cap) {
  BitWriter w{out, cap};
  std::unordered_map<uint32_t, uint32_t> table;
  table.reserve(1 << 12);
  uint32_t hi = kEof;           // last assigned code
  int width = 9;
  uint32_t overflow_at = 1u << 9;
  long i = 0;
  if (n > 0) {
    uint32_t saved = in[i++];
    for (; i < n; i++) {
      uint32_t key = (saved << 8) | in[i];
      auto it = table.find(key);
      if (it != table.end()) {
        saved = it->second;
        continue;
      }
      w.put(saved, width);
      saved = in[i];
      // incHi (Go writer.incHi): assign, grow width, or clear+reset.
      hi++;
      if (hi == overflow_at) { width++; overflow_at <<= 1; }
      if (hi == kMaxCode) {
        w.put(kClear, width);
        width = 9;
        hi = kEof;
        overflow_at = 1u << 9;
        table.clear();
      } else {
        table.emplace(key, hi);
      }
    }
    w.put(saved, width);
    // Final assignment may still grow the width before EOF is written
    // (Go increments hi for the pending code at Close).
    hi++;
    if (hi == overflow_at) { width++; overflow_at <<= 1; }
  }
  w.put(kEof, width);
  w.flush();
  if (w.overflow) return -2;
  return w.n;
}

long lzw_decompress(const uint8_t* in, long n, uint8_t* out, long cap) {
  BitReader r{in, n};
  // prefix/suffix chain per code; expansion walks to literals.
  std::vector<uint32_t> prefix(1 << 12, 0);
  std::vector<uint8_t> suffix(1 << 12, 0);
  std::vector<uint8_t> buf;  // reversed expansion scratch
  buf.reserve(1 << 12);

  // The Go reader's schedule (compress/lzw decode): entry `hi` is
  // completed while processing the NEXT code (its first byte becomes
  // known then); `hi` increments unconditionally per code, keeping the
  // width-growth boundaries aligned with the encoder's incHi.
  uint32_t hi = kEof;
  int width = 9;
  uint32_t overflow_at = 1u << 9;
  constexpr uint32_t kInvalid = UINT32_MAX;
  uint32_t last = kInvalid;
  long outn = 0;

  for (;;) {
    uint32_t code = r.get(width);
    if (code == UINT32_MAX) return -1;  // truncated (no EOF)
    if (code == kEof) return outn;
    if (code == kClear) {
      width = 9;
      hi = kEof;
      overflow_at = 1u << 9;
      last = kInvalid;
      continue;
    }

    uint32_t expand_code = code;
    bool kwkwk = false;
    if (code < kClear) {
      // literal
    } else if (code == hi && last != kInvalid) {
      kwkwk = true;          // entry being defined now: last + first(last)
      expand_code = last;
    } else if (code < hi && code >= kFirst) {
      // known composite entry
    } else {
      return -1;             // corrupt stream
    }

    // Expand to bytes (reversed), literals terminate the chain.
    buf.clear();
    uint32_t c = expand_code;
    while (c >= kFirst) {
      buf.push_back(suffix[c]);
      c = prefix[c];
    }
    buf.push_back(static_cast<uint8_t>(c));
    uint8_t first_byte = buf.back();
    if (kwkwk) buf.insert(buf.begin(), first_byte);

    if (outn + static_cast<long>(buf.size()) > cap) return -2;
    for (auto it = buf.rbegin(); it != buf.rend(); ++it) out[outn++] = *it;

    // Complete the pending entry `hi` = expand(last) + first byte of
    // this code's expansion, then advance (unconditionally, mirroring
    // the encoder's per-emit incHi).
    if (last != kInvalid && hi < kMaxCode) {
      prefix[hi] = last;
      suffix[hi] = first_byte;
    }
    last = code;
    hi++;
    if (hi >= overflow_at) {
      if (width < 12) {
        width++;
        overflow_at <<= 1;
      } else {
        // Encoder must send CLEAR before assigning past the table;
        // hold position until it arrives.
        hi--;
      }
    }
  }
}

}  // extern "C"
