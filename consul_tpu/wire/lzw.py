"""LZW (LSB, 8-bit literals) — memberlist's payload compression.

Mirrors Go's ``compress/lzw`` as used by the reference
(memberlist/util.go:221-275: ``lzw.NewWriter(buf, lzw.LSB,
lzwLitWidth=8)``): 9→12-bit codes, CLEAR=256/EOF=257, table reset via
CLEAR when code 4095 is reached.

Two implementations with identical output: the native C++ codec
(native/lzw.cpp, built on first use with g++ and loaded via ctypes —
the framework's hot byte path), and the pure-Python fallback below
(used when no compiler is available; also the cross-check in tests).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_CLEAR, _EOF, _FIRST, _MAX_CODE = 256, 257, 258, (1 << 12) - 1

# ----------------------------------------------------------------------
# Pure-Python reference implementation
# ----------------------------------------------------------------------


def compress_py(data: bytes) -> bytes:
    out = bytearray()
    acc = 0
    nbits = 0

    def put(code: int, width: int):
        nonlocal acc, nbits
        acc |= code << nbits
        nbits += width
        while nbits >= 8:
            out.append(acc & 0xFF)
            acc >>= 8
            nbits -= 8

    table: dict[int, int] = {}
    hi, width, overflow = _EOF, 9, 1 << 9
    if data:
        saved = data[0]
        for x in data[1:]:
            key = (saved << 8) | x
            nxt = table.get(key)
            if nxt is not None:
                saved = nxt
                continue
            put(saved, width)
            saved = x
            hi += 1
            if hi == overflow:
                width += 1
                overflow <<= 1
            if hi == _MAX_CODE:
                put(_CLEAR, width)
                hi, width, overflow = _EOF, 9, 1 << 9
                table.clear()
            else:
                table[key] = hi
        put(saved, width)
        hi += 1
        if hi == overflow:
            width += 1
            overflow <<= 1
    put(_EOF, width)
    if nbits:
        out.append(acc & 0xFF)
    return bytes(out)


def decompress_py(data: bytes) -> bytes:
    pos = acc = nbits = 0

    def get(width: int) -> Optional[int]:
        nonlocal pos, acc, nbits
        while nbits < width:
            if pos >= len(data):
                return None
            acc |= data[pos] << nbits
            pos += 1
            nbits += 8
        code = acc & ((1 << width) - 1)
        acc >>= width
        nbits -= width
        return code

    prefix = [0] * (1 << 12)
    suffix = bytearray(1 << 12)
    out = bytearray()
    hi, width, overflow, last = _EOF, 9, 1 << 9, None
    while True:
        code = get(width)
        if code is None:
            raise ValueError("truncated LZW stream")
        if code == _EOF:
            return bytes(out)
        if code == _CLEAR:
            hi, width, overflow, last = _EOF, 9, 1 << 9, None
            continue
        kwkwk = False
        expand = code
        if code < _CLEAR:
            pass
        elif code == hi and last is not None:
            kwkwk, expand = True, last
        elif not (_FIRST <= code < hi):
            raise ValueError(f"corrupt LZW stream (code {code}, hi {hi})")
        chunk = bytearray()
        c = expand
        while c >= _FIRST:
            chunk.append(suffix[c])
            c = prefix[c]
        chunk.append(c)
        first_byte = chunk[-1]
        if kwkwk:
            chunk.insert(0, first_byte)
        out.extend(reversed(chunk))
        if last is not None and hi < _MAX_CODE:
            prefix[hi] = last
            suffix[hi] = first_byte
        last = code
        hi += 1
        if hi >= overflow:
            if width < 12:
                width += 1
                overflow <<= 1
            else:
                hi -= 1


# ----------------------------------------------------------------------
# Native codec (ctypes over native/lzw.cpp)
# ----------------------------------------------------------------------

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "liblzw.so")
_lib = None
_lock = threading.Lock()


def _load_native():
    """Build (once) and load the native codec; None when unavailable.
    Failure is cached so a compiler-less host pays the probe once."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib if _lib is not False else None
        src = os.path.join(_NATIVE_DIR, "lzw.cpp")
        if not os.path.exists(_SO_PATH) or (
            os.path.getmtime(_SO_PATH) < os.path.getmtime(src)
        ):
            try:
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     "-o", _SO_PATH, src],
                    check=True, capture_output=True, timeout=120,
                )
            except (OSError, subprocess.SubprocessError):
                _lib = False
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            _lib = False
            return None
        for fn in (lib.lzw_compress, lib.lzw_decompress):
            fn.restype = ctypes.c_long
            fn.argtypes = [ctypes.c_char_p, ctypes.c_long,
                           ctypes.POINTER(ctypes.c_uint8), ctypes.c_long]
        _lib = lib
        return lib


def native_available() -> bool:
    return _load_native() is not None


def _call_native(fn, data: bytes, cap: int) -> bytes:
    while True:
        buf = (ctypes.c_uint8 * cap)()
        n = fn(data, len(data), buf, cap)
        if n == -2:
            cap *= 4
            continue
        if n < 0:
            raise ValueError("corrupt LZW stream (native)")
        return bytes(buf[:n])


def compress(data: bytes) -> bytes:
    lib = _load_native()
    if lib is None:
        return compress_py(data)
    return _call_native(lib.lzw_compress, data, 2 * len(data) + 1024)


def decompress(data: bytes) -> bytes:
    lib = _load_native()
    if lib is None:
        return decompress_py(data)
    return _call_native(lib.lzw_decompress, data, 8 * len(data) + 1024)
