"""Cluster-wide gossip-key rotation via internal queries.

Mirrors the reference KeyManager (reference serf/keymanager.go:
InstallKey/UseKey/RemoveKey/ListKeys issue the internal serf queries
``_serf_install-key`` / ``use-key`` / ``remove-key`` / ``list-keys``,
serf/internal_query.go; every member applies the operation to its local
keyring and acks, and the manager aggregates per-node acks/errors into a
KeyResponse). The install→use→remove sequence is the flag-day-free
rotation the multi-key ring exists for (wire/keyring.py).

Here the "cluster" is the set of keyring holders at the host boundary —
the simulation's own ring plus every bridge-attached agent's — and
query distribution is pluggable: ``reachable()`` names the members the
query round actually reached (wire it to the simulated query plane's
response tally, or leave as everyone for direct use). Members the query
misses simply don't apply the operation — exactly the partial-failure
surface the reference reports via NumErr/NumResp.
"""

from __future__ import annotations

import base64
from typing import Callable, Optional

from consul_tpu.wire.keyring import Keyring, validate_key


class KeyResponse:
    """reference serf/keymanager.go KeyResponse."""

    def __init__(self):
        self.messages: dict[str, str] = {}   # node -> error message
        self.num_nodes = 0
        self.num_resp = 0
        self.num_err = 0
        self.keys: dict[str, int] = {}       # b64 key -> holders

    @property
    def ok(self) -> bool:
        return self.num_err == 0 and self.num_resp == self.num_nodes


def _b64(key: bytes) -> str:
    return base64.b64encode(key).decode()


class KeyManager:
    def __init__(self, members: dict[str, Keyring],
                 reachable: Optional[Callable[[], set]] = None):
        self.members = members
        self._reachable = reachable or (lambda: set(members))

    def _query(self, apply) -> KeyResponse:
        """One internal query round: every reachable member applies and
        acks; errors are collected per node (keymanager.go
        streamKeyResp)."""
        resp = KeyResponse()
        resp.num_nodes = len(self.members)
        reached = self._reachable()
        for name, ring in self.members.items():
            if name not in reached:
                continue
            resp.num_resp += 1
            try:
                apply(ring)
            except (ValueError, KeyError) as e:
                resp.num_err += 1
                resp.messages[name] = str(e)
        return resp

    def install_key(self, key: bytes) -> KeyResponse:
        """Phase 1: every member learns the key (can decrypt) without
        using it to encrypt (keymanager.go InstallKey)."""
        validate_key(key)
        return self._query(lambda ring: ring.install(key))

    def use_key(self, key: bytes) -> KeyResponse:
        """Phase 2: switch the primary. Members that never got the key
        error out, which the caller must treat as a failed rotation
        (keymanager.go UseKey -> keyring.UseKey)."""
        return self._query(lambda ring: ring.use(key))

    def remove_key(self, key: bytes) -> KeyResponse:
        """Phase 3: retire the old key; removing a primary errors
        (keyring.go RemoveKey)."""
        return self._query(lambda ring: ring.remove(key))

    def list_keys(self) -> KeyResponse:
        """Aggregate per-key holder counts (keymanager.go ListKeys) —
        the operator's view of rotation progress."""
        resp = KeyResponse()
        resp.num_nodes = len(self.members)
        reached = self._reachable()
        for name, ring in self.members.items():
            if name not in reached:
                continue
            resp.num_resp += 1
            for k in ring.keys:
                resp.keys[_b64(k)] = resp.keys.get(_b64(k), 0) + 1
        return resp
