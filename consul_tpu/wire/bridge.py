"""The tpu-sim transport seam: real agents on the simulated fabric.

This is the BASELINE-named integration boundary: the reference's
``memberlist.Transport`` is a six-method interface (reference
vendor/github.com/hashicorp/memberlist/transport.go:27-65) behind which
an in-process mock network already exists (mock_transport.go:12-121) —
the model for this module. A *real* (non-simulated) agent gets a
:class:`BridgeTransport` whose methods mirror that interface:

    final_advertise_addr(ip, port)   FinalAdvertiseAddr
    write_to(buf, addr) -> ts        WriteTo (best-effort packets)
    packet_ch                        PacketCh (queue of Packet)
    dial_timeout(addr, timeout)      DialTimeout (reliable streams)
    stream_ch                        StreamCh (queue of Stream)
    shutdown()                       Shutdown

and whose wire format is memberlist's own: msgType-framed msgpack
bodies, compound batching, optional stream encryption — all via
wire/codec.py. The agent literally joins the simulated cluster: its
packets merge into sim views, sim nodes probe it, its liveness is
decided by whether it answers, and its Vivaldi coordinate converges
against the sim's planted latency model.

Seat semantics. Each attached agent claims a **seat** (a node index)
in the simulated world; ``SimState.external[seat]`` is set so the
simulation answers probes *to* the seat from ground truth but never
originates protocol traffic *for* it (models/state.py) — the real
agent does that itself through this bridge. Concretely, per
:meth:`PacketBridge.step` (host-side, once per tick — the batched
host<->device boundary of SURVEY §7, precedent: the reference's 5 s
coordinate batching, agent/consul/coordinate_endpoint.go:42-53):

  - inbound agent packets are decoded and staged: membership facts
    join into the receiving seat's device view row (and the agent's
    own-alive announcements bump ``own_inc[seat]``), so the sim
    epidemic spreads them;
  - the agent's announced coordinate is written into the seat's device
    Vivaldi row, so sim probes of the seat feed on its real coordinate;
  - sim-side probes of the agent are emitted as real ping packets from
    neighbor addresses; unanswered probes eventually flip the seat's
    ground truth to dead, so the sim detects a crashed agent
    organically (no special-casing);
  - neighbor gossip is emitted to the agent as compound
    alive/suspect/dead messages, and push-pull streams answer with the
    seat's neighborhood state (pushPullHeader/pushNodeState schema,
    net.go:145-168).

Time and RTT. The bridge runs on **simulated time** (tick *
tick_ms/1000 seconds). ``write_to`` returns the send timestamp and
reply packets carry ``timestamp = send + model_rtt`` — exactly the
Transport contract's RTT mechanism (transport.go:36-43: the timestamps
exist "to help make accurate RTT measurements during probes"), with
the RTT drawn from the same planted-world latency model the simulation
itself uses, so the agent's Vivaldi solves the same geometry.
"""

from __future__ import annotations

import dataclasses
import queue
from typing import Optional

import jax.numpy as jnp
import msgpack
import numpy as np

from consul_tpu.ops import merge, scaling, topology, vivaldi
from consul_tpu.wire import codec
from consul_tpu.wire.codec import MessageType
from consul_tpu.wire.keyring import Keyring

# memberlist nodeStateType values (state.go:754-760); distinct from the
# sim's merge-lattice codes, translated at the seam.
WIRE_ALIVE = 0
WIRE_SUSPECT = 1
WIRE_DEAD = 2
WIRE_LEFT = 3

_TO_WIRE = {merge.ALIVE: WIRE_ALIVE, merge.SUSPECT: WIRE_SUSPECT,
            merge.DEAD: WIRE_DEAD, merge.LEFT: WIRE_LEFT}
_FROM_WIRE = {v: k for k, v in _TO_WIRE.items()}

# Protocol version vector (pushNodeState.Vsn, net.go:166: [pmin, pmax,
# pcur, dmin, dmax, dcur]).
VSN = [1, 5, 1, 2, 5, 4]


def _node_state(seat: int, incarnation: int, state: int) -> dict:
    """One pushNodeState body (net.go:158-168)."""
    return {
        "Name": seat_name(seat), "Addr": seat_name(seat).encode(),
        "Port": 7946, "Meta": b"", "Incarnation": incarnation,
        "State": state, "Vsn": bytes(VSN),
    }


def seat_name(i: int) -> str:
    return f"sim-{i}"


def seat_addr(i: int) -> str:
    return f"{seat_name(i)}:7946"


def addr_to_seat(addr: str) -> int:
    host = addr.split(":", 1)[0]
    if not host.startswith("sim-"):
        raise ValueError(f"not a sim address: {addr!r}")
    return int(host[4:])


def encode_coordinate(vec, height, error, adjustment) -> bytes:
    """Ping-ack coordinate payload (serf/ping_delegate.go:28-45 encodes
    the serf coordinate.Coordinate struct as the ack payload)."""
    return codec._pack_go({
        "Vec": [float(x) for x in vec], "Error": float(error),
        "Adjustment": float(adjustment), "Height": float(height),
    })


def decode_coordinate(payload: bytes) -> Optional[dict]:
    if not payload:
        return None
    if isinstance(payload, str):
        # decode_message maps legacy-raw to str via surrogateescape;
        # recover the original payload bytes the same way.
        payload = payload.encode("utf-8", "surrogateescape")
    return msgpack.unpackb(payload, raw=False,
                           unicode_errors="surrogateescape")


class NameConflict(ValueError):
    """A live member already holds this name and the cluster majority
    agrees (reference serf.go:1413-1486 name-conflict resolution)."""


@dataclasses.dataclass
class Packet:
    """transport.go:10-22."""
    buf: bytes
    from_addr: str
    timestamp: float  # simulated seconds


class Stream:
    """In-memory reliable bidirectional message stream — the net.Pipe of
    mock_transport.go:117-120. ``send``/``recv`` move whole frames (the
    codec's stream framing handles encryption)."""

    def __init__(self):
        self._a: queue.Queue = queue.Queue()
        self._b: queue.Queue = queue.Queue()
        self.closed = False

    def peer(self) -> "Stream":
        p = Stream.__new__(Stream)
        p._a, p._b = self._b, self._a
        p.closed = False
        return p

    def send(self, frame: bytes):
        self._a.put(frame)

    def recv(self, timeout: float = 1.0) -> bytes:
        return self._b.get(timeout=timeout)

    def close(self):
        self.closed = True


class BridgeTransport:
    """The agent-facing six-method Transport (transport.go:27-65)."""

    def __init__(self, bridge: "PacketBridge", seat: int):
        self._bridge = bridge
        self.seat = seat
        self.addr = seat_addr(seat)
        self.packet_ch: queue.Queue = queue.Queue()
        self.stream_ch: queue.Queue = queue.Queue()
        self.down = False

    def final_advertise_addr(self, ip: str = "", port: int = 0):
        """FinalAdvertiseAddr: the seat's simulated address wins over
        any user-configured value (net_transport.go would consult the
        bound socket here)."""
        return seat_name(self.seat), 7946

    def write_to(self, buf: bytes, addr: str) -> float:
        """Best-effort packet send; returns the transmit timestamp (in
        simulated seconds) for RTT measurement."""
        if self.down:
            raise RuntimeError("transport is shut down")
        now = self._bridge.now()
        self._bridge._inbound(self.seat, buf, addr, now)
        return now

    def dial_timeout(self, addr: str, timeout: float = 1.0) -> Stream:
        if self.down:
            raise RuntimeError("transport is shut down")
        return self._bridge._dial(self.seat, addr)

    def shutdown(self):
        """The agent's process is gone: its seat stops answering and
        the simulated cluster is left to detect the failure (the
        reference cluster likewise only learns via SWIM)."""
        self.down = True
        self._bridge._agent_down(self.seat)


class PacketBridge:
    """Wires external agents into a running :class:`Simulation`.

    One instance per simulated DC; drive it with ``bridge.step()``
    after every ``sim`` tick (or use :meth:`run`)."""

    def __init__(self, sim, keyring: Optional[Keyring] = None,
                 probe_miss_limit: int = 2):
        self.sim = sim
        self.keyring = keyring
        self.probe_miss_limit = probe_miss_limit
        self.transports: dict[int, BridgeTransport] = {}
        # Per-seat probe bookkeeping (host-side ints, sim-time ticks).
        self._next_probe: dict[int, int] = {}
        self._pending: dict[int, tuple[int, int]] = {}  # seat -> (seq, deadline)
        self._misses: dict[int, int] = {}
        self._seq = 0
        # Staged device writes, applied once per step.
        self._stage_view: list[tuple[int, int, int]] = []  # (row, col, key)
        self._stage_inc: dict[int, int] = {}
        self._stage_coord: dict[int, dict] = {}
        self._stage_alive: dict[int, bool] = {}
        # Streams dialed but not yet answered: (from, to, stream,
        # deadline_tick).
        self._pending_streams: list = []
        # Host-side copy of the offset table (no per-fact transfers).
        self._off = np.asarray(sim.topo.off)
        # Serf user events (needs a serf-level driver, cluster.
        # SerfSimulation): fired-event staging, the string<->int name
        # registry (the sim's event plane keys names as 8-bit ints —
        # models/serf.py make_event_key; a documented narrowing), and
        # per-agent delivered-event dedup for the outbound feed.
        self._stage_fired: list[tuple[int, int]] = []   # (seat, name_int)
        # Serf queries across the seam (serf/query.go): agent-fired
        # queries stage into the device plane; agent responses to
        # sim-origin queries tally into q_acks/q_resps; and the tracker
        # keeps the per-responder names + payload bytes the device
        # plane's counts cannot carry (the reference's QueryResponse
        # acks/responses channels, host-side).
        self._stage_query: list[tuple[int, int]] = []   # (seat, name_int)
        self._stage_qtally: list[tuple[int, bool]] = []  # (origin, is_resp)
        self._known_queries: dict[tuple, None] = {}     # (name, ltime)
        self._query_names: dict[int, str] = {}
        self._query_name_ids: dict[str, int] = {}   # reverse map (O(1))
        self._query_payloads: dict[int, bytes] = {}
        # (ltime, name_int) -> {"acks": [member], "responses":
        #   {member: payload}, "origin_seat": int|None}
        self.query_tracker: dict[tuple[int, int], dict] = {}
        self._event_names: dict[int, str] = {}
        self._event_name_ids: dict[str, int] = {}   # reverse map (O(1))
        # (evicted-name, newly-registered-name) pairs, recorded when a
        # new name takes over a least-recently-used id under full
        # occupancy — the NEW name holds the id from then on.
        self.collisions: list[tuple[str, str]] = []
        # The sim plane stores only packed keys; payloads ride this
        # host-side registry (latest per name slot) across the seam.
        self._event_payloads: dict[int, bytes] = {}
        # (name_int, ltime) pairs already fired or echoed (bounded).
        self._known_events: dict[tuple, None] = {}
        # Bounded per-agent delivered-key dedup (insertion-ordered; the
        # sim's own retention is ltime-bucketed, so old keys can never
        # redeliver once evicted here either).
        self._delivered_events: dict[int, dict] = {}
        # Host-side queue bound: the reference's dynamic depth limit
        # max(2N, MinQueueDepth) (getQueueMax, serf/serf.go:1612-1624)
        # guards these unbounded-in-Go structures; 2x for _known_events
        # which holds two insert sites' worth.
        scfg = sim.cfg.serf
        # A computed limit of 0 (min_queue_depth=0 with the unlimited
        # max_queue_depth=0 default) must mean "unbounded" here — an
        # empty dedup dict would re-deliver every event each tick and
        # feed the agent-echo loop this buffer exists to break — so 0
        # falls back to the Consul MinQueueDepth floor. A deliberately
        # small nonzero configured cap is respected.
        self._queue_max = scaling.queue_max_depth(
            scfg.max_queue_depth, scfg.min_queue_depth, sim.cfg.n
        ) or 4096

    # ------------------------------------------------------------------
    # Attachment
    # ------------------------------------------------------------------
    def attach(self, seat: int, replace: bool = False) -> BridgeTransport:
        """Claim ``seat`` for an external agent. The seat's ground truth
        becomes alive (the process exists) and ``external`` is set so
        the sim stops originating protocol traffic for it.

        Claiming a seat whose name is *currently held by a live in-sim
        member* is a name conflict; it resolves the reference's way
        (serf.go:1413-1486 handleNodeConflict -> resolveNodeConflict:
        query the cluster, majority keeps the name, the minority
        claimant shuts down): the seat's trackers vote with their
        current beliefs, and a majority-alive verdict rejects the
        newcomer with :class:`NameConflict`. A majority believing the
        seat dead/left means the cluster has moved on — the newcomer
        wins the name (the restarted-agent takeover case).
        ``replace=True`` skips the vote: an explicit operator takeover
        of a simulated member's seat."""
        if seat in self.transports:
            raise ValueError(f"seat {seat} already attached")
        st = self.sim.swim_state
        if not replace and bool(st.alive_truth[seat]) \
                and not bool(st.external[seat]) and not bool(st.left[seat]):
            votes_alive = 0
            n = self.sim.cfg.n
            view = np.asarray(st.view_key)
            up = np.asarray(st.alive_truth & ~st.left)
            voters = 0
            for j in range(self._off.shape[0]):
                r = (seat - int(self._off[j])) % n
                if not up[r]:
                    continue
                # seat sits at column j of r's view: r + off[j] == seat.
                voters += 1
                if merge.key_status_int(int(view[r, j])) == merge.ALIVE:
                    votes_alive += 1
            if voters and votes_alive * 2 > voters:
                raise NameConflict(
                    f"seat {seat} is held by a live member "
                    f"({votes_alive}/{voters} trackers vote alive)"
                )
        mask = np.zeros(self.sim.cfg.n, bool)
        mask[seat] = True
        m = jnp.asarray(mask)
        self.sim.set_swim_state(st._replace(
            external=st.external | m,
            alive_truth=st.alive_truth | m,
            left=st.left & ~m,
        ))
        t = BridgeTransport(self, seat)
        self.transports[seat] = t
        self._next_probe[seat] = int(self.sim.swim_state.t) + 1
        self._misses[seat] = 0
        return t

    def now(self) -> float:
        g = self.sim.cfg.gossip
        return float(int(self.sim.swim_state.t)) * g.tick_ms / 1000.0

    def _model_rtt(self, a: int, b: int) -> float:
        return float(topology.true_rtt(self.sim.world, a, b))

    def _seat_of(self, addr_or_name: str) -> int:
        """Parse and range-check a sim address; raises ValueError for
        seats outside the world (a real probe of a nonexistent address
        times out — it must never alias onto a live node via gather
        clamping or modulo wrap)."""
        seat = addr_to_seat(addr_or_name)
        if not 0 <= seat < self.sim.cfg.n:
            raise ValueError(f"seat {seat} outside world of {self.sim.cfg.n}")
        return seat

    # ------------------------------------------------------------------
    # Inbound: agent -> sim
    # ------------------------------------------------------------------
    def _inbound(self, from_seat: int, buf: bytes, addr: str, sent: float):
        try:
            to_seat = self._seat_of(addr)
        except ValueError:
            return  # not a sim address / out of range: dropped
        rtt = self._model_rtt(from_seat, to_seat)
        if to_seat in self.transports:
            # Agent-to-agent traffic: a real transport delivers the raw
            # packet to the peer's PacketCh (mock_transport.go WriteTo);
            # the bridge must not answer on a live agent's behalf.
            self._deliver(to_seat, buf, seat_addr(from_seat), sent + rtt)
            return
        try:
            msgs = codec.decode_packet(buf, keyring=self.keyring)
        except ValueError:
            return  # undecodable packet: best-effort transport drops it
        for mtype, body in msgs:
            try:
                self._handle_msg(from_seat, to_seat, mtype, body, sent, rtt)
            except (ValueError, KeyError, TypeError):
                # Malformed-but-decodable message (bad field, non-sim
                # target, missing SeqNo): best-effort packets drop, they
                # never propagate into the agent's send path.
                continue

    def _bounded_insert(self, d: dict, key, value=None, mult: int = 2):
        """Insert with the host-side queue bound (getQueueMax
        discipline, serf/serf.go:1612-1624): oldest entries evict."""
        d[key] = value
        while len(d) > mult * self._queue_max:
            d.pop(next(iter(d)))

    def _register_name(self, registry: dict, rev: dict, payloads: dict,
                       name: str, payload: bytes) -> tuple[int, bool]:
        """8-bit name-space registration shared by the event and query
        planes (the sim keys names as ints — a documented narrowing).
        Ids are DYNAMICALLY allocated: a known name keeps its id (an
        O(1) reverse-map hit, LRU-touched so recency tracks USE, and
        its payload refreshes — latest fire wins, as before); a new
        name takes the lowest free id; with all 256 ids held, the
        least-recently-USED name is evicted (recorded in
        ``self.collisions``). This uses the full id space — the
        previous crc32 hashing collided at the ~20-name birthday
        bound. Host-side dedup (`_known_events`/`_known_queries`) keys
        on the true NAME, not the id, so an evicted name's lingering
        retransmissions re-register under a fresh id without re-firing
        already-seen Lamport times. Residual narrowing: the device
        plane still sees at most 256 distinct names concurrently."""
        name_int = rev.get(name)
        if name_int is not None:
            payloads[name_int] = payload
            del registry[name_int]       # LRU touch: re-insert at tail
            registry[name_int] = name
            return name_int, False
        evicted_now = False
        if len(registry) < 256:
            name_int = next(i for i in range(256) if i not in registry)
        else:
            name_int, evicted = next(iter(registry.items()))
            del registry[name_int]
            del rev[evicted]
            self.collisions.append((evicted, name))
            evicted_now = True
        registry[name_int] = name
        rev[name] = name_int
        payloads[name_int] = payload
        return name_int, evicted_now

    def _track_query(self, lt: int, name_int: int) -> dict:
        rec = self.query_tracker.get((lt, name_int))
        if rec is None:
            rec = {"acks": [], "responses": {}, "origin_seat": None}
            self._bounded_insert(self.query_tracker, (lt, name_int), rec)
        return rec

    def _handle_msg(self, from_seat, to_seat, mtype, body, sent, rtt):
        if mtype == MessageType.PING:
            # Answer on behalf of the sim node, ack payload = its
            # coordinate (ping_delegate.go:28-45); the ack's timestamp
            # carries the model RTT (see module docstring).
            st = self.sim.swim_state
            if not bool(st.alive_truth[to_seat]) or \
                    bool(st.left[to_seat]):
                return
            v = st.viv
            payload = encode_coordinate(
                np.asarray(v.vec[to_seat]), float(v.height[to_seat]),
                float(v.error[to_seat]), float(v.adjustment[to_seat]),
            )
            ack = codec.encode_message(
                MessageType.ACK_RESP,
                {"SeqNo": body["SeqNo"], "Payload": payload},
            )
            self._deliver(from_seat, codec.encode_packet([ack]),
                          seat_addr(to_seat), sent + rtt)
        elif mtype == MessageType.ACK_RESP:
            # The agent answered a sim-side probe: alive, and its
            # payload refreshes the seat's device coordinate.
            pend = self._pending.get(from_seat)
            if pend is not None and body["SeqNo"] == pend[0]:
                del self._pending[from_seat]
                self._misses[from_seat] = 0
            coord = decode_coordinate(body.get("Payload", b""))
            if coord is not None:
                self._stage_coord[from_seat] = coord
        elif mtype in (MessageType.ALIVE, MessageType.SUSPECT,
                       MessageType.DEAD):
            status = {MessageType.ALIVE: merge.ALIVE,
                      MessageType.SUSPECT: merge.SUSPECT,
                      MessageType.DEAD: merge.DEAD}[mtype]
            self._merge_fact(to_seat, body["Node"],
                             body["Incarnation"], status)
            # Mirror the fact into the sender's own seat row too: the
            # agent would not gossip what it does not believe, and the
            # seat's device view row is what sim-initiated push-pulls
            # read as "the agent's state".
            self._merge_fact(from_seat, body["Node"],
                             body["Incarnation"], status)
        elif mtype == MessageType.USER:
            # Serf envelope (serf rides memberlist user messages).
            stype, sbody = codec.decode_serf_message(body.get("Raw", b""))
            if stype == codec.SERF_USER_EVENT and \
                    self.sim.serf_state is not None:
                # Dedup across retransmissions AND the bridge's own
                # outbound echoes: a serf agent retransmits each event
                # several times and re-gossips what it receives; only
                # the first (name, ltime) sighting fires into the sim,
                # or one event would re-fire at fresh Lamport times
                # forever (an unbounded feedback loop).
                ev_name = str(sbody.get("Name", ""))
                name_int, _ = self._register_name(
                    self._event_names, self._event_name_ids,
                    self._event_payloads, ev_name,
                    codec.as_bytes(sbody.get("Payload", b"") or b""))
                # Dedup keys on the true NAME (not the 8-bit id): an
                # id reassigned after eviction must never alias another
                # name's Lamport times.
                ek = (ev_name, int(sbody.get("LTime", 0)))
                if ek in self._known_events:
                    return
                self._bounded_insert(self._known_events, ek)
                self._stage_fired.append((from_seat, name_int))
            elif stype == codec.SERF_QUERY and \
                    self.sim.serf_state is not None:
                # An attached agent fires a query (messageQueryType,
                # serf/messages.go): stage it into the device plane so
                # the epidemic carries it; dedup retransmissions like
                # user events.
                q_name = str(sbody.get("Name", ""))
                name_int, _ = self._register_name(
                    self._query_names, self._query_name_ids,
                    self._query_payloads, q_name,
                    codec.as_bytes(sbody.get("Payload", b"") or b""))
                qk = (q_name, int(sbody.get("LTime", 0)))
                if qk in self._known_queries:
                    return
                self._bounded_insert(self._known_queries, qk)
                self._stage_query.append((from_seat, name_int))
            elif stype == codec.SERF_QUERY_RESPONSE and \
                    self.sim.serf_state is not None:
                # An agent answers a sim-origin query addressed to the
                # origin's seat (messageQueryResponseType; Flags bit 0
                # marks a delivery ack, serf/query.go queryFlagAck).
                # Tally into the device counters and keep the
                # per-responder name + payload host-side.
                from consul_tpu.models import serf as serf_mod

                qid = int(sbody.get("ID", 0))
                s = self.sim.serf_state
                slot = serf_mod.query_slot(s, to_seat, qid)
                if slot < 0:
                    return  # closed or stale: drop, like the reference
                lt, name_int = qid >> 9, (qid >> 1) & 0xFF
                frm = str(sbody.get("From", "")) or seat_name(from_seat)
                rec = self._track_query(lt, name_int)
                rec["origin_seat"] = to_seat
                rec["slot"] = slot
                if int(sbody.get("Flags", 0)) & 1:
                    if frm not in rec["acks"]:
                        rec["acks"].append(frm)
                        self._stage_qtally.append((to_seat, slot, False))
                elif frm not in rec["responses"]:
                    rec["responses"][frm] = codec.as_bytes(
                        sbody.get("Payload", b"") or b"")
                    self._stage_qtally.append((to_seat, slot, True))
        elif mtype == MessageType.INDIRECT_PING:
            # Relay: target reachability from ground truth; ack or nack
            # back to the requester (net.go handleIndirectPing:491).
            raw_t = body["Target"]
            target = self._seat_of(
                codec.as_bytes(raw_t).decode("utf-8", "surrogateescape")
                if not isinstance(raw_t, str) else raw_t)
            stt = self.sim.swim_state
            up = bool(stt.alive_truth[target]) and \
                not bool(stt.left[target])
            rtt2 = self._model_rtt(to_seat, target)
            if up:
                ack = codec.encode_message(
                    MessageType.ACK_RESP, {"SeqNo": body["SeqNo"],
                                           "Payload": b""})
                self._deliver(from_seat, codec.encode_packet([ack]),
                              seat_addr(to_seat), sent + rtt + rtt2)
            elif body.get("Nack"):
                nack = codec.encode_message(
                    MessageType.NACK_RESP, {"SeqNo": body["SeqNo"]})
                self._deliver(from_seat, codec.encode_packet([nack]),
                              seat_addr(to_seat), sent + rtt + rtt2)

    def _subject_col(self, row: int, subject: int) -> int:
        """Host-side column lookup (no device chatter per fact)."""
        topo = self.sim.topo
        d = (subject - row) % self.sim.cfg.n
        if d == 0:
            return topology.SELF
        if topo.dense:
            return d - 1
        off = self._off
        c = int(np.searchsorted(off, d))
        if c < off.shape[0] and off[c] == d:
            return c
        return topology.ABSENT

    def _merge_fact(self, to_seat: int, node: str, inc: int, status: int):
        """Stage a membership fact into the receiving seat's view row
        (the receiver-side delivery of a gossiped message)."""
        try:
            subject = self._seat_of(node)
        except ValueError:
            return  # fact about a node outside the simulated world
        if subject in self.transports and status == merge.ALIVE and \
                node == seat_name(subject):
            # An agent's own-alive announcement refreshes its seat's
            # incarnation (aliveNode on self, state.go:868-…).
            self._stage_inc[subject] = max(
                self._stage_inc.get(subject, 0), int(inc))
        col = self._subject_col(to_seat, subject)
        if col < 0:
            return  # receiver does not track the subject (partial view)
        self._stage_view.append(
            (to_seat, col, merge.make_key_int(inc, status)))

    # ------------------------------------------------------------------
    # Streams: push-pull (net.go:777-1070)
    # ------------------------------------------------------------------
    def _dial(self, from_seat: int, addr: str) -> Stream:
        to_seat = self._seat_of(addr)
        s = Stream()
        peer = s.peer()
        if to_seat in self.transports:
            # Dialing another attached agent: the stream goes to that
            # agent's StreamCh — the bridge never answers on a live
            # agent's behalf (same invariant as the packet path).
            self.transports[to_seat].stream_ch.put(peer)
            return s
        # The sim side of the stream is serviced at step() once the
        # caller's frame arrives (streams are "more expensive ...
        # infrequent", transport.go:50-54); unanswered dials expire
        # after a generous window.
        deadline = int(self.sim.swim_state.t) + 50
        self._pending_streams.append((from_seat, to_seat, peer, deadline))
        return s

    def _serve_stream(self, from_seat: int, to_seat: int, stream: Stream):
        """Answer one push-pull exchange on the sim side: read the
        agent's state, stage its merge, reply with the seat's
        neighborhood state (sendLocalState/mergeRemoteState).
        Returns True when the exchange completed (or was malformed),
        False when the caller's frame has not arrived yet."""
        try:
            frame = stream.recv(timeout=0)
        except queue.Empty:
            return False
        try:
            buf = codec.decode_stream_frame(frame, self.keyring)
            _, remote, _ = codec.decode_push_pull(buf)
        except ValueError:
            return True  # malformed: consumed, no reply
        for nstate in remote:
            self._merge_fact(
                to_seat, nstate["Name"], nstate["Incarnation"],
                _FROM_WIRE.get(nstate["State"], merge.SUSPECT),
            )
        # Reply: the dialed seat's own fact, plus the *caller's*
        # neighborhood. The reference replies with its full member map
        # (net.go:824-860); the sparse plane's equivalent of "the part
        # of the map the newcomer needs" is the caller's own view row —
        # the seats it will track, which by offset symmetry are exactly
        # the seats that track *it*, i.e. the audience its join
        # announcement must reach. Statuses come from the seat
        # directory (ground truth + incarnation), the converged
        # cluster's answer.
        st = self.sim.swim_state
        states = [self._push_node_state(to_seat)]
        topo = self.sim.topo
        off = self._off
        n = self.sim.cfg.n
        incs = np.asarray(st.own_inc)
        up = np.asarray(st.alive_truth & ~st.left)
        for c in range(topo.degree):
            j = (from_seat + int(off[c])) % n
            states.append(_node_state(
                j, int(incs[j]), WIRE_ALIVE if up[j] else WIRE_DEAD))
        reply = codec.encode_push_pull(states)
        stream.send(codec.encode_stream_frame(reply, self.keyring))
        return True

    def _push_node_state(self, seat: int) -> dict:
        st = self.sim.swim_state
        if bool(st.left[seat]):
            wire = WIRE_LEFT
        elif bool(st.alive_truth[seat]):
            wire = WIRE_ALIVE
        else:
            wire = WIRE_DEAD
        return _node_state(seat, int(st.own_inc[seat]), wire)

    # ------------------------------------------------------------------
    # Outbound: sim -> agent
    # ------------------------------------------------------------------
    def _deliver(self, seat: int, buf: bytes, from_addr: str, ts: float):
        t = self.transports.get(seat)
        if t is not None and not t.down:
            t.packet_ch.put(Packet(buf, from_addr, ts))

    def _agent_down(self, seat: int):
        self._stage_alive[seat] = False

    def _emit_probes_and_gossip(self):
        """Sim-side traffic toward each attached agent: probes on the
        seat's probe cadence from a rotating in-neighbor, with the
        neighbor's hottest facts piggybacked (gossip rides probe
        packets, net.go:631 piggyback)."""
        g = self.sim.cfg.gossip
        t_now = int(self.sim.swim_state.t)
        topo = self.sim.topo
        n = self.sim.cfg.n
        off = self._off
        # One host transfer per tick, not one per candidate probe
        # source (the re-source scan below is per-seat x degree).
        alive_np = np.asarray(self.sim.swim_state.alive_truth)
        ext_np = np.asarray(self.sim.swim_state.external)
        for seat, tr in list(self.transports.items()):
            if tr.down:
                continue
            # Missed-probe bookkeeping -> seat ground-truth death.
            pend = self._pending.get(seat)
            if pend is not None and t_now >= pend[1]:
                del self._pending[seat]
                self._misses[seat] = self._misses.get(seat, 0) + 1
                if self._misses[seat] >= self.probe_miss_limit:
                    self._stage_alive[seat] = False
            if t_now < self._next_probe[seat] or pend is not None:
                continue
            self._next_probe[seat] = t_now + g.probe_period_ticks
            # Rotate through in-neighbors as probe sources. An EXTERNAL
            # in-neighbor never sources a synthesized probe: its real
            # agent owns its own probing, and an ack addressed back to
            # that seat would land in the other agent's packet queue
            # instead of completing this probe (seen at fleet scale —
            # adjacent attached seats starving each other's liveness).
            # Fall through to the next live non-external in-neighbor
            # rather than skipping the round, so a seat whose rotation
            # lands on attached neighbors still gets probed (its dead
            # agent must still be detectable).
            c0 = (t_now // g.probe_period_ticks) % topo.degree
            src = None
            for d in range(topo.degree):
                cand = (seat - int(off[(c0 + d) % topo.degree])) % n
                if bool(alive_np[cand]) and not bool(ext_np[cand]):
                    src = cand
                    break
            if src is None:
                continue  # no live sim in-neighbor this tick
            self._seq += 1
            self._pending[seat] = (self._seq, t_now + g.probe_timeout_ticks)
            msgs = [codec.encode_message(
                MessageType.PING,
                {"SeqNo": self._seq, "Node": seat_name(seat)})]
            # Piggyback the source's hottest facts as gossip.
            src_view = np.asarray(self.sim.swim_state.view_key[src])
            src_tx = np.asarray(self.sim.swim_state.tx_left[src])
            hot = np.argsort(-src_tx)[:g.piggyback_msgs]
            for c2 in hot:
                if src_tx[c2] <= 0:
                    continue
                subj = (src + int(off[c2])) % n
                key = int(src_view[c2])
                status = merge.key_status_int(key)
                mt = {merge.ALIVE: MessageType.ALIVE,
                      merge.SUSPECT: MessageType.SUSPECT,
                      merge.DEAD: MessageType.DEAD,
                      merge.LEFT: MessageType.DEAD}[status]
                body = {"Incarnation": merge.key_incarnation_int(key),
                        "Node": seat_name(subj)}
                if mt != MessageType.ALIVE:
                    body["From"] = seat_name(src)
                else:
                    body.update({"Addr": seat_name(subj).encode(),
                                 "Port": 7946, "Meta": b"",
                                 "Vsn": bytes(VSN)})
                msgs.append(codec.encode_message(mt, body))
            rtt = self._model_rtt(src, seat)
            self._deliver(seat, codec.encode_packet(msgs),
                          seat_addr(src), self.now() + rtt)

    def _emit_events(self, t_now: int):
        """The serf delegate's event feed for attached agents
        (GetBroadcasts piggyback, serf/delegate.go:19-282): every tick,
        scan a rotating in-neighbor's event queue and deliver any event
        the agent has not seen — the rotation visits all K in-neighbors
        within K ticks, well inside the dedup buffer's retention, so an
        epidemic that reached ANY in-neighbor reaches the agent."""
        s = self.sim.serf_state
        n = self.sim.cfg.n
        k_deg = self._off.shape[0]
        up = np.asarray(self.sim.swim_state.alive_truth &
                        ~self.sim.swim_state.left)
        for seat, tr in self.transports.items():
            if tr.down:
                continue
            src = (seat - int(self._off[t_now % k_deg])) % n
            if not up[src]:
                continue  # dead members never source event traffic
            keys = np.asarray(s.ev_key[src])
            origins = np.asarray(s.ev_origin[src])
            seen = self._delivered_events.setdefault(seat, {})
            out = []
            for slot in range(keys.shape[0]):
                key = int(keys[slot])
                if key == 0 or key in seen:
                    continue  # empty or already delivered
                seen[key] = None
                while len(seen) > self._queue_max:
                    seen.pop(next(iter(seen)))
                name_int = (key >> 1) & 0xFF
                if key & 1:
                    # Query envelope (messageQueryType): the agent can
                    # respond with messageQueryResponse to the origin's
                    # address; Flags bit 0 requests a delivery ack.
                    q_name = self._query_names.get(
                        name_int, f"query-{name_int}")
                    self._bounded_insert(
                        self._known_queries, (q_name, key >> 9))
                    from consul_tpu.models import serf as serf_mod

                    origin = int(origins[slot]) % n
                    timeout_ticks = serf_mod.query_timeout_ticks(
                        self.sim.cfg)
                    out.append(codec.encode_serf_message(
                        codec.SERF_QUERY, {
                            "LTime": key >> 9,
                            "ID": key,
                            "Addr": seat_name(origin).encode(),
                            "Port": 7946,
                            "Filters": [],
                            "Flags": 1,  # queryFlagAck
                            "RelayFactor": 0,
                            "Timeout": int(
                                timeout_ticks
                                * self.sim.cfg.gossip.tick_ms * 1e6),
                            "Name": q_name,
                            "Payload": self._query_payloads.get(
                                name_int, b""),
                        }))
                    continue
                # Mark the echo as known so the agent's re-gossip of it
                # cannot re-fire into the sim (bounded here too — this
                # insert site sees one entry per sim-originated event).
                ev_name = self._event_names.get(
                    name_int, f"evt-{name_int}")
                self._bounded_insert(
                    self._known_events, (ev_name, key >> 9))
                out.append(codec.encode_serf_message(
                    codec.SERF_USER_EVENT, {
                        "LTime": key >> 9,
                        "Name": ev_name,
                        "Payload": self._event_payloads.get(
                            name_int, b""),
                        "CC": True,
                    }))
            if out:
                rtt = self._model_rtt(src, seat)
                self._deliver(seat, codec.encode_packet(out),
                              seat_addr(src), self.now() + rtt)

    # ------------------------------------------------------------------
    # The per-tick host boundary
    # ------------------------------------------------------------------
    def step(self):
        """Process staged traffic both ways; call after each sim tick."""
        t_now = int(self.sim.swim_state.t)
        still = []
        for from_seat, to_seat, stream, deadline in self._pending_streams:
            if not self._serve_stream(from_seat, to_seat, stream) \
                    and t_now < deadline:
                still.append((from_seat, to_seat, stream, deadline))
        self._pending_streams = still
        self._emit_probes_and_gossip()
        if self.sim.serf_state is not None:
            self._emit_events(t_now)
        self._apply_staged()

    def _apply_staged(self):
        st = self.sim.swim_state
        if self._stage_view:
            rows = jnp.asarray([r for r, _, _ in self._stage_view], jnp.int32)
            cols = jnp.asarray([c for _, c, _ in self._stage_view], jnp.int32)
            keys = jnp.asarray([k for _, _, k in self._stage_view], jnp.uint32)
            old = st.view_key[rows, cols]
            # Entries the join actually raised re-arm their gossip
            # budget, exactly as an in-sim delivery would (swim.step's
            # end-of-tick changed-detection can't see writes staged
            # between ticks, so the bridge is responsible for queueing
            # the rebroadcast — queue.go:182-242 semantics).
            from consul_tpu.ops import scaling
            tx0 = int(scaling.retransmit_limit(
                self.sim.cfg.gossip.retransmit_mult, self.sim.cfg.n))
            changed = keys > old
            st = st._replace(
                view_key=st.view_key.at[rows, cols].max(keys),
                tx_left=st.tx_left.at[rows, cols].max(
                    jnp.where(changed, tx0, 0)),
            )
            self._stage_view = []
        if self._stage_inc:
            rows = jnp.asarray(list(self._stage_inc.keys()), jnp.int32)
            incs = jnp.asarray(list(self._stage_inc.values()), jnp.uint32)
            st = st._replace(own_inc=st.own_inc.at[rows].max(incs))
            self._stage_inc = {}
        if self._stage_coord:
            v = st.viv
            vec, h = v.vec, v.height
            err, adj = v.error, v.adjustment
            for seat, c in self._stage_coord.items():
                vec = vec.at[seat].set(jnp.asarray(c["Vec"], jnp.float32))
                h = h.at[seat].set(c["Height"])
                err = err.at[seat].set(c["Error"])
                adj = adj.at[seat].set(c["Adjustment"])
            st = st._replace(viv=v._replace(vec=vec, height=h,
                                            error=err, adjustment=adj))
            self._stage_coord = {}
        if self._stage_alive:
            alive = st.alive_truth
            for seat, up in self._stage_alive.items():
                alive = alive.at[seat].set(up)
            st = st._replace(alive_truth=alive)
            self._stage_alive = {}
        self.sim.set_swim_state(st)
        if self._stage_fired and self.sim.serf_state is not None:
            # Fire the agents' user events into the sim event plane
            # (serf.UserEvent from the external seats' queues; the
            # event plane broadcasts them like any member's).
            from consul_tpu.models import serf as serf_mod

            n = self.sim.cfg.n
            by_name: dict[int, np.ndarray] = {}
            for seat, name_int in self._stage_fired:
                m = by_name.setdefault(name_int, np.zeros(n, bool))
                m[seat] = True
            for name_int, mask in by_name.items():
                self.sim.state = serf_mod.user_event(
                    self.sim.cfg, self.sim.serf_state,
                    jnp.asarray(mask), name_int)
            self._stage_fired = []
        if self._stage_query and self.sim.serf_state is not None:
            # Agent-fired queries enter the device plane (serf.query);
            # the tracker learns the device-assigned key so responses
            # and the per-responder record stay correlated.
            from consul_tpu.models import serf as serf_mod

            n = self.sim.cfg.n
            for seat, name_int in self._stage_query:
                mask = np.zeros(n, bool)
                mask[seat] = True
                self.sim.state = serf_mod.query(
                    self.sim.cfg, self.sim.serf_state,
                    jnp.asarray(mask), name_int)
                slot = serf_mod.newest_query_slot(
                    self.sim.serf_state, seat)
                key = int(self.sim.serf_state.q_open_key[seat, slot])
                rec = self._track_query(key >> 9, name_int)
                rec["origin_seat"] = seat
                rec["slot"] = slot
            self._stage_query = []
        if self._stage_qtally and self.sim.serf_state is not None:
            # Agent responses/acks to sim-origin queries land in the
            # device counters at (origin row, query slot) — one batched
            # .at[].add per kind.
            s = self.sim.serf_state
            acks = [(o, sl) for o, sl, is_resp in self._stage_qtally
                    if not is_resp]
            resps = [(o, sl) for o, sl, is_resp in self._stage_qtally
                     if is_resp]
            if acks:
                r, c = zip(*acks)
                s = s._replace(q_acks=s.q_acks.at[
                    jnp.asarray(r, jnp.int32), jnp.asarray(c, jnp.int32)
                ].add(1))
            if resps:
                r, c = zip(*resps)
                s = s._replace(q_resps=s.q_resps.at[
                    jnp.asarray(r, jnp.int32), jnp.asarray(c, jnp.int32)
                ].add(1))
            self.sim.state = s
            self._stage_qtally = []

    def query_status(self, origin_row: int,
                     qid: Optional[int] = None) -> Optional[dict]:
        """The consumer-facing view of a query fired by ``origin_row``
        (seat or sim node): the device plane's exactly-once aggregate
        counts plus the per-responder names and payload bytes collected
        from attached agents — the QueryResponse acks/responses
        channels a `consul exec`-style consumer reads (serf/query.go).
        ``qid`` selects one of the origin's concurrent queries (the
        [N, Q] slot axis); default is the most recently opened. None
        when the node has no open or tracked query."""
        from consul_tpu.models import serf as serf_mod

        s = self.sim.serf_state
        if s is None:
            return None
        if qid is not None:
            slot = serf_mod.query_slot(s, origin_row, qid)
            key = qid if slot >= 0 else 0
        else:
            slot = serf_mod.newest_query_slot(s, origin_row)
            key = int(s.q_open_key[origin_row, slot]) if slot >= 0 else 0
        rec = None
        if key:
            rec = self.query_tracker.get((key >> 9, (key >> 1) & 0xFF))
        else:  # closed: the freshest tracker entry for this origin
            if qid is not None:
                rec = self.query_tracker.get((qid >> 9, (qid >> 1) & 0xFF))
                if rec is None or rec.get("origin_seat") != origin_row:
                    return None
            else:
                for k in reversed(list(self.query_tracker)):
                    if self.query_tracker[k].get("origin_seat") == \
                            origin_row:
                        rec = self.query_tracker[k]
                        break
                if rec is None:
                    return None
            # The slot the closed query last owned still holds its
            # final tallies (until reuse).
            slot = rec.get("slot", 0)
        return {
            "open": bool(key),
            "acks_total": int(s.q_acks[origin_row, slot]),
            "responses_total": int(s.q_resps[origin_row, slot]),
            "agent_acks": list((rec or {}).get("acks", [])),
            "agent_responses": dict((rec or {}).get("responses", {})),
        }

    def run(self, ticks: int):
        """Advance sim + bridge together, one tick at a time (the
        external seam forces tick-granular host sync; pure-sim runs use
        the chunked scan path in models/cluster.py instead)."""
        for _ in range(ticks):
            self.sim.run(1, chunk=1, with_metrics=False)
            self.step()
