"""Gossip encryption keyring: AES-GCM with multi-key rotation.

Mirrors the reference (memberlist/security.go:90-156 + keyring.go):
payloads are ``[version byte | 12-byte nonce | ciphertext+tag]`` with
encryption version 1 (no padding — version 0's PKCS7 form is accepted
on decrypt); the keyring holds several installed keys with one primary
used for encryption, and decryption tries every key so clusters can
rotate keys without a flag day (serf/keymanager.go install → use →
remove).

Keys are 16/24/32 bytes (AES-128/192/256, security.go ValidateKey).
"""

from __future__ import annotations

import os
from typing import Optional

# Optional dependency: the wire package (codec framing, bridge, key
# manager) must import — and the pure-framing paths must work — on a
# box without ``cryptography``; only actually encrypting/decrypting
# requires it (HAVE_CRYPTOGRAPHY gates, RuntimeError on use).
try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover — crypto-less environment
    HAVE_CRYPTOGRAPHY = False
    AESGCM = None

    class InvalidTag(Exception):
        """Stand-in so ``except InvalidTag`` clauses keep working."""

VERSION_SIZE = 1
NONCE_SIZE = 12
TAG_SIZE = 16
MAX_ENCRYPTION_VERSION = 1


def _require_crypto():
    if not HAVE_CRYPTOGRAPHY:
        raise RuntimeError(
            "gossip encryption requires the 'cryptography' package")


def validate_key(key: bytes):
    if len(key) not in (16, 24, 32):
        raise ValueError(
            f"key size {len(key)} not 16/24/32 bytes (AES-128/192/256)"
        )


def encrypt_payload(key: bytes, msg: bytes, aad: bytes = b"",
                    version: int = 1) -> bytes:
    """security.go:90 encryptPayload (version 1: no padding)."""
    _require_crypto()
    validate_key(key)
    if version != 1:
        raise ValueError("only encryption version 1 is produced")
    nonce = os.urandom(NONCE_SIZE)
    ct = AESGCM(key).encrypt(nonce, msg, aad or None)
    return bytes([version]) + nonce + ct


def decrypt_with_key(key: bytes, payload: bytes, aad: bytes = b"") -> bytes:
    """security.go:137 decryptMessage + version handling (:158-...):
    version 0 strips PKCS7 padding after decryption."""
    _require_crypto()
    if len(payload) < VERSION_SIZE + NONCE_SIZE + TAG_SIZE:
        raise ValueError("payload too small to decrypt")
    version = payload[0]
    if version > MAX_ENCRYPTION_VERSION:
        raise ValueError(f"unsupported encryption version {version}")
    nonce = payload[VERSION_SIZE:VERSION_SIZE + NONCE_SIZE]
    ct = payload[VERSION_SIZE + NONCE_SIZE:]
    plain = AESGCM(key).decrypt(nonce, ct, aad or None)
    if version == 0 and plain:
        plain = plain[:len(plain) - plain[-1]]  # pkcs7decode
    return plain


class Keyring:
    """Multi-key ring (memberlist/keyring.go): ``keys[0]`` is the
    primary (used to encrypt); all keys are tried on decrypt."""

    def __init__(self, keys: Optional[list[bytes]] = None,
                 primary: Optional[bytes] = None):
        self._keys: list[bytes] = []
        if primary is not None:
            validate_key(primary)
            self._keys.append(primary)
        for k in keys or []:
            self.install(k)

    def install(self, key: bytes):
        validate_key(key)
        if key not in self._keys:
            self._keys.append(key)

    def use(self, key: bytes):
        """Make an installed key the primary (keyring.go UseKey)."""
        if key not in self._keys:
            raise KeyError("key is not in the keyring")
        self._keys.remove(key)
        self._keys.insert(0, key)

    def remove(self, key: bytes):
        """keyring.go RemoveKey: the primary cannot be removed."""
        if self._keys and key == self._keys[0]:
            raise ValueError("removing the primary key is not allowed")
        if key in self._keys:
            self._keys.remove(key)

    @property
    def keys(self) -> list[bytes]:
        return list(self._keys)

    @property
    def primary(self) -> Optional[bytes]:
        return self._keys[0] if self._keys else None

    def encrypt(self, msg: bytes, aad: bytes = b"") -> bytes:
        if not self._keys:
            raise ValueError("keyring is empty")
        return encrypt_payload(self._keys[0], msg, aad)

    def decrypt(self, payload: bytes, aad: bytes = b"") -> bytes:
        """Try every installed key (security.go decryptPayload loop)."""
        err: Exception = ValueError("keyring is empty")
        for key in self._keys:
            try:
                return decrypt_with_key(key, payload, aad)
            except (InvalidTag, ValueError) as e:
                err = e
        raise ValueError(f"no installed key decrypts the payload: {err}")
