"""Wire protocol: memberlist-compatible framing, compression, crypto.

The reference's gossip messages are msgpack bodies behind a msgType
byte, with compound batching, LZW compression, CRC32 integrity, and
AES-GCM encryption (reference memberlist/net.go:46-59, util.go:157-275,
security.go, keyring.go). This package implements that wire format so
the framework can interoperate at the byte level — the seam SURVEY.md
§7 phase 7 describes for bridging real agents into the simulated
fabric. The LZW codec's hot path is native C++ (consul_tpu/wire/native)
with a pure-Python fallback.
"""

from consul_tpu.wire.codec import (  # noqa: F401
    MessageType,
    decode_message,
    decode_packet,
    encode_message,
    encode_packet,
    make_compound,
    split_compound,
)
from consul_tpu.wire.keyring import Keyring  # noqa: F401
