"""Message codec: msgType framing + msgpack bodies + packet pipeline.

The reference frames every gossip message as ``[msgType byte | msgpack
body]`` (memberlist/net.go:46-59 for the type ids, go-msgpack encodes
structs as maps keyed by Go field name), batches small messages into
compound messages (util.go:157-217: ``[compoundMsg | count | u16
lengths... | bodies...]``), optionally LZW-compresses
(util.go:221-275: a ``compress{Algo, Buf}`` body behind compressMsg),
optionally prefixes a CRC32-IEEE (net.go hasCrcMsg), and optionally
encrypts the whole packet (security.go, see keyring.py).

:func:`encode_packet`/:func:`decode_packet` run that full pipeline in
wire order — encrypt(crc(compress(compound(messages)))) — matching
``rawSendMsgPacket``/``ingestPacket`` (net.go:631-700, :299-346).
"""

from __future__ import annotations

import enum
import zlib
from typing import Optional

import msgpack

from consul_tpu.wire import lzw
from consul_tpu.wire.keyring import (NONCE_SIZE, TAG_SIZE, VERSION_SIZE,
                                     Keyring)


class MessageType(enum.IntEnum):
    """memberlist/net.go:45-60."""

    PING = 0
    INDIRECT_PING = 1
    ACK_RESP = 2
    SUSPECT = 3
    ALIVE = 4
    DEAD = 5
    PUSH_PULL = 6
    COMPOUND = 7
    USER = 8
    COMPRESS = 9
    ENCRYPT = 10
    NACK_RESP = 11
    HAS_CRC = 12
    ERR = 13


LZW_ALGO = 0  # compressionType lzwAlgo (util.go:64)

# Struct field names per message type (Go struct fields — go-msgpack
# writes them as map keys; net.go:80-175).
_FIELDS = {
    MessageType.PING: ("SeqNo", "Node"),
    MessageType.INDIRECT_PING: ("SeqNo", "Target", "Port", "Node", "Nack"),
    MessageType.ACK_RESP: ("SeqNo", "Payload"),
    MessageType.NACK_RESP: ("SeqNo",),
    MessageType.ERR: ("Error",),
    MessageType.SUSPECT: ("Incarnation", "Node", "From"),
    MessageType.ALIVE: ("Incarnation", "Node", "Addr", "Port", "Meta", "Vsn"),
    MessageType.DEAD: ("Incarnation", "Node", "From"),
    MessageType.PUSH_PULL: ("Nodes", "UserStateLen", "Join"),
    MessageType.COMPRESS: ("Algo", "Buf"),
}


def _pack_go(obj) -> bytes:
    """msgpack bytes in hashicorp/go-msgpack's default encoding
    (``codec.MsgpackHandle{}``, WriteExt=false): struct maps carry
    their keys in ALPHABETICAL order (the codec sorts struct fields by
    encoded name — go-msgpack codec/helper.go sfi "sorted. Used when
    enc/dec struct to map"), and strings/bytes use the legacy raw
    family (fixraw / raw16 / raw32 — no str8, no bin), which
    ``use_bin_type=False`` reproduces exactly."""
    if isinstance(obj, dict):
        obj = {k: obj[k] for k in sorted(obj)}
    return msgpack.packb(obj, use_bin_type=False)


def encode_message(mtype: MessageType, body: dict) -> bytes:
    """``[msgType | msgpack(body)]`` (net.go encode :1098-1104),
    byte-compatible with go-msgpack framing (see :func:`_pack_go`)."""
    allowed = _FIELDS.get(MessageType(mtype))
    if allowed is not None:
        unknown = set(body) - set(allowed)
        if unknown:
            raise ValueError(f"unknown fields for {mtype!r}: {sorted(unknown)}")
    return bytes([mtype]) + _pack_go(body)


def as_bytes(field) -> bytes:
    """Recover raw bytes from a decoded legacy-raw field: the decoder
    maps old-format raw to str via surrogateescape (see
    :func:`decode_message`); the same handler inverts losslessly."""
    if isinstance(field, str):
        return field.encode("utf-8", "surrogateescape")
    return bytes(field)


def decode_message(buf: bytes) -> tuple[MessageType, dict]:
    if not buf:
        raise ValueError("empty message")
    if buf[0] == MessageType.USER:
        # User messages are opaque to memberlist ([userMsg | raw],
        # net.go handleUser hands the raw bytes to the delegate);
        # serf's envelope inside is decoded by the consumer
        # (decode_serf_message).
        return MessageType.USER, {"Raw": buf[1:]}
    # Legacy-raw fields (Addr, Meta, Payload) hold arbitrary bytes that
    # are not necessarily UTF-8; surrogateescape keeps them lossless
    # (re-encode with the same handler to recover the bytes).
    return MessageType(buf[0]), msgpack.unpackb(
        buf[1:], raw=False, unicode_errors="surrogateescape")


# ----------------------------------------------------------------------
# Serf envelope: serf rides memberlist user messages as
# [userMsg | serf messageType byte | msgpack body]
# (serf/delegate.go NotifyMsg dispatches on the first byte;
# serf/messages.go:10-25 type ids).
# ----------------------------------------------------------------------

SERF_LEAVE = 0
SERF_JOIN = 1
SERF_PUSH_PULL = 2
SERF_USER_EVENT = 3
SERF_QUERY = 4
SERF_QUERY_RESPONSE = 5


def encode_serf_message(serf_type: int, body: dict) -> bytes:
    """One serf message ready for Transport.WriteTo: the memberlist
    user envelope around the serf type byte + go-msgpack body."""
    return bytes([MessageType.USER, serf_type]) + _pack_go(body)


def decode_serf_message(raw) -> tuple[int, dict]:
    """Inverse of :func:`encode_serf_message` given a USER message's
    Raw bytes (str via surrogateescape accepted)."""
    raw = as_bytes(raw)
    if not raw:
        raise ValueError("empty serf message")
    try:
        body = msgpack.unpackb(raw[1:], raw=False,
                               unicode_errors="surrogateescape")
    except msgpack.exceptions.UnpackException as e:
        raise ValueError(f"malformed serf message: {e!r}") from e
    if not isinstance(body, dict):
        raise ValueError("serf message body must be a map")
    return raw[0], body


# ----------------------------------------------------------------------
# Compound batching (util.go:157-217)
# ----------------------------------------------------------------------

def make_compound(msgs: list[bytes]) -> bytes:
    if len(msgs) > 255:
        raise ValueError("compound messages hold at most 255 parts")
    out = bytearray([MessageType.COMPOUND, len(msgs)])
    for m in msgs:
        if len(m) > 0xFFFF:
            raise ValueError("compound part exceeds u16 length")
        out += len(m).to_bytes(2, "big")
    for m in msgs:
        out += m
    return bytes(out)


def split_compound(buf: bytes) -> list[bytes]:
    """decodeCompoundMessage (util.go:181-217); ``buf`` excludes the
    leading compoundMsg byte. Truncated parts raise."""
    if not buf:
        raise ValueError("missing compound length byte")
    n_parts, buf = buf[0], buf[1:]
    if len(buf) < n_parts * 2:
        raise ValueError("truncated compound length slice")
    lengths = [int.from_bytes(buf[i * 2:i * 2 + 2], "big")
               for i in range(n_parts)]
    buf = buf[n_parts * 2:]
    parts = []
    for ln in lengths:
        if len(buf) < ln:
            raise ValueError(
                f"compound truncated ({len(parts)} of {n_parts} parts)"
            )
        parts.append(buf[:ln])
        buf = buf[ln:]
    return parts


# ----------------------------------------------------------------------
# Full packet pipeline (rawSendMsgPacket/ingestPacket order)
# ----------------------------------------------------------------------

def encode_packet(msgs: list[bytes], *, compress: bool = False,
                  crc: bool = False,
                  keyring: Optional[Keyring] = None) -> bytes:
    """Sender pipeline (net.go:631-714 rawSendMsgPacket): compound when
    multiple messages, then compress, then CRC, then encrypt. The
    encrypted payload is sent RAW — no msgType prefix, no AAD
    (net.go:697-708); the receiver knows to decrypt from its own
    config, not from the bytes. (The encryptMsg byte exists only on the
    *stream* path — see :func:`encode_stream_frame`.)"""
    pkt = msgs[0] if len(msgs) == 1 else make_compound(msgs)
    if compress:
        body = _pack_go({"Algo": LZW_ALGO, "Buf": lzw.compress(pkt)})
        pkt = bytes([MessageType.COMPRESS]) + body
    if crc:
        digest = zlib.crc32(pkt) & 0xFFFFFFFF
        pkt = bytes([MessageType.HAS_CRC]) + digest.to_bytes(4, "big") + pkt
    if keyring is not None and keyring.primary is not None:
        pkt = keyring.encrypt(pkt)
    return pkt


def decode_packet(pkt: bytes,
                  keyring: Optional[Keyring] = None, *,
                  verify_incoming: bool = True) -> list[tuple[MessageType, dict]]:
    """Receiver pipeline (ingestPacket net.go:310-346 + handleCompound):
    decrypt (by config — the packet carries no encryption marker), verify
    CRC, decompress, split compounds, decode each body. Returns
    (type, body) pairs in arrival order.

    ``verify_incoming=False`` mirrors GossipVerifyIncoming=false
    (net.go:315-321): a payload no key decrypts is processed as
    plaintext instead of rejected (the key-rotation upgrade window).
    """
    if not pkt:
        raise ValueError("empty packet")
    if keyring is not None and keyring.primary is not None:
        try:
            pkt = keyring.decrypt(pkt)
        except ValueError:
            if verify_incoming:
                raise
            # fall through: treat as plaintext
    if pkt and pkt[0] == MessageType.HAS_CRC:
        if len(pkt) < 5:
            raise ValueError("truncated CRC header")
        want = int.from_bytes(pkt[1:5], "big")
        pkt = pkt[5:]
        got = zlib.crc32(pkt) & 0xFFFFFFFF
        if got != want:
            raise ValueError(f"packet CRC mismatch ({got:#x} != {want:#x})")
    if pkt and pkt[0] == MessageType.COMPRESS:
        body = msgpack.unpackb(pkt[1:], raw=False,
                               unicode_errors="surrogateescape")
        if body["Algo"] != LZW_ALGO:
            raise ValueError(f"unknown compression algo {body['Algo']}")
        pkt = lzw.decompress(as_bytes(body["Buf"]))
    if pkt and pkt[0] == MessageType.COMPOUND:
        return [decode_message(part) for part in split_compound(pkt[1:])]
    return [decode_message(pkt)]


# ----------------------------------------------------------------------
# Push-pull stream payload (net.go:818-860 sendLocalState): the
# pushPullMsg type byte, a pushPullHeader, then one pushNodeState body
# per node, then the raw user state — a *sequence* of msgpack objects,
# not a single nested document.
# ----------------------------------------------------------------------

def encode_push_pull(states: list[dict], user_state: bytes = b"",
                     join: bool = False) -> bytes:
    out = bytearray([MessageType.PUSH_PULL])
    out += _pack_go(
        {"Nodes": len(states), "UserStateLen": len(user_state),
         "Join": join})
    for s in states:
        out += _pack_go(s)
    out += user_state
    return bytes(out)


def decode_push_pull(buf: bytes) -> tuple[dict, list[dict], bytes]:
    """readRemoteState (net.go:995-1035): returns (header, states,
    user_state). Any malformation — truncation, wrong shapes, bad
    msgpack — raises ValueError, so stream handlers need one guard."""
    if not buf or buf[0] != MessageType.PUSH_PULL:
        raise ValueError("not a pushPull stream")
    try:
        unpacker = msgpack.Unpacker(raw=False,
                                    unicode_errors="surrogateescape")
        unpacker.feed(buf[1:])
        header = unpacker.unpack()
        states = [unpacker.unpack() for _ in range(int(header["Nodes"]))]
        tail = bytes(buf[1 + unpacker.tell():])
        if len(tail) < header["UserStateLen"]:
            raise ValueError("truncated push-pull user state")
        return header, states, tail[:header["UserStateLen"]]
    except ValueError:
        raise
    except (msgpack.exceptions.UnpackException, TypeError, KeyError) as e:
        raise ValueError(f"malformed push-pull stream: {e!r}") from e


# ----------------------------------------------------------------------
# Stream (push-pull / TCP) encryption framing. Unlike the packet path,
# streams DO carry an explicit encryptMsg header:
#   [encryptMsg byte | u32 big-endian ciphertext length | ciphertext]
# with the 5 header bytes as AAD (net.go:878-900 encryptLocalState,
# :946-976 readRemoteState).
# ----------------------------------------------------------------------

def encode_stream_frame(buf: bytes, keyring: Optional[Keyring]) -> bytes:
    """encryptLocalState (net.go:878-900); plaintext passthrough when
    encryption is off (sendLocalState writes the raw stream)."""
    if keyring is None or keyring.primary is None:
        return buf
    # AES-GCM ciphertext length is deterministic (version + nonce +
    # plaintext + tag — the reference's encryptedLength, security.go),
    # so the header the AAD commits to is computable up front.
    ct_len = VERSION_SIZE + NONCE_SIZE + len(buf) + TAG_SIZE
    header = bytes([MessageType.ENCRYPT]) + ct_len.to_bytes(4, "big")
    ct = keyring.encrypt(buf, aad=header)
    assert len(ct) == ct_len
    return header + ct


def decode_stream_frame(frame: bytes, keyring: Optional[Keyring]) -> bytes:
    """decryptRemoteState (net.go:903-976): enforce the encryption
    expectation both ways, verify the header as AAD, decrypt."""
    encrypted = bool(frame) and frame[0] == MessageType.ENCRYPT
    enabled = keyring is not None and keyring.primary is not None
    if encrypted and not enabled:
        raise ValueError(
            "remote state is encrypted and encryption is not configured"
        )
    if not encrypted:
        if enabled:
            raise ValueError(
                "encryption is configured but remote state is not encrypted"
            )
        return frame
    if len(frame) < 5:
        raise ValueError("truncated stream encryption header")
    want_len = int.from_bytes(frame[1:5], "big")
    ct = frame[5:]
    if len(ct) != want_len:
        raise ValueError(
            f"stream ciphertext length {len(ct)} != header {want_len}"
        )
    return keyring.decrypt(ct, aad=frame[:5])
