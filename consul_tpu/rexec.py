"""Remote exec: KV-coordinated command execution across agents.

Mirrors the reference flow (reference agent/remote_exec.go +
command/exec): the submitter creates a session, writes the job spec at
``_rexec/<session>/job``, and fires a ``_rexec`` user event whose
payload names the KV prefix + session. Every participating agent that
sees the event reads the spec, acknowledges at
``_rexec/<session>/<node>/ack``, runs the command, streams output
chunks under ``.../out/<seq>``, and records the exit code at
``.../exit``. The submitter collects results by watching the prefix
until the agents it heard from have all exited (or the deadline
passes), then destroys the session (its delete behavior GCs the
session-held job key) and delete-trees the response keys — the
command/exec cleanup path.

Commands here are **callables** (the framework's CheckMonitor
convention: a callable generalizes the reference's shell-out), so
simulated fleets can execute anything host-side without forking
processes; a subprocess runner is one ``lambda`` away.
"""

from __future__ import annotations

import base64
import json
import time
from typing import Callable, Optional

from consul_tpu.api import Client, watch

PREFIX = "_rexec"
EVENT = "_rexec"


def submit(client: Client, node: str, command: str,
           wait_s: float = 5.0, quiesce_s: float = 0.3,
           target: str = "") -> dict:
    """Fire a remote-exec job and collect results (the ``consul exec``
    submitter, command/exec + remote_exec.go flow). Returns
    {node: {"ack": bool, "output": bytes, "exit": int}}.

    ``target`` names one node to execute on (the reference event
    payload's node filter); empty targets every worker, and collection
    then ends only after a ``quiesce_s`` window with no new responders
    (the reference's ExecWait quiescence, never first-subset-done —
    a fast responder must not cut off a slower one's results).
    ``node`` holds the coordination session (the submitter's agent)."""
    session = client.session.create(node=node, behavior="delete")
    key_prefix = f"{PREFIX}/{session}"
    spec = {"Command": command, "Wait": wait_s}
    if not client.kv.put(f"{key_prefix}/job", json.dumps(spec).encode(),
                         acquire=session):
        client.session.destroy(session)
        raise RuntimeError("remote exec: failed to acquire job key")
    payload = json.dumps({"Prefix": PREFIX, "Session": session,
                          "Node": target}).encode()
    client._call("PUT", f"/v1/event/fire/{EVENT}", {}, payload)

    deadline = time.monotonic() + wait_s
    results: dict[str, dict] = {}
    rows_box: dict = {"rows": []}
    plan = watch(client, "keyprefix",
                 lambda i, rows: rows_box.update(rows=rows),
                 prefix=key_prefix + "/")
    plan.run_once(wait="10ms")  # initial snapshot
    last_change = time.monotonic()
    prev_state: tuple = ()
    while time.monotonic() < deadline:
        # Blocking keyprefix watch instead of busy polling: run_once
        # long-polls on the prefix index (api.WatchPlan "keyprefix").
        plan.run_once(wait="200ms")
        results = {}
        acked, exited = set(), set()
        for r in rows_box["rows"]:
            tail = r["Key"][len(key_prefix) + 1:]
            parts = tail.split("/")
            if len(parts) < 2:
                continue
            # Raw API rows carry base64 values (the watch plan speaks
            # the wire shape, unlike kv.list's decoded convenience).
            value = base64.b64decode(r["Value"]) if r.get("Value") else b""
            rnode = parts[0]
            rec = results.setdefault(
                rnode, {"ack": False, "output": b"", "exit": None})
            if parts[1] == "ack":
                rec["ack"] = True
                acked.add(rnode)
            elif parts[1] == "exit":
                rec["exit"] = int(value)
                exited.add(rnode)
            elif parts[1] == "out":
                rec.setdefault("_chunks", {})[int(parts[2])] = value
        state = (tuple(sorted(acked)), tuple(sorted(exited)))
        if state != prev_state:
            prev_state = state
            last_change = time.monotonic()
        if target:
            if target in exited:
                break
        elif acked and acked == exited and \
                time.monotonic() - last_change >= quiesce_s:
            break
    for rec in results.values():
        chunks = rec.pop("_chunks", {})
        rec["output"] = b"".join(v for _, v in sorted(chunks.items()))
    # Cleanup: the session's delete behavior GCs the held job key; the
    # responders' ack/out/exit keys were written sessionless, so the
    # submitter delete-trees them (command/exec cleanup).
    client.session.destroy(session)
    client.kv.delete(key_prefix + "/", recurse=True)
    return results


class ExecWorker:
    """Agent-side responder (remote_exec.go handleRemoteExec): watches
    for ``_rexec`` events, runs the command, uploads ack/out/exit."""

    def __init__(self, client: Client, node: str,
                 runner: Optional[Callable[[str], tuple[int, bytes]]] = None,
                 chunk_size: int = 4 * 1024):
        self.client = client
        self.node = node
        # Default runner: a no-op echo (deployments supply their own;
        # the reference shells out via exec.Command).
        self.runner = runner or (lambda cmd: (0, cmd.encode()))
        self.chunk_size = chunk_size
        self._plan = watch(client, "event", self._on_events, name=EVENT)
        self._seen: dict[str, None] = {}  # insertion-ordered, bounded

    def poll(self, wait: str = "50ms") -> bool:
        """One watch round (drivers pump this on their schedule)."""
        return self._plan.run_once(wait=wait)

    def _on_events(self, index, events):
        for e in events:
            payload = e.get("Payload")
            if not payload:
                continue
            try:
                body = json.loads(base64.b64decode(payload))
                session = body["Session"]
            except (ValueError, KeyError, TypeError):
                continue  # malformed event (wrong shape too): not ours
            if not isinstance(session, str) or session in self._seen:
                continue
            tgt = body.get("Node", "")
            if tgt and tgt != self.node:
                continue  # the event names someone else
            self._seen[session] = None
            while len(self._seen) > 1024:  # bounded memory
                self._seen.pop(next(iter(self._seen)))
            self._execute(body.get("Prefix", PREFIX), session)

    def _execute(self, prefix: str, session: str):
        base = f"{prefix}/{session}"
        row, _ = self.client.kv.get(f"{base}/job")
        if row is None:
            return  # job already GC'd (late event delivery)
        try:
            spec = json.loads(row["Value"])
        except ValueError:
            return
        if not isinstance(spec, dict):
            return  # wrong-shape job spec: same hardening as the event
        me = f"{base}/{self.node}"
        self.client.kv.put(f"{me}/ack", b"")
        code, out = self.runner(spec.get("Command", ""))
        for seq in range(0, max(len(out), 1), self.chunk_size):
            chunk = out[seq:seq + self.chunk_size]
            if chunk or seq == 0:
                self.client.kv.put(f"{me}/out/{seq:05d}", chunk)
        self.client.kv.put(f"{me}/exit", str(int(code)).encode())
