"""Python API client for the HTTP interface.

The equivalent of the reference's Go client library (reference api/,
9071 LoC: api.Client with KV/Catalog/Health/Session/Coordinate/Status/
Agent handles, blocking-query QueryOptions, lock recipes). Speaks the
same wire conventions as :mod:`consul_tpu.agent.http` — JSON, base64 KV
values, ``X-Consul-Index`` — over stdlib ``http.client`` only.
"""

from __future__ import annotations

import base64
import hashlib
import json
import time
import urllib.parse
import urllib.request
from typing import Any, Optional


class QueryMeta:
    def __init__(self, index: int):
        self.index = index


class APIError(RuntimeError):
    def __init__(self, status: int, body: Any):
        super().__init__(f"HTTP {status}: {body}")
        self.status = status
        self.body = body


class Client:
    """``Client("127.0.0.1", 8500)`` — handles are attributes:
    ``kv``, ``catalog``, ``health``, ``session``, ``coordinate``,
    ``status``, ``agent`` (reference api/api.go NewClient)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8500,
                 scheme: str = "http", ssl_context=None,
                 token: str = ""):
        """``scheme="https"`` with an ``ssl_context`` (e.g.
        ``utils.tls.Configurator.outgoing_ctx()``) speaks TLS to the
        agent — the reference client's HttpClient with TLSConfig
        (api/api.go SetupTLSConfig). ``token`` rides every request as
        X-Consul-Token (api/api.go Config.Token)."""
        self.base = f"{scheme}://{host}:{port}"
        self.ssl_context = ssl_context
        self.token = token
        self.kv = KV(self)
        self.catalog = Catalog(self)
        self.health = Health(self)
        self.session = Session(self)
        self.coordinate = Coordinate(self)
        self.status = Status(self)
        self.agent = AgentAPI(self)
        self.operator = Operator(self)
        self.config = ConfigEntries(self)
        self.internal = Internal(self)
        self.query = PreparedQuery(self)
        self.acl = ACL(self)
        self.connect = Connect(self)

    def _call(self, method: str, path: str, params: Optional[dict] = None,
              body: Optional[bytes] = None) -> tuple[Any, QueryMeta, int]:
        qs = urllib.parse.urlencode(
            {k: v for k, v in (params or {}).items() if v is not None}
        )
        url = f"{self.base}{path}" + (f"?{qs}" if qs else "")
        req = urllib.request.Request(url, data=body, method=method)
        if self.token:
            req.add_header("X-Consul-Token", self.token)
        try:
            with urllib.request.urlopen(req, context=self.ssl_context) as resp:
                payload = json.loads(resp.read() or b"null")
                idx = int(resp.headers.get("X-Consul-Index", 0))
                return payload, QueryMeta(idx), resp.status
        except urllib.error.HTTPError as e:
            payload = e.read()
            try:
                payload = json.loads(payload or b"null")
            except json.JSONDecodeError:
                pass
            if e.code == 404:
                idx = int(e.headers.get("X-Consul-Index", 0))
                return None, QueryMeta(idx), 404
            raise APIError(e.code, payload) from e


class KV:
    def __init__(self, c: Client):
        self.c = c

    def get(self, key: str, index: int = 0, wait: str = "10s",
            dc: Optional[str] = None):
        params = {"index": index or None, "wait": wait if index else None,
                  "dc": dc}
        out, meta, status = self.c._call("GET", f"/v1/kv/{key}", params)
        if status == 404 or not out:
            return None, meta
        row = out[0]
        value = base64.b64decode(row["Value"]) if row["Value"] else b""
        return {**row, "Value": value}, meta

    def put(self, key: str, value: bytes, cas: Optional[int] = None,
            flags: int = 0, acquire: Optional[str] = None,
            release: Optional[str] = None,
            dc: Optional[str] = None) -> bool:
        params = {"cas": cas, "flags": flags or None,
                  "acquire": acquire, "release": release, "dc": dc}
        out, _, _ = self.c._call("PUT", f"/v1/kv/{key}", params, value)
        return bool(out)

    def delete(self, key: str, recurse: bool = False) -> bool:
        params = {"recurse": "" if recurse else None}
        out, _, _ = self.c._call("DELETE", f"/v1/kv/{key}", params)
        return bool(out)

    def keys(self, prefix: str = "", separator: str = "") -> list[str]:
        """Key listing; ``separator`` gives directory-style truncation
        (reference api/kv.go Keys)."""
        out, _, _ = self.c._call("GET", f"/v1/kv/{prefix}",
                                 {"keys": "", "separator":
                                  separator or None})
        return out or []

    def list(self, prefix: str = "") -> list[dict]:
        out, _, status = self.c._call("GET", f"/v1/kv/{prefix}",
                                      {"recurse": ""})
        if status == 404 or not out:
            return []
        return [{**r, "Value": base64.b64decode(r["Value"])
                 if r["Value"] else b""} for r in out]


class Catalog:
    def __init__(self, c: Client):
        self.c = c

    def nodes(self, near: str = "", index: int = 0, wait: str = "10s",
              dc: Optional[str] = None):
        params = {"near": near or None, "index": index or None,
                  "wait": wait if index else None, "dc": dc}
        out, meta, _ = self.c._call("GET", "/v1/catalog/nodes", params)
        return out, meta

    def services(self):
        out, meta, _ = self.c._call("GET", "/v1/catalog/services")
        return out, meta

    def datacenters(self) -> list[str]:
        """Known DCs sorted by WAN distance (reference
        api/catalog.go Datacenters)."""
        out, _, _ = self.c._call("GET", "/v1/catalog/datacenters")
        return out

    def service(self, name: str, tag: Optional[str] = None, near: str = "",
                dc: Optional[str] = None):
        params = {"tag": tag, "near": near or None, "dc": dc}
        out, meta, _ = self.c._call("GET", f"/v1/catalog/service/{name}",
                                    params)
        return out, meta

    def register(self, node: str, address: str,
                 service: Optional[dict] = None,
                 check: Optional[dict] = None) -> bool:
        body = {"Node": node, "Address": address}
        if service:
            body["Service"] = service
        if check:
            body["Check"] = check
        out, _, _ = self.c._call("PUT", "/v1/catalog/register", None,
                                 json.dumps(body).encode())
        return bool(out)

    def deregister(self, node: str, service_id: Optional[str] = None) -> bool:
        body = {"Node": node}
        if service_id:
            body["ServiceID"] = service_id
        out, _, _ = self.c._call("PUT", "/v1/catalog/deregister", None,
                                 json.dumps(body).encode())
        return bool(out)


class Health:
    def __init__(self, c: Client):
        self.c = c

    def service(self, name: str, passing: bool = False, near: str = "",
                index: int = 0, wait: str = "10s"):
        params = {"passing": "" if passing else None, "near": near or None,
                  "index": index or None, "wait": wait if index else None}
        out, meta, _ = self.c._call("GET", f"/v1/health/service/{name}",
                                    params)
        return out, meta

    def node(self, node: str):
        out, meta, _ = self.c._call("GET", f"/v1/health/node/{node}")
        return out, meta

    def state(self, state: str = "any"):
        out, meta, _ = self.c._call("GET", f"/v1/health/state/{state}")
        return out, meta


class Session:
    def __init__(self, c: Client):
        self.c = c

    def create(self, node: Optional[str] = None, ttl: str = "",
               behavior: str = "release",
               lock_delay: str = "15s") -> str:
        body: dict = {"Behavior": behavior, "LockDelay": lock_delay}
        if node:
            body["Node"] = node
        if ttl:
            body["TTL"] = ttl
        out, _, _ = self.c._call("PUT", "/v1/session/create", None,
                                 json.dumps(body).encode())
        return out["ID"]

    def destroy(self, session_id: str) -> bool:
        out, _, _ = self.c._call("PUT", f"/v1/session/destroy/{session_id}")
        return bool(out)

    def renew(self, session_id: str) -> dict:
        """Reset the session's TTL deadline (reference api/session.go
        Renew)."""
        out, _, _ = self.c._call("PUT", f"/v1/session/renew/{session_id}")
        return out[0] if isinstance(out, list) and out else out

    def list(self):
        out, meta, _ = self.c._call("GET", "/v1/session/list")
        return out, meta

    def info(self, session_id: str):
        """One session — a list, empty for an unknown id (reference
        api/session.go Info)."""
        out, meta, _ = self.c._call("GET", f"/v1/session/info/{session_id}")
        return out, meta

    def node(self, node: str):
        """Sessions held by one node (reference api/session.go Node)."""
        out, meta, _ = self.c._call("GET", f"/v1/session/node/{node}")
        return out, meta


class Coordinate:
    def __init__(self, c: Client):
        self.c = c

    def nodes(self):
        out, meta, _ = self.c._call("GET", "/v1/coordinate/nodes")
        return out, meta

    def node(self, node: str):
        out, meta, _ = self.c._call("GET", f"/v1/coordinate/node/{node}")
        return out, meta

    def datacenters(self) -> list[dict]:
        """Per-DC WAN server coordinates (reference
        api/coordinate.go Datacenters)."""
        out, _, _ = self.c._call("GET", "/v1/coordinate/datacenters")
        return out


class Status:
    def __init__(self, c: Client):
        self.c = c

    def leader(self) -> str:
        out, _, _ = self.c._call("GET", "/v1/status/leader")
        return out

    def peers(self) -> list[str]:
        out, _, _ = self.c._call("GET", "/v1/status/peers")
        return out


class AgentAPI:
    def __init__(self, c: Client):
        self.c = c

    def self_(self) -> dict:
        out, _, _ = self.c._call("GET", "/v1/agent/self")
        return out

    def metrics(self) -> dict:
        out, _, _ = self.c._call("GET", "/v1/agent/metrics")
        return out

    def join(self, address: str) -> bool:
        """Route a running agent onto a server set (reference
        api/agent.go Join → /v1/agent/join/:address)."""
        out, _, _ = self.c._call("PUT", f"/v1/agent/join/{address}")
        return bool(out)

    def services(self) -> dict:
        """The agent's LOCAL service registrations (reference
        api/agent.go Services)."""
        out, _, _ = self.c._call("GET", "/v1/agent/services")
        return out

    def checks(self) -> dict:
        """The agent's LOCAL check states (reference api/agent.go
        Checks)."""
        out, _, _ = self.c._call("GET", "/v1/agent/checks")
        return out

    def service_register(self, name: str, service_id: str = "",
                         port: int = 0, tags: Optional[list] = None,
                         check_ttl: str = "") -> bool:
        body: dict = {"Name": name, "Port": port}
        if service_id:
            body["ID"] = service_id
        if tags:
            body["Tags"] = tags
        if check_ttl:
            body["Check"] = {"TTL": check_ttl}
        out, _, _ = self.c._call("PUT", "/v1/agent/service/register", None,
                                 json.dumps(body).encode())
        return bool(out)

    def service_deregister(self, service_id: str) -> bool:
        out, _, _ = self.c._call(
            "PUT", f"/v1/agent/service/deregister/{service_id}")
        return bool(out)

    def check_pass(self, check_id: str, note: str = "") -> bool:
        out, _, _ = self.c._call("PUT", f"/v1/agent/check/pass/{check_id}",
                                 {"note": note or None})
        return bool(out)

    def check_fail(self, check_id: str, note: str = "") -> bool:
        out, _, _ = self.c._call("PUT", f"/v1/agent/check/fail/{check_id}",
                                 {"note": note or None})
        return bool(out)

    def maintenance(self, enable: bool, reason: str = "") -> bool:
        """Node maintenance mode (reference api/agent.go EnableNodeMaintenance)."""
        out, _, _ = self.c._call(
            "PUT", "/v1/agent/maintenance",
            {"enable": "true" if enable else "false",
             "reason": reason or None})
        return bool(out)

    def service_maintenance(self, service_id: str, enable: bool,
                            reason: str = "") -> bool:
        out, _, _ = self.c._call(
            "PUT", f"/v1/agent/service/maintenance/{service_id}",
            {"enable": "true" if enable else "false",
             "reason": reason or None})
        return bool(out)

    def members(self, wan: bool = False) -> list[dict]:
        """The agent's member view (reference api/agent.go Members)."""
        out, _, _ = self.c._call("GET", "/v1/agent/members",
                                 {"wan": "1"} if wan else None)
        return out

    def leave(self) -> bool:
        """Graceful leave + shutdown (reference api/agent.go Leave)."""
        out, _, _ = self.c._call("PUT", "/v1/agent/leave")
        return bool(out)

    def host(self) -> dict:
        out, _, _ = self.c._call("GET", "/v1/agent/host")
        return out

    def service(self, service_id: str) -> dict:
        """One LOCAL service registration (reference api/agent.go
        AgentService)."""
        out, _, _ = self.c._call("GET", f"/v1/agent/service/{service_id}")
        return out

    def check_register(self, name: str, check_id: str = "",
                       ttl: str = "", http: str = "", tcp: str = "",
                       alias_node: str = "", interval: str = "",
                       service_id: str = "") -> bool:
        """Standalone check registration (reference api/agent.go
        CheckRegister)."""
        body: dict = {"Name": name}
        for k, v in (("ID", check_id), ("TTL", ttl), ("HTTP", http),
                     ("TCP", tcp), ("AliasNode", alias_node),
                     ("Interval", interval), ("ServiceID", service_id)):
            if v:
                body[k] = v
        out, _, _ = self.c._call("PUT", "/v1/agent/check/register", None,
                                 json.dumps(body).encode())
        return bool(out)

    def check_deregister(self, check_id: str) -> bool:
        out, _, _ = self.c._call(
            "PUT", f"/v1/agent/check/deregister/{check_id}")
        return bool(out)

    def check_update(self, check_id: str, status: str,
                     output: str = "") -> bool:
        """Set a TTL check's status + output (reference api/agent.go
        UpdateTTL)."""
        out, _, _ = self.c._call(
            "PUT", f"/v1/agent/check/update/{check_id}", None,
            json.dumps({"Status": status, "Output": output}).encode())
        return bool(out)

    def health_service_by_id(self, service_id: str) -> tuple[str, dict]:
        """(aggregated status, body) for one local service (reference
        api/agent.go AgentHealthServiceByID). Status rides the HTTP
        code (200/429/503), so non-2xx is data here, not an error."""
        try:
            out, _, _ = self.c._call(
                "GET", f"/v1/agent/health/service/id/{service_id}")
        except APIError as e:
            if e.status in (429, 503) and isinstance(e.body, dict):
                return e.body["AggregatedStatus"], e.body
            raise
        return out["AggregatedStatus"], out


class ConfigEntries:
    """Config-entry endpoints (reference api/config_entry.go:
    ConfigEntries.Set/CAS/Get/List/Delete over /v1/config)."""

    def __init__(self, c: Client):
        self.c = c

    def set(self, kind: str, name: str, entry: dict,
            cas: Optional[int] = None) -> bool:
        body = {"Kind": kind, "Name": name, **entry}
        out, _, _ = self.c._call(
            "PUT", "/v1/config",
            {"cas": cas if cas is not None else None},
            json.dumps(body).encode())
        return bool(out)

    def get(self, kind: str, name: str, index: int = 0,
            wait: str = "10s"):
        out, meta, status = self.c._call(
            "GET", f"/v1/config/{kind}/{name}",
            {"index": index or None, "wait": wait if index else None})
        return (None if status == 404 else out), meta

    def list(self, kind: str = "*", index: int = 0, wait: str = "10s"):
        out, meta, _ = self.c._call(
            "GET", f"/v1/config/{kind}",
            {"index": index or None, "wait": wait if index else None})
        return out, meta

    def delete(self, kind: str, name: str,
               cas: Optional[int] = None) -> bool:
        out, _, _ = self.c._call(
            "DELETE", f"/v1/config/{kind}/{name}",
            {"cas": cas if cas is not None else None})
        return bool(out)


class Operator:
    """Operator endpoints (reference api/operator_keyring.go)."""

    def __init__(self, c: Client):
        self.c = c

    def keyring_list(self) -> list[dict]:
        out, _, _ = self.c._call("GET", "/v1/operator/keyring")
        return out

    def _keyring_op(self, method: str, key_b64: str) -> bool:
        out, _, _ = self.c._call(
            method, "/v1/operator/keyring", None,
            json.dumps({"Key": key_b64}).encode())
        return bool(out)

    def keyring_install(self, key_b64: str) -> bool:
        return self._keyring_op("POST", key_b64)

    def keyring_use(self, key_b64: str) -> bool:
        return self._keyring_op("PUT", key_b64)

    def keyring_remove(self, key_b64: str) -> bool:
        return self._keyring_op("DELETE", key_b64)

    # Raft + autopilot operator surface (reference api/operator_raft.go,
    # api/operator_autopilot.go).
    def raft_get_configuration(self) -> dict:
        out, _, _ = self.c._call("GET", "/v1/operator/raft/configuration")
        return out

    def raft_remove_peer(self, id: str) -> bool:
        out, _, _ = self.c._call(
            "DELETE", "/v1/operator/raft/peer", {"id": id})
        return bool(out)

    def autopilot_get_configuration(self) -> dict:
        out, _, _ = self.c._call(
            "GET", "/v1/operator/autopilot/configuration")
        return out

    def autopilot_set_configuration(self, config: dict,
                                    cas: Optional[int] = None) -> bool:
        out, _, _ = self.c._call(
            "PUT", "/v1/operator/autopilot/configuration",
            {"cas": cas} if cas is not None else None,
            json.dumps(config).encode())
        return bool(out)

    def autopilot_server_health(self) -> dict:
        """Per-server autopilot health (reference api/operator_autopilot.go
        AutopilotServerHealth → /v1/operator/autopilot/health)."""
        out, _, _ = self.c._call("GET", "/v1/operator/autopilot/health")
        return out


class PreparedQuery:
    """Prepared-query CRUD + execute (reference api/prepared_query.go
    PreparedQuery.Create/Update/List/Get/Delete/Execute over
    /v1/query)."""

    def __init__(self, c: Client):
        self.c = c

    def create(self, definition: dict) -> str:
        out, _, _ = self.c._call("POST", "/v1/query", None,
                                 json.dumps(definition).encode())
        return out["ID"]

    def update(self, query_id: str, definition: dict) -> bool:
        out, _, _ = self.c._call("PUT", f"/v1/query/{query_id}", None,
                                 json.dumps(definition).encode())
        return bool(out)

    def get(self, query_id: str):
        out, meta, _ = self.c._call("GET", f"/v1/query/{query_id}")
        return out, meta

    def list(self):
        out, meta, _ = self.c._call("GET", "/v1/query")
        return out, meta

    def delete(self, query_id: str) -> bool:
        out, _, _ = self.c._call("DELETE", f"/v1/query/{query_id}")
        return bool(out)

    def execute(self, id_or_name: str, near: str = "",
                limit: int = 0) -> dict:
        params: dict = {}
        if near:
            params["near"] = near
        if limit:
            params["limit"] = limit
        out, _, _ = self.c._call("GET", f"/v1/query/{id_or_name}/execute",
                                 params or None)
        return out

    def explain(self, name: str) -> dict:
        out, _, _ = self.c._call("GET", f"/v1/query/{name}/explain")
        return out


class Connect:
    """Intention CRUD + match/check (reference api/connect_intention.go
    over /v1/connect/intentions)."""

    def __init__(self, c: Client):
        self.c = c

    def intention_create(self, source: str, destination: str,
                         action: str, description: str = "") -> str:
        out, _, _ = self.c._call(
            "POST", "/v1/connect/intentions", None, json.dumps({
                "SourceName": source, "DestinationName": destination,
                "Action": action, "Description": description,
            }).encode())
        return out["ID"]

    def intention_get(self, intention_id: str):
        out, _, _ = self.c._call(
            "GET", f"/v1/connect/intentions/{intention_id}")
        return out

    def intention_list(self):
        out, meta, _ = self.c._call("GET", "/v1/connect/intentions")
        return out, meta

    def intention_delete(self, intention_id: str) -> bool:
        out, _, _ = self.c._call(
            "DELETE", f"/v1/connect/intentions/{intention_id}")
        return bool(out)

    def intention_match(self, name: str,
                        by: str = "destination") -> list[dict]:
        out, _, _ = self.c._call("GET", "/v1/connect/intentions/match",
                                 {"by": by, "name": name})
        return out.get(name, [])

    def intention_check(self, source: str, destination: str) -> bool:
        out, _, _ = self.c._call("GET", "/v1/connect/intentions/check",
                                 {"source": source,
                                  "destination": destination})
        return bool(out["Allowed"])

    def discovery_chain(self, service: str) -> dict:
        """Compiled discovery chain (reference api/discovery_chain.go
        Get → /v1/discovery-chain/:service)."""
        out, _, _ = self.c._call("GET", f"/v1/discovery-chain/{service}")
        return out["Chain"]

    def ca_roots(self) -> dict:
        """CA trust bundle (reference api/connect_ca.go CARoots)."""
        out, _, _ = self.c._call("GET", "/v1/connect/ca/roots")
        return out

    def ca_get_config(self) -> dict:
        out, _, _ = self.c._call("GET", "/v1/connect/ca/configuration")
        return out

    def ca_set_config(self, config: dict) -> bool:
        out, _, _ = self.c._call("PUT", "/v1/connect/ca/configuration",
                                 None, json.dumps(config).encode())
        return bool(out)

    def ca_leaf(self, service: str) -> dict:
        """Mint/fetch a leaf certificate for a service (reference
        api/agent.go ConnectCALeaf → /v1/agent/connect/ca/leaf)."""
        out, _, _ = self.c._call(
            "GET", f"/v1/agent/connect/ca/leaf/{service}")
        return out


class ACL:
    """Token + policy API (reference api/acl.go: ACL.Bootstrap,
    TokenCreate/Read/Update/Delete/List, PolicyCreate/Read/Delete/
    List over /v1/acl/*)."""

    def __init__(self, c: Client):
        self.c = c

    def bootstrap(self) -> dict:
        out, _, _ = self.c._call("PUT", "/v1/acl/bootstrap")
        return out

    def token_create(self, description: str = "",
                     policies: Optional[list] = None) -> dict:
        out, _, _ = self.c._call("PUT", "/v1/acl/token", None, json.dumps({
            "Description": description,
            "Policies": [{"Name": p} for p in policies or []],
        }).encode())
        return out

    def token_read(self, accessor_id: str):
        out, _, _ = self.c._call("GET", f"/v1/acl/token/{accessor_id}")
        return out

    def token_update(self, accessor_id: str, description: str = "",
                     policies: Optional[list] = None) -> dict:
        out, _, _ = self.c._call(
            "PUT", f"/v1/acl/token/{accessor_id}", None, json.dumps({
                "Description": description,
                "Policies": [{"Name": p} for p in policies or []],
            }).encode())
        return out

    def token_delete(self, accessor_id: str) -> bool:
        out, _, _ = self.c._call("DELETE", f"/v1/acl/token/{accessor_id}")
        return bool(out)

    def token_list(self) -> list[dict]:
        out, _, _ = self.c._call("GET", "/v1/acl/tokens")
        return out

    def policy_create(self, name: str, rules: Any = "",
                      description: str = "") -> dict:
        out, _, _ = self.c._call("PUT", "/v1/acl/policy", None, json.dumps({
            "Name": name, "Rules": rules, "Description": description,
        }).encode())
        return out

    def policy_read(self, name: str):
        out, _, _ = self.c._call("GET", f"/v1/acl/policy/name/{name}")
        return out

    def policy_delete(self, name: str) -> bool:
        out, _, _ = self.c._call("DELETE", f"/v1/acl/policy/{name}")
        return bool(out)

    def policy_list(self) -> list[dict]:
        out, _, _ = self.c._call("GET", "/v1/acl/policies")
        return out


class Internal:
    """The combined node+services+checks dump (reference
    internal_endpoint.go NodeInfo/NodeDump via /v1/internal/ui/*)."""

    def __init__(self, c: Client):
        self.c = c

    def node_dump(self):
        out, meta, _ = self.c._call("GET", "/v1/internal/ui/nodes")
        return out, meta

    def node_info(self, node: str):
        out, meta, _ = self.c._call("GET", f"/v1/internal/ui/node/{node}")
        return out, meta

    def ui_services(self):
        """Per-service rollup — instance count + check status counts
        (reference ui_endpoint.go UIServices)."""
        out, meta, _ = self.c._call("GET", "/v1/internal/ui/services")
        return out, meta


class Lock:
    """Leader-election lock recipe over KV acquire/release (reference
    api/lock.go): create a session, spin on acquire, hold, release."""

    def __init__(self, client: Client, key: str, node: Optional[str] = None):
        self.client = client
        self.key = key
        self.node = node
        self.session: Optional[str] = None

    def acquire(self, value: bytes = b"", retries: int = 10,
                backoff_s: float = 0.1) -> bool:
        if self.session is None:
            self.session = self.client.session.create(node=self.node)
        for _ in range(retries):
            if self.client.kv.put(self.key, value, acquire=self.session):
                return True
            time.sleep(backoff_s)
        return False

    def release(self) -> bool:
        if self.session is None:
            return False
        ok = self.client.kv.put(self.key, b"", release=self.session)
        self.client.session.destroy(self.session)
        self.session = None
        return ok


class Semaphore:
    """Distributed counting semaphore over KV (reference
    api/semaphore.go): up to ``limit`` concurrent holders of a prefix.
    Each contender session-locks its own contender key under the
    prefix; the holder set lives in ``<prefix>/.lock``, mutated with
    CAS so two racing acquirers cannot both take the last slot, and
    pruned of holders whose contender key (and so session) died."""

    LOCK_KEY = ".lock"

    def __init__(self, client: Client, prefix: str, limit: int,
                 node: Optional[str] = None):
        if limit < 1:
            raise ValueError("semaphore limit must be >= 1")
        self.client = client
        self.prefix = prefix.rstrip("/")
        self.limit = limit
        self.node = node
        self.session: Optional[str] = None

    def _contender_key(self) -> str:
        return f"{self.prefix}/{self.session}"

    def _live_contenders(self) -> set:
        rows = self.client.kv.list(self.prefix + "/")
        return {r["Key"].rsplit("/", 1)[1] for r in rows
                if not r["Key"].endswith(self.LOCK_KEY)
                and r.get("Session")}

    def acquire(self, retries: int = 10, backoff_s: float = 0.1) -> bool:
        if self.session is None:
            self.session = self.client.session.create(node=self.node)
        lock_key = f"{self.prefix}/{self.LOCK_KEY}"
        # Announce contention: session-lock our contender key
        # (semaphore.go: the contender entry proves liveness — its
        # session dying releases the key, pruning us from the set).
        if not self.client.kv.put(self._contender_key(), b"",
                                  acquire=self.session):
            return False
        for _ in range(retries):
            row, _ = self.client.kv.get(lock_key)
            if row is None:
                holders: dict = {}
                cas = 0
            else:
                doc = json.loads(row["Value"] or b"{}")
                holders = doc.get("Holders", {})
                cas = row["ModifyIndex"]
            live = self._live_contenders()
            holders = {s: True for s in holders if s in live}
            if self.session in holders:
                return True
            if len(holders) < self.limit:
                holders[self.session] = True
                if self.client.kv.put(lock_key, json.dumps(
                        {"Limit": self.limit,
                         "Holders": holders}).encode(), cas=cas):
                    return True
                # CAS lost: another contender moved first — re-read.
            time.sleep(backoff_s)
        return False

    def release(self) -> bool:
        if self.session is None:
            return False
        lock_key = f"{self.prefix}/{self.LOCK_KEY}"
        for _ in range(10):
            row, _ = self.client.kv.get(lock_key)
            if row is None:
                break
            doc = json.loads(row["Value"] or b"{}")
            holders = doc.get("Holders", {})
            if self.session not in holders:
                break
            del holders[self.session]
            if self.client.kv.put(lock_key, json.dumps(
                    {"Limit": doc.get("Limit", self.limit),
                     "Holders": holders}).encode(),
                    cas=row["ModifyIndex"]):
                break
            time.sleep(0.05)
        self.client.kv.put(self._contender_key(), b"",
                           release=self.session)
        self.client.kv.delete(self._contender_key())
        self.client.session.destroy(self.session)
        self.session = None
        return True


class WatchPlan:
    """Watch-plan engine (reference api/watch/plan.go over the typed
    watch functions of api/watch/funcs.go:18-30): one blocking query
    re-run in a loop; the handler fires whenever X-Consul-Index moves.

    Types and their parameters:

      key        key=...            one KV entry        (funcs.go keyWatch)
      keyprefix  prefix=...         KV prefix listing   (keyPrefixWatch)
      services   —                  catalog service map (servicesWatch)
      nodes      —                  catalog node list   (nodesWatch)
      service    service=[, tag=]   one service's nodes (serviceWatch)
      checks     [state=|service=]  health checks       (checksWatch)
      event      [name=]            agent user events   (eventWatch)
      agent_service  service_id=    one LOCAL service   (agentServiceWatch)
      connect_roots  —              CA trust bundle     (connectRootsWatch)
      connect_leaf   service=       one service's leaf  (connectLeafWatch)

    ``handler(index, result)`` is the WatchPlan Handler contract. Drive
    it explicitly with :meth:`run_once` (tests, schedulers) or loop it
    on a thread with :meth:`run` / :meth:`stop`.

    ``agent_service`` is HASH-based like the reference (funcs.go
    agentServiceWatch uses hash blocking, not raft indexes — agent
    local state has no index): the plan fires when the response body's
    digest changes, surfacing a locally-monotonic change counter as
    the index.
    """

    TYPES = ("key", "keyprefix", "services", "nodes", "service",
             "checks", "event", "agent_service", "connect_roots",
             "connect_leaf")

    def __init__(self, client: Client, wtype: str, handler, **params):
        if wtype not in self.TYPES:
            raise ValueError(f"unsupported watch type {wtype!r}")
        self.client = client
        self.type = wtype
        self.handler = handler
        self.params = params
        self.index = 0
        self._stop = False
        # Hash-watch state (agent_service / connect_leaf).
        self._last_hash = None
        self._hash_seq = 0
        self._leaf_cache = None

    def _query(self, wait: str):
        c, p = self.client, self.params
        idx = {"index": self.index or None,
               "wait": wait if self.index else None}
        if self.type == "key":
            row, meta = c.kv.get(p["key"], index=self.index,
                                 wait=wait if self.index else "10s")
            return meta.index, row
        if self.type == "keyprefix":
            out, meta, _ = c._call(
                "GET", f"/v1/kv/{p.get('prefix', '')}",
                {"recurse": "", **idx})
            return meta.index, out or []
        if self.type == "services":
            out, meta, _ = c._call("GET", "/v1/catalog/services", idx)
            return meta.index, out
        if self.type == "nodes":
            out, meta, _ = c._call("GET", "/v1/catalog/nodes", idx)
            return meta.index, out
        if self.type == "service":
            # cached=True rides the agent cache's typed health-services
            # entry (?cached): N watch plans of one service share a
            # single agent-side store watch (reference serviceWatch hits
            # /v1/health/service, funcs.go:18-30 + HTTP ?cached). NOTE:
            # the cached result is HEALTH-shaped rows (node + service +
            # checks), not catalog rows; a tag filter has no cached
            # entry, so it falls back to the direct catalog path.
            if p.get("cached") and not p.get("tag"):
                out, meta, _ = c._call(
                    "GET", f"/v1/health/service/{p['service']}",
                    {"cached": "", **idx})
                return meta.index, out
            out, meta, _ = c._call(
                "GET", f"/v1/catalog/service/{p['service']}",
                {"tag": p.get("tag"), **idx})
            return meta.index, out
        if self.type == "checks":
            if p.get("service"):
                path = f"/v1/health/checks/{p['service']}"
            else:
                path = f"/v1/health/state/{p.get('state', 'any')}"
            out, meta, _ = c._call("GET", path, idx)
            return meta.index, out
        if self.type == "event":
            out, meta, _ = c._call(
                "GET", "/v1/event/list", {"name": p.get("name"), **idx})
            return meta.index, out
        if self.type == "connect_roots":
            out, meta, _ = c._call("GET", "/v1/connect/ca/roots", idx)
            return meta.index, out
        if self.type == "connect_leaf":
            # Change detection rides the CHEAP roots read (minting a
            # leaf generates a keypair + signs a cert server-side —
            # doing that every poll round and discarding it would be
            # ~86k wasted signings/day per watched service). A fresh
            # leaf is fetched only when the active root actually
            # changed — rotation, the reload signal a proxy needs.
            roots, _, _ = c._call("GET", "/v1/connect/ca/roots")
            digest = roots["ActiveRootID"]
            if digest != self._last_hash:
                self._last_hash = digest
                self._hash_seq += 1
                self._leaf_cache = c._call(
                    "GET",
                    f"/v1/agent/connect/ca/leaf/{p['service']}")[0]
            return self._hash_seq, self._leaf_cache
        if self.type == "agent_service":
            out, _, status = c._call(
                "GET", f"/v1/agent/service/{p['service_id']}")
            digest = hashlib.sha1(
                json.dumps(out, sort_keys=True).encode()).hexdigest()
            if digest != self._last_hash:
                self._last_hash = digest
                self._hash_seq += 1
            return self._hash_seq, out
        raise AssertionError(self.type)

    def run_once(self, wait: str = "10s") -> bool:
        """One blocking-query round; returns True when the handler
        fired (the index moved). Hash-based types (agent_service) have
        no server-side blocking — an unchanged round PACES itself with
        a short client-side sleep so run() cannot busy-loop the agent
        (the reference's watch retry interval)."""
        new_index, result = self._query(wait)
        if new_index == self.index:
            if self.type in ("agent_service", "connect_leaf"):
                try:
                    w = float(str(wait).rstrip("s"))
                except ValueError:
                    w = 1.0
                time.sleep(min(w, 1.0))
            return False
        # Reset on index regression, like the reference plan loop
        # (plan.go: an index that goes backwards restarts from 0).
        self.index = new_index if new_index > self.index else 0
        if self.handler is not None and self.index:
            self.handler(self.index, result)
        return True

    def run(self, wait: str = "10s", max_rounds: Optional[int] = None):
        """Loop run_once until stop() (reference plan.Run)."""
        rounds = 0
        while not self._stop:
            self.run_once(wait)
            rounds += 1
            if max_rounds is not None and rounds >= max_rounds:
                break

    def stop(self):
        self._stop = True


def watch(client: Client, wtype: str, handler=None, **params) -> WatchPlan:
    """Factory matching api/watch.Parse + Plan: ``watch(client, "key",
    handler, key="config/db")``."""
    return WatchPlan(client, wtype, handler, **params)
