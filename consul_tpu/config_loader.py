"""Layered configuration: files + environment + overrides → SimConfig.

Mirrors the reference config system's shape (reference agent/config/:
multi-source HCL/JSON files + env + CLI flags merged by ``Builder`` into
one validated, immutable ``RuntimeConfig``; runtime reload via SIGHUP
re-applies only a safe subset — agent/agent.go ReloadConfig). Here:

  - sources: JSON config files (merged in order, later wins), then
    ``CONSUL_TPU_*`` environment variables, then explicit overrides —
    the same later-source-wins layering as the reference builder;
  - keys use the dataclass field paths of config.py with ``.``
    separators (``gossip.probe_interval_ms``, ``n``, ``view_degree``);
    env vars upper-case them with ``__`` separators
    (``CONSUL_TPU_GOSSIP__PROBE_INTERVAL_MS=500``);
  - unknown keys fail loudly (the reference rejects unknown fields);
  - :func:`diff_reload` classifies a proposed new config against the
    running one: XLA bakes most simulation knobs into the compiled
    step at trace time, so anything that changes the compiled program
    is restart-only — the classification makes that explicit instead
    of silently ignoring the change (the reference's reload likewise
    applies only its safe subset and warns about the rest).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Iterable, Mapping, Optional

from consul_tpu.config import GossipConfig, SerfConfig, SimConfig, VivaldiConfig

ENV_PREFIX = "CONSUL_TPU_"

# Fields a running system can apply without recompiling the step
# program. Everything else is baked into traced constants or array
# shapes (tick cadences, view degree, capacities) and needs a restart.
SAFE_RELOAD = frozenset({
    # Traced constants re-read at the next runner compilation.
    "rtt_jitter_frac", "packet_loss",
    "serf.reconnect_timeout_ms", "serf.tombstone_timeout_ms",
    # World-shape knobs (world_diameter_ms, height_*) are NOT here:
    # the planted ground-truth world is built once at Simulation
    # construction, so changing them requires a restart.
})

_SECTIONS = {"gossip": GossipConfig, "vivaldi": VivaldiConfig,
             "serf": SerfConfig}


def _flatten(d: Mapping, prefix: str = "") -> dict[str, Any]:
    out: dict[str, Any] = {}
    for k, v in d.items():
        path = f"{prefix}{k}"
        if isinstance(v, Mapping):
            out.update(_flatten(v, path + "."))
        else:
            out[path] = v
    return out


def _known_paths() -> dict[str, type]:
    paths: dict[str, type] = {}
    for f in dataclasses.fields(SimConfig):
        if f.name in _SECTIONS:
            for sf in dataclasses.fields(_SECTIONS[f.name]):
                paths[f"{f.name}.{sf.name}"] = sf.type
        else:
            paths[f.name] = f.type
    return paths


def _coerce(path: str, value: Any, known: Mapping[str, type]) -> Any:
    """Env values arrive as strings; coerce by target field type."""
    if not isinstance(value, str):
        return value
    ftype = str(known.get(path, ""))
    if "bool" in ftype:
        return value.lower() in ("1", "true", "yes", "on")
    if "int" in ftype:
        return int(value)
    if "float" in ftype:
        return float(value)
    return value


def env_overrides(env: Optional[Mapping[str, str]] = None) -> dict[str, Any]:
    """CONSUL_TPU_GOSSIP__PROBE_INTERVAL_MS=500 → gossip.probe_interval_ms."""
    env = os.environ if env is None else env
    known = _known_paths()
    out = {}
    for k, v in env.items():
        if not k.startswith(ENV_PREFIX):
            continue
        path = k[len(ENV_PREFIX):].lower().replace("__", ".")
        if path in known:
            out[path] = _coerce(path, v, known)
    return out


def _read_config_file(p: str) -> Any:
    """One config file, JSON or HCL by extension (the reference's
    builder sniffs the format the same way, agent/config/builder.go
    format detection; .hcl via utils/hcl.py)."""
    if p.endswith((".hcl", ".tf")):
        from consul_tpu.utils import hcl

        try:
            return hcl.load(p)
        except hcl.HCLError as e:
            raise ValueError(f"config file {p}: {e}") from e
    with open(p, encoding="utf-8") as f:
        try:
            return json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"config file {p}: {e}") from e


def load(paths: Iterable[str] = (),
         env: Optional[Mapping[str, str]] = None,
         overrides: Optional[Mapping[str, Any]] = None) -> SimConfig:
    """Build one validated SimConfig from layered sources (the
    config.Builder pipeline: files in order, then env, then explicit
    overrides — later wins)."""
    flat: dict[str, Any] = {}
    for p in paths:
        doc = _read_config_file(p)
        if not isinstance(doc, dict):
            raise ValueError(f"config file {p}: top level must be an object")
        flat.update(_flatten(doc))
    flat.update(env_overrides(env))
    for k, v in (overrides or {}).items():
        flat[k] = v

    known = _known_paths()
    unknown = sorted(set(flat) - set(known))
    if unknown:
        raise ValueError(f"unknown config keys: {unknown}")

    sections: dict[str, dict] = {name: {} for name in _SECTIONS}
    top: dict[str, Any] = {}
    for path, value in flat.items():
        value = _coerce(path, value, known)
        if "." in path:
            sec, field = path.split(".", 1)
            sections[sec][field] = value
        else:
            top[path] = value
    kwargs: dict[str, Any] = dict(top)
    for name, cls in _SECTIONS.items():
        if sections[name]:
            kwargs[name] = cls(**sections[name])
    return SimConfig(**kwargs)


def to_flat(cfg: SimConfig) -> dict[str, Any]:
    return _flatten(dataclasses.asdict(cfg))


def diff_reload(old: SimConfig, new: SimConfig) -> dict[str, list[str]]:
    """Classify a proposed reload (the SIGHUP path): which changed keys
    apply live and which require a restart (recompile). Returns
    {"safe": [...], "restart": [...]} — empty lists mean no change."""
    a, b = to_flat(old), to_flat(new)
    changed = sorted(k for k in a if a[k] != b.get(k))
    return {
        "safe": [k for k in changed if k in SAFE_RELOAD],
        "restart": [k for k in changed if k not in SAFE_RELOAD],
    }


def apply_safe(sim, new: SimConfig) -> list[str]:
    """Apply the safe subset of a reload to a running Simulation
    (models/cluster.py): rebuild cfg with only SAFE_RELOAD changes so
    compiled programs stay valid; returns the applied keys."""
    d = diff_reload(sim.cfg, new)
    if not d["safe"]:
        return []
    flat_new = to_flat(new)
    merged = to_flat(sim.cfg)
    for k in d["safe"]:
        merged[k] = flat_new[k]
    nested: dict[str, Any] = {}
    for path, v in merged.items():
        cur = nested
        parts = path.split(".")
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = v
    kwargs = dict(nested)
    for name, cls in _SECTIONS.items():
        kwargs[name] = cls(**nested[name])
    sim.cfg = SimConfig(**kwargs)
    # Changed knobs that feed traced constants (loss, jitter) take
    # effect on the next runner compilation; invalidate the cache.
    sim._runners.clear()
    sim._warmed.clear()
    return d["safe"]
