"""Device-resident serving plane (consul_tpu/serving + ops/serving.py).

Covers the golden-parity contract against the host reference
(server/rtt.py), snapshot semantics (consistent-as-of-tick, never
torn), the QueryBatcher's bucketing/padding/fan-out, the compile-ledger
pin (steady-state serving adds zero executables), the agent-cache
front, telemetry counters, and the DNS / endpoints / prepared-query
wiring."""

import math
import random
import threading

import pytest

from consul_tpu.config import SimConfig
from consul_tpu.models.cluster import Simulation
from consul_tpu.server import rtt
from consul_tpu.server.prepared_query import nearest_sorted
from consul_tpu.serving import (MODE_DIST, MODE_NEAREST, QueryBatcher,
                                ServingPlane)


def make_coord_sets(n=12, seed=7, dims=4):
    """Random host coordinate sets exercising every edge the reference
    math has: continuous coords (no accidental ties), one huge negative
    adjustment (the adjusted<=0 clamp), one dimensionality mismatch
    (+inf pairs)."""
    rng = random.Random(seed)
    sets = {}
    for i in range(n):
        sets[f"n{i}"] = {"": {
            "vec": [rng.uniform(-0.05, 0.05) for _ in range(dims)],
            "height": rng.uniform(1e-5, 0.01),
            "adjustment": rng.uniform(-0.02, 0.02),
        }}
    sets["n3"][""]["adjustment"] = -10.0      # clamp: adjusted <= 0
    sets["n7"] = {"": {"vec": [0.1, 0.2],     # wrong dimensionality
                       "height": 0.001, "adjustment": 0.0}}
    return sets


def host_pair_distance(sets, a, b):
    sa, sb = sets.get(a), sets.get(b)
    if not sa or not sb:
        return math.inf
    return rtt.compute_distance(*rtt.intersect(sa, sb))


class TestGoldenParity:
    """Device kernel vs server/rtt.py — the documented reference."""

    def test_sort_rows_matches_reference(self):
        sets = make_coord_sets()
        rows = [{"node": f"n{i}"} for i in range(12)]
        rows += [{"node": "ghost"}, {"node": "ghost2"}]  # unregistered
        random.Random(3).shuffle(rows)
        plane = ServingPlane(k=4, buckets=(1, 4, 16))
        got = plane.sort_rows(sets, "n0", [dict(r) for r in rows])
        want = rtt.sort_nodes_by_distance(sets, "n0",
                                          [dict(r) for r in rows])
        assert [r["node"] for r in got] == [r["node"] for r in want]
        # Unknown coordinates (wrong dims, unregistered) sorted last.
        assert {r["node"] for r in got[-3:]} == {"n7", "ghost", "ghost2"}

    def test_sort_rows_from_every_source(self):
        sets = make_coord_sets(seed=11)
        rows = [{"node": f"n{i}"} for i in range(12)]
        plane = ServingPlane(k=4, buckets=(1, 4, 16))
        for src in ("n1", "n3", "n5"):  # incl. the clamped source
            got = plane.sort_rows(sets, src, [dict(r) for r in rows])
            want = rtt.sort_nodes_by_distance(sets, src,
                                              [dict(r) for r in rows])
            assert [r["node"] for r in got] == [r["node"] for r in want]

    def test_node_distance_matches_compute_distance(self):
        sets = make_coord_sets()
        plane = ServingPlane(k=2, buckets=(1, 4))
        assert plane.publish_coords(sets)
        for a, b in [("n0", "n1"), ("n0", "n3"), ("n2", "n5"),
                     ("n3", "n4"), ("n0", "n0")]:
            want = host_pair_distance(sets, a, b)
            got = plane.node_distance(a, b)
            assert got == pytest.approx(want, rel=1e-4, abs=1e-6)

    def test_unknown_coordinate_is_inf(self):
        sets = make_coord_sets()
        plane = ServingPlane(k=2, buckets=(1, 4))
        assert plane.publish_coords(sets)
        # Wrong dimensionality pairs and unregistered nodes: +inf on
        # both paths (reference lib/rtt.go nil/mismatch rule).
        assert math.isinf(host_pair_distance(sets, "n0", "n7"))
        assert math.isinf(plane.node_distance("n0", "n7"))
        assert math.isinf(plane.node_distance("n0", "ghost"))

    def test_adjustment_clamp_matches(self):
        # n3 carries adjustment=-10: adjusted <= 0, so both paths must
        # return the UNadjusted distance (coordinate.go clamp).
        sets = make_coord_sets()
        plane = ServingPlane(k=2, buckets=(1, 4))
        assert plane.publish_coords(sets)
        want = host_pair_distance(sets, "n3", "n5")
        c3, c5 = sets["n3"][""], sets["n5"][""]
        unadjusted = (math.dist(c3["vec"], c5["vec"])
                      + c3["height"] + c5["height"])
        assert want == pytest.approx(unadjusted)  # clamp engaged
        assert plane.node_distance("n3", "n5") == pytest.approx(
            want, rel=1e-4)

    def test_unknown_source_returns_rows_unchanged(self):
        sets = make_coord_sets()
        rows = [{"node": f"n{i}"} for i in range(5)]
        plane = ServingPlane(k=2, buckets=(1, 8))
        got = plane.sort_rows(sets, "nope", [dict(r) for r in rows])
        assert [r["node"] for r in got] == [r["node"] for r in rows]

    def test_segmented_sets_fall_back_to_reference(self):
        # Named segments aren't modeled on device; the plane must defer
        # to rtt.py and still produce the reference order.
        sets = make_coord_sets()
        sets["n1"]["alpha"] = {"vec": [0.0] * 4, "height": 0.0,
                               "adjustment": 0.0}
        rows = [{"node": f"n{i}"} for i in range(12)]
        plane = ServingPlane(k=4, buckets=(1, 16))
        got = plane.sort_rows(sets, "n0", [dict(r) for r in rows])
        want = rtt.sort_nodes_by_distance(sets, "n0",
                                          [dict(r) for r in rows])
        assert [r["node"] for r in got] == [r["node"] for r in want]
        assert plane.batcher.queries == 0  # device path never ran


@pytest.fixture(scope="module")
def served_sim():
    """One small formed simulation with an attached plane, shared by
    the sim-mode tests (module-scoped: forming is the slow part)."""
    sim = Simulation(SimConfig(n=64, view_degree=8), seed=3)
    sim.run(64, chunk=32, with_metrics=False)
    plane = ServingPlane(k=8, buckets=(1, 4, 16))
    sim.attach_serving(plane)
    return sim, plane


class TestSimServing:
    def test_nearest_matches_host_math_on_device_coords(self, served_sim):
        import jax

        sim, plane = served_sim
        snap = plane.snapshot()
        vec, height, adj = jax.device_get(
            (snap.vec, snap.height, snap.adjustment))
        src = 5
        res = plane.nearest(src)
        assert res.count == int(jax.device_get(snap.live).sum())

        def host_dist(j):
            d = (math.dist(vec[src].tolist(), vec[j].tolist())
                 + float(height[src]) + float(height[j]))
            a = d + float(adj[src]) + float(adj[j])
            return a if a > 0.0 else d

        rtts = [r for _, r in res.nodes]
        assert rtts == sorted(rtts)  # ascending RTT
        for node, r in res.nodes:
            assert r == pytest.approx(host_dist(node), rel=1e-4, abs=1e-6)

    def test_snapshot_is_consistent_as_of_tick_never_torn(self, served_sim):
        import jax

        sim, plane = served_sim
        old = plane.snapshot()
        old_tick = int(jax.device_get(old.tick))
        old_live = int(jax.device_get(old.live).sum())
        sim.run(32, chunk=32, with_metrics=False)
        # The plane republished at the chunk boundary...
        assert plane.tick == old_tick + 32
        # ...but a reader's previously-grabbed snapshot is untouched:
        # same tick, same live view (immutable arrays, double buffer).
        assert int(jax.device_get(old.tick)) == old_tick
        assert int(jax.device_get(old.live).sum()) == old_live

    def test_kill_excludes_from_nearest_and_health(self, served_sim):
        sim, plane = served_sim
        before = plane.health_nodes().count
        sim.kill([1] * 8 + [0] * 56)
        res = plane.nearest(20)
        assert all(node >= 8 for node, _ in res.nodes)
        assert plane.health_nodes().count == before - 8
        sim.revive([1] * 8 + [0] * 56)

    def test_catalog_includes_dead_nodes(self, served_sim):
        sim, plane = served_sim
        sim.kill([1] * 4 + [0] * 60)
        try:
            # Catalog = registered, health = live (reference catalog vs
            # health endpoint split).
            assert plane.catalog_nodes().count == 64
            assert plane.health_nodes().count == 60
        finally:
            sim.revive([1] * 4 + [0] * 60)


class TestQueryBatcher:
    def test_bucketing_pads_to_fixed_shapes(self, served_sim):
        _, plane = served_sim
        b = QueryBatcher(plane, k=4, buckets=(1, 4, 16))
        b.execute([(MODE_NEAREST, 2, -1)] * 3)  # 3 -> bucket 4
        assert b.batches == 1 and b.queries == 3 and b.padded_slots == 1
        st = b.stats()
        assert st["padding_waste_pct"] == pytest.approx(25.0)

    def test_oversize_batch_chunks_at_max_bucket(self, served_sim):
        _, plane = served_sim
        b = QueryBatcher(plane, k=4, buckets=(1, 4))
        out = b.execute([(MODE_DIST, i % 64, (i + 1) % 64)
                        for i in range(10)])
        assert len(out) == 10
        assert b.batches == 3  # 4 + 4 + 2(->4)
        assert all(r.count == 1 for r in out)

    def test_concurrent_submits_coalesce_and_fan_out(self, served_sim):
        _, plane = served_sim
        b = QueryBatcher(plane, k=4, buckets=(1, 4, 16),
                         max_wait_s=0.05)
        results = {}
        errors = []

        def reader(i):
            try:
                results[i] = b.submit(MODE_DIST, i, (i + 1) % 64,
                                      timeout_s=10.0)
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=reader, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert not errors
        assert len(results) == 12
        assert b.queries == 12
        # Every waiter got ITS OWN answer fanned back (src-specific).
        for i, r in results.items():
            assert r.count == 1
            assert math.isfinite(r.rtts[0])
        # Coalescing happened: fewer kernel launches than queries.
        assert b.batches < 12

    def test_telemetry_counters_through_shared_sink(self, served_sim):
        sim, plane = served_sim
        before_q = sim.sink.counter_sum("sim.serving.queries")
        before_b = sim.sink.counter_sum("sim.serving.batches")
        before_p = sim.sink.counter_sum("sim.serving.padded_slots")
        plane.batcher.execute([(MODE_NEAREST, 1, -1)] * 3)  # bucket 4
        assert sim.sink.counter_sum("sim.serving.queries") == before_q + 3
        assert sim.sink.counter_sum("sim.serving.batches") == before_b + 1
        assert (sim.sink.counter_sum("sim.serving.padded_slots")
                == before_p + 1)


class TestCompileLedgerPin:
    def test_steady_state_serving_adds_zero_compiles(self, compile_ledger):
        """Bucketed shapes = one executable per bucket: after one warm
        batch per bucket, any mix of batch sizes, modes, and republishes
        compiles NOTHING new (the acceptance-criteria pin)."""
        sim = Simulation(SimConfig(n=64, view_degree=8), seed=5)
        sim.run(32, chunk=32, with_metrics=False)
        plane = ServingPlane(k=4, buckets=(1, 4, 16))
        sim.attach_serving(plane)  # warms project()
        # Warm each bucket's executable once.
        plane.batcher.execute([(MODE_NEAREST, 0, -1)] * 1)
        plane.batcher.execute([(MODE_NEAREST, 0, -1)] * 4)
        plane.batcher.execute([(MODE_NEAREST, 0, -1)] * 16)
        with compile_ledger.expect(0):
            # New batch sizes within warmed buckets, different modes,
            # different values, and scan-loop republishes.
            sim.run(64, chunk=32, with_metrics=False)
            plane.batcher.execute([(MODE_DIST, 1, 2)] * 3)       # -> 4
            plane.batcher.execute([(MODE_NEAREST, 7, -1)] * 9)   # -> 16
            plane.nearest(11)                                    # -> 1
            plane.health_nodes()
            plane.catalog_nodes()


class TestCacheFront:
    def test_cache_type_fetcher_is_the_device_path(self, served_sim):
        from consul_tpu.agent.cache import Cache

        _, plane = served_sim
        cache = Cache()
        plane.register_cache_type(cache, ttl_s=30.0)
        before_hits = plane.cache_hits
        v1 = plane.cached_nearest(cache, 3)
        v2 = plane.cached_nearest(cache, 3)
        assert v1 == v2
        assert v1["count"] > 0 and v1["nodes"][0][0] == 3  # self nearest
        # One device fetch, one cache hit.
        assert cache.fetch_count("serving-nearest", src=3, service=-1) == 1
        assert plane.cache_hits == before_hits + 1
        cache.close()

    def test_agent_attach_serving(self, served_sim):
        from consul_tpu.agent.agent import Agent

        _, plane = served_sim
        agent = Agent("n0", "10.0.0.1", rpc=lambda *a, **k: None)
        agent.attach_serving(plane)
        out1 = agent.serving_nearest(9)
        out2 = agent.serving_nearest(9)
        assert out1 == out2 and out1["count"] > 0
        assert (agent.cache.fetch_count("serving-nearest", src=9,
                                        service=-1) == 1)
        agent.close()


class TestWiring:
    def test_dns_serving_order(self):
        """DNS answers come back in serving-plane NearestN order from
        the agent's node (instead of the reference shuffle) when a
        sorter is wired."""
        from consul_tpu.agent import dns

        sets = make_coord_sets(n=6, seed=2)
        plane = ServingPlane(k=4, buckets=(1, 8))
        srv = dns.DNSServer(
            lambda *a, **k: None, node_name="n0",
            serving=lambda rows: plane.sort_rows(sets, "n0", rows))
        rows = [{"node": f"n{i}",
                 "service": {"address": f"10.0.0.{i}", "port": 80}}
                for i in range(5, -1, -1)]
        recs = srv._service_rows_to_records(
            "web.service.consul", dns.A, rows, 0)
        got = [r[3] for r in recs]
        want_rows = rtt.sort_nodes_by_distance(
            sets, "n0", [{"node": f"n{i}"} for i in range(5, -1, -1)])
        want = [f"10.0.0.{r['node'][1:]}" for r in want_rows]
        assert got == want

    def test_endpoints_near_sorting_through_plane(self):
        from consul_tpu.server.endpoints import ServerCluster

        c = ServerCluster(3, seed=1)
        c.wait_converged()
        leader = c.leader_server()
        for i in range(3):
            c.write(leader, "Catalog.Register", node=f"n{i}",
                    address=f"10.0.0.{i}",
                    service={"id": "web", "service": "web"})
            leader.rpc("Coordinate.Update", node=f"n{i}",
                       coord={"vec": [i * 0.010] + [0.0] * 7,
                              "error": 1.5, "height": 0.0,
                              "adjustment": 0.0})
        leader.flush_coordinates()
        c.step(30)
        plane = ServingPlane(k=4, buckets=(1, 8))
        leader.attach_serving(plane)
        out = leader.rpc("Catalog.ListNodes", near="n2")
        assert [n["node"] for n in out["value"]] == ["n2", "n1", "n0"]
        out = leader.rpc("Health.ServiceNodes", service="web", near="n0")
        assert [n["node"] for n in out["value"]] == ["n0", "n1", "n2"]
        assert plane.batcher.queries > 0  # the device path served them

    def test_prepared_query_nearest_sorted_pins_near_node_first(self):
        sets = make_coord_sets(n=6, seed=4)
        plane = ServingPlane(k=4, buckets=(1, 8))
        nodes = [{"node": f"n{i}"} for i in range(6)]

        def sort_fn(near, rows):
            return plane.sort_rows(sets, near, rows)

        got = nearest_sorted([dict(r) for r in nodes], "n4", sort_fn)
        # n4 floats to position 0; the rest keep reference RTT order.
        assert got[0]["node"] == "n4"
        want = rtt.sort_nodes_by_distance(sets, "n4",
                                          [dict(r) for r in nodes])
        assert sorted(r["node"] for r in got) == sorted(
            r["node"] for r in want)

    def test_http_metrics_exposes_consul_serving_gauges(self, served_sim):
        from consul_tpu.agent.agent import Agent
        from consul_tpu.agent.http import HTTPApi

        _, plane = served_sim
        agent = Agent("n0", "10.0.0.1", rpc=lambda *a, **k: None)
        agent.attach_serving(plane)
        api = HTTPApi(agent)
        status, snap, _ = api.handle("GET", "/v1/agent/metrics", {}, b"")
        assert status == 200
        gauges = {g["Name"] for g in snap["Gauges"]}
        for name in ("consul.serving.queries", "consul.serving.batches",
                     "consul.serving.padded_slots",
                     "consul.serving.cache_hits",
                     "consul.serving.p50_batch_ms"):
            assert name in gauges
        agent.close()


class TestPlaneGuards:
    def test_one_plane_one_source(self, served_sim):
        _, plane = served_sim
        with pytest.raises(RuntimeError, match="simulation"):
            plane.publish_coords(make_coord_sets())
        host_plane = ServingPlane(k=2, buckets=(1, 4))
        assert host_plane.publish_coords(make_coord_sets())
        with pytest.raises(RuntimeError, match="host"):
            host_plane.attach(object())

    def test_unpublished_plane_refuses_reads(self):
        plane = ServingPlane(k=2, buckets=(1,))
        with pytest.raises(RuntimeError, match="snapshot"):
            plane.nearest(0)


class TestLockLedgerHotPath:
    """The serving read hot path under the LockLedger (the dynamic half
    of TH114-TH117, consul_tpu/analysis/ledger.py): a stack built while
    the ledger is installed gets traced shim locks, so concurrent
    batched reads record real acquisition orders. Clean = no blocking
    region under a lock, acyclic observed order graph, nothing leaked.
    Seeds perturb the acquisition schedule deterministically."""

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_concurrent_reads_stay_clean(self, lock_ledger, seed):
        lock_ledger.fuzz(seed)
        # Fresh stack INSIDE the ledger's scope — locks built before
        # install would be plain primitives and invisible.
        sim = Simulation(SimConfig(n=64, view_degree=8), seed=3)
        sim.run(32, chunk=32, with_metrics=False)
        plane = ServingPlane(k=8, buckets=(1, 4, 16))
        sim.attach_serving(plane)
        b = QueryBatcher(plane, k=4, buckets=(1, 4, 16), max_wait_s=0.05)
        results, errors = {}, []

        def reader(i):
            try:
                results[i] = b.submit(MODE_DIST, i, (i + 1) % 64,
                                      timeout_s=10.0)
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=reader, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert not errors and len(results) == 12

        # The shims were live: the hot-path locks appear in the trace
        # (a regression to bare threading.Lock would pass vacuously).
        names = {a[0] for a in lock_ledger.acquisitions}
        assert "QueryBatcher._lock" in names
        lock_ledger.assert_clean()
