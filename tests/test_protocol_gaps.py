"""Tests for the round-3 protocol-gap closures: event coalescing, query
relay factor, cluster keyring rotation, and bridge name conflicts
(reference serf/coalesce*.go, serf/query.go RelayFactor,
serf/keymanager.go, serf/serf.go:1413-1486)."""

import os

import jax
import jax.numpy as jnp
import pytest

from consul_tpu.config import SerfConfig, SimConfig
from consul_tpu.models import coalesce
from consul_tpu.models import serf as serf_mod
from consul_tpu.ops import topology
from consul_tpu.wire.keymanager import KeyManager
from consul_tpu.wire.keyring import HAVE_CRYPTOGRAPHY, Keyring


class TestMemberCoalescer:
    def test_burst_collapses_to_latest_per_member(self):
        c = coalesce.MemberEventCoalescer(coalesce_period=5,
                                          quiescent_period=10)
        for t, typ in [(0, coalesce.MEMBER_JOIN),
                       (1, coalesce.MEMBER_FAILED),
                       (2, coalesce.MEMBER_JOIN)]:
            assert c.ingest(coalesce.Event(typ, name="n1"), t) is None
        assert c.tick(4) == []  # quantum not reached
        out = c.tick(5)
        assert [(e.type, e.name) for e in out] == [(coalesce.MEMBER_JOIN, "n1")]

    def test_repeat_flush_suppressed_except_update(self):
        c = coalesce.MemberEventCoalescer(2, 10)
        c.ingest(coalesce.Event(coalesce.MEMBER_JOIN, name="n1"), 0)
        assert len(c.tick(2)) == 1
        c.ingest(coalesce.Event(coalesce.MEMBER_JOIN, name="n1"), 3)
        assert c.tick(5) == []  # same type re-flushed: suppressed
        c.ingest(coalesce.Event(coalesce.MEMBER_UPDATE, name="n1"), 6)
        assert len(c.tick(8)) == 1
        c.ingest(coalesce.Event(coalesce.MEMBER_UPDATE, name="n1"), 9)
        assert len(c.tick(11)) == 1  # updates always flush

    def test_quiescent_flush_before_quantum(self):
        c = coalesce.MemberEventCoalescer(coalesce_period=100,
                                          quiescent_period=2)
        c.ingest(coalesce.Event(coalesce.MEMBER_JOIN, name="n1"), 0)
        assert c.tick(1) == []
        assert len(c.tick(2)) == 1  # idle 2 ticks -> quiescent flush

    def test_non_member_events_pass_through(self):
        c = coalesce.MemberEventCoalescer(5, 5)
        e = coalesce.Event(coalesce.USER, name="deploy")
        assert c.ingest(e, 0) is e


class TestUserCoalescer:
    def test_latest_ltime_wins(self):
        c = coalesce.UserEventCoalescer(3, 10)
        c.ingest(coalesce.Event(coalesce.USER, name="deploy", ltime=5), 0)
        c.ingest(coalesce.Event(coalesce.USER, name="deploy", ltime=7), 1)
        c.ingest(coalesce.Event(coalesce.USER, name="deploy", ltime=6), 2)
        out = c.tick(3)
        assert [e.ltime for e in out] == [7]

    def test_same_ltime_all_flush(self):
        c = coalesce.UserEventCoalescer(3, 10)
        c.ingest(coalesce.Event(coalesce.USER, name="d", ltime=5,
                                payload=b"a"), 0)
        c.ingest(coalesce.Event(coalesce.USER, name="d", ltime=5,
                                payload=b"b"), 1)
        assert {e.payload for e in c.tick(3)} == {b"a", b"b"}

    def test_no_coalesce_flag_passes_through(self):
        c = coalesce.UserEventCoalescer(3, 10)
        e = coalesce.Event(coalesce.USER, name="d", ltime=1, coalesce=False)
        assert c.ingest(e, 0) is e

    def test_pipeline_routes_both_kinds(self):
        p = coalesce.CoalescePipeline(2, 1, 2, 1)
        assert p.ingest(
            coalesce.Event(coalesce.MEMBER_JOIN, name="n1"), 0) == []
        assert p.ingest(
            coalesce.Event(coalesce.USER, name="d", ltime=3), 0) == []
        out = p.tick(2)
        assert {e.type for e in out} == {coalesce.MEMBER_JOIN, coalesce.USER}


class TestQueryRelay:
    def _run_query(self, relay_factor, loss=0.25, n=48, seed=5):
        cfg = SimConfig(
            n=n, view_degree=16, packet_loss=loss,
            serf=SerfConfig(query_relay_factor=relay_factor),
        )
        key = jax.random.PRNGKey(seed)
        kw, kn, ks = jax.random.split(key, 3)
        world = topology.make_world(cfg, kw)
        topo = topology.make_topology(cfg, kn)
        state = serf_mod.init(cfg, ks)
        step = jax.jit(lambda st, k: serf_mod.step(cfg, topo, world, st, k))
        state = serf_mod.query(cfg, state, jnp.arange(n) == 0, 3)
        base = jax.random.PRNGKey(seed + 1)
        for i in range(serf_mod.query_timeout_ticks(cfg) - 1):
            state = step(state, jax.random.fold_in(base, i))
        return int(state.q_resps[0, 0]), n

    def test_relay_recovers_lost_responses(self):
        """RelayFactor exists to survive response loss (query.go:31-33):
        under 25% packet loss, relayed duplicates must recover most of
        the responses the direct-only path drops."""
        base, n = self._run_query(relay_factor=0)
        relayed, _ = self._run_query(relay_factor=3)
        assert relayed > base
        assert relayed >= (n - 1) * 0.9

    def test_relay_never_double_counts(self):
        relayed, n = self._run_query(relay_factor=4, loss=0.0)
        assert relayed == n - 1  # exactly one tally per responder


class TestKeyManager:
    def make(self, n=4):
        k0 = os.urandom(16)
        members = {f"m{i}": Keyring(primary=k0) for i in range(n)}
        return k0, members

    @pytest.mark.skipif(
        not HAVE_CRYPTOGRAPHY,
        reason="requires the 'cryptography' package (AES-GCM)")
    def test_full_rotation_flow(self):
        k0, members = self.make()
        mgr = KeyManager(members)
        k1 = os.urandom(32)
        r = mgr.install_key(k1)
        assert r.ok and r.num_resp == 4
        # Everyone can decrypt k1 traffic, but primary is still k0.
        blob = members["m0"].encrypt(b"x")
        assert members["m3"].decrypt(blob) == b"x"
        r = mgr.use_key(k1)
        assert r.ok
        assert all(ring.primary == k1 for ring in members.values())
        r = mgr.remove_key(k0)
        assert r.ok
        keys = mgr.list_keys()
        assert keys.keys == {__import__("base64").b64encode(k1).decode(): 4}

    def test_use_key_fails_on_member_missing_it(self):
        k0, members = self.make()
        k1 = os.urandom(16)

        # One member is unreachable during install (partition).
        mgr = KeyManager(members,
                         reachable=lambda: {"m0", "m1", "m2"})
        r = mgr.install_key(k1)
        assert r.num_resp == 3 and not r.ok  # partial install visible
        # Now everyone reachable: use-key errors on the member that
        # missed the install — the operator sees the failed rotation.
        mgr_all = KeyManager(members)
        r = mgr_all.use_key(k1)
        assert r.num_err == 1 and "m3" in r.messages

    def test_remove_primary_rejected_per_member(self):
        k0, members = self.make(2)
        mgr = KeyManager(members)
        r = mgr.remove_key(k0)
        assert r.num_err == 2 and not r.ok
