"""Intentions (reference agent/consul/intention_endpoint.go +
structs/intention.go): raft-replicated source→destination allow/deny
rules with wildcard support, precedence ordering, Match and Check."""

import json
import threading
import time

import pytest

from consul_tpu.agent.agent import Agent
from consul_tpu.agent.http import HTTPApi
from consul_tpu.api import Client
from consul_tpu.server.endpoints import Server, ServerCluster


class TestPrecedence:
    def test_ordering(self):
        p = Server._intention_precedence
        assert p("web", "db") > p("*", "db")
        assert p("*", "db") > p("web", "*")
        assert p("web", "*") > p("*", "*")


@pytest.fixture
def cluster():
    c = ServerCluster(3, seed=23)
    c.wait_converged()
    return c


class TestEndpoint:
    def test_crud_replicates(self, cluster):
        leader = cluster.leader_server()
        out = cluster.write(leader, "Intention.Apply", op="create",
                            intention={"source": "web",
                                       "destination": "db",
                                       "action": "allow"})
        iid = out["id"]
        for s in cluster.servers:
            assert s.store.intention_get(iid)["source"] == "web"
        got = leader.rpc("Intention.Get", intention_id=iid)
        assert got["value"][0]["precedence"] == 9
        cluster.write(leader, "Intention.Apply", op="update",
                      intention={"id": iid, "source": "web",
                                 "destination": "db", "action": "deny"})
        assert leader.store.intention_get(iid)["action"] == "deny"
        cluster.write(leader, "Intention.Apply", op="delete",
                      intention_id=iid)
        assert leader.store.intention_get(iid) is None

    def test_validation(self, cluster):
        leader = cluster.leader_server()
        with pytest.raises(ValueError, match="source must be set"):
            leader.rpc("Intention.Apply", op="create",
                       intention={"destination": "db", "action": "allow"})
        with pytest.raises(ValueError, match="partial"):
            leader.rpc("Intention.Apply", op="create",
                       intention={"source": "web*", "destination": "db",
                                  "action": "allow"})
        with pytest.raises(ValueError, match="allow or deny"):
            leader.rpc("Intention.Apply", op="create",
                       intention={"source": "a", "destination": "b",
                                  "action": "maybe"})

    def test_duplicate_pair_is_verdict(self, cluster):
        leader = cluster.leader_server()
        cluster.write(leader, "Intention.Apply", op="create",
                      intention={"source": "a", "destination": "b",
                                 "action": "allow"})
        out = cluster.write(leader, "Intention.Apply", op="create",
                            intention={"source": "a", "destination": "b",
                                       "action": "deny"})
        res = leader.rpc("Status.ApplyResult", index=out["index"])
        assert res["found"] and res["result"] is False
        assert len([x for x in leader.store.intention_list()
                    if x["source"] == "a"]) == 1

    def test_match_and_check_precedence(self, cluster):
        leader = cluster.leader_server()
        for src, dst, act in (("*", "db", "deny"),
                              ("web", "db", "allow"),
                              ("*", "*", "allow")):
            cluster.write(leader, "Intention.Apply", op="create",
                          intention={"source": src, "destination": dst,
                                     "action": act})
        m = leader.rpc("Intention.Match", by="destination", name="db")
        # Highest precedence first: exact/exact, then */db, then */*.
        assert [(x["source"], x["destination"]) for x in m["value"]] == \
            [("web", "db"), ("*", "db"), ("*", "*")]
        # web→db: the exact rule wins over the */db deny.
        assert leader.rpc("Intention.Check", source="web",
                          destination="db")["allowed"] is True
        # api→db: */db deny wins over */* allow.
        assert leader.rpc("Intention.Check", source="api",
                          destination="db")["allowed"] is False
        # api→cache: only */* matches -> allow.
        assert leader.rpc("Intention.Check", source="api",
                          destination="cache")["allowed"] is True
        # No match at all -> default.
        solo = ServerCluster(1, seed=29)
        solo.wait_converged()
        assert solo.leader_server().rpc(
            "Intention.Check", source="x", destination="y")["allowed"] \
            is True
        assert solo.leader_server().rpc(
            "Intention.Check", source="x", destination="y",
            default_allow=False)["allowed"] is False


@pytest.fixture(scope="module")
def stack():
    cluster = ServerCluster(3, seed=31)
    cluster.wait_converged()
    stop = threading.Event()
    lock = threading.Lock()

    def pump():
        while not stop.is_set():
            with lock:
                cluster.step()
            time.sleep(0.002)

    threading.Thread(target=pump, daemon=True).start()

    def rpc(method, **args):
        with lock:
            server = cluster.registry[cluster.raft.wait_converged().id]
        return server.rpc(method, **args)

    def wait_write(idx):
        deadline = time.time() + 5.0
        while time.time() < deadline:
            with lock:
                led = cluster.raft.leader()
                if led is not None and led.last_applied >= idx:
                    return
            time.sleep(0.002)

    from consul_tpu.agent.http import serve
    agent = Agent("ixn-agent", "10.12.0.1", rpc, cluster_size=3)
    api = HTTPApi(agent, wait_write=wait_write)
    httpd, port = serve(api)
    yield Client("127.0.0.1", port), port
    stop.set()
    httpd.shutdown()


class TestHTTP:
    def test_roundtrip_over_the_wire(self, stack):
        client, port = stack
        iid = client.connect.intention_create("web", "db", "allow")
        x = client.connect.intention_get(iid)
        assert x["SourceName"] == "web" and x["Precedence"] == 9
        rows, _ = client.connect.intention_list()
        assert any(r["ID"] == iid for r in rows)
        assert client.connect.intention_match("db") and \
            client.connect.intention_check("web", "db") is True
        # Duplicate pair -> 409.
        from consul_tpu.api import APIError
        with pytest.raises(APIError, match="duplicate"):
            client.connect.intention_create("web", "db", "deny")
        assert client.connect.intention_delete(iid)
        assert client.connect.intention_get(iid) is None

    def test_cli_flow(self, stack):
        import subprocess
        import sys
        _, port = stack

        def cli(*args):
            return subprocess.run(
                [sys.executable, "-m", "consul_tpu.cli", "--http-addr",
                 f"127.0.0.1:{port}", "intention", *args],
                capture_output=True, text=True, timeout=30)

        out = cli("create", "cli-src", "cli-dst", "-deny")
        assert out.returncode == 0, out.stderr
        assert cli("check", "cli-src", "cli-dst").returncode == 2  # denied
        out = cli("list")
        assert "cli-src => cli-dst (deny)" in out.stdout
        iid = next(ln.split()[0] for ln in out.stdout.splitlines()
                   if "cli-src" in ln)
        assert cli("delete", iid).returncode == 0
        assert cli("check", "cli-src", "cli-dst").returncode == 0

class TestHTTPHardening:
    def test_check_requires_params_and_get_only(self, stack):
        client, _ = stack
        from consul_tpu.api import APIError
        with pytest.raises(APIError, match="required"):
            client._call("GET", "/v1/connect/intentions/check",
                         {"source": "a"})
        with pytest.raises(APIError, match="method not allowed"):
            client._call("DELETE", "/v1/connect/intentions/match",
                         {"by": "destination", "name": "db"})
        with pytest.raises(APIError, match="method not allowed"):
            client._call("PUT", "/v1/connect/intentions/check",
                         {"source": "a", "destination": "b"})

    def test_acl_gate_uses_stored_destination(self):
        """DELETE/PUT by id authorize against the STORED intention's
        destination, not the caller's body (reference: intention
        management needs service:intentions write on the
        destination)."""
        cluster = ServerCluster(1, seed=37)
        cluster.wait_converged()
        leader = cluster.leader_server()

        def rpc(method, **args):
            cluster.step(5)
            out = leader.rpc(method, **args)
            cluster.step(5)
            return out

        agent = Agent("gate-agent", "10.13.0.1", rpc, cluster_size=1)
        api = HTTPApi(agent, wait_write=lambda idx: cluster.step(20),
                      acl={"enabled": True, "default_policy": "deny",
                           "master_token": "mt"})

        def call(method, path, body=b"", token="", q=None):
            return api.handle(method, path, q or {}, body,
                              headers={"X-Consul-Token": token})

        st, _, _ = call("PUT", "/v1/acl/policy", json.dumps({
            "Name": "svc-mine", "Rules": {
                "service_prefix": {"": {"policy": "write"}},
                "service": {"secret": {"policy": "deny"}},
            }}).encode(), token="mt")
        assert st == 200
        st, tok, _ = call("PUT", "/v1/acl/token", json.dumps(
            {"Policies": [{"Name": "svc-mine"}]}).encode(), token="mt")
        limited = tok["SecretID"]
        # Management creates an intention protecting "secret".
        st, made, _ = call("POST", "/v1/connect/intentions", json.dumps({
            "SourceName": "*", "DestinationName": "secret",
            "Action": "deny"}).encode(), token="mt")
        assert st == 200
        iid = made["ID"]
        # The limited token may NOT delete it (stored dest = secret),
        # even though its body/prefix rules would pass an empty-name
        # check.
        st, _, _ = call("DELETE", f"/v1/connect/intentions/{iid}",
                        token=limited)
        assert st == 403
        # Nor overwrite it by claiming a writable destination in the
        # body (both stored and body destinations are checked).
        st, _, _ = call("PUT", f"/v1/connect/intentions/{iid}",
                        json.dumps({"SourceName": "*",
                                    "DestinationName": "mine",
                                    "Action": "allow"}).encode(),
                        token=limited)
        assert st == 403
        # Intentions on non-denied services are manageable.
        st, made2, _ = call("POST", "/v1/connect/intentions", json.dumps({
            "SourceName": "web", "DestinationName": "mine",
            "Action": "allow"}).encode(), token=limited)
        assert st == 200
        st, _, _ = call("DELETE",
                        f"/v1/connect/intentions/{made2['ID']}",
                        token=limited)
        assert st == 200


class TestConnectAuthorize:
    def test_authorize_and_agent_service_watch(self, stack):
        """/v1/agent/connect/authorize (AgentConnectAuthorize) and the
        agent_service hash watch (api/watch funcs.go
        agentServiceWatch)."""
        client, port = stack
        client.connect.intention_create("caller", "payments", "deny")
        out, _, _ = client._call(
            "POST", "/v1/agent/connect/authorize", None, json.dumps({
                "Target": "payments",
                "ClientCertURI":
                    "spiffe://x.consul/ns/default/dc/dc1/svc/caller",
            }).encode())
        assert out["Authorized"] is False
        assert "intention" in out["Reason"]
        out, _, _ = client._call(
            "POST", "/v1/agent/connect/authorize", None, json.dumps({
                "Target": "payments",
                "ClientServiceName": "other"}).encode())
        assert out["Authorized"] is True
        from consul_tpu.api import APIError
        with pytest.raises(APIError, match="Target"):
            client._call("POST", "/v1/agent/connect/authorize", None,
                         b"{}")

        # agent_service watch: fires on registration change, not
        # otherwise.
        from consul_tpu.api import watch
        fired = []
        client.agent.service_register("wsvc", service_id="w-1", port=1)
        plan = watch(client, "agent_service",
                     lambda idx, res: fired.append(res), service_id="w-1")
        assert plan.run_once() is True      # first observation fires
        assert plan.run_once() is False     # unchanged: no fire
        client.agent.service_register("wsvc", service_id="w-1", port=2)
        assert plan.run_once() is True
        assert fired[-1]["Port"] == 2

    def test_authorize_rejects_non_service_uri(self, stack):
        client, _ = stack
        from consul_tpu.api import APIError
        with pytest.raises(APIError, match="not a service identity"):
            client._call("POST", "/v1/agent/connect/authorize", None,
                         json.dumps({
                             "Target": "payments",
                             "ClientCertURI":
                                 "spiffe://x.consul/agent/client/dc/dc1"
                         }).encode())
