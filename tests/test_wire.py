"""Wire protocol tests: LZW (native vs Python byte-equivalence +
roundtrips across table resets), msgpack message codec, compound
batching, CRC, encryption keyring rotation, and the full packet
pipeline (reference memberlist/net.go, util.go, security.go tests)."""

import os
import random

import pytest

from consul_tpu.wire import (
    Keyring,
    MessageType,
    decode_message,
    decode_packet,
    encode_message,
    encode_packet,
    make_compound,
    split_compound,
)
from consul_tpu.wire import lzw
from consul_tpu.wire.keyring import HAVE_CRYPTOGRAPHY

# AES-GCM paths need the optional 'cryptography' package; the framing,
# codec, and compression paths must pass without it.
needs_crypto = pytest.mark.skipif(
    not HAVE_CRYPTOGRAPHY,
    reason="requires the 'cryptography' package (AES-GCM)")


def corpus():
    rng = random.Random(7)
    return [
        b"",
        b"a",
        b"hello world " * 100,
        bytes(range(256)) * 8,
        bytes(rng.randrange(256) for _ in range(20_000)),  # forces resets
        bytes(rng.randrange(4) for _ in range(50_000)),    # long matches
    ]


class TestLZW:
    def test_python_roundtrip(self):
        for data in corpus():
            assert lzw.decompress_py(lzw.compress_py(data)) == data

    @pytest.mark.skipif(not lzw.native_available(), reason="no g++")
    def test_native_matches_python_bytes(self):
        for data in corpus():
            assert lzw.compress(data) == lzw.compress_py(data)

    @pytest.mark.skipif(not lzw.native_available(), reason="no g++")
    def test_cross_roundtrips(self):
        for data in corpus():
            assert lzw.decompress(lzw.compress_py(data)) == data
            assert lzw.decompress_py(lzw.compress(data)) == data

    def test_compresses_redundancy(self):
        data = b"abc" * 10_000
        assert len(lzw.compress(data)) < len(data) // 5

    def test_corrupt_stream_raises(self):
        blob = lzw.compress(b"hello hello hello")
        with pytest.raises(ValueError):
            lzw.decompress(blob[:-2] + b"\xff\xff")


class TestMessages:
    def test_ping_roundtrip(self):
        raw = encode_message(MessageType.PING, {"SeqNo": 42, "Node": "n1"})
        assert raw[0] == MessageType.PING
        mtype, body = decode_message(raw)
        assert mtype == MessageType.PING
        assert body == {"SeqNo": 42, "Node": "n1"}

    def test_alive_with_binary_fields(self):
        # Binary fields ride the legacy raw family (go-msgpack
        # WriteExt=false has no bin type), so the decoder surfaces them
        # as surrogateescape str; as_bytes() recovers them losslessly —
        # including non-UTF-8 contents like raw IPs.
        from consul_tpu.wire.codec import as_bytes
        body = {"Incarnation": 7, "Node": "n2", "Addr": bytes([10, 0, 0, 2]),
                "Port": 8301, "Meta": b"\xff\x02", "Vsn": [1, 5, 2, 2, 5, 4]}
        mtype, out = decode_message(encode_message(MessageType.ALIVE, body))
        assert as_bytes(out["Addr"]) == body["Addr"]
        assert as_bytes(out["Meta"]) == body["Meta"]
        assert out["Incarnation"] == 7 and out["Port"] == 8301

    def test_unknown_field_rejected(self):
        with pytest.raises(ValueError, match="unknown fields"):
            encode_message(MessageType.PING, {"SeqNo": 1, "Bogus": 2})

    def test_compound_roundtrip(self):
        msgs = [encode_message(MessageType.SUSPECT,
                               {"Incarnation": i, "Node": f"n{i}",
                                "From": "me"})
                for i in range(5)]
        blob = make_compound(msgs)
        assert blob[0] == MessageType.COMPOUND
        assert split_compound(blob[1:]) == msgs

    def test_compound_truncation_detected(self):
        blob = make_compound([b"abcdef", b"ghijkl"])
        with pytest.raises(ValueError, match="truncated"):
            split_compound(blob[1:-3])


class TestPacketPipeline:
    MSGS = [
        encode_message(MessageType.PING, {"SeqNo": 1, "Node": "a"}),
        encode_message(MessageType.SUSPECT,
                       {"Incarnation": 3, "Node": "b", "From": "a"}),
    ]

    def test_plain(self):
        out = decode_packet(encode_packet(self.MSGS))
        assert [m for m, _ in out] == [MessageType.PING, MessageType.SUSPECT]

    def test_compressed_and_crc(self):
        pkt = encode_packet(self.MSGS, compress=True, crc=True)
        assert pkt[0] == MessageType.HAS_CRC
        out = decode_packet(pkt)
        assert out[0][1]["SeqNo"] == 1

    def test_crc_detects_flip(self):
        pkt = bytearray(encode_packet(self.MSGS, crc=True))
        pkt[-1] ^= 0xFF
        with pytest.raises(ValueError, match="CRC mismatch"):
            decode_packet(bytes(pkt))

    @needs_crypto
    def test_encrypted_roundtrip(self):
        ring = Keyring(primary=os.urandom(16))
        pkt = encode_packet(self.MSGS, compress=True, keyring=ring)
        # The packet path sends the RAW encrypted payload — no
        # encryptMsg prefix byte (net.go:697-714; a real memberlist
        # agent would fail to decrypt a prefixed packet). Byte 0 is the
        # encryption version (1), not a message type.
        assert pkt[0] != MessageType.ENCRYPT
        assert pkt[0] == 1  # encryption version byte
        out = decode_packet(pkt, keyring=ring)
        assert out[1][1]["Node"] == "b"

    @needs_crypto
    def test_plaintext_rejected_when_encrypting(self):
        ring = Keyring(primary=os.urandom(16))
        pkt = encode_packet(self.MSGS)
        with pytest.raises(ValueError, match="no installed key"):
            decode_packet(pkt, keyring=ring)

    @needs_crypto
    def test_plaintext_accepted_without_verify_incoming(self):
        # GossipVerifyIncoming=false (net.go:315-321): an undecryptable
        # payload is processed as plaintext — the rotation window.
        ring = Keyring(primary=os.urandom(16))
        pkt = encode_packet(self.MSGS)
        out = decode_packet(pkt, keyring=ring, verify_incoming=False)
        assert out[0][1]["SeqNo"] == 1

    @needs_crypto
    def test_wrong_key_fails(self):
        pkt = encode_packet(self.MSGS, keyring=Keyring(primary=os.urandom(16)))
        with pytest.raises(ValueError, match="no installed key"):
            decode_packet(pkt, keyring=Keyring(primary=os.urandom(16)))


class TestStreamFraming:
    """Stream (push-pull/TCP) encryption framing: [encryptMsg | u32 len
    | ciphertext] with the header as AAD (net.go:878-900, :946-976) —
    distinct from the packet path, which has no marker byte."""

    @needs_crypto
    def test_roundtrip(self):
        from consul_tpu.wire.codec import (decode_stream_frame,
                                           encode_stream_frame)
        ring = Keyring(primary=os.urandom(32))
        frame = encode_stream_frame(b"push-pull-state", ring)
        assert frame[0] == MessageType.ENCRYPT
        assert int.from_bytes(frame[1:5], "big") == len(frame) - 5
        assert decode_stream_frame(frame, ring) == b"push-pull-state"

    def test_plaintext_passthrough(self):
        from consul_tpu.wire.codec import (decode_stream_frame,
                                           encode_stream_frame)
        assert encode_stream_frame(b"x", None) == b"x"
        assert decode_stream_frame(b"x", None) == b"x"

    @needs_crypto
    def test_expectation_enforced_both_ways(self):
        from consul_tpu.wire.codec import (decode_stream_frame,
                                           encode_stream_frame)
        ring = Keyring(primary=os.urandom(16))
        frame = encode_stream_frame(b"s", ring)
        with pytest.raises(ValueError, match="not configured"):
            decode_stream_frame(frame, None)
        with pytest.raises(ValueError, match="not encrypted"):
            decode_stream_frame(b"plain", ring)

    @needs_crypto
    def test_header_tamper_detected(self):
        from consul_tpu.wire.codec import (decode_stream_frame,
                                           encode_stream_frame)
        ring = Keyring(primary=os.urandom(16))
        frame = bytearray(encode_stream_frame(b"s" * 100, ring))
        frame[2] ^= 0x01  # flip a length byte (bound as AAD)
        with pytest.raises(ValueError):
            decode_stream_frame(bytes(frame), ring)


class TestKeyring:
    @needs_crypto
    def test_rotation_flow(self):
        # install -> use -> remove (serf/keymanager.go rotation).
        k1, k2 = os.urandom(16), os.urandom(32)
        ring = Keyring(primary=k1)
        pkt_old = ring.encrypt(b"payload")
        ring.install(k2)
        assert ring.decrypt(pkt_old) == b"payload"  # old key still works
        ring.use(k2)
        pkt_new = ring.encrypt(b"payload2")
        assert ring.decrypt(pkt_old) == b"payload"   # non-primary decrypts
        assert ring.decrypt(pkt_new) == b"payload2"
        ring.remove(k1)
        with pytest.raises(ValueError):
            ring.decrypt(pkt_old)

    @needs_crypto
    def test_primary_cannot_be_removed(self):
        k = os.urandom(16)
        ring = Keyring(primary=k)
        with pytest.raises(ValueError, match="primary"):
            ring.remove(k)

    def test_bad_key_size(self):
        with pytest.raises(ValueError, match="key size"):
            Keyring(primary=b"short")

    @needs_crypto
    def test_aad_binds_header(self):
        ring = Keyring(primary=os.urandom(16))
        pkt = ring.encrypt(b"msg", aad=b"header")
        assert ring.decrypt(pkt, aad=b"header") == b"msg"
        with pytest.raises(ValueError):
            ring.decrypt(pkt, aad=b"tampered")


class TestGoldenFixtures:
    """Byte-for-byte fixtures derived BY HAND from the reference wire
    spec — go-msgpack default handle (codec.MsgpackHandle{}): struct
    fields as a map in alphabetical key order, legacy raw string family
    (fixraw < 32, raw16 >= 32), minimal integers — framed per
    net.go:46-59 / util.go:157-217. These pin the exact bytes a real
    memberlist agent would emit/accept, independent of our encoder."""

    def test_ping_bytes(self):
        # ping{SeqNo: 1, Node: "a"} -> keys sorted: Node, SeqNo
        want = bytes([
            0x00,                    # pingMsg
            0x82,                    # fixmap(2)
            0xA4]) + b"Node" + bytes([0xA1]) + b"a" + \
            bytes([0xA5]) + b"SeqNo" + bytes([0x01])
        got = encode_message(MessageType.PING, {"SeqNo": 1, "Node": "a"})
        assert got == want, f"{got.hex()} != {want.hex()}"

    def test_ack_bytes_with_payload(self):
        # ackResp{SeqNo: 300, Payload: 0xDEAD} -> keys: Payload, SeqNo;
        # 300 needs uint16 (0xcd); bytes -> legacy fixraw.
        want = bytes([
            0x02, 0x82,
            0xA7]) + b"Payload" + bytes([0xA2, 0xDE, 0xAD]) + \
            bytes([0xA5]) + b"SeqNo" + bytes([0xCD, 0x01, 0x2C])
        got = encode_message(MessageType.ACK_RESP,
                             {"SeqNo": 300, "Payload": b"\xde\xad"})
        assert got == want, f"{got.hex()} != {want.hex()}"

    def test_suspect_bytes(self):
        # suspect{Incarnation: 7, Node: "b", From: "a"} -> From,
        # Incarnation, Node.
        want = bytes([0x03, 0x83,
                      0xA4]) + b"From" + bytes([0xA1]) + b"a" + \
            bytes([0xAB]) + b"Incarnation" + bytes([0x07]) + \
            bytes([0xA4]) + b"Node" + bytes([0xA1]) + b"b"
        got = encode_message(
            MessageType.SUSPECT,
            {"Incarnation": 7, "Node": "b", "From": "a"})
        assert got == want, f"{got.hex()} != {want.hex()}"

    def test_alive_bytes(self):
        # alive{Incarnation: 2, Node: "n", Addr: [10,0,0,1], Port: 7946,
        # Meta: "", Vsn: [1,5,1,2,5,4]} -> Addr, Incarnation, Meta,
        # Node, Port, Vsn. Addr/Meta/Vsn are []byte in Go -> legacy raw.
        want = bytes([0x04, 0x86,
                      0xA4]) + b"Addr" + bytes([0xA4, 10, 0, 0, 1]) + \
            bytes([0xAB]) + b"Incarnation" + bytes([0x02]) + \
            bytes([0xA4]) + b"Meta" + bytes([0xA0]) + \
            bytes([0xA4]) + b"Node" + bytes([0xA1]) + b"n" + \
            bytes([0xA4]) + b"Port" + bytes([0xCD, 0x1F, 0x0A]) + \
            bytes([0xA3]) + b"Vsn" + bytes([0xA6, 1, 5, 1, 2, 5, 4])
        got = encode_message(MessageType.ALIVE, {
            "Incarnation": 2, "Node": "n", "Addr": bytes([10, 0, 0, 1]),
            "Port": 7946, "Meta": b"", "Vsn": bytes([1, 5, 1, 2, 5, 4]),
        })
        assert got == want, f"{got.hex()} != {want.hex()}"

    def test_compound_bytes(self):
        # [compoundMsg | count | u16 big-endian lengths | bodies]
        # (util.go:157-217).
        p1 = encode_message(MessageType.PING, {"SeqNo": 1, "Node": "a"})
        p2 = encode_message(MessageType.NACK_RESP, {"SeqNo": 2})
        want = bytes([0x07, 0x02]) + \
            len(p1).to_bytes(2, "big") + len(p2).to_bytes(2, "big") + p1 + p2
        assert make_compound([p1, p2]) == want
        assert split_compound(want[1:]) == [p1, p2]

    def test_nack_bytes(self):
        want = bytes([0x0B, 0x81, 0xA5]) + b"SeqNo" + bytes([0x05])
        assert encode_message(MessageType.NACK_RESP, {"SeqNo": 5}) == want

    def test_crc_framing_bytes(self):
        # [hasCrcMsg | crc32-IEEE big-endian | body] (net.go:329-339).
        import zlib as _z
        body = encode_message(MessageType.NACK_RESP, {"SeqNo": 5})
        pkt = encode_packet([body], crc=True)
        assert pkt[0] == 0x0C
        assert pkt[1:5] == (_z.crc32(body) & 0xFFFFFFFF).to_bytes(4, "big")
        assert pkt[5:] == body

    def test_compress_envelope_bytes(self):
        # compress{Algo: 0, Buf: lzw(...)}: keys Algo, Buf; envelope
        # byte 0x09 (util.go:221-243). The LZW bytes themselves are
        # covered by TestLZW's cross-checks.
        body = encode_message(MessageType.NACK_RESP, {"SeqNo": 5})
        pkt = encode_packet([body], compress=True)
        assert pkt[0] == 0x09
        assert pkt[1] == 0x82                       # fixmap(2)
        assert pkt[2:7] == bytes([0xA4]) + b"Algo"  # first key
        assert pkt[7] == 0x00                       # lzwAlgo
        assert pkt[8:12] == bytes([0xA3]) + b"Buf"
        assert decode_packet(pkt)[0][1]["SeqNo"] == 5

    def test_long_string_uses_raw16_not_str8(self):
        # go-msgpack with WriteExt=false has no str8: a 100-char name
        # must use raw16 (0xda) (codec/msgpack.go:241 gate).
        name = "x" * 100
        from consul_tpu.wire.codec import _pack_go
        packed = _pack_go({"Node": name, "SeqNo": 1})
        i = packed.index(b"Node") + 4
        assert packed[i] == 0xDA, f"str8/bin leaked: {packed[i]:#x}"

    @needs_crypto
    def test_encrypted_packet_layout(self):
        # [vsn=1 | nonce(12) | ciphertext+tag(16)], no prefix byte, no
        # AAD (security.go:90-116 encryptPayload, net.go:697-708).
        ring = Keyring(primary=bytes(range(16)))
        body = encode_message(MessageType.NACK_RESP, {"SeqNo": 5})
        pkt = encode_packet([body], keyring=ring)
        assert pkt[0] == 1
        assert len(pkt) == 1 + 12 + len(body) + 16
        # Independent decrypt with the raw key proves the layout.
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        plain = AESGCM(bytes(range(16))).decrypt(pkt[1:13], pkt[13:], None)
        assert plain == body
