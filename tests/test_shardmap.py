"""Explicit-collective (shard_map + ppermute) execution tests.

Runs on the 8-device virtual CPU mesh (tests/conftest.py). Validates the
framework's hand-written ICI communication backend
(parallel/collective.py, parallel/shard_step.py): the circulant-roll
message plane decomposed into ppermute neighbor transfers, and the full
SWIM step under shard_map agreeing with the single-device step.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from consul_tpu.config import SimConfig
from consul_tpu.models import state as sim_state
from consul_tpu.models import swim
from consul_tpu.ops import topology
from consul_tpu.parallel import collective as coll
from consul_tpu.parallel import mesh as pmesh
from consul_tpu.parallel import shard_step

N_DEV = 8


def _mesh():
    return Mesh(np.array(jax.devices()[:N_DEV]), (pmesh.NODE_AXIS,))


@functools.lru_cache(maxsize=None)
def _swim_cfg(n, view_degree):
    # Memoized per shape: derivation is deterministic (PRNGKey(0)) and
    # JAX arrays are immutable. The initial STATE is built fresh per
    # test (see _swim_world): place() may alias replicated leaves
    # rather than copy, and the sharded step donates its state — a
    # cached state would come back deleted.
    cfg = SimConfig(n=n, view_degree=view_degree)
    key = jax.random.PRNGKey(0)
    kw, kn, _ = jax.random.split(key, 3)
    world = topology.make_world(cfg, kw)
    topo = topology.make_topology(cfg, kn)
    return cfg, topo, world


def _swim_world(n, view_degree):
    cfg, topo, world = _swim_cfg(n, view_degree)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)[2]
    return cfg, topo, world, sim_state.init(cfg, ks)


@functools.lru_cache(maxsize=None)
def _swim_steps(n, view_degree):
    """One sharded + one unsharded compiled step per shape, shared by
    every trajectory/convergence test instead of re-paying XLA."""
    cfg, topo, world = _swim_cfg(n, view_degree)
    sstep = shard_step.make_sharded_step(cfg, topo, _mesh())
    ustep = jax.jit(functools.partial(swim.step, cfg, topo, world))
    return sstep, ustep


SHIFTS = [0, 1, 7, 8, 9, 32, 63, -3, -17, 100]


class TestRingRoll:
    """collective.roll == jnp.roll in global row coordinates."""

    @pytest.mark.parametrize("shift", SHIFTS)
    def test_static_shift(self, shift):
        mesh = _mesh()
        n = 64
        x = jnp.arange(n, dtype=jnp.int32)

        def f(xl):
            with coll.node_axis(pmesh.NODE_AXIS, N_DEV, n):
                return coll.roll(xl, shift)

        got = jax.jit(
            pmesh.shard_map(
                f, mesh=mesh, in_specs=P(pmesh.NODE_AXIS),
                out_specs=P(pmesh.NODE_AXIS),
            )
        )(x)
        np.testing.assert_array_equal(np.asarray(got), np.roll(np.asarray(x), shift))

    @pytest.mark.parametrize("shift", SHIFTS)
    def test_traced_shift(self, shift):
        mesh = _mesh()
        n = 64
        x = jnp.arange(n, dtype=jnp.int32)

        def f(xl, s):
            with coll.node_axis(pmesh.NODE_AXIS, N_DEV, n):
                return coll.roll(xl, s)

        got = jax.jit(
            pmesh.shard_map(
                f, mesh=mesh, in_specs=(P(pmesh.NODE_AXIS), P()),
                out_specs=P(pmesh.NODE_AXIS),
            )
        )(x, jnp.int32(shift))
        np.testing.assert_array_equal(np.asarray(got), np.roll(np.asarray(x), shift))

    @pytest.mark.parametrize("traced", [False, True])
    def test_2d_and_bool(self, traced):
        mesh = _mesh()
        n = 64
        x2 = jnp.stack([jnp.arange(n), jnp.arange(n) * 10], axis=1)
        b = jnp.arange(n) % 3 == 0
        for arr, spec in [(x2, P(pmesh.NODE_AXIS, None)), (b, P(pmesh.NODE_AXIS))]:
            for shift in (5, 13):
                def f(xl, s):
                    with coll.node_axis(pmesh.NODE_AXIS, N_DEV, n):
                        return coll.roll(xl, s if traced else shift)

                got = jax.jit(
                    pmesh.shard_map(
                        f, mesh=mesh, in_specs=(spec, P()), out_specs=spec
                    )
                )(arr, jnp.int32(shift))
                np.testing.assert_array_equal(
                    np.asarray(got), np.roll(np.asarray(arr), shift, axis=0)
                )
                assert got.dtype == arr.dtype

    def test_rows_and_any(self):
        mesh = _mesh()
        n = 64

        def f(flag):
            with coll.node_axis(pmesh.NODE_AXIS, N_DEV, n):
                return coll.rows(n), coll.any_rows(flag) & True

        flag = jnp.zeros(n, bool).at[37].set(True)
        rows, anyv = jax.jit(
            pmesh.shard_map(
                f, mesh=mesh, in_specs=P(pmesh.NODE_AXIS),
                out_specs=(P(pmesh.NODE_AXIS), P()),
                check_vma=False,
            )
        )(flag)
        np.testing.assert_array_equal(np.asarray(rows), np.arange(n))
        assert bool(anyv)
        assert not bool(
            jax.jit(
                pmesh.shard_map(
                    f, mesh=mesh, in_specs=P(pmesh.NODE_AXIS),
                    out_specs=(P(pmesh.NODE_AXIS), P()),
                    check_vma=False,
                )
            )(jnp.zeros(n, bool))[1]
        )

    def test_uniform_rows_match_global_stream(self):
        mesh = _mesh()
        n = 64
        key = jax.random.PRNGKey(3)

        def f():
            with coll.node_axis(pmesh.NODE_AXIS, N_DEV, n):
                return coll.uniform_rows(key, n, (4,))

        got = jax.jit(
            pmesh.shard_map(
                f, mesh=mesh, in_specs=(), out_specs=P(pmesh.NODE_AXIS, None)
            )
        )()
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(jax.random.uniform(key, (n, 4)))
        )


class TestShardedStep:
    """Full SWIM step under shard_map vs the single-device step."""

    def _build(self, n=256, view_degree=16):
        return _swim_world(n, view_degree)

    def test_matches_unsharded_trajectory(self):
        cfg, topo, world, st0 = self._build()
        mesh = _mesh()
        sstep, ustep = _swim_steps(cfg.n, 16)

        su = st0
        ss = shard_step.place(mesh, st0, cfg.n)
        wg = shard_step.place(mesh, world, cfg.n)
        for t in range(30):
            k = jax.random.fold_in(jax.random.PRNGKey(7), t)
            su = ustep(su, k)
            ss = sstep(wg, ss, k)

        float_leaves = 0
        for name, a, b in zip(su._fields, su, ss):
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                x, y = np.asarray(x), np.asarray(y)
                if np.issubdtype(x.dtype, np.floating):
                    # Different XLA fusions round float math differently
                    # (~1 ulp); discrete protocol state must be exact.
                    np.testing.assert_allclose(
                        x, y, rtol=1e-4, atol=1e-6, err_msg=name
                    )
                    float_leaves += 1
                else:
                    np.testing.assert_array_equal(x, y, err_msg=name)
        assert float_leaves > 0

    def test_sharded_convergence_after_kill(self):
        """Kill a block of nodes; the sharded-step cluster must detect
        and re-converge exactly like the protocol demands."""
        cfg, topo, world, st0 = self._build()
        mesh = _mesh()
        sstep, _ = _swim_steps(cfg.n, 16)

        ss = shard_step.place(mesh, st0, cfg.n)
        wg = shard_step.place(mesh, world, cfg.n)
        for t in range(40):
            ss = sstep(wg, ss, jax.random.fold_in(jax.random.PRNGKey(1), t))
        dead_mask = jnp.arange(cfg.n) < 12
        ss = shard_step.place(mesh, sim_state.kill(ss, dead_mask), cfg.n)
        # Suspicion at n=256: min 4*log10(256)*5 = 48 ticks, max 6x =
        # 289; plus probe-cycle detection latency (K=16 targets x 5-tick
        # period). 640 ticks = 128 simulated seconds covers the
        # un-accelerated worst case with margin.
        for t in range(640):
            ss = sstep(wg, ss, jax.random.fold_in(jax.random.PRNGKey(2), t + 100))

        from consul_tpu.ops import merge
        st = merge.key_status(ss.view_key)
        alive = np.asarray(ss.alive_truth)
        statuses = np.asarray(st)
        nbrs = np.asarray(topology.nbrs_table(topo))
        # Every surviving observer sees every dead tracked peer as dead,
        # and no live tracked peer as dead/suspect (no false positives).
        for i in np.nonzero(alive)[0][:64]:
            for c, j in enumerate(nbrs[i]):
                if not alive[j]:
                    assert statuses[i, c] == merge.DEAD, (i, c, j)
                else:
                    assert statuses[i, c] in (merge.ALIVE,), (i, c, j)

    def test_dense_mode_matches_unsharded_trajectory(self):
        """Dense mode (view_degree=0, the complete graph — BASELINE
        config 2's shape) under shard_map: the row-addressed probe
        reads ride collective.take_rows (all-gather + local gather),
        and the trajectory is bit-identical to the single-device step
        for discrete state."""
        cfg, topo, world, st0 = self._build(n=128, view_degree=0)
        mesh = _mesh()
        sstep, ustep = _swim_steps(cfg.n, 0)

        su = st0
        ss = shard_step.place(mesh, st0, cfg.n)
        wg = shard_step.place(mesh, world, cfg.n)
        for t in range(25):
            k = jax.random.fold_in(jax.random.PRNGKey(3), t)
            su = ustep(su, k)
            ss = sstep(wg, ss, k)
        for name, a, b in zip(su._fields, su, ss):
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                x, y = np.asarray(x), np.asarray(y)
                if np.issubdtype(x.dtype, np.floating):
                    np.testing.assert_allclose(
                        x, y, rtol=1e-4, atol=1e-6, err_msg=name)
                else:
                    np.testing.assert_array_equal(x, y, err_msg=name)

    def test_dense_sharded_convergence_after_kill(self):
        """Dense sharded cluster detects a kill and re-converges (the
        end-to-end behavior, not just trajectory equality)."""
        cfg, topo, world, st0 = self._build(n=128, view_degree=0)
        mesh = _mesh()
        sstep, _ = _swim_steps(cfg.n, 0)
        ss = shard_step.place(mesh, st0, cfg.n)
        wg = shard_step.place(mesh, world, cfg.n)
        for t in range(30):
            ss = sstep(wg, ss, jax.random.fold_in(jax.random.PRNGKey(4), t))
        ss = shard_step.place(
            mesh, sim_state.kill(ss, jnp.arange(cfg.n) < 6), cfg.n)
        for t in range(600):
            ss = sstep(wg, ss, jax.random.fold_in(jax.random.PRNGKey(5), t + 50))
        from consul_tpu.ops import merge
        alive = np.asarray(ss.alive_truth)
        statuses = np.asarray(merge.key_status(ss.view_key))
        nbrs = np.asarray(topology.nbrs_table(topo))
        for i in np.nonzero(alive)[0][:32]:
            for c, j in enumerate(nbrs[i]):
                if not alive[j]:
                    assert statuses[i, c] == merge.DEAD, (i, c, j)
                else:
                    assert statuses[i, c] == merge.ALIVE, (i, c, j)


class TestShardedSerfStep:
    """The full serf plane (events/queries over SWIM) under shard_map,
    including the row-addressed collectives (all_gather origin reads +
    psum response tallies)."""

    def _build(self, n=256, view_degree=16, **cfg_kw):
        from consul_tpu.models import serf
        cfg = SimConfig(n=n, view_degree=view_degree, **cfg_kw)
        key = jax.random.PRNGKey(0)
        kw, kn, ks = jax.random.split(key, 3)
        world = topology.make_world(cfg, kw)
        topo = topology.make_topology(cfg, kn)
        st = serf.init(cfg, ks)
        return cfg, topo, world, st

    @pytest.mark.parametrize("lossy", [False, True])
    def test_matches_unsharded_trajectory_with_event_and_query(self, lossy):
        import dataclasses

        from consul_tpu.models import serf
        kw = {}
        if lossy:
            # Exercise the sharded loss draws and the query relay path
            # (traced negative-shift bool rolls + sliced uniforms).
            kw["packet_loss"] = 0.1
        cfg, topo, world, st0 = self._build(**kw)
        if lossy:
            cfg = dataclasses.replace(
                cfg, serf=dataclasses.replace(cfg.serf, query_relay_factor=2)
            )
        mesh = _mesh()
        sstep = shard_step.make_sharded_serf_step(cfg, topo, mesh)
        ustep = jax.jit(functools.partial(serf.step, cfg, topo, world))

        mask5 = jnp.zeros(cfg.n, bool).at[5].set(True)
        mask9 = jnp.zeros(cfg.n, bool).at[9].set(True)
        su = st0
        ss = shard_step.place(mesh, st0, cfg.n)
        wg = shard_step.place(mesh, world, cfg.n)
        for t in range(30):
            if t == 3:  # fire a user event + open a query mid-run
                su = serf.user_event(cfg, su, mask5, 7)
                ss = shard_step.place(
                    mesh, serf.user_event(cfg, ss, mask5, 7), cfg.n)
            if t == 5:
                su = serf.query(cfg, su, mask9, 2)
                ss = shard_step.place(
                    mesh, serf.query(cfg, ss, mask9, 2), cfg.n)
            k = jax.random.fold_in(jax.random.PRNGKey(7), t)
            su = ustep(su, k)
            ss = sstep(wg, ss, k)

        for name, a, b in zip(su._fields, su, ss):
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                x, y = np.asarray(x), np.asarray(y)
                if np.issubdtype(x.dtype, np.floating):
                    np.testing.assert_allclose(
                        x, y, rtol=1e-4, atol=1e-6, err_msg=name)
                else:
                    np.testing.assert_array_equal(x, y, err_msg=name)
        # The exchange did real work: the event spread and the query
        # collected responses, identically in both executions.
        assert int(np.asarray(ss.q_resps[9, 0])) == int(np.asarray(su.q_resps[9, 0]))
        assert int(np.asarray(ss.q_resps[9, 0])) > 0
        assert float(np.asarray(ss.ev_delivered).sum()) > 0
