"""Agent/API-layer gap closures: watch plans, HTTP/TCP check runners,
the user-event endpoint, and bootstrap-expect (reference
api/watch/funcs.go:18-30 + plan.go, agent/checks/check.go CheckHTTP/
CheckTCP, agent/event_endpoint.go, agent/consul/server_serf.go:236)."""

import http.server
import json
import socket
import threading
import time

import pytest

from consul_tpu.agent.agent import Agent
from consul_tpu.agent.checks import http_probe, tcp_probe
from consul_tpu.agent.http import HTTPApi, serve
from consul_tpu.agent.local import LocalState
from consul_tpu.api import Client, WatchPlan, watch
from consul_tpu.server.endpoints import ServerCluster


@pytest.fixture(scope="module")
def stack():
    cluster = ServerCluster(3, seed=21)
    leader = cluster.wait_converged()
    stop = threading.Event()
    lock = threading.Lock()

    def pump():
        while not stop.is_set():
            with lock:
                cluster.step()
            time.sleep(0.002)

    threading.Thread(target=pump, daemon=True).start()

    def rpc(method, **args):
        with lock:
            server = cluster.registry[cluster.raft.wait_converged().id]
        return server.rpc(method, **args)

    def wait_write(idx):
        deadline = time.time() + 5.0
        while time.time() < deadline:
            with lock:
                led = cluster.raft.leader()
                if led is not None and led.last_applied >= idx:
                    return
            time.sleep(0.002)

    agent = Agent("watch-agent", "10.0.0.1", rpc, cluster_size=3)
    api = HTTPApi(agent, server=leader, wait_write=wait_write)
    httpd, port = serve(api)
    client = Client("127.0.0.1", port)
    yield cluster, agent, client
    stop.set()
    httpd.shutdown()


def fire_and_collect(plan, mutate, rounds=8, wait="2s"):
    """Prime the plan (first round always fires), mutate, then poll."""
    plan.run_once(wait="10ms")  # initial snapshot
    mutate()
    for _ in range(rounds):
        if plan.run_once(wait=wait):
            return True
    return False


class TestWatchPlans:
    def test_key_watch(self, stack):
        _, _, client = stack
        got = []
        plan = watch(client, "key", lambda i, r: got.append(r),
                     key="watch/key1")
        assert fire_and_collect(
            plan, lambda: client.kv.put("watch/key1", b"v1"))
        assert got and got[-1]["Value"] == b"v1"

    def test_keyprefix_watch(self, stack):
        _, _, client = stack
        got = []
        plan = watch(client, "keyprefix", lambda i, r: got.append(r),
                     prefix="wp/")
        assert fire_and_collect(
            plan, lambda: (client.kv.put("wp/a", b"1"),
                           client.kv.put("wp/b", b"2")))
        assert {r["Key"] for r in got[-1]} >= {"wp/a", "wp/b"}

    def test_service_and_services_watch(self, stack):
        _, _, client = stack
        got_svc, got_all = [], []
        p1 = watch(client, "service", lambda i, r: got_svc.append(r),
                   service="web")
        p2 = watch(client, "services", lambda i, r: got_all.append(r))
        mut = lambda: client.catalog.register(
            "wnode", "10.0.0.9",
            service={"ID": "web1", "Service": "web", "Port": 80})
        assert fire_and_collect(p1, mut)
        p2.run_once(wait="10ms")
        assert any(s["id"] == "web1" for s in got_svc[-1])
        assert "web" in (got_all[-1] if got_all else
                         client.catalog.services()[0])

    def test_nodes_watch(self, stack):
        _, _, client = stack
        got = []
        plan = watch(client, "nodes", lambda i, r: got.append(r))
        assert fire_and_collect(
            plan, lambda: client.catalog.register("fresh-node", "10.0.0.77"))
        assert any(n["node"] == "fresh-node" for n in got[-1])

    def test_checks_watch(self, stack):
        _, _, client = stack
        got = []
        plan = watch(client, "checks", lambda i, r: got.append(r),
                     state="critical")
        assert fire_and_collect(
            plan, lambda: client.catalog.register(
                "cnode", "10.0.0.8",
                check={"CheckID": "c1", "Status": "critical"}))
        assert any(c["check_id"] == "c1" for c in got[-1])

    def test_event_watch(self, stack):
        _, _, client = stack
        got = []
        plan = watch(client, "event", lambda i, r: got.append(r),
                     name="deploy")
        assert fire_and_collect(
            plan,
            lambda: client._call("PUT", "/v1/event/fire/deploy", {},
                                 b"v2.0"))
        assert got[-1] and got[-1][-1]["Name"] == "deploy"

    def test_unsupported_type_rejected(self, stack):
        # connect_roots/connect_leaf graduated to SUPPORTED types in
        # round 5 — the negative case needs a genuinely unknown one.
        _, _, client = stack
        with pytest.raises(ValueError, match="unsupported watch type"):
            WatchPlan(client, "definitely_not_a_type", None)

    def test_handler_not_fired_without_change(self, stack):
        _, _, client = stack
        fired = []
        plan = watch(client, "key", lambda i, r: fired.append(i),
                     key="watch/static")
        client.kv.put("watch/static", b"x")
        plan.run_once(wait="10ms")
        n = len(fired)
        assert plan.run_once(wait="100ms") is False  # no change: no fire
        assert len(fired) == n


class TestEventEndpoint:
    def test_fire_and_list(self, stack):
        _, _, client = stack
        out, _, _ = client._call("PUT", "/v1/event/fire/restart", {},
                                 b"now")
        assert out["Name"] == "restart" and out["ID"]
        evs, meta, _ = client._call("GET", "/v1/event/list",
                                    {"name": "restart"})
        assert evs and evs[-1]["Name"] == "restart"
        import base64
        assert base64.b64decode(evs[-1]["Payload"]) == b"now"

    def test_fire_hook_forwards(self, stack):
        _, agent, client = stack
        seen = []
        agent.fire_hook = lambda name, payload: seen.append((name, payload))
        client._call("PUT", "/v1/event/fire/hooked", {}, b"p")
        assert seen == [("hooked", b"p")]
        agent.fire_hook = None


class TestCheckProbes:
    @pytest.fixture(scope="class")
    def web(self):
        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                code = int(self.path.rsplit("/", 1)[-1])
                self.send_response(code)
                self.end_headers()
                self.wfile.write(b"body")

            def log_message(self, *a):
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        yield f"http://127.0.0.1:{httpd.server_port}"
        httpd.shutdown()

    def test_http_statuses(self, web):
        assert http_probe(f"{web}/200")[0] == "passing"
        assert http_probe(f"{web}/429")[0] == "warning"
        assert http_probe(f"{web}/500")[0] == "critical"

    def test_http_unreachable_critical(self):
        status, out = http_probe("http://127.0.0.1:1/x", timeout_s=0.3)
        assert status == "critical"

    def test_tcp_probe(self, web):
        port = int(web.rsplit(":", 1)[1])
        assert tcp_probe("127.0.0.1", port)[0] == "passing"
        # A port nothing listens on.
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        free = s.getsockname()[1]
        s.close()
        assert tcp_probe("127.0.0.1", free, timeout_s=0.3)[0] == "critical"

    def test_runner_integration(self, web):
        from consul_tpu.agent.checks import CheckRunner
        local = LocalState("n1", "addr")
        runner = CheckRunner(local)
        runner.add_http("web-ok", f"{web}/200", interval_s=1.0,
                        background=False)
        runner.add_http("web-bad", f"{web}/503", interval_s=1.0,
                        background=False)
        runner.tick(0.0)
        assert local.checks["web-ok"].status == "passing"
        assert local.checks["web-bad"].status == "critical"

    def test_background_probe_does_not_stall_tick(self, web):
        from consul_tpu.agent.checks import CheckRunner
        local = LocalState("n1", "addr")
        runner = CheckRunner(local)
        # A target that can never answer, with a long timeout: the tick
        # must return immediately anyway (the goroutine-per-check model).
        runner.add_http("hung", "http://10.255.255.1:9/x", interval_s=1.0,
                        timeout_s=5.0)
        t0 = time.monotonic()
        runner.tick(0.0)
        assert time.monotonic() - t0 < 0.5, "tick blocked on the probe"
        # The backgrounded result eventually lands.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if local.checks["hung"].output.startswith("HTTP"):
                break
            time.sleep(0.1)


class TestBootstrapExpect:
    def member(self, name, expect=3):
        return {"name": name, "tags": {"role": "consul",
                                       "expect": str(expect)}}

    def test_no_leader_until_expect_met(self):
        c = ServerCluster(3, seed=4, bootstrap_expect=3)
        c.step(300)
        assert c.raft.leader() is None, "elected before expectation met"
        assert not c.maybe_bootstrap([self.member("s0"), self.member("s1")])
        c.step(300)
        assert c.raft.leader() is None
        assert c.maybe_bootstrap(
            [self.member(f"s{i}") for i in range(3)])
        leader = c.wait_converged()
        assert leader is not None

    def test_conflicting_expectations_refuse(self):
        c = ServerCluster(3, seed=5, bootstrap_expect=3)
        members = [self.member("s0", 3), self.member("s1", 3),
                   self.member("s2", 5)]
        assert not c.maybe_bootstrap(members)
        c.step(200)
        assert c.raft.leader() is None

    def test_non_server_members_dont_count(self):
        c = ServerCluster(3, seed=6, bootstrap_expect=3)
        members = [self.member("s0"), self.member("s1"),
                   {"name": "client-1", "tags": {"role": "node"}}]
        assert not c.maybe_bootstrap(members)


class TestNewCLI:
    """CLI surface for the new subcommands (reference command/event,
    command/watch, command/forceleave, command/operator)."""

    def run_cli(self, client, *argv):
        import io
        from contextlib import redirect_stdout

        from consul_tpu.cli import main as cli_main
        host, port = client.base.replace("http://", "").split(":")
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = cli_main(["--http-addr", f"{host}:{port}", *argv])
        return rc, buf.getvalue()

    def test_event_fire_and_list(self, stack):
        _, _, client = stack
        rc, out = self.run_cli(client, "event", "fire", "cli-deploy", "v9")
        assert rc == 0 and "Event ID:" in out
        rc, out = self.run_cli(client, "event", "list", "cli-deploy")
        assert rc == 0 and "cli-deploy" in out

    def test_watch_once(self, stack):
        _, _, client = stack
        client.kv.put("cliwatch/a", b"1")
        rc, out = self.run_cli(
            client, "watch", "--type", "key",
            "--param", "key=cliwatch/a", "--once", "--wait", "100ms")
        assert rc == 0
        assert json.loads(out.strip())["Result"]["Key"] == "cliwatch/a"

    def test_operator_raft_list_peers(self, stack):
        _, _, client = stack
        rc, out = self.run_cli(client, "operator", "raft", "list-peers")
        assert rc == 0 and "leader" in out and out.count("\n") == 3

    def test_force_leave_via_hook(self, stack):
        _, agent, client = stack
        seen = []
        agent.force_leave_hook = lambda node: (seen.append(node), True)[1]
        rc, out = self.run_cli(client, "force-leave", "sim-40")
        assert rc == 0 and "ok" in out
        assert seen == ["sim-40"]
        agent.force_leave_hook = None
        rc, out = self.run_cli(client, "force-leave", "sim-41")
        assert "no-op" in out


class TestRemoteExec:
    """The consul-exec flow: session + KV job spec + _rexec event +
    per-node ack/out/exit + session-GC (reference agent/remote_exec.go,
    command/exec)."""

    def test_submit_execute_collect(self, stack):
        from consul_tpu import rexec
        _, agent, client = stack
        client.catalog.register("exec-node", "10.0.0.50")

        worker = rexec.ExecWorker(
            client, "exec-node",
            runner=lambda cmd: (0, f"ran:{cmd}".encode()))
        worker.poll()  # prime the event watch index

        done = {}

        def run_submit():
            done["res"] = rexec.submit(client, "exec-node", "uptime",
                                       wait_s=6.0)

        th = threading.Thread(target=run_submit)
        th.start()
        deadline = time.monotonic() + 6.0
        while time.monotonic() < deadline and "res" not in done:
            worker.poll(wait="100ms")
            time.sleep(0.02)
        th.join(8)
        res = done["res"]
        assert res["exec-node"]["ack"] is True
        assert res["exec-node"]["exit"] == 0
        assert res["exec-node"]["output"] == b"ran:uptime"
        # Session destruction GC'd the job subtree (delete behavior).
        assert client.kv.list(rexec.PREFIX + "/") == []

    def test_large_output_chunked(self, stack):
        from consul_tpu import rexec
        _, _, client = stack
        client.catalog.register("exec-big", "10.0.0.51")
        big = bytes(range(256)) * 40  # > 2 chunks at 4 KiB
        worker = rexec.ExecWorker(client, "exec-big",
                                  runner=lambda cmd: (3, big))
        worker.poll()
        done = {}
        th = threading.Thread(
            target=lambda: done.update(
                res=rexec.submit(client, "exec-big", "dump", wait_s=6.0)))
        th.start()
        deadline = time.monotonic() + 6.0
        while time.monotonic() < deadline and "res" not in done:
            worker.poll(wait="100ms")
            time.sleep(0.02)
        th.join(8)
        rec = done["res"]["exec-big"]
        assert rec["exit"] == 3
        assert rec["output"] == big

    def test_worker_ignores_malformed_event(self, stack):
        from consul_tpu import rexec
        _, _, client = stack
        worker = rexec.ExecWorker(client, "exec-x")
        worker.poll()
        client._call("PUT", f"/v1/event/fire/{rexec.EVENT}", {},
                     b"not json")
        worker.poll(wait="200ms")  # must not raise

    def test_target_filter_runs_on_named_node_only(self, stack):
        from consul_tpu import rexec
        _, _, client = stack
        client.catalog.register("exec-t1", "10.0.0.52")
        ran = []
        w1 = rexec.ExecWorker(client, "exec-t1",
                              runner=lambda c: (ran.append("t1"), (0, b"1"))[1])
        w2 = rexec.ExecWorker(client, "exec-t2",
                              runner=lambda c: (ran.append("t2"), (0, b"2"))[1])
        w1.poll(); w2.poll()
        done = {}
        th = threading.Thread(target=lambda: done.update(
            res=rexec.submit(client, "exec-t1", "job", wait_s=6.0,
                             target="exec-t1")))
        th.start()
        deadline = time.monotonic() + 6.0
        while time.monotonic() < deadline and "res" not in done:
            w1.poll(wait="100ms"); w2.poll(wait="100ms")
            time.sleep(0.02)
        th.join(8)
        assert set(done["res"]) == {"exec-t1"}
        assert ran == ["t1"], "the non-targeted worker must not execute"

    def test_worker_ignores_non_dict_json_payload(self, stack):
        from consul_tpu import rexec
        _, _, client = stack
        worker = rexec.ExecWorker(client, "exec-y")
        worker.poll()
        client._call("PUT", f"/v1/event/fire/{rexec.EVENT}", {},
                     b'["a list"]')
        client._call("PUT", f"/v1/event/fire/{rexec.EVENT}", {}, b'3')
        worker.poll(wait="200ms")  # must not raise


class TestAliasCheck:
    """Alias checks (reference agent/checks/alias.go): mirror another
    node's (or service's) health into a local check."""

    def _runner(self):
        from consul_tpu.agent.checks import CheckRunner
        from consul_tpu.agent.local import LocalState
        local = LocalState("n1", "addr")
        return local, CheckRunner(local)

    def test_alias_mirrors_target_health(self):
        remote = {"rows": []}

        def rpc(method, **kw):
            assert method == "Health.NodeChecks" and kw["node"] == "db-1"
            return {"index": 1, "value": list(remote["rows"])}

        local, runner = self._runner()
        runner.add_alias("alias-db", rpc, "db-1", interval_s=1.0,
                         background=False)
        runner.tick(0.0)
        # No checks on the target -> passing (alias.go:150-158).
        assert local.checks["alias-db"].status == "passing"
        remote["rows"] = [{"check_id": "x", "status": "warning"},
                         {"check_id": "y", "status": "critical"}]
        runner.tick(1.0)  # worst status wins
        assert local.checks["alias-db"].status == "critical"
        remote["rows"] = [{"check_id": "x", "status": "passing"}]
        runner.tick(2.0)
        assert local.checks["alias-db"].status == "passing"

    def test_alias_service_filter_and_rpc_failure(self):
        rows = [
            {"check_id": "a", "status": "critical", "service_id": "web1"},
            {"check_id": "b", "status": "passing", "service_id": "api1"},
        ]
        calls = {"fail": False}

        def rpc(method, **kw):
            if calls["fail"]:
                raise ConnectionError("no leader")
            return {"index": 1, "value": rows}

        local, runner = self._runner()
        runner.add_alias("alias-api", rpc, "db-1",
                         target_service_id="api1", interval_s=1.0,
                         background=False)
        runner.tick(0.0)  # only api1's checks count
        assert local.checks["alias-api"].status == "passing"
        calls["fail"] = True  # unreachable catalog -> critical
        runner.tick(1.0)
        assert local.checks["alias-api"].status == "critical"


class TestScriptCheck:
    def test_exit_codes_map_to_statuses(self):
        """Script checks (reference exec checks): exit 0/1/other ->
        passing/warning/critical; timeouts and spawn failures are
        critical."""
        import sys as _sys

        from consul_tpu.agent.checks import CheckRunner
        from consul_tpu.agent.local import LocalState

        local = LocalState("script-node", "10.0.0.1")
        runner = CheckRunner(local)
        for code, want in ((0, "passing"), (1, "warning"),
                           (3, "critical")):
            cid = f"sc-{code}"
            runner.add_script(
                cid, [_sys.executable, "-c", f"import sys; print('out');"
                      f" sys.exit({code})"],
                interval_s=0.01, background=False)
            runner.tick(1.0)
            assert local.checks[cid].status == want, (code, want)
        assert "out" in local.checks["sc-0"].output
        # Spawn failure -> critical with a reason.
        runner.add_script("sc-bad", ["/definitely/not/a/binary"],
                          interval_s=0.01, background=False)
        runner.tick(2.0)
        assert local.checks["sc-bad"].status == "critical"
        assert "failed to run" in local.checks["sc-bad"].output

    def test_register_over_http_requires_opt_in(self, stack):
        import sys as _sys
        import time as _t

        import pytest as _pytest

        from consul_tpu.api import APIError
        cluster, agent, client = stack
        body = json.dumps({
            "Name": "script-ck",
            "Args": [_sys.executable, "-c", "print('ok')"],
            "Interval": "10s",
        }).encode()
        # OFF by default: registering an exec check is remote command
        # execution, so it must be refused (reference
        # enable_script_checks).
        with _pytest.raises(APIError, match="disabled"):
            client._call("PUT", "/v1/agent/check/register", {}, body)
        # Find the api object to opt in (the stack serves one HTTPApi).
        import gc

        from consul_tpu.agent.http import HTTPApi
        api = next(o for o in gc.get_objects()
                   if isinstance(o, HTTPApi) and o.agent is agent)
        api.enable_script_checks = True
        try:
            out, _, _ = client._call("PUT", "/v1/agent/check/register",
                                     {}, body)
            assert out is True
            # The background probe posts its result directly to local
            # state; poll until it lands.
            deadline = _t.time() + 5
            while _t.time() < deadline:
                if client.agent.checks().get("script-ck", {}).get(
                        "Status") == "passing":
                    break
                _t.sleep(0.1)
            assert client.agent.checks()["script-ck"]["Status"] == \
                "passing"
        finally:
            api.enable_script_checks = False
