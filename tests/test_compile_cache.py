"""Persistent XLA compile cache plumbing (utils/compile_cache.py).

Everything that flips process-global jax state (the cache dir, the
monitoring listener, reset_cache) runs in a SUBPROCESS: enabling the
cache in the tier-1 process would change what backend_compile events
the shared CompileLedger pins observe for every test after this one.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from consul_tpu.utils import compile_cache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The regression scenario: a model module is imported BEFORE the cache
# is enabled. consul_tpu.models.swim materializes a module-level device
# constant at import, which triggers the process's first XLA compile —
# and jax initializes its persistent-cache state at most once, on that
# first compile. Without the reset_cache() call in enable(), pointing
# jax_compilation_cache_dir at a directory afterwards is a silent no-op
# (zero hits, zero misses, empty directory — exactly what bench.py's
# child used to record).
_CHILD = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
import jax
import consul_tpu.models.swim  # first XLA compile happens HERE
from consul_tpu.utils import compile_cache
compile_cache.enable({cache!r})
import jax.numpy as jnp
jax.jit(lambda x: x * 2 + 1)(jnp.arange(8, dtype=jnp.int32))
print(json.dumps(compile_cache.stats()))
"""


def _run_child(cache_dir: str) -> dict:
    out = subprocess.run(
        [sys.executable, "-c", _CHILD.format(repo=REPO, cache=cache_dir)],
        capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


class TestEnableAfterFirstCompile:
    def test_cache_engages_despite_prior_import(self, tmp_path):
        cache = str(tmp_path / "cc")
        stats = _run_child(cache)
        assert stats["enabled"] and stats["dir"] == cache
        assert stats["misses"] > 0, (
            "enable() after an import-time compile never engaged the "
            "persistent cache — the reset_cache() latch fix regressed")
        assert stats["hits"] == 0
        assert os.listdir(cache), "no executables serialized to disk"

    @pytest.mark.slow
    def test_second_cold_process_warms_from_disk(self, tmp_path):
        cache = str(tmp_path / "cc")
        cold = _run_child(cache)
        assert cold["misses"] > 0
        warm = _run_child(cache)
        assert warm["hits"] > 0
        assert warm["misses"] == 0


class TestHostSide:
    def test_maybe_enable_from_env_empty_is_none(self):
        assert compile_cache.maybe_enable_from_env({}) is None
        assert compile_cache.maybe_enable_from_env(
            {compile_cache.ENV_VAR: "  "}) is None

    def test_stats_delta_arithmetic(self):
        before = {"hits": 3, "misses": 5}
        now = compile_cache.stats()
        delta = compile_cache.stats_delta(before)
        assert delta["hits"] == now["hits"] - 3
        assert delta["misses"] == now["misses"] - 5
        assert delta["enabled"] == now["enabled"]
