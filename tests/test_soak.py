"""Cross-subsystem randomized soak: a seeded op mix over the whole
HTTP surface with replica-equality and referential-integrity
invariants — the cross-feature interaction hunter (the reference's
fuzz/soak idiom over an in-process cluster).

Every op is driven through HTTPApi.handle (the real routing/ACL/
confirm paths, no sockets for speed); after the storm the three
replicas' stores must be IDENTICAL and the store's invariants hold.
"""

import base64
import json
import random
import time

import pytest

OPS = 600


@pytest.fixture(scope="module")
def stack():
    from conftest import pumped_cluster_stack
    cluster, _agent, api, lock, stop = pumped_cluster_stack(
        3, seed=61, node="soak-agent", address="10.99.0.1")
    yield cluster, api, lock
    stop.set()


def call(api, method, path, q=None, body=b""):
    return api.handle(method, path, {k: [v] for k, v in (q or {}).items()},
                      body)


class TestSoak:
    @pytest.mark.parametrize("seed", [20260731, 7, 424242])
    def test_randomized_storm_keeps_replicas_identical(self, stack,
                                                       seed):
        cluster, api, lock = stack
        rng = random.Random(seed)
        nodes = [f"sn-{i}" for i in range(6)]
        for i, n in enumerate(nodes):
            st, _, _ = call(api, "PUT", "/v1/catalog/register",
                            body=json.dumps(
                                {"Node": n,
                                 "Address": f"10.99.1.{i}"}).encode())
            assert st == 200
        sessions: list[str] = []
        intentions: list[str] = []
        queries: list[str] = []
        statuses = {"2xx": 0, "4xx": 0}

        def record(st):
            assert st < 500, f"unexpected {st}"
            statuses["2xx" if st < 400 else "4xx"] += 1

        for opno in range(OPS):
            op = rng.randrange(14)
            key = f"k/{rng.randrange(20)}"
            if op == 0:
                st, _, _ = call(api, "PUT", f"/v1/kv/{key}",
                                body=f"v{opno}".encode())
            elif op == 1:
                st, _, _ = call(api, "DELETE", f"/v1/kv/{key}")
            elif op == 2:
                st, _, _ = call(api, "GET", f"/v1/kv/{key}")
                if st == 404:
                    st = 200  # a missing key is fine; a 500 is not
            elif op == 3:
                st, body, _ = call(
                    api, "PUT", "/v1/session/create",
                    body=json.dumps({"Node": rng.choice(nodes),
                                     "LockDelay": "0s"}).encode())
                if st == 200:
                    sessions.append(body["ID"])
            elif op == 4 and sessions:
                sid = rng.choice(sessions)
                st, _, _ = call(api, "PUT", f"/v1/session/destroy/{sid}")
                sessions.remove(sid)
            elif op == 5 and sessions:
                st, _, _ = call(api, "PUT", f"/v1/kv/lock/{key}",
                                {"acquire": rng.choice(sessions)},
                                b"holder")
            elif op == 6:
                ops = [{"KV": {"Verb": "set", "Key": f"txn/{key}",
                               "Value": base64.b64encode(
                                   str(opno).encode()).decode()}},
                       {"Node": {"Verb": "set",
                                 "Node": {"Node": rng.choice(nodes),
                                          "Address": "10.99.2.1"}}}]
                st, _, _ = call(api, "PUT", "/v1/txn",
                                body=json.dumps(ops).encode())
            elif op == 7:
                st, body, _ = call(
                    api, "POST", "/v1/connect/intentions",
                    body=json.dumps({
                        "SourceName": f"s{rng.randrange(5)}",
                        "DestinationName": f"d{rng.randrange(5)}",
                        "Action": rng.choice(["allow", "deny"]),
                    }).encode())
                if st == 200:
                    intentions.append(body["ID"])
                elif st == 409:
                    st = 200
            elif op == 8 and intentions:
                iid = rng.choice(intentions)
                st, _, _ = call(api, "DELETE",
                                f"/v1/connect/intentions/{iid}")
                intentions.remove(iid)
            elif op == 9:
                st, _, _ = call(api, "GET",
                                "/v1/connect/intentions/check",
                                {"source": f"s{rng.randrange(5)}",
                                 "destination": f"d{rng.randrange(5)}"})
            elif op == 10:
                name = f"q{rng.randrange(5)}"
                st, body, _ = call(
                    api, "POST", "/v1/query",
                    body=json.dumps({
                        "Name": name,
                        "Service": {"Service": "web"}}).encode())
                if st == 200:
                    queries.append(body["ID"])
                elif st == 400:
                    st = 200  # duplicate name
            elif op == 11 and queries:
                st, _, _ = call(api, "GET",
                                f"/v1/query/{rng.choice(queries)}/execute")
            elif op == 12:
                st, _, _ = call(
                    api, "PUT", "/v1/catalog/register",
                    body=json.dumps({
                        "Node": rng.choice(nodes),
                        "Address": "10.99.1.9",
                        "Service": {"ID": f"svc-{rng.randrange(8)}",
                                    "Service": "web",
                                    "Port": 80}}).encode())
            elif op == 13:
                st, _, _ = call(api, "GET", "/v1/catalog/nodes",
                                {"filter": 'Node matches "^sn-"'})
            else:
                continue
            record(st)

        assert statuses["2xx"] > OPS // 2  # the storm mostly succeeded

        # Quiesce: let every replica apply everything.
        deadline = time.time() + 10.0
        while time.time() < deadline:
            with lock:
                idxs = {n.last_applied
                        for n in cluster.raft.nodes.values()}
            if len(idxs) == 1:
                break
            time.sleep(0.01)
        with lock:
            snaps = [s.store.snapshot() for s in cluster.servers]

        # Invariant 1: replicas identical, table by table.
        for name in snaps[0]["tables"]:
            rows0 = snaps[0]["tables"][name]
            for i, snap in enumerate(snaps[1:], start=1):
                assert snap["tables"][name] == rows0, \
                    f"replica {i} diverged on table {name!r}"

        # Invariant 2: referential integrity on the final state.
        store = cluster.servers[0].store
        session_ids = {s["id"] for s in store.session_list()}
        for k in store.tables["kv"].rows:
            sess = store.tables["kv"].rows[k].value.get("session")
            assert sess is None or sess in session_ids, \
                f"kv {k!r} holds a lock for a dead session"
        for s in store.session_list():
            assert store.get_node(s["node"]) is not None

        # Invariant 3: indexes monotone and consistent.
        assert all(snap["index"] == snaps[0]["index"] for snap in snaps)


class TestFailoverStorm:
    def test_leader_kill_mid_storm_converges_survivors(self):
        """Kill the leader in the middle of a write storm: survivors
        re-elect, writes resume, and the two surviving replicas end
        bit-identical (failover under load, the reference's
        leader-loss drill)."""
        from conftest import pumped_cluster_stack

        cluster, _agent, api, lock, stop = pumped_cluster_stack(
            3, seed=67, node="fo-agent", address="10.98.0.1")
        try:
            rng = random.Random(7)

            def storm(n, allow_5xx=False):
                ok = 0
                for i in range(n):
                    st, _, _ = call(api, "PUT",
                                    f"/v1/kv/fo/{rng.randrange(30)}",
                                    body=f"v{i}".encode())
                    if st == 200:
                        ok += 1
                    elif not allow_5xx:
                        assert st < 500, f"unexpected {st}"
                return ok

            assert storm(100) == 100
            with lock:
                led = cluster.raft.leader()
                dead = led.id
                led.stop()
            # The failover window: 5xx tolerated while the survivors
            # elect; afterwards the storm must fully succeed again.
            deadline = time.time() + 10.0
            while time.time() < deadline:
                with lock:
                    new = cluster.raft.leader()
                if new is not None and new.id != dead and \
                        not new.stopped:
                    break
                time.sleep(0.02)
            storm(30, allow_5xx=True)   # drain the transition
            assert storm(100) == 100    # fully live again
            # Survivors quiesce identical.
            survivors = [s for s in cluster.servers if s.id != dead]
            deadline = time.time() + 10.0
            while time.time() < deadline:
                with lock:
                    idxs = {n.last_applied for n in
                            cluster.raft.nodes.values()
                            if n.id != dead and not n.stopped}
                if len(idxs) == 1:
                    break
                time.sleep(0.01)
            with lock:
                snaps = [s.store.snapshot() for s in survivors]
            for name in snaps[0]["tables"]:
                assert snaps[0]["tables"][name] == \
                    snaps[1]["tables"][name], f"diverged on {name!r}"
        finally:
            stop.set()
