"""Golden parity: the packed StateLayout vs the dense f32/i32 reference.

The packed path (models/layout.py) re-encodes the whole SWIM plane
between ticks; the dense path is the golden reference every prior PR
pinned against. The contract, same seed, same verbs:

  - the **discrete plane is bit-identical** — statuses, incarnations,
    suspicion timers, probe cursors, gossip budgets, accuser bitmasks.
    The codec is exact on integers (pack widths hold every protocol
    bound, tick-anchored deltas are canonicalized in the step), and the
    float plane provably never feeds back into integer decisions
    (probe RTTs come from world.pos; lat/viv only feed Vivaldi), so
    any drift here is a codec bug, not a tolerance question;
  - the **Vivaldi plane is allclose** — coordinates round through
    bfloat16 every tick (~0.4% relative, an order below the 5% RTT
    jitter the world model injects) and the RTT windows through scaled
    float8 (~6%% worst-case relative). Tolerances here are set ~10x
    above the drift measured at this exact scenario, and the final
    coordinate-fit RMSE must not degrade;
  - the **SLO counters are equal** — they count discrete-plane events.

Scenarios: quiet convergence, a chaos partition (the SLO counters
must bit-match through fault windows), and the sharded packed runner
(8-device virtual mesh) vs the single-device dense reference. Plus the
beyond-HBM acceptance run: 4M nodes end-to-end on the CPU tier through
the planner-shaped cohort stream.

Slow tier: 4096 nodes, full convergence windows.
"""

import functools

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from consul_tpu import chaos
from consul_tpu.config import SimConfig
from consul_tpu.models import layout
from consul_tpu.models.cluster import (
    SLO_KEYS,
    SerfSimulation,
    Simulation,
    StreamedSimulation,
)
from consul_tpu.parallel import mesh as pmesh
from consul_tpu.runtime import membudget

pytestmark = pytest.mark.slow

N = 4096
SEED = 3
TICKS = 48
CHUNK = 16

# Integer/boolean SimState fields: exact, no tolerance.
DISCRETE = (
    "t", "alive_truth", "left", "leaving", "external", "own_inc",
    "own_tx", "awareness", "probe_perm", "probe_ptr", "next_probe_tick",
    "pending_col", "pending_fail_tick", "pending_nack_miss", "view_key",
    "susp_start", "susp_seen", "tx_left", "lat_cnt",
)

# Measured drift at this scenario: RTT-scale fields ~1.4e-4 abs,
# O(1)-scale fields (viv.error) ~1.25% rel (48 ticks of bf16
# rounding), lat_buf ~3.9e-3 abs (fp8 resolution at RTT scale).
# Asserted with >=2x headroom over measurement.
VIV_RTOL = 3e-2
VIV_ATOL = 2e-3
LAT_ATOL = 2e-2


def _assert_swim_parity(dense_st, packed_st):
    for field in DISCRETE:
        np.testing.assert_array_equal(
            np.asarray(getattr(dense_st, field)),
            np.asarray(getattr(packed_st, field)), err_msg=field)
    for field in ("vec", "height", "error", "adjustment", "adj_samples"):
        np.testing.assert_allclose(
            np.asarray(getattr(packed_st.viv, field)),
            np.asarray(getattr(dense_st.viv, field)),
            rtol=VIV_RTOL,
            atol=VIV_ATOL if field != "adj_samples" else LAT_ATOL,
            err_msg=f"viv.{field}")
    np.testing.assert_array_equal(np.asarray(dense_st.viv.adj_idx),
                                  np.asarray(packed_st.viv.adj_idx))
    np.testing.assert_array_equal(np.asarray(dense_st.viv.resets),
                                  np.asarray(packed_st.viv.resets))
    np.testing.assert_allclose(np.asarray(packed_st.lat_buf),
                               np.asarray(dense_st.lat_buf),
                               atol=LAT_ATOL, err_msg="lat_buf")


def _slo(sim):
    return {f: sim.counters[f] for f in SLO_KEYS}


@functools.lru_cache(maxsize=None)
def _pair(with_chaos: bool, kind: str = "swim"):
    """One (dense, packed) twin per scenario: same seed, same verbs —
    the 4096-node runs compile and execute once, shared by every
    assertion below."""
    cls = SerfSimulation if kind == "serf" else Simulation
    cfg = SimConfig(n=N, view_degree=16)
    sims = [cls(cfg, seed=SEED, layout=lay)
            for lay in (layout.DENSE, layout.PACKED)]
    for sim in sims:
        # Host-side verbs route through the _to_dense/_from_dense seam
        # on the packed sim — the parity must survive them too.
        sim.kill(np.arange(N) == 7)
        if with_chaos:
            sim.run_scenario(
                [chaos.Partition(start=2, stop=18,
                                 side_a=slice(0, N // 4))],
                ticks=TICKS, chunk=CHUNK)
        else:
            sim.run(TICKS, chunk=CHUNK, with_metrics=False)
    return sims


class TestPackedParityQuiet:
    def test_swim_plane(self):
        dense, packed = _pair(False)
        assert packed.layout == layout.PACKED
        assert layout.is_packed(packed.state)
        _assert_swim_parity(dense.swim_state, packed.swim_state)

    def test_rmse_not_degraded(self):
        dense, packed = _pair(False)
        rd, rp = dense.rmse(), packed.rmse()
        assert rp <= rd * 1.25 + 1e-3, (rd, rp)

    def test_slo_counters_identical(self):
        dense, packed = _pair(False)
        assert _slo(dense) == _slo(packed)


class TestPackedParityChaos:
    def test_swim_plane(self):
        dense, packed = _pair(True)
        _assert_swim_parity(dense.swim_state, packed.swim_state)

    def test_slo_counters_identical(self):
        dense, packed = _pair(True)
        assert _slo(dense) == _slo(packed)
        assert _slo(dense)["chaos_msgs_dropped"] > 0  # the faults bit


class TestPackedParitySerf:
    """The serf driver swaps only the SWIM plane (the event/query lanes
    are already packed); full-stack parity incl. the fused counters."""

    def test_swim_plane_and_counters(self):
        dense, packed = _pair(False, "serf")
        _assert_swim_parity(dense.swim_state, packed.swim_state)
        assert dense.counters == packed.counters


class TestPackedParitySharded:
    """Packed layout under shard_map (8-device virtual mesh) vs the
    single-device dense reference: the discrete plane stays bit-exact
    (integer arithmetic is reduction-order-free), floats take the
    sharded tolerance on top of the quantization one."""

    def test_sharded_packed_matches_dense(self):
        cfg = SimConfig(n=N, view_degree=16)
        mesh = Mesh(np.array(jax.devices()[:8]), (pmesh.NODE_AXIS,))
        dense = Simulation(cfg, seed=SEED)
        packed = Simulation(cfg, seed=SEED, mesh=mesh,
                            layout=layout.PACKED)
        for sim in (dense, packed):
            sim.run(TICKS, chunk=CHUNK, with_metrics=False)
        ds, ps = dense.swim_state, packed.swim_state
        for field in DISCRETE:
            np.testing.assert_array_equal(
                np.asarray(getattr(ds, field)),
                np.asarray(getattr(ps, field)), err_msg=field)
        np.testing.assert_allclose(np.asarray(ps.viv.vec),
                                   np.asarray(ds.viv.vec),
                                   atol=VIV_ATOL, rtol=1e-3)


class TestBeyondHBM:
    """The acceptance run: a 4M-node population streams end-to-end on
    the CPU tier through the planner's cohort shape, inside the
    planner's budget."""

    def test_4m_nodes_stream_within_budget(self):
        cfg = SimConfig(n=4 * 1024 * 1024, view_degree=8)
        plan = membudget.plan(cfg, budget="1GB")
        assert plan.streamed and plan.layout == layout.PACKED
        assert plan.packed_cut >= 2.5
        sim = StreamedSimulation(cfg, cohort_n=plan.cohort_n, seed=0,
                                 layout=plan.layout, chunk=2)
        out = sim.run(2)
        assert out["n"] == cfg.n and sim._tick() == 2
        assert sim.resident_bytes() <= plan.budget_bytes
        assert sim.counters["probes_sent"] > 0
