"""ConfigEntry RPC surface over the existing store table (reference
agent/consul/config_endpoint.go: Apply w/ CAS, Get, List, Delete;
agent/config_endpoint.go HTTP routes): raft-replicated writes,
blocking reads, CAS verdicts from the FSM."""

import threading
import time

import pytest

from consul_tpu.server.endpoints import ServerCluster


@pytest.fixture
def cluster():
    c = ServerCluster(3, seed=7)
    c.wait_converged()
    return c


PROXY_DEFAULTS = {"config": {"protocol": "http"}}


class TestConfigEntryRPC:
    def test_apply_get_roundtrip(self, cluster):
        led = cluster.leader_server()
        cluster.write(led, "ConfigEntry.Apply", kind="proxy-defaults",
                      name="global", entry=PROXY_DEFAULTS)
        out = led.rpc("ConfigEntry.Get", kind="proxy-defaults",
                      name="global")
        assert out["value"]["entry"] == PROXY_DEFAULTS
        assert out["value"]["modify_index"] > 0
        # Replicated: a follower serves the same read.
        fol = cluster.any_follower()
        assert fol.rpc("ConfigEntry.Get", kind="proxy-defaults",
                       name="global")["value"]["entry"] == PROXY_DEFAULTS

    def test_get_absent_is_none(self, cluster):
        led = cluster.leader_server()
        assert led.rpc("ConfigEntry.Get", kind="proxy-defaults",
                       name="nope")["value"] is None

    def test_list_filters_by_kind(self, cluster):
        led = cluster.leader_server()
        cluster.write(led, "ConfigEntry.Apply", kind="proxy-defaults",
                      name="global", entry=PROXY_DEFAULTS)
        cluster.write(led, "ConfigEntry.Apply", kind="service-defaults",
                      name="web", entry={"protocol": "grpc"})
        cluster.write(led, "ConfigEntry.Apply", kind="service-defaults",
                      name="db", entry={"protocol": "tcp"})
        all_out = led.rpc("ConfigEntry.List")["value"]
        assert [(e["kind"], e["name"]) for e in all_out] == [
            ("proxy-defaults", "global"), ("service-defaults", "db"),
            ("service-defaults", "web")]
        svc = led.rpc("ConfigEntry.List", kind="service-defaults")["value"]
        assert {e["name"] for e in svc} == {"web", "db"}

    def test_cas_set_semantics(self, cluster):
        led = cluster.leader_server()
        # cas=0: only-if-absent — first wins, second loses.
        idx = cluster.write(led, "ConfigEntry.Apply", kind="k", name="n",
                            entry={"v": 1}, cas_index=0)
        verdict = led.rpc("Status.ApplyResult", index=idx)
        assert verdict == {"found": True, "result": True}
        idx2 = cluster.write(led, "ConfigEntry.Apply", kind="k", name="n",
                             entry={"v": 2}, cas_index=0)
        assert led.rpc("Status.ApplyResult",
                       index=idx2)["result"] is False
        assert led.store.config_get("k", "n") == {"v": 1}
        # cas at the current modify index wins.
        cur = led.store.config_get_meta("k", "n")["modify_index"]
        idx3 = cluster.write(led, "ConfigEntry.Apply", kind="k", name="n",
                             entry={"v": 3}, cas_index=cur)
        assert led.rpc("Status.ApplyResult", index=idx3)["result"] is True
        assert led.store.config_get("k", "n") == {"v": 3}

    def test_delete_and_cas_delete(self, cluster):
        led = cluster.leader_server()
        cluster.write(led, "ConfigEntry.Apply", kind="k", name="n",
                      entry={"v": 1})
        idx = cluster.write(led, "ConfigEntry.Delete", kind="k", name="n",
                            cas_index=99999)  # wrong index: refused
        assert led.rpc("Status.ApplyResult", index=idx)["result"] is False
        assert led.store.config_get("k", "n") is not None
        cluster.write(led, "ConfigEntry.Delete", kind="k", name="n")
        assert led.store.config_get("k", "n") is None

    def test_blocking_list_wakes_on_write(self, cluster):
        led = cluster.leader_server()
        cluster.write(led, "ConfigEntry.Apply", kind="k", name="a",
                      entry={"v": 1})
        idx = led.rpc("ConfigEntry.List")["index"]
        got = {}

        def block():
            got["out"] = led.rpc("ConfigEntry.List", min_index=idx,
                                 wait_s=5.0)

        th = threading.Thread(target=block)
        th.start()
        time.sleep(0.1)
        cluster.write(led, "ConfigEntry.Apply", kind="k", name="b",
                      entry={"v": 2})
        th.join(timeout=5.0)
        assert {e["name"] for e in got["out"]["value"]} == {"a", "b"}
        assert got["out"]["index"] > idx
