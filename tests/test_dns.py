"""DNS interface (reference agent/dns.go + dns_test.go): real UDP/TCP
packets against the `.consul` domain — node and service lookups, RFC
2782 SRV, tags, prepared queries, PTR, NXDOMAIN+SOA, truncation."""

import threading
import time

import pytest

from consul_tpu.agent import dns
from consul_tpu.server.endpoints import ServerCluster


class TestCodec:
    def test_name_roundtrip(self):
        data = dns.encode_name("web.service.consul")
        name, off = dns.decode_name(data, 0)
        assert name == "web.service.consul" and off == len(data)

    def test_query_roundtrip(self):
        pkt = dns.encode_query(77, "db.service.consul", dns.SRV)
        msg = dns.decode_message(pkt)
        assert msg["id"] == 77
        assert msg["questions"] == [{"name": "db.service.consul",
                                     "qtype": dns.SRV}]

    def test_response_records_roundtrip(self):
        pkt = dns.encode_response(5, "x.node.consul", dns.A, [
            ("x.node.consul", dns.A, 60, "10.1.2.3"),
            ("x.node.consul", dns.SRV, 30, (1, 1, 8080, "x.node.consul")),
            ("3.2.1.10.in-addr.arpa", dns.PTR, 0, "x.node.consul"),
        ])
        msg = dns.decode_message(pkt)
        vals = [(r["rtype"], r["value"]) for r in msg["answers"]]
        assert (dns.A, "10.1.2.3") in vals
        assert (dns.SRV, (1, 1, 8080, "x.node.consul")) in vals
        assert (dns.PTR, "x.node.consul") in vals

    def test_compressed_pointer_decode(self):
        # Hand-build: name at offset 12, then a pointer to it.
        base = dns.encode_name("a.consul")
        data = b"\x00" * 12 + base + b"\xc0\x0c"
        name, _ = dns.decode_name(data, 12 + len(base))
        assert name == "a.consul"

    def test_pointer_loop_rejected(self):
        data = b"\x00" * 12 + b"\xc0\x0c"
        with pytest.raises(ValueError, match="loop|pointer"):
            dns.decode_name(data, 12)


@pytest.fixture(scope="module")
def stack():
    """Cluster + pumped raft + DNSServer on a real UDP/TCP port."""
    cluster = ServerCluster(3, seed=5)
    leader = cluster.wait_converged()
    stop = threading.Event()
    lock = threading.Lock()

    def pump():
        while not stop.is_set():
            with lock:
                cluster.step()
            time.sleep(0.002)

    threading.Thread(target=pump, daemon=True).start()

    def rpc(method, **args):
        with lock:
            server = cluster.registry[cluster.raft.wait_converged().id]
        return server.rpc(method, **args)

    def write(method, **args):
        out = rpc(method, **args)
        idx = out["index"] if isinstance(out, dict) and "index" in out \
            else out
        deadline = time.time() + 5.0
        while time.time() < deadline:
            with lock:
                led = cluster.raft.leader()
                if led is not None and led.last_applied >= idx:
                    return out
            time.sleep(0.002)
        raise TimeoutError(f"apply {idx} not confirmed")

    write("Catalog.Register", node="dns-n1", address="10.5.0.1",
          service={"id": "web-1", "service": "web", "port": 8080,
                   "tags": ["prod"]},
          check={"check_id": "w1", "status": "passing",
                 "service_id": "web-1"})
    write("Catalog.Register", node="dns-n2", address="10.5.0.2",
          service={"id": "web-2", "service": "web", "port": 8081},
          check={"check_id": "w2", "status": "critical",
                 "service_id": "web-2"})
    write("PreparedQuery.Apply", op="create",
          query={"name": "webq", "service": {"service": "web"}})
    srv = dns.DNSServer(rpc, node_name="dns-n1", datacenter="dc1",
                        service_ttl_s=30)
    port = srv.serve("127.0.0.1", 0)
    yield srv, port, write
    srv.close()
    stop.set()


def q(port, name, qtype=dns.A, tcp=False):
    return dns.lookup("127.0.0.1", port, name, qtype, tcp=tcp)


class TestLookups:
    def test_node_a_record(self, stack):
        _, port, _ = stack
        msg = q(port, "dns-n1.node.consul")
        assert msg["rcode"] == dns.NOERROR
        assert msg["answers"][0]["value"] == "10.5.0.1"
        assert msg["answers"][0]["rtype"] == dns.A

    def test_node_with_dc_label(self, stack):
        _, port, _ = stack
        msg = q(port, "dns-n1.node.dc1.consul")
        assert msg["answers"][0]["value"] == "10.5.0.1"

    def test_unknown_node_nxdomain_with_soa(self, stack):
        _, port, _ = stack
        msg = q(port, "ghost.node.consul")
        assert msg["rcode"] == dns.NXDOMAIN
        assert msg["authority"][0]["rtype"] == dns.SOA

    def test_service_a_excludes_critical(self, stack):
        _, port, _ = stack
        msg = q(port, "web.service.consul")
        assert msg["rcode"] == dns.NOERROR
        # dns-n2 is critical: only the passing instance answers.
        assert [a["value"] for a in msg["answers"]] == ["10.5.0.1"]

    def test_service_srv_records(self, stack):
        _, port, _ = stack
        msg = q(port, "web.service.consul", dns.SRV)
        assert msg["answers"][0]["rtype"] == dns.SRV
        pri, weight, sport, target = msg["answers"][0]["value"]
        assert sport == 8080 and target == "dns-n1.node.consul"

    def test_rfc2782_srv_syntax(self, stack):
        _, port, _ = stack
        msg = q(port, "_web._tcp.service.consul", dns.SRV)
        assert msg["answers"][0]["value"][2] == 8080
        msg = q(port, "_web._prod.service.consul", dns.SRV)
        assert msg["answers"][0]["value"][2] == 8080
        msg = q(port, "_web._missingtag.service.consul", dns.SRV)
        assert msg["rcode"] == dns.NXDOMAIN

    def test_tag_service_lookup(self, stack):
        _, port, _ = stack
        msg = q(port, "prod.web.service.consul")
        assert [a["value"] for a in msg["answers"]] == ["10.5.0.1"]
        msg = q(port, "nope.web.service.consul")
        assert msg["rcode"] == dns.NXDOMAIN

    def test_prepared_query_lookup(self, stack):
        _, port, _ = stack
        msg = q(port, "webq.query.consul")
        assert msg["rcode"] == dns.NOERROR
        assert [a["value"] for a in msg["answers"]] == ["10.5.0.1"]
        assert msg["answers"][0]["ttl"] == 30
        msg = q(port, "webq.query.consul", dns.SRV)
        assert msg["answers"][0]["value"][3] == "dns-n1.node.consul"

    def test_ptr_lookup(self, stack):
        _, port, _ = stack
        msg = q(port, "1.0.5.10.in-addr.arpa", dns.PTR)
        assert msg["answers"][0]["value"] == "dns-n1.node.consul"
        msg = q(port, "9.9.9.9.in-addr.arpa", dns.PTR)
        assert msg["rcode"] == dns.NXDOMAIN

    def test_other_domain_refused(self, stack):
        _, port, _ = stack
        msg = q(port, "example.com")
        assert msg["rcode"] == dns.REFUSED

    def test_tcp_transport(self, stack):
        _, port, _ = stack
        msg = q(port, "web.service.consul", tcp=True)
        assert [a["value"] for a in msg["answers"]] == ["10.5.0.1"]


class TestTruncation:
    def test_udp_truncates_tcp_does_not(self, stack):
        srv, port, write = stack
        for i in range(6):
            write("Catalog.Register", node=f"many-{i}",
                  address=f"10.6.0.{i}",
                  service={"id": f"m-{i}", "service": "many", "port": 80},
                  check={"check_id": f"mc-{i}", "status": "passing",
                         "service_id": f"m-{i}"})
        msg = q(port, "many.service.consul")
        assert msg["tc"] is True
        assert len(msg["answers"]) == srv.udp_answer_limit
        msg = q(port, "many.service.consul", tcp=True)
        assert msg["tc"] is False and len(msg["answers"]) == 6

    def test_addr_echo(self, stack):
        _, port, _ = stack
        msg = q(port, "0a050001.addr.consul")
        assert msg["answers"][0]["value"] == "10.5.0.1"


class TestACL:
    """DNS requests carry no token, so lookups resolve through the
    agent's configured authz (reference agent/dns.go resolves the
    agent token; CVE-2020-25864-class bypass: an unfiltered DNS path
    leaks the catalog even when HTTP enforces ACLs)."""

    @pytest.fixture(scope="class")
    def acl_port(self, stack):
        from consul_tpu.server.acl import Authorizer, parse_rules

        srv, _, _ = stack
        rules = parse_rules('service "web" { policy = "read" }')
        authz = Authorizer([rules], default_allow=False)
        acl_srv = dns.DNSServer(
            srv.rpc, node_name="dns-n1", datacenter="dc1",
            service_ttl_s=30,
            authz=lambda res, name, access: authz.allowed(
                res, name, access))
        port = acl_srv.serve("127.0.0.1", 0)
        yield port
        acl_srv.close()

    def test_granted_service_answers(self, acl_port):
        msg = q(acl_port, "web.service.consul")
        assert msg["rcode"] == dns.NOERROR
        assert [a["value"] for a in msg["answers"]] == ["10.5.0.1"]

    def test_denied_service_refused(self, acl_port):
        # "many" exists in the catalog but the token has no rule for
        # it: REFUSED, not NXDOMAIN, so resolvers don't negative-cache
        # the denial as nonexistence.
        msg = q(acl_port, "many.service.consul")
        assert msg["rcode"] == dns.REFUSED
        assert msg["answers"] == []

    def test_denied_node_refused(self, acl_port):
        msg = q(acl_port, "dns-n1.node.consul")
        assert msg["rcode"] == dns.REFUSED
        assert msg["answers"] == []

    def test_denied_ptr_nxdomain(self, acl_port):
        # PTR vets per-row (reference dns.go filters the matched
        # node): with the node unreadable the answer set is empty.
        msg = q(acl_port, "1.0.5.10.in-addr.arpa", dns.PTR)
        assert msg["rcode"] == dns.NXDOMAIN

    def test_no_authz_stays_open(self, stack):
        # The unfiltered module server (authz=None) still answers
        # node lookups — ACLs off means the DNS plane is open.
        _, port, _ = stack
        msg = q(port, "dns-n1.node.consul")
        assert msg["rcode"] == dns.NOERROR
