"""Async/selector frontend (serving/frontend.py): one event loop on
one owned thread multiplexing reads, writes, and blocking queries in
front of the same QueryBatcher / WriteBatcher / WatchPlane the
threaded path uses.

The parity contract (COVERAGE.md game-day section): for the same
workload the async frontend returns byte-identical results to the
threaded path — same kernels, same admission policy, same blocking-
query floor — while parking blocking queries as loop timers instead
of threads (strictly fewer live threads under concurrent waiters).
"""

import concurrent.futures
import threading
import time

import numpy as np
import pytest

from consul_tpu.config import SimConfig
from consul_tpu.models.cluster import Simulation
from consul_tpu.ops import deltas as deltas_mod
from consul_tpu.ops import serving as kernels
from consul_tpu.serving import AsyncFrontend, ServingPlane
from consul_tpu.serving.frontend import ServingClosedError
from consul_tpu.serving.writes import ServingOverloadError


def _stack(n=256, seed=3, **write_kw):
    sim = Simulation(SimConfig(n=n, view_degree=16), seed=seed)
    plane = ServingPlane(k=8, buckets=(64,), num_services=4)
    sim.attach_serving(plane, writes=True, kv_slots=64, **write_kw)
    sim.run(64, chunk=32, with_metrics=False)
    return sim, plane


def _queries(rng_n, count, seed=7):
    import random

    rng = random.Random(seed)
    return [(kernels.MODE_NEAREST, rng.randrange(rng_n), -1)
            for _ in range(count)]


class TestParity:
    def test_read_results_identical_to_threaded(self):
        """The same read batch through both frontends yields identical
        QueryResults — the async loop runs the SAME bucketed kernel."""
        sim, plane = _stack()
        qs = _queries(256, 32)
        threaded = plane.batcher.execute(qs)

        fe = AsyncFrontend(plane).start()
        try:
            futs = [fe.submit_read(m, s, a) for m, s, a in qs]
            async_res = [f.result(30.0) for f in futs]
        finally:
            fe.close()

        assert len(async_res) == len(threaded)
        for t, a in zip(threaded, async_res):
            np.testing.assert_array_equal(t.ids, a.ids)
            np.testing.assert_array_equal(t.rtts, a.rtts)
            assert t.count == a.count

    def test_write_results_identical_to_threaded(self):
        """The same write batch against two identically-seeded stacks
        produces identical WriteResults and identical KV readback."""
        ops = [(deltas_mod.OP_REGISTER, i, i % 4) for i in range(8)]

        sim_t, plane_t = _stack(seed=5)
        kslot_t = plane_t.keys.slot_for("parity/k", create=True)
        threaded = plane_t.writes.execute(
            ops + [(deltas_mod.OP_KV_PUT, kslot_t, 42)])

        sim_a, plane_a = _stack(seed=5)
        fe = AsyncFrontend(plane_a).start()
        try:
            futs = [fe.submit_write(o, t, a) for o, t, a in ops]
            futs.append(fe.kv_put("parity/k", 42))
            async_res = [f.result(30.0) for f in futs]
        finally:
            fe.close()

        assert async_res == threaded
        assert all(r.applied for r in async_res)
        # KV readback needs the write-carrying flip published.
        for s in (sim_t, sim_a):
            s.run(8, chunk=8, with_metrics=False)
            s.publish_serving()
        row_t = plane_t.kv_get("parity/k")
        row_a = plane_a.kv_get("parity/k")
        assert row_t is not None and row_a is not None
        assert row_t["Value"] == row_a["Value"]
        assert row_t["ModifyIndex"] == row_a["ModifyIndex"]

    def test_wait_index_floor_contract(self):
        """Same floor as WatchPlane.wait_index: never below min_index,
        never below 1, immediate when already satisfied."""
        sim, plane = _stack()
        # Advance the apply index past zero with one write-carrying
        # flip, so min_index=0 is already satisfied in both paths.
        plane.writes.execute([(deltas_mod.OP_REGISTER, 0, 1)])
        sim.run(8, chunk=8, with_metrics=False)
        sim.publish_serving()
        assert int(plane.apply_index) >= 1
        fe = AsyncFrontend(plane).start()
        try:
            # Already satisfied: resolves without the full wait.
            t0 = time.perf_counter()
            idx = fe.wait_index(0, 5.0).result(30.0)
            assert time.perf_counter() - t0 < 2.0
            assert idx == plane.watch.wait_index(0, 0.0)
            # Unsatisfiable: parks as a loop timer, then returns the
            # floor (min_index), exactly like the threaded waiter.
            want = int(plane.apply_index) + 10**6
            assert fe.wait_index(want, 0.05).result(30.0) == \
                plane.watch.wait_index(want, 0.0)
        finally:
            fe.close()

    def test_wait_index_wakes_on_publish(self):
        """A parked blocking query wakes when a flip advances the
        apply index past its floor — via the WatchPlane index-listener
        seam, not by burning its full wait."""
        sim, plane = _stack()
        fe = AsyncFrontend(plane).start()
        try:
            seen = int(plane.apply_index)
            fut = fe.wait_index(seen, 10.0)
            time.sleep(0.05)
            assert not fut.done()
            plane.writes.execute([(deltas_mod.OP_REGISTER, 1, 2)])
            sim.run(8, chunk=8, with_metrics=False)
            t0 = time.perf_counter()
            sim.publish_serving()
            idx = fut.result(5.0)
            assert time.perf_counter() - t0 < 5.0
            assert idx > seen
        finally:
            fe.close()


class TestThreadDiscipline:
    def test_blocking_queries_park_on_one_thread(self):
        """N concurrent blocking queries: the threaded path parks N
        live threads; the async frontend parks N loop timers on its
        ONE owned thread — strictly fewer live threads."""
        sim, plane = _stack()
        n_waiters = 16
        unreachable = int(plane.apply_index) + 10**6

        # Threaded: each concurrent blocking query is a parked thread.
        before = threading.active_count()
        threads = [
            threading.Thread(
                target=plane.watch.wait_index, args=(unreachable, 0.8))
            for _ in range(n_waiters)
        ]
        for t in threads:
            t.start()
        time.sleep(0.1)  # let every waiter park
        threaded_live = threading.active_count() - before
        for t in threads:
            t.join()
        assert threaded_live >= n_waiters

        # Async: the same concurrency is N futures on one loop thread.
        before = threading.active_count()
        fe = AsyncFrontend(plane).start()
        try:
            futs = [fe.wait_index(unreachable, 0.3)
                    for _ in range(n_waiters)]
            time.sleep(0.1)
            async_live = threading.active_count() - before
            assert fe.owned_threads() == 1
            assert async_live < threaded_live
            floors = {f.result(30.0) for f in futs}
            assert floors == {unreachable}  # same floor contract
        finally:
            fe.close()

    def test_close_discipline(self):
        """close() joins the owned thread, later submits raise, and a
        second close is a no-op."""
        sim, plane = _stack()
        fe = AsyncFrontend(plane).start()
        assert fe.owned_threads() == 1
        fe.close()
        assert fe.owned_threads() == 0
        assert fe.closed
        with pytest.raises(ServingClosedError):
            fe.submit_read(kernels.MODE_NEAREST, 0)
        fe.close()  # idempotent


class TestAdmissionParity:
    def test_reject_policy_surfaces_on_future(self):
        """Overflow under policy=reject raises ServingOverloadError on
        the overflowing FUTURE (no synchronous raise point on the
        loop), mirroring WriteBatcher.submit's bound and counter."""
        sim, plane = _stack(max_pending=4, policy="reject")
        fe = AsyncFrontend(plane, max_wait_s=0.5).start()
        try:
            futs = [fe.submit_write(deltas_mod.OP_REGISTER, i, 0)
                    for i in range(6)]
            outcomes = []
            for f in futs:
                try:
                    outcomes.append(f.result(30.0).status)
                except ServingOverloadError:
                    outcomes.append("rejected")
            assert outcomes.count("rejected") == 2
            assert outcomes.count("applied") == 4
            assert plane.writes.rejected == 2
        finally:
            fe.close()

    def test_shed_oldest_policy_resolves_shed_result(self):
        """Overflow under policy=shed_oldest drops the OLDEST pending
        write: its future resolves to WriteResult(status='shed') — the
        same visible outcome the threaded batcher gives."""
        sim, plane = _stack(max_pending=4, policy="shed_oldest")
        fe = AsyncFrontend(plane, max_wait_s=0.5).start()
        try:
            futs = [fe.submit_write(deltas_mod.OP_REGISTER, i, 0)
                    for i in range(6)]
            results = [f.result(30.0) for f in futs]
            statuses = [r.status for r in results]
            # The two oldest were shed to admit the two newest.
            assert statuses[:2] == ["shed", "shed"]
            assert statuses[2:] == ["applied"] * 4
            assert plane.writes.shed == 2
        finally:
            fe.close()


class TestHttpSurface:
    def test_http_listener_serves_and_blocks(self):
        """serve_http binds a real socket on the SAME loop: agent
        self, KV PUT/GET round-trip with X-Consul-Index, and a short
        blocking query that rides ?index= + ?wait=."""
        import http.client
        import json

        sim, plane = _stack()
        fe = AsyncFrontend(plane).start()
        try:
            host, port = fe.serve_http("127.0.0.1", 0)
            conn = http.client.HTTPConnection(host, port, timeout=10)

            conn.request("GET", "/v1/agent/self")
            resp = conn.getresponse()
            body = json.loads(resp.read())
            assert resp.status == 200
            assert body["Config"]["NodeName"] == "serving-frontend"

            conn.request("PUT", "/v1/kv/http/smoke", body="7")
            assert conn.getresponse().read() == b"true"
            # The PUT becomes readable at the next published flip.
            sim.run(8, chunk=8, with_metrics=False)
            sim.publish_serving()
            conn.request("GET", "/v1/kv/http/smoke")
            resp = conn.getresponse()
            rows = json.loads(resp.read())
            idx = int(resp.getheader("X-Consul-Index"))
            assert rows[0]["Key"] == "http/smoke"
            assert idx >= 1

            # Blocking query at the current index: times out at the
            # short ?wait= and re-serves with the index header intact.
            conn.request("GET", f"/v1/kv/http/smoke?index={idx}&wait=60ms")
            resp = conn.getresponse()
            resp.read()
            assert int(resp.getheader("X-Consul-Index")) >= idx
            conn.close()
            assert fe.stats()["frontend_http"] >= 4
        finally:
            fe.close()


class TestLockLedgerHotPath:
    """The async-frontend hot path under the LockLedger: the event loop
    multiplexes reads, writes, and parked blocking queries over the
    same traced batcher/watch/plane locks the threaded path uses. Clean
    = acyclic observed order graph, no blocking region under a lock,
    nothing held at teardown — across three fuzz seeds."""

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_frontend_mixed_load_stays_clean(self, lock_ledger, seed):
        lock_ledger.fuzz(seed)
        # Stack AND frontend built inside the ledger's scope so every
        # lock they construct is a traced shim.
        sim, plane = _stack(n=64, seed=seed)
        fe = AsyncFrontend(plane).start()
        try:
            futs = [fe.submit_read(m, s, a)
                    for m, s, a in _queries(64, 16, seed=seed)]
            wfuts = [fe.submit_write(deltas_mod.OP_REGISTER, i, i % 4)
                     for i in range(4)]
            wfuts.append(fe.kv_put("ledger/k", 7))
            parked = fe.wait_index(int(plane.apply_index), 10.0)
            for f in futs + wfuts:
                f.result(30.0)
            # Wake the parked blocking query through a real flip.
            sim.run(8, chunk=8, with_metrics=False)
            sim.publish_serving()
            assert parked.result(10.0) > 0
        finally:
            fe.close()

        names = {a[0] for a in lock_ledger.acquisitions}
        assert "WriteBatcher._lock" in names
        assert "WatchPlane._index_cond" in names
        lock_ledger.assert_clean()
