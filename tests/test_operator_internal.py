"""Operator + Internal endpoint families (reference
agent/consul/operator_raft_endpoint.go:1-89 RaftGetConfiguration /
RaftRemovePeerByAddress, operator_autopilot_endpoint.go:1-76 autopilot
get/set, internal_endpoint.go:1-100 NodeInfo/NodeDump): the day-2
operator surface over the raft mechanics that already existed."""

import threading
import time

import pytest

from consul_tpu.server import autopilot
from consul_tpu.server.endpoints import ServerCluster


@pytest.fixture
def cluster():
    c = ServerCluster(3, seed=11)
    c.wait_converged()
    return c


class TestOperatorRaft:
    def test_get_configuration_lists_members(self, cluster):
        led = cluster.leader_server()
        cfg = led.rpc("Operator.RaftGetConfiguration")
        assert [s["id"] for s in cfg["servers"]] == ["srv0", "srv1", "srv2"]
        assert sum(s["leader"] for s in cfg["servers"]) == 1
        assert all(s["voter"] for s in cfg["servers"])
        lead_row = next(s for s in cfg["servers"] if s["leader"])
        assert lead_row["id"] == led.id

    def test_configuration_from_follower_view(self, cluster):
        fol = cluster.any_follower()
        cfg = fol.rpc("Operator.RaftGetConfiguration")
        assert len(cfg["servers"]) == 3
        assert next(s for s in cfg["servers"] if s["leader"])["id"] == \
            cluster.leader_server().id

    def test_remove_live_follower_converges(self, cluster):
        """The VERDICT acceptance case: kick a live follower out via
        the operator surface; the change replicates as a raft config
        entry and the two survivors keep committing."""
        led = cluster.leader_server()
        victim = cluster.any_follower()
        idx = led.rpc("Operator.RaftRemovePeer", id=victim.id)
        for _ in range(200):
            cluster.step()
            if victim.raft.stopped and led.raft.commit_index >= idx:
                break
        # The victim applied its own removal and halted.
        assert victim.raft.stopped
        assert victim.id not in led.raft.voters
        assert victim.id not in led.raft.peers
        # Cluster of two keeps working (quorum 2 of 2).
        cluster.write(led, "KVS.Apply", op="set", key="after", value=b"x")
        assert led.store.kv_get("after")["value"] == b"x"
        cfg = led.rpc("Operator.RaftGetConfiguration")
        assert victim.id not in [s["id"] for s in cfg["servers"]]

    def test_remove_leader_itself_answers_then_halts(self, cluster):
        """Removing the leader is allowed (reference RaftRemovePeer):
        the leader stays on just long enough to COMMIT and answer the
        entry, then halts; the survivors elect a successor."""
        led = cluster.leader_server()
        idx = led.rpc("Operator.RaftRemovePeer", id=led.id)
        for _ in range(300):
            cluster.step()
            if led.raft.stopped:
                break
        assert led.raft.stopped
        # The entry committed on the ex-leader, so its apply result
        # resolved (no 'apply result unavailable' for a success).
        res = led.raft.apply_results.get(idx)
        assert res == {"ok": True, "op": "remove"}
        new_led = cluster.raft.wait_converged()
        assert new_led.id != led.id
        assert led.id not in new_led.voters
        cluster.write(cluster.registry[new_led.id], "KVS.Apply",
                      op="set", key="post-leader-removal", value=b"y")

    def test_remove_unknown_peer_is_an_error(self, cluster):
        led = cluster.leader_server()
        with pytest.raises(ValueError, match="not a raft peer"):
            led.rpc("Operator.RaftRemovePeer", id="srv9")

    def test_remove_guard_refuses_quorum_break(self, cluster):
        """Sequential removals stop when the survivors would no longer
        be a quorum of the current configuration (reference autopilot
        canRemoveServers guard applied to the operator path)."""
        led = cluster.leader_server()
        victim = cluster.any_follower()
        led.rpc("Operator.RaftRemovePeer", id=victim.id)
        cluster.step(50)
        second = next(s for s in cluster.servers
                      if s.id not in (led.id, victim.id))
        with pytest.raises(ValueError, match="quorum"):
            led.rpc("Operator.RaftRemovePeer", id=second.id)

    def test_remove_forwards_from_follower(self, cluster):
        """The endpoint rides _raft_apply, so a follower accepts the
        call and forwards to the leader (rpc.go:231 forward)."""
        led = cluster.leader_server()
        fol = cluster.any_follower()
        other = next(s for s in cluster.servers
                     if s.id not in (led.id, fol.id))
        fol.rpc("Operator.RaftRemovePeer", id=other.id)
        for _ in range(200):
            cluster.step()
            if other.raft.stopped:
                break
        assert other.id not in led.raft.voters


class TestOperatorAutopilot:
    def test_get_returns_defaults_when_unset(self, cluster):
        led = cluster.leader_server()
        cfg = led.rpc("Operator.AutopilotGetConfiguration")
        assert cfg == autopilot.DEFAULT_AUTOPILOT_CONFIG

    def test_set_replicates_and_cas(self, cluster):
        led = cluster.leader_server()
        cluster.write(led, "Operator.AutopilotSetConfiguration",
                      config={"cleanup_dead_servers": False})
        # Every replica serves the stored config (raft-replicated).
        for s in cluster.servers:
            got = s.rpc("Operator.AutopilotGetConfiguration")
            assert got["cleanup_dead_servers"] is False
        # CAS on the stored modify index: stale index loses.
        stored = led.store.autopilot_get()
        out = cluster.write(led, "Operator.AutopilotSetConfiguration",
                            config={"max_trailing_logs": 99},
                            cas_index=stored["modify_index"])
        res = led.rpc("Status.ApplyResult", index=out)
        assert res["found"] and res["result"] is True
        out2 = cluster.write(led, "Operator.AutopilotSetConfiguration",
                             config={"max_trailing_logs": 7},
                             cas_index=stored["modify_index"])  # stale
        res2 = led.rpc("Status.ApplyResult", index=out2)
        assert res2["found"] and res2["result"] is False
        assert led.rpc("Operator.AutopilotGetConfiguration")[
            "max_trailing_logs"] == 99

    def test_get_put_roundtrip_accepts_modify_index(self, cluster):
        """The standard CAS flow — GET the config, PUT it back — must
        not be rejected over the modify_index the GET included."""
        led = cluster.leader_server()
        cluster.write(led, "Operator.AutopilotSetConfiguration",
                      config={"cleanup_dead_servers": False})
        got = led.rpc("Operator.AutopilotGetConfiguration")
        assert "modify_index" in got
        out = cluster.write(led, "Operator.AutopilotSetConfiguration",
                            config=got, cas_index=got["modify_index"])
        res = led.rpc("Status.ApplyResult", index=out)
        assert res["found"] and res["result"] is True

    def test_operator_knobs_drive_health_scoring(self, cluster):
        """max_trailing_logs set via the operator surface changes the
        health verdicts the autopilot loop computes (the knob is live,
        not just stored)."""
        led = cluster.leader_server()
        ap = autopilot.Autopilot(
            cluster.raft,
            config_fn=lambda: led.rpc("Operator.AutopilotGetConfiguration"))
        cluster.write(led, "Operator.AutopilotSetConfiguration",
                      config={"max_trailing_logs": 0,
                              "cleanup_dead_servers": False})
        ap.run()
        assert ap.max_trailing_logs == 0
        assert ap.last_contact_threshold_ticks == \
            autopilot.LAST_CONTACT_THRESHOLD_TICKS

    def test_unknown_keys_rejected(self, cluster):
        led = cluster.leader_server()
        with pytest.raises(ValueError, match="unknown autopilot"):
            led.rpc("Operator.AutopilotSetConfiguration",
                    config={"redundancy_zones": True})

    def test_autopilot_loop_reads_live_config(self, cluster):
        """The Autopilot loop re-reads the operator config each pass
        (config_fn wiring): flipping cleanup_dead_servers off stops
        dead-server pruning."""
        led = cluster.leader_server()
        ap = autopilot.Autopilot(
            cluster.raft,
            config_fn=lambda: led.rpc("Operator.AutopilotGetConfiguration"))
        cluster.write(led, "Operator.AutopilotSetConfiguration",
                      config={"cleanup_dead_servers": False})
        victim = cluster.any_follower()
        victim.raft.stop()
        for _ in range(60):
            cluster.step()
            ap.run()
        assert ap.removed == [] and victim.id in cluster.raft.nodes
        assert ap.cleanup_dead_servers is False


class TestInternal:
    def test_node_dump_aggregates(self, cluster):
        led = cluster.leader_server()
        cluster.write(led, "Catalog.Register", node="n1", address="10.0.0.1",
                      service={"service": "web", "port": 80},
                      check={"check_id": "web-up", "status": "passing",
                             "service_id": "web"})
        cluster.write(led, "Catalog.Register", node="n2", address="10.0.0.2")
        out = led.rpc("Internal.NodeDump")
        rows = out["value"]
        assert [r["node"] for r in rows] == ["n1", "n2"]
        n1 = rows[0]
        assert n1["address"] == "10.0.0.1"
        assert [s["service"] for s in n1["services"]] == ["web"]
        assert [c["check_id"] for c in n1["checks"]] == ["web-up"]
        assert rows[1]["services"] == [] and rows[1]["checks"] == []

    def test_node_info_single(self, cluster):
        led = cluster.leader_server()
        cluster.write(led, "Catalog.Register", node="n1", address="a",
                      service={"service": "db", "port": 5432})
        out = led.rpc("Internal.NodeInfo", node="n1")
        assert len(out["value"]) == 1
        assert out["value"][0]["services"][0]["service"] == "db"
        assert led.rpc("Internal.NodeInfo", node="ghost")["value"] == []

    def test_node_dump_blocks_until_change(self, cluster):
        led = cluster.leader_server()
        cluster.write(led, "Catalog.Register", node="n1", address="a")
        idx = led.rpc("Internal.NodeDump")["index"]
        got = {}

        def blocked():
            t0 = time.monotonic()
            got["out"] = led.rpc("Internal.NodeDump", min_index=idx,
                                 wait_s=8.0)
            got["dt"] = time.monotonic() - t0

        th = threading.Thread(target=blocked)
        th.start()
        time.sleep(0.2)
        stop = threading.Event()

        def pump():
            while not stop.is_set():
                cluster.step()
                time.sleep(0.002)

        pt = threading.Thread(target=pump, daemon=True)
        pt.start()
        led.rpc("Catalog.Register", node="n2", address="b")
        th.join(timeout=10.0)
        stop.set()
        assert got["dt"] < 5.0
        assert [r["node"] for r in got["out"]["value"]] == ["n1", "n2"]


class TestHTTPAndCLISurface:
    """The /v1/operator/raft/*, /v1/operator/autopilot/*, and
    /v1/internal/ui/* routes (reference http_register.go) plus the
    operator CLI verbs, over a live HTTP agent."""

    @pytest.fixture
    def served(self):
        from consul_tpu.agent.agent import Agent
        from consul_tpu.agent.http import HTTPApi, serve

        cluster = ServerCluster(3, seed=13)
        cluster.wait_converged()
        stop = threading.Event()

        def pump():
            while not stop.is_set():
                cluster.step()
                time.sleep(0.002)

        threading.Thread(target=pump, daemon=True).start()

        def rpc(method, **args):
            led = cluster.raft.wait_converged()
            return cluster.registry[led.id].rpc(method, **args)

        def wait_write(idx):
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                led = cluster.raft.leader()
                if led is not None and led.last_applied >= idx:
                    return
                time.sleep(0.002)

        agent = Agent("op-agent", "127.0.0.1", rpc, cluster_size=3)
        api = HTTPApi(agent, server=cluster.leader_server(),
                      wait_write=wait_write)
        httpd, port = serve(api, "127.0.0.1", 0)
        yield cluster, port
        stop.set()
        httpd.shutdown()

    def test_http_raft_configuration_and_remove(self, served):
        from consul_tpu.api import Client

        cluster, port = served
        client = Client("127.0.0.1", port)
        cfg = client.operator.raft_get_configuration()
        assert len(cfg["servers"]) == 3
        victim = next(s["id"] for s in cfg["servers"] if not s["leader"])
        assert client.operator.raft_remove_peer(victim) is True
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            cfg = client.operator.raft_get_configuration()
            if len(cfg["servers"]) == 2:
                break
            time.sleep(0.05)
        assert victim not in [s["id"] for s in cfg["servers"]]

    def test_http_autopilot_roundtrip(self, served):
        from consul_tpu.api import Client

        _, port = served
        client = Client("127.0.0.1", port)
        cfg = client.operator.autopilot_get_configuration()
        assert cfg["cleanup_dead_servers"] is True
        assert client.operator.autopilot_set_configuration(
            {"server_stabilization_ticks": 77}) is True
        got = client.operator.autopilot_get_configuration()
        assert got["server_stabilization_ticks"] == 77

    def test_http_internal_ui_nodes(self, served):
        from consul_tpu.api import Client

        cluster, port = served
        client = Client("127.0.0.1", port)
        led = cluster.leader_server()
        led.rpc("Catalog.Register", node="web-1", address="10.1.1.1",
                service={"service": "web", "port": 80})
        deadline = time.monotonic() + 5
        rows = []
        while time.monotonic() < deadline:
            rows, _ = client.internal.node_dump()
            if rows:
                break
            time.sleep(0.05)
        assert rows and rows[0]["node"] == "web-1"
        info, _ = client.internal.node_info("web-1")
        assert info["services"][0]["service"] == "web"

    def test_cli_operator_verbs(self, served, capsys):
        from consul_tpu.cli import main as cli_main

        _, port = served
        addr = ["--http-addr", f"127.0.0.1:{port}"]
        assert cli_main([*addr, "operator", "raft", "list-peers"]) == 0
        out = capsys.readouterr().out
        assert out.count("\n") == 3 and "leader" in out
        assert cli_main([*addr, "operator", "autopilot", "get-config"]) == 0
        assert "cleanup_dead_servers = True" in capsys.readouterr().out
        assert cli_main([*addr, "operator", "autopilot", "set-config",
                         "-max-trailing-logs", "123"]) == 0
        assert cli_main([*addr, "operator", "autopilot", "get-config"]) == 0
        assert "max_trailing_logs = 123" in capsys.readouterr().out
