"""Inter-mesh (DCN) federation tests: the WAN tier across islands.

The communication-backend tier map (SURVEY §2.5): in-sim tensor
exchange on-chip, ICI collectives intra-mesh (test_shardmap.py), and —
this file — host-mediated DCN reconciliation between meshes
(parallel/dcn.py): per-island WAN replicas, owner-authoritative
superstep sync, cross-island dissemination in-protocol.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.config import SimConfig
from consul_tpu.models.federation import Federation, FederationConfig
from consul_tpu.parallel import mesh as pmesh
from consul_tpu.parallel.dcn import DcnFederation, LinkFault, LinkPolicy
from consul_tpu.utils.telemetry import Sink


def _cfg(n_dc=4, nodes=32, servers=3, view=8):
    return FederationConfig(
        n_dc=n_dc, nodes_per_dc=nodes, servers_per_dc=servers,
        lan=SimConfig(n=nodes, view_degree=view),
    )


class TestPartitioning:
    def test_island_worlds_match_single_mesh_slices(self):
        """An island's LAN worlds must be the same worlds its DCs get in
        the equivalent single-mesh federation (global key indexing)."""
        cfg = _cfg()
        single = Federation(cfg, seed=5)
        dcn = DcnFederation(cfg, n_islands=2, seed=5)
        for k, isl in enumerate(dcn.islands):
            lo = k * 2
            np.testing.assert_array_equal(
                np.asarray(isl.lan_world.pos),
                np.asarray(single.lan_world.pos[lo:lo + 2]),
            )
        # And the WAN plant (sites, topology) is identical across
        # replicas — one shared geometry.
        np.testing.assert_array_equal(
            np.asarray(dcn.islands[0].wan_world.pos),
            np.asarray(dcn.islands[1].wan_world.pos),
        )
        np.testing.assert_array_equal(
            np.asarray(dcn.islands[0].wan_topo.off),
            np.asarray(dcn.islands[1].wan_topo.off),
        )

    def test_bad_partition_rejected(self):
        try:
            DcnFederation(_cfg(n_dc=3), n_islands=2)
            assert False, "expected ValueError"
        except ValueError:
            pass


class TestDcnSync:
    def test_owned_rows_authoritative_after_sync(self):
        cfg = _cfg()
        fed = DcnFederation(cfg, n_islands=2, seed=0)
        fed.run(32, sync_every=16)
        # Post-sync, all replicas agree on every WAN row.
        w0, w1 = fed.islands[0].state.wan, fed.islands[1].state.wan
        np.testing.assert_array_equal(
            np.asarray(w0.alive_truth), np.asarray(w1.alive_truth)
        )
        np.testing.assert_array_equal(
            np.asarray(w0.view_key), np.asarray(w1.view_key)
        )
        np.testing.assert_array_equal(
            np.asarray(w0.viv.vec), np.asarray(w1.viv.vec)
        )

    def test_cross_island_failure_detection(self):
        """Servers killed on island 0 must be seen dead by island 1's
        replica — the fact crosses the DCN seam at sync cadence, then
        spreads in-protocol."""
        cfg = _cfg()
        fed = DcnFederation(cfg, n_islands=2, seed=0)
        fed.run(64, sync_every=16)
        fed.kill(0, jnp.arange(cfg.nodes_per_dc) < cfg.servers_per_dc)
        fed.run(1400, sync_every=16)
        seen = fed.wan_status_seen_by(3, 0)   # dc3 lives on island 1
        tracked = [s for s in seen if s != "untracked"]
        assert tracked and all(s == "dead" for s in tracked), seen
        # Live DCs stay clean across the seam (no false positives).
        seen_live = fed.wan_status_seen_by(3, 1)
        assert all(s in ("alive", "untracked") for s in seen_live), seen_live

    def test_remote_coordinates_cross_the_seam(self):
        """Island 1's replica must carry island 0's server coordinates
        (learned on island 0, shipped by sync)."""
        cfg = _cfg()
        fed = DcnFederation(cfg, n_islands=2, seed=0)
        fed.run(256, sync_every=16)
        s = cfg.servers_per_dc
        v0 = np.asarray(fed.islands[0].state.wan.viv.vec[:2 * s])
        v1 = np.asarray(fed.islands[1].state.wan.viv.vec[:2 * s])
        np.testing.assert_array_equal(v0, v1)
        assert np.abs(v0).sum() > 0.0  # actually learned, not origin


class TestLinkFaultEnvelope:
    """The DCN fault envelope (parallel/dcn.py LinkPolicy): faulted
    links retry under bounded exponential backoff, buffer undelivered
    anti-entropy payloads in a bounded drop-oldest queue, and re-merge
    on heal — with every event counted through the telemetry sink."""

    def _fed(self, sink, policy, n_dc=4):
        return DcnFederation(_cfg(n_dc=n_dc), n_islands=2, seed=0,
                             sink=sink, link_policy=policy)

    def test_faulted_links_heal_and_reconverge(self):
        """The ISSUE acceptance drill: both directions of the island
        seam fail for rounds [1, 4) (one as a modeled send timeout, one
        as a fast drop); after the window the links heal with bounded
        retries and the replicas reconverge."""
        sink = Sink()
        fed = self._fed(sink, LinkPolicy(retry_max=3, queue_bound=4))
        fed.inject_link_faults([
            LinkFault(0, 1, start=1, stop=4, kind="timeout"),
            LinkFault(1, 0, start=1, stop=4, kind="drop"),
        ])
        fed.run(16 * 12, sync_every=16)
        assert fed.replicas_agree()
        assert sink.counter_sum("sim.dcn.retries") > 0
        assert sink.counter_sum("sim.dcn.send_timeouts") > 0
        assert sink.counter_sum("sim.dcn.link_down_ticks") > 0
        assert sink.counter_sum("sim.dcn.heals") >= 2  # both directions
        assert fed.queue_peak() <= 4
        # Healed links reset their retry machines.
        assert fed.link_state(0, 1).attempt == 0
        assert fed.link_state(1, 0).attempt == 0
        assert not fed.link_state(0, 1).degraded

    def test_retransmit_queue_is_bounded_drop_oldest(self):
        """An arbitrarily long partition must not grow memory: the
        queue caps at queue_bound, the oldest payloads drop (a newer
        anti-entropy payload supersedes them row-for-row), and the
        post-heal merge still converges."""
        sink = Sink()
        fed = self._fed(sink, LinkPolicy(retry_max=2, queue_bound=2))
        fed.inject_link_faults([LinkFault(0, 1, start=1, stop=10)])
        fed.run(16 * 16, sync_every=16)
        assert sink.counter_sum("sim.dcn.retx_dropped") > 0
        assert fed.queue_peak() <= 2
        assert fed.replicas_agree()

    def test_backoff_spaces_out_retries(self):
        """Backoff means strictly fewer attempts than faulted rounds:
        a downed link skips rounds instead of hammering every sync."""
        sink = Sink()
        fed = self._fed(sink, LinkPolicy(retry_max=8, backoff_base=1,
                                         backoff_cap=8, queue_bound=4))
        fed.inject_link_faults([LinkFault(0, 1, start=1, stop=12)])
        fed.run(16 * 14, sync_every=16)
        # 11 faulted rounds; exponential backoff admits far fewer
        # attempts (first failure isn't a retry, so < 10 is the loose
        # bound and < 6 the real behavior).
        assert 0 < sink.counter_sum("sim.dcn.retries") < 6

    def test_exhausted_retries_mark_degraded_until_heal(self):
        sink = Sink()
        fed = self._fed(sink, LinkPolicy(retry_max=2, queue_bound=4))
        fed.inject_link_faults([LinkFault(0, 1, start=1, stop=12)])
        fed.run(16 * 16, sync_every=16)
        assert sink.counter_sum("sim.dcn.link_degraded") == 1
        # It kept retrying at the capped cadence and healed afterwards.
        assert sink.counter_sum("sim.dcn.heals") >= 1
        assert not fed.link_state(0, 1).degraded
        assert fed.replicas_agree()

    def test_clean_links_count_nothing(self):
        sink = Sink()
        fed = self._fed(sink, LinkPolicy())
        fed.run(16 * 4, sync_every=16)
        for name in ("sim.dcn.retries", "sim.dcn.send_timeouts",
                     "sim.dcn.link_down_ticks", "sim.dcn.retx_dropped",
                     "sim.dcn.heals", "sim.dcn.link_degraded"):
            assert sink.counter_sum(name) == 0.0
        assert fed.replicas_agree()


class TestDcnOnMeshes:
    def test_islands_on_disjoint_device_subsets(self):
        """Each island sharded over its own 4-device mesh (the 8-device
        CPU harness models two hosts); the run must execute and keep
        every island's state on its own devices."""
        cfg = _cfg(n_dc=4, nodes=32)
        devs = jax.devices()
        meshes = [
            pmesh.make_mesh(devs[:4], n_dc=2),
            pmesh.make_mesh(devs[4:8], n_dc=2),
        ]
        fed = DcnFederation(cfg, n_islands=2, seed=0, meshes=meshes)
        fed.run(48, sync_every=16)
        for isl, m, dset in (
            (fed.islands[0], meshes[0], set(devs[:4])),
            (fed.islands[1], meshes[1], set(devs[4:8])),
        ):
            got = set(isl.state.lan.view_key.sharding.device_set)
            assert got <= dset, (got, dset)
        w0, w1 = fed.islands[0].state.wan, fed.islands[1].state.wan
        np.testing.assert_array_equal(
            np.asarray(w0.view_key), np.asarray(w1.view_key)
        )


class TestDcnRouterIntegration:
    """The server tier consuming cross-island membership: a Router fed
    from a REMOTE island's WAN replica (the reference's WAN-serf ->
    router adapter, agent/router/serf_adapter.go, operating across the
    DCN seam)."""

    def test_dead_dc_fails_over_across_islands(self):
        from consul_tpu.server.router import Router

        cfg = _cfg()
        fed = DcnFederation(cfg, n_islands=2, seed=0)
        fed.run(64, sync_every=16)
        fed.kill(0, jnp.ones(cfg.nodes_per_dc, bool))  # whole DC 0 dies
        fed.run(1400, sync_every=16)

        # dc3 lives on island 1; its replica feeds its router. Failed
        # members cycle to the back of the rotation (FailServer), reaped
        # members drop out (RemoveServer) — the two serf->router adapter
        # paths of reference agent/router/serf_adapter.go.
        isl, _ = fed.island_of_dc(3)
        router = Router("dc3")
        members = isl.wan_members_seen_by(3)
        dead_ids = {m["id"] for m in members
                    if m["dc"] == "dc0" and m["status"] == "dead"}
        assert dead_ids  # the observer tracked and detected dc0 deaths
        for m in members:
            router.add_server(m["id"], m["dc"])
            if m["status"] in ("dead", "left"):
                router.fail_server(m["id"])
        # Surviving DCs stay routable throughout.
        assert router.find_route("dc1") is not None
        assert router.find_route("dc2") is not None
        # After the reap sweep removes the dead members, dc0 has no
        # route at all.
        for sid in dead_ids:
            router.remove_server(sid)
        tracked0 = [m for m in members if m["dc"] == "dc0"]
        if all(m["status"] == "dead" for m in tracked0):
            assert router.find_route("dc0") is None
        assert router.find_route("dc1") is not None

    def test_remote_coordinates_order_dcs_across_islands(self):
        from consul_tpu.server.router import Router

        cfg = _cfg()
        fed = DcnFederation(cfg, n_islands=2, seed=0)
        fed.run(512, sync_every=16)
        # Island 1's replica holds island 0's learned coordinates
        # (crossed the seam); the distance ordering they induce must
        # match the shared ground-truth plant.
        isl, _ = fed.island_of_dc(3)
        router = Router("dc3")
        for dc in range(cfg.n_dc):
            for s in range(cfg.servers_per_dc):
                router.add_server(
                    f"srv{s}.dc{dc}", f"dc{dc}",
                    coord=isl.wan_server_coord(dc, s))
        got = [int(d[2:]) for d in router.get_datacenters_by_distance()]
        assert got == isl.true_dc_distance_order(3)
