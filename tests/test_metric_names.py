"""Lint test: every metric name the code emits is documented.

COVERAGE.md carries the table mapping each emitted metric name to the
reference instrumentation site it mirrors. This test extracts the names
the code can actually emit — every ``set_gauge`` / ``incr_counter`` /
``add_sample`` / ``measure_since`` call site under ``consul_tpu/`` plus
the device-counter name map (``models/counters.py METRIC_NAMES``) — and
fails if any is missing from the table, so the mapping can never rot
silently when someone adds an instrumentation point.
"""

import pathlib
import re

from consul_tpu.models import counters as counters_mod
from consul_tpu.ops import raft_ops

ROOT = pathlib.Path(__file__).resolve().parent.parent
EMIT_RE = re.compile(
    r'(?:set_gauge|incr_counter|add_sample|measure_since)\(\s*f?"([^"]+)"'
)


def _emitted_names():
    """(name, where) for every literal/f-string emission site. F-string
    names are truncated at the first placeholder — the static prefix is
    what the table must document."""
    out = []
    for p in sorted(ROOT.glob("consul_tpu/**/*.py")):
        for m in EMIT_RE.finditer(p.read_text()):
            name = m.group(1).split("{")[0].rstrip(".")
            if name:
                out.append((name, f"{p.relative_to(ROOT)}"))
    for field, name in sorted(counters_mod.METRIC_NAMES.items()):
        out.append((name, f"counters.METRIC_NAMES[{field!r}]"))
    for field, name in sorted(raft_ops.METRIC_NAMES.items()):
        out.append((name, f"raft_ops.METRIC_NAMES[{field!r}]"))
    return out


def test_all_emitted_names_are_extracted():
    """The extraction itself must keep finding the known fixed points —
    guards against the regex silently matching nothing."""
    names = {n for n, _ in _emitted_names()}
    assert "consul.rpc.request" in names
    assert "consul.raft.apply" in names
    assert "consul.leader.reconcile" in names
    assert "consul.http" in names            # f-string prefix
    assert "memberlist.udp.sent" in names    # via METRIC_NAMES
    assert "consul.raft.commit.advances" in names  # device raft tier
    assert len(names) >= 35


def test_every_emitted_name_is_in_coverage_table():
    table = (ROOT / "COVERAGE.md").read_text()
    missing = sorted(
        {(name, where) for name, where in _emitted_names()
         if name not in table}
    )
    assert not missing, (
        "metric names emitted but undocumented in COVERAGE.md "
        f"telemetry table: {missing}"
    )


def test_counter_metric_names_cover_all_fields():
    """The device-counter name map stays total over the pytree."""
    assert set(counters_mod.METRIC_NAMES) == set(counters_mod.FIELDS)
