"""WatchPlane under sustained churn (serving/watch.py): the game-day
watch-tier guarantees at unit scale.

- Bounded-queue shed accounting is EXACT: across thousands of watchers
  and many flips under a churn wave, the plane's ``deltas``/``shed``
  counters (and their sink mirrors) equal the per-watcher ground truth
  — every offer is either drainable from a queue or counted dropped;
  nothing is lost silently.
- Blocking-query waiters parked across a leader-kill window wake with
  a quorum-COMMITTED apply index, never a provisional one: while
  RaftKill freezes every leader, flips keep happening but the apply
  index does not move and no waiter wakes; after the window lifts and
  the re-elected leader commits, every waiter returns an index inside
  the committed range.
"""

import threading
import time

import pytest

from consul_tpu.chaos import schedule as chaos_mod
from consul_tpu.config import RaftConfig, SimConfig
from consul_tpu.models.cluster import Simulation
from consul_tpu.ops import deltas as deltas_mod
from consul_tpu.serving import ServingPlane


def _stack(n=256, seed=2, raft=None, **attach_kw):
    sim = Simulation(SimConfig(n=n, view_degree=16), seed=seed)
    if raft is not None:
        sim.set_raft(raft)
    plane = ServingPlane(k=8, buckets=(64,), num_services=4)
    sim.attach_serving(plane, writes=True, **attach_kw)
    sim.run(64, chunk=32, with_metrics=False)
    return sim, plane


class TestShedAccountingExact:
    def test_thousands_of_watchers_under_churn(self):
        """2048 watchers with 2-deep queues across a churn wave: the
        shed counter equals the sum of per-watcher drops, and every
        counted delivery is either drained or counted shed — offers =
        drained + dropped, exactly."""
        sim, plane = _stack(kv_slots=64, watch_queue=2)
        n_watch = 2048
        watchers = []
        for i in range(n_watch):
            kind = ("service", "any", "kv_prefix")[i % 3]
            key = {"service": i % 4, "any": None,
                   "kv_prefix": "churn/"}[kind]
            watchers.append(plane.watch.register(kind, key))

        sched = chaos_mod.shift_schedule(
            chaos_mod.compile_schedule(sim.cfg.n, [
                chaos_mod.ChurnWave(start=0, stop=96,
                                    nodes=slice(0, 32),
                                    period=16, down_ticks=8)]),
            sim._tick())
        sim.set_chaos(sched)
        try:
            for r in range(8):
                slot = plane.keys.slot_for(f"churn/k{r % 4}",
                                           create=True)
                ops = [(deltas_mod.OP_REGISTER, (r * 7 + j) % sim.cfg.n,
                        (r + j) % 4) for j in range(4)]
                plane.writes.execute(
                    ops + [(deltas_mod.OP_KV_PUT, slot, r)])
                sim.run(12, chunk=12, with_metrics=False)
                sim.publish_serving()
        finally:
            sim.set_chaos(None)

        st = plane.watch.stats()
        assert st["watchers"] == n_watch
        assert st["flips"] >= 8

        # Ground truth, watcher by watcher: whatever was not dropped
        # is still drainable; nothing else ever existed.
        dropped = sum(w.dropped for w in watchers)
        drained = 0
        final_index = int(plane.apply_index)
        for w in watchers:
            assert len(w.queue) <= 2
            while True:
                ev = w.poll(0)
                if ev is None:
                    break
                drained += 1
                assert 0 < ev.index <= final_index
        assert dropped > 0, "churn at 2-deep queues must shed"
        assert st["watch_shed"] == dropped
        assert st["deltas"] == drained + dropped
        # The sink mirrors agree with the plane's own tallies.
        assert sim.sink.counter_sum("sim.serving.shed") == dropped
        assert sim.sink.counter_sum("sim.serving.deltas") == \
            st["deltas"]
        assert sim.sink.counter_sum("sim.serving.watchers") == n_watch


class TestWaitIndexAcrossLeaderKill:
    def test_waiters_wake_committed_never_provisional(self):
        """Blocking queries parked through a RaftKill window: frozen
        leaders mean proposals stay inflight and the apply index stays
        put across flips — nobody wakes on provisional state. The
        post-window commit is the ONLY thing that wakes them, with the
        quorum-committed index."""
        sim, plane = _stack(
            seed=4, kv_slots=64,
            raft=RaftConfig(groups=2, peers=3, window=64))

        # Pre-window committed write: proves the commit path is live
        # and moves the apply index off zero before anyone parks.
        slot0 = plane.keys.slot_for("kill/base", create=True)
        plane.writes.execute([(deltas_mod.OP_KV_PUT, slot0, 1)])
        for _ in range(30):
            sim.run(8, chunk=8, with_metrics=False)
            sim.publish_serving()
            if sim.raft.inflight == 0:
                break
        assert sim.raft.inflight == 0
        seen = int(plane.apply_index)
        assert seen >= 1

        results = []
        waiters = [
            threading.Thread(
                target=lambda: results.append(
                    plane.watch.wait_index(seen, 60.0)))
            for _ in range(12)
        ]
        for t in waiters:
            t.start()
        kv_watch = plane.watch.register("kv_prefix", "kill/")

        # Composed kill window, 48 ticks: RaftKill freezes whoever
        # leads each group at each tick, and a RaftStorm blacks out
        # in-group delivery so even a mid-tick election winner cannot
        # replicate before the next tick's mask catches it — commits
        # are deterministically impossible until the window lifts.
        sched = chaos_mod.shift_schedule(
            chaos_mod.compile_schedule(sim.cfg.n, [
                chaos_mod.RaftKill(start=2, stop=50,
                                   group=-1, peer=-1),
                chaos_mod.RaftStorm(start=2, stop=50, group=-1)]),
            sim._tick())
        sim.set_chaos(sched)
        try:
            sim.run(4, chunk=4, with_metrics=False)  # inside window
            slots = [plane.keys.slot_for(f"kill/k{i}", create=True)
                     for i in range(4)]
            res = plane.writes.execute(
                [(deltas_mod.OP_KV_PUT, s, 9) for s in slots])
            assert all(r.status == "proposed" for r in res)

            # Flips keep coming inside the window, but with every
            # leader frozen nothing commits: the apply index is
            # pinned, the waiters stay parked, the kv watcher never
            # hears a provisional delivery.
            flips_before = plane.watch.flips
            for _ in range(3):
                sim.run(12, chunk=12, with_metrics=False)
                sim.publish_serving()
            time.sleep(0.1)
            assert plane.watch.flips > flips_before
            assert sim.raft.inflight >= 1
            assert int(plane.apply_index) == seen
            assert results == []
            assert len(kv_watch.queue) == 0

            # Past the window: a fresh election commits the staged
            # entries; the next flip carries the committed index.
            committed = False
            for _ in range(40):
                sim.run(16, chunk=16, with_metrics=False)
                sim.publish_serving()
                if sim.raft.inflight == 0:
                    committed = True
                    break
            assert committed, "proposals never committed after heal"
        finally:
            sim.set_chaos(None)
        sim.run(8, chunk=8, with_metrics=False)
        sim.publish_serving()

        for t in waiters:
            t.join(30.0)
        assert not any(t.is_alive() for t in waiters)
        final_index = int(plane.apply_index)
        assert len(results) == 12
        # Every woken index is committed state: past what the waiter
        # had seen, never past the committed frontier.
        assert all(seen < r <= final_index for r in results)
        # The watcher's delivery for the killed-window writes is the
        # committed index too, and the writes really are durable.
        ev = kv_watch.poll(5.0)
        assert ev is not None and seen < ev.index <= final_index
        for i in range(4):
            row = plane.kv_get(f"kill/k{i}")
            assert row is not None
            assert seen < row["ModifyIndex"] <= final_index
        plane.watch.unregister(kv_watch)


class TestLockLedgerHotPath:
    """The watch fan-out hot path under the LockLedger: a write-attached
    stack built inside the ledger's scope runs registrations, writes,
    flips, sheds, and drains with every WatchPlane/WriteBatcher/KeyTable
    lock traced. Clean = the observed lock-order graph is acyclic and
    no blocking work ran under a held lock, across three fuzz seeds."""

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_watch_churn_stays_clean(self, lock_ledger, seed):
        lock_ledger.fuzz(seed)
        sim, plane = _stack(n=64, seed=seed, kv_slots=16, watch_queue=2)
        watchers = [plane.watch.register(
            ("service", "any", "kv_prefix")[i % 3],
            {"service": i % 4, "any": None, "kv_prefix": "churn/"}[
                ("service", "any", "kv_prefix")[i % 3]])
            for i in range(96)]

        for r in range(3):
            slot = plane.keys.slot_for(f"churn/k{r}", create=True)
            ops = [(deltas_mod.OP_REGISTER, (r * 7 + j) % sim.cfg.n,
                    (r + j) % 4) for j in range(4)]
            plane.writes.execute(ops + [(deltas_mod.OP_KV_PUT, slot, r)])
            sim.run(12, chunk=12, with_metrics=False)
            sim.publish_serving()

        # Drain concurrently with one more flip so watcher conds are
        # exercised against on_flip's delivery path.
        drained = []

        def drain(w):
            while True:
                ev = w.poll(0.2)
                if ev is None:
                    return
                drained.append(ev)

        threads = [threading.Thread(target=drain, args=(w,))
                   for w in watchers[:16]]
        for t in threads:
            t.start()
        plane.writes.execute([(deltas_mod.OP_REGISTER, 9, 1)])
        sim.run(12, chunk=12, with_metrics=False)
        sim.publish_serving()
        for t in threads:
            t.join(30.0)
        assert plane.watch.stats()["flips"] >= 3

        # The shims were live: the watch-tier locks appear in the trace.
        names = {a[0] for a in lock_ledger.acquisitions}
        assert "WatchPlane._lock" in names
        assert "WatchPlane._index_cond" in names
        assert "Watcher.cond" in names
        lock_ledger.assert_clean()
